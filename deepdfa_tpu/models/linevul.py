"""LineVul: transformer sequence classifier, optionally combined with the
FlowGNN graph encoder.

Re-design of the reference's combined model
(LineVul/linevul/linevul_model.py:6-69): RoBERTa-family encoder (codebert or
unixcoder weights), classification vector = <s>/CLS hidden state, optionally
concatenated with the pooled FlowGNN embedding, then the RoBERTa head
(dropout → dense(hidden+extra → hidden) → tanh → dropout → proj(2)), CE loss.

Missing-graph semantics: the reference drops batch rows whose graph was not
parsed (``keep_idx``, linevul_main.py:191-197) and counts ``num_missing``.
Static shapes make that a mask: ``example_mask`` excludes those rows from
loss and metrics identically.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepdfa_tpu.core.config import FlowGNNConfig
from deepdfa_tpu.graphs.batch import GraphBatch
from deepdfa_tpu.models.flowgnn import FlowGNN
from deepdfa_tpu.models.transformer import EncoderConfig, RobertaEncoder


class ClassificationHead(nn.Module):
    """RobertaClassificationHead with an extra-feature slot
    (linevul_model.py:6-24)."""

    hidden_size: int
    dropout_rate: float = 0.1

    @nn.compact
    def __call__(self, cls_vec, graph_embed, deterministic: bool = True):
        x = cls_vec
        if graph_embed is not None:
            x = jnp.concatenate([x, graph_embed], axis=-1)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        x = nn.Dense(self.hidden_size, name="dense")(x)
        x = jnp.tanh(x)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        return nn.Dense(2, name="out_proj")(x)


class LineVul(nn.Module):
    """Text (+graph) classifier.

    ``graph_config`` None → pure LineVul; set → DeepDFA+LineVul combined
    (the primary parity target, paper Table 3b).
    """

    encoder_config: EncoderConfig
    graph_config: Optional[FlowGNNConfig] = None
    mesh: object = None  # needed for attention_impl == "ring" and for
    # sharded tile graph batches (stacked adjacency under shard_map)

    @nn.compact
    def __call__(
        self,
        input_ids: jnp.ndarray,
        graphs: Optional[GraphBatch] = None,
        deterministic: bool = True,
        output_attentions: bool = False,
        input_embeds: Optional[jnp.ndarray] = None,
    ):
        attn_mask = input_ids != self.encoder_config.pad_token_id
        hidden, attentions = RobertaEncoder(
            self.encoder_config, mesh=self.mesh, name="roberta"
        )(
            input_ids,
            attn_mask,
            deterministic=deterministic,
            output_attentions=output_attentions,
            input_embeds=input_embeds,
        )
        cls_vec = hidden[:, 0, :]

        graph_embed = None
        if self.graph_config is not None:
            assert graphs is not None, "combined model needs a GraphBatch"
            enc_cfg = self.graph_config
            assert enc_cfg.encoder_mode, "graph_config must set encoder_mode"
            graph_embed = FlowGNN(enc_cfg, mesh=self.mesh, name="flowgnn")(graphs)

        logits = ClassificationHead(
            self.encoder_config.hidden_size,
            self.encoder_config.dropout_rate,
            name="classifier",
        )(cls_vec, graph_embed, deterministic=deterministic)
        if output_attentions:
            return logits, attentions
        return logits


def cross_entropy_loss(
    logits: jnp.ndarray, labels: jnp.ndarray, example_mask: jnp.ndarray
) -> jnp.ndarray:
    """Masked mean 2-class CE (linevul_model.py CE over keep_idx rows).

    Masked rows are neutralized BEFORE log_softmax: padded tail rows
    (all-pad inputs) can produce non-finite logits, and both the forward
    (``NaN * 0 == NaN`` in a masked sum) and the backward (log_softmax's
    VJP emits NaN for a non-finite row even under a zero cotangent — the
    double-where problem) would poison the batch through the shared
    parameters."""
    logits = jnp.where(example_mask[:, None], logits, 0.0)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(log_probs, labels[:, None].astype(jnp.int32), axis=1)[:, 0]
    picked = jnp.where(example_mask, picked, 0.0)
    m = example_mask.astype(jnp.float32)
    return -jnp.sum(picked) / jnp.maximum(jnp.sum(m), 1.0)

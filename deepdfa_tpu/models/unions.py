"""Differentiable bitwise-union (dataflow meet) operators.

Parity with the reference's experimental smooth union ops
(DDFA/code_gnn/models/clipper.py:6-25) used to simulate dataflow-analysis
meet functions inside a differentiable model, plus a segment-based
union-aggregation over graph edges replacing the DGL node UDF factory
(clipper.py:50-77).
"""

from __future__ import annotations

import jax.numpy as jnp

from deepdfa_tpu.graphs.segment import segment_sum


def simple_union(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Probabilistic OR: a ∪ b = a + b − a·b (clipper.py:6-14)."""
    return (a + b) - (a * b)


def relu_union(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Clipped-sum OR: 1 − relu(1 − (a+b)) (clipper.py:17-25).

    For binary inputs equals bitwise OR; for reals it is min(a + b, 1) when
    a + b ≥ 0, giving a piecewise-linear, gradient-friendly union.
    """
    ones = jnp.ones_like(a)
    return ones - jnp.maximum(ones - (a + b), 0.0)


def segment_union(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    kind: str = "simple",
) -> jnp.ndarray:
    """Union-reduce rows into segments.

    Replaces the reference's sequential mailbox loop (clipper.py:62-72) with
    closed forms that XLA reduces in one pass:
      simple: 1 − Π(1 − x)  computed as exp(Σ log(1−x)) — the n-ary extension
              of a+b−ab.
      relu:   min(Σ x, 1)   — the n-ary extension of the clipped sum.
    """
    if kind == "simple":
        log_keep = jnp.log1p(-jnp.clip(data, 0.0, 1.0 - 1e-7))
        summed = segment_sum(log_keep, segment_ids, num_segments)
        return 1.0 - jnp.exp(summed)
    if kind == "relu":
        summed = segment_sum(data, segment_ids, num_segments)
        return jnp.minimum(summed, 1.0)
    raise ValueError(f"unknown union kind: {kind}")

"""FlowGNN: gated graph network over program CFGs with abstract-dataflow
node embeddings.

Re-design of the reference's ``FlowGNNGGNNModule``
(DDFA/code_gnn/models/flow_gnn/ggnn.py:22-109) for TPU:

- DGL ``GatedGraphConv`` (CUDA SpMM + GRU) becomes a ``lax.scan`` over gated
  message-passing steps built from masked segment sums — static shapes, XLA
  fuses the edge gather/transform/scatter; a Pallas kernel can drop in for
  the message step (``deepdfa_tpu.ops``).
- DGL ``GlobalAttentionPooling`` becomes a masked segment softmax.
- The 4 per-subkey ``nn.Embedding`` tables (ggnn.py:47-54) become one stacked
  embedding lookup.

Architecture parity (config_ggnn.yaml: hidden 32, 5 steps, 3 output layers,
concat_all): per-subkey embed(input_dim, 32) -> concat 128 -> 5 gated steps at
width 128 -> skip-concat [ggnn_out, embed] 256 -> attention-pool -> MLP
256-256-1.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepdfa_tpu.core.backend import resolve_auto
from deepdfa_tpu.core.config import FlowGNNConfig, subkeys_for
from deepdfa_tpu.graphs.batch import GraphBatch
from deepdfa_tpu.graphs.segment import (
    onehot_take,
    segment_softmax,
    segment_sum,
)


class EmbedTable(nn.Module):
    """``nn.Embed``-compatible lookup table (same param tree —
    ``{name}/embedding`` — and the same variance-scaling fan-in init) whose
    gradient accumulation can run as an assignment-matrix matmul instead of
    XLA's serialized scatter-add (segment.onehot_take: measured 0.83 ->
    0.61 ms/step on the GNN flagship, bench.py).

    ``impl``: "take" = plain gather (scatter-add backward, the oracle);
    "matmul" = onehot_take backward; "auto" = matmul on TPU, take
    elsewhere (the dense backward's zero-fill is free on the MXU only) —
    the same backend gate as pool_impl/message_impl.
    """

    num: int
    dim: int
    dtype: jnp.dtype = jnp.float32
    impl: str = "auto"

    @nn.compact
    def __call__(self, idx: jnp.ndarray) -> jnp.ndarray:
        emb_init = nn.initializers.variance_scaling(
            1.0, "fan_in", "normal", out_axis=0
        )
        table = self.param("embedding", emb_init, (self.num, self.dim))
        impl = resolve_auto(self.impl, tpu="matmul", other="take")
        if impl == "take":
            return jnp.take(table, idx, axis=0).astype(self.dtype)
        if impl != "matmul":
            raise ValueError(f"unknown embed impl {impl!r}")
        precision = (
            jax.lax.Precision.HIGHEST
            if jnp.dtype(self.dtype) == jnp.float32
            else jax.lax.Precision.DEFAULT
        )
        return onehot_take(table, idx, precision).astype(self.dtype)


class GatedGraphStep(nn.Module):
    """One gated message-passing step: a_v = Σ_{(u,v)∈E} W h_u ; h' = GRU(a, h).

    Semantics of DGL ``GatedGraphConv`` with ``n_etypes=1`` (ggnn.py:57-60):
    a single edge-typed linear applied to sender states, summed into
    receivers, fed to a GRU cell as the input with the node state as carry.

    Four aggregation paths: XLA segment ops (gather + scatter-add), the
    Pallas block-sparse tile SpMM (``deepdfa_tpu.ops.tile_spmm``) when the
    batch carries a precomputed ``TileAdjacency``, the block-banded
    batched matmul (``deepdfa_tpu.ops.band_spmm``) — dense MXU work instead
    of irregular memory traffic, fully parallel in the banded case — and
    ``"fused"`` (``deepdfa_tpu.ops.fused_gnn``): the whole step (edge
    message + band SpMM + GRU gate) as ONE Pallas kernel whose
    intermediates never leave VMEM. Off-TPU (and on sharded batches) the
    fused flag dispatches the band composition through the same flax
    modules, so it degrades to the bitwise band path.
    """

    hidden: int
    dtype: jnp.dtype = jnp.float32
    message_impl: str = "segment"
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, h, batch: GraphBatch):
        impl = self.message_impl
        if impl == "fused":
            if batch.band_adj is None:
                raise ValueError(
                    "message_impl='fused' needs batch_graphs(build_band_adj"
                    "=True) — the fused kernel consumes the band adjacency"
                )
            from deepdfa_tpu.ops import fused_gnn

            fimpl = fused_gnn.resolve_impl()
            sharded = batch.band_adj.vals.ndim == 5
            if fimpl != "xla" and not sharded:
                # The megakernel: gather + band SpMM + GRU gate in one
                # pallas_call (ops/fused_gnn.py). Params are declared
                # through holder modules at the SAME scope paths as the
                # flax Dense/GRUCell below, so the tree (and every
                # checkpoint) is identical across impls — pinned by
                # tests/test_fused_gnn.py.
                params = fused_gnn.declare_step_params(
                    self.hidden, int(h.shape[-1]))
                return fused_gnn.fused_gate_step(
                    params, h, batch.band_adj, impl=fimpl)
            # Numerically-identical XLA fallback (CPU tier-1, sharded
            # meshes): fall through to the band composition — literally
            # the same flax modules, so fused-on-CPU IS the band path
            # bitwise (the gradient-parity acceptance gate).
            impl = "band"
        msg = nn.Dense(self.hidden, dtype=self.dtype, name="edge_linear")(h)
        if impl == "tile":
            if batch.tile_adj is None:
                raise ValueError(
                    "message_impl='tile' needs batch_graphs(build_tile_adj=True)"
                )
            from deepdfa_tpu.ops.tile_spmm import tile_spmm, tile_spmm_sharded

            if batch.tile_adj.vals.ndim == 4:
                # Stacked per-shard adjacency (shard_concat on a dp mesh):
                # each device runs the kernel on its own tile list.
                if self.mesh is None:
                    raise ValueError(
                        "sharded tile batch needs FlowGNN(config, mesh=mesh)"
                    )
                agg = tile_spmm_sharded(batch.tile_adj, msg, self.mesh)
            else:
                agg = tile_spmm(batch.tile_adj, msg)
        elif impl == "band":
            if batch.band_adj is None:
                raise ValueError(
                    "message_impl='band' needs batch_graphs(build_band_adj=True)"
                )
            from deepdfa_tpu.ops.band_spmm import band_spmm, band_spmm_sharded

            if batch.band_adj.vals.ndim == 5:
                # Stacked per-shard adjacency (shard_concat on a dp mesh).
                if self.mesh is None:
                    raise ValueError(
                        "sharded band batch needs FlowGNN(config, mesh=mesh)"
                    )
                agg = band_spmm_sharded(batch.band_adj, msg, self.mesh)
            else:
                agg = band_spmm(batch.band_adj, msg)
        else:
            gathered = jnp.take(msg, batch.senders, axis=0)
            gathered = jnp.where(batch.edge_mask[:, None], gathered, 0.0)
            agg = segment_sum(gathered, batch.receivers, batch.max_nodes)
        new_h, _ = nn.GRUCell(self.hidden, dtype=self.dtype, name="gru")(h, agg)
        return new_h


class _PersistentUnroll(nn.Module):
    """The K-step persistent megakernel's flax face (ISSUE 15).

    Declares the SAME param tree at the same scope paths as the scanned
    ``GatedGraphStep`` (``edge_linear`` + ``gru/{ir,iz,in,hr,hz,hn}`` via
    the fused_gnn holder modules, broadcast across steps — nn.scan with
    ``variable_broadcast`` adds no scan axis), so checkpoints survive
    flips between ``persistent``, ``fused``, and ``band``. Dispatches the
    whole unroll as ONE ``pallas_call`` per direction
    (``fused_gnn.persistent_unroll``): h VMEM-resident across all
    ``n_steps``, bitwise equal to the scan-of-fused-step oracle in
    forward AND gradients (pinned by tests/test_persistent_gnn.py).
    """

    hidden: int
    n_steps: int

    @nn.compact
    def __call__(self, h, band_adj, impl: str):
        from deepdfa_tpu.ops import fused_gnn

        params = fused_gnn.declare_step_params(self.hidden,
                                               int(h.shape[-1]))
        return fused_gnn.persistent_unroll(params, h, band_adj,
                                           self.n_steps, impl=impl)


class GlobalAttentionPool(nn.Module):
    """Masked per-graph attention pooling.

    DGL ``GlobalAttentionPooling`` with a Linear(out_in, 1) gate
    (ggnn.py:66-68): gate logits softmaxed over each graph's nodes, then a
    weighted sum of node features. Padded node slots get zero weight via the
    mask, so pooling over a padded batch equals pooling over the dynamic
    batch.

    ``impl="matmul"`` (the default on TPU via "auto") routes every
    per-graph reduction AND
    every graph-to-node broadcast through one dense assignment matrix
    (graphs/segment.py:segment_onehot): TPU scatters serialize and even the
    [graphs]->[nodes] broadcast gathers cost ~190 us each in the traced
    train step, ~0.9 ms/step total in this pooling (bench.py module
    docstring). The per-graph softmax shift itself is kept (numerics
    identical to the segment path) but computed under stop_gradient, so its
    scatter-max has no backward transpose. ``impl="segment"`` keeps the
    scatter formulation (the oracle the matmul path is tested against).
    """

    dtype: jnp.dtype = jnp.float32
    impl: str = "auto"

    @nn.compact
    def __call__(self, feat, node_graph, node_mask, n_graphs):
        # Backend-gated like message_impl: the dense formulation's
        # zero-fill is free on the MXU but real FLOPs on CPU hosts.
        impl = resolve_auto(self.impl, tpu="matmul", other="segment")
        gate = nn.Dense(1, dtype=self.dtype, name="gate")(feat)[:, 0]
        if impl == "segment":
            weights = segment_softmax(gate, node_graph, n_graphs, mask=node_mask)
            weighted = feat * weights[:, None]
            weighted = jnp.where(node_mask[:, None], weighted, 0.0)
            return segment_sum(weighted, node_graph, n_graphs)
        if impl != "matmul":
            raise ValueError(f"unknown pool impl {impl!r}")
        from deepdfa_tpu.graphs.segment import segment_onehot

        gate32 = jnp.where(node_mask, gate.astype(jnp.float32), -jnp.inf)
        onehot32 = segment_onehot(node_graph, n_graphs, mask=node_mask)
        # Per-graph stability shift, same values as segment_softmax's
        # segment_max — computed as a dense masked row-max (one reduce
        # fusion; the scatter-max alone cost ~70 us) under stop_gradient
        # (softmax is shift-invariant, so the shift carries no true
        # gradient). The [graphs]->[nodes] broadcast rides the onehot
        # matmul instead of a (slow) gather.
        shift = jax.lax.stop_gradient(
            jnp.where(onehot32 != 0, gate32[None, :], -jnp.inf).max(axis=1)
        )
        shift = jnp.where(jnp.isneginf(shift), 0.0, shift)  # empty graphs
        # f32 runs keep HIGHEST matmul precision so TPU stays comparable
        # with the segment oracle (DEFAULT lowers f32 dots to bf16 MXU
        # passes) — the same rule as band_spmm/tile_spmm. bf16 runs take
        # DEFAULT everywhere: a bf16-rounded shift/denominator is no
        # coarser than the surrounding bf16 compute, and HIGHEST's 6-pass
        # decomposition over the [graphs, nodes] onehot costs ~0.27 ms of
        # the 0.83 ms step (measured).
        precision = (
            jax.lax.Precision.HIGHEST
            if jnp.dtype(self.dtype) == jnp.float32
            else jax.lax.Precision.DEFAULT
        )
        shift_b = jnp.matmul(  # [nodes]; masked slots broadcast 0
            shift, onehot32, precision=precision
        )
        e = jnp.where(node_mask, jnp.exp(gate32 - shift_b), 0.0)
        denom = jnp.matmul(onehot32, e, precision=precision)
        denom = jnp.where(denom > 0, denom, 1.0)  # empty graphs pool to 0
        weighted = feat * e[:, None].astype(feat.dtype)
        pooled = jax.lax.dot_general(
            onehot32.astype(feat.dtype), weighted,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
        return (pooled / denom[:, None]).astype(feat.dtype)


class FlowGNN(nn.Module):
    """The DeepDFA graph model.

    ``encoder_mode=True`` returns the pooled graph embedding of width
    ``config.out_dim`` for the combined graph+text models (ggnn.py:104-107);
    otherwise the MLP head produces one logit per graph (label_style
    "graph") or per node (label_style "node"/"dataflow_solution_*").
    """

    config: FlowGNNConfig
    mesh: Optional[Any] = None

    @nn.compact
    def __call__(self, batch: GraphBatch) -> jnp.ndarray:
        cfg = self.config
        dtype = jnp.dtype(cfg.dtype)
        subkeys = subkeys_for(cfg.feature)

        # Per-subkey embedding tables, concatenated (ggnn.py:84-89).
        embeds = []
        for key in subkeys:
            table = EmbedTable(
                cfg.input_dim, cfg.hidden_dim, dtype=dtype,
                impl=cfg.embed_impl, name=f"embed_{key}"
            )
            embeds.append(table(batch.node_feats[key]))
        feat_embed = jnp.concatenate(embeds, axis=-1)

        # Embedding width and GGNN width are equal by construction
        # (FlowGNNConfig defines both as hidden_dim * n_subkeys), so unlike
        # DGL's GatedGraphConv no zero-padding of the input is needed.
        h = feat_embed

        # message_impl="persistent" (ISSUE 15): the WHOLE K-step unroll as
        # one pallas_call per direction — h stays VMEM-resident across all
        # steps, so HBM sees h once in and h_K once out instead of 2×K
        # per-step tile round-trips. Eligibility mirrors the fused flag
        # (unsharded band adjacency, a real kernel backend); sharded and
        # off-TPU batches degrade to the scan of fused steps below, which
        # itself degrades to the bitwise band composition.
        message_impl = cfg.message_impl
        persistent_kernel = None
        if message_impl == "persistent":
            if batch.band_adj is None:
                raise ValueError(
                    "message_impl='persistent' needs batch_graphs("
                    "build_band_adj=True) — the persistent kernel consumes "
                    "the band adjacency"
                )
            from deepdfa_tpu.ops import fused_gnn

            fimpl = fused_gnn.resolve_impl()
            sharded = batch.band_adj.vals.ndim == 5
            # The third eligibility leg: the resident h + windows must
            # fit the VMEM budget, or Mosaic would fail the allocation
            # at compile time — a batch the fused-scan degrade runs
            # fine must never crash the persistent flag.
            fits = fused_gnn.persistent_vmem_ok(
                batch.band_adj, cfg.ggnn_hidden, dtype)
            if fimpl != "xla" and not sharded and fits:
                persistent_kernel = fimpl
            else:
                message_impl = "fused"
        if persistent_kernel is not None:
            ggnn_out = _PersistentUnroll(
                cfg.ggnn_hidden, n_steps=cfg.n_steps, name="ggnn_step"
            )(h, batch.band_adj, persistent_kernel)
        else:
            # remat: recompute step activations in the backward instead of
            # saving them — the step is HBM-bound, so this is faster on TPU
            # (~7% at the published shape) and lighter on memory.
            step_cls = (nn.remat(GatedGraphStep) if cfg.remat_steps
                        else GatedGraphStep)
            step = step_cls(
                cfg.ggnn_hidden,
                dtype=dtype,
                message_impl=message_impl,
                mesh=self.mesh,
                name="ggnn_step",
            )
            # Weight sharing across steps (one GatedGraphConv applied
            # n_steps times) — scan over a length-n_steps axis with
            # broadcast params. Fully unrolled (capped at 8 iterations per
            # loop step): at the published 5-step depth XLA fuses across
            # step boundaries that the rolled scan's carry structure
            # forbids — whole-step A/B on v5e: 405-410k vs 392-394k
            # graphs/s (+3-4%), consistent across interleaved repeats
            # (round-5 notes, bench.py). The hint is gated on the
            # RESOLVED impl structurally: when the persistent kernel
            # dispatches above, no scan (and no unroll hint) exists at
            # all — the hint would be dead weight on that path — while
            # every path that actually scans (band/fused/segment/tile
            # AND the persistent flag's degrade, which must stay
            # program-identical to the fused scan) keeps today's unroll
            # bit-for-bit.
            scan = nn.scan(
                lambda mod, carry, _: (mod(carry, batch), None),
                variable_broadcast="params",
                split_rngs={"params": False},
                length=cfg.n_steps,
                unroll=min(cfg.n_steps, 8),
            )
            ggnn_out, _ = scan(step, h, None)

        # Skip-concat with the input embedding (ggnn.py:98).
        out = jnp.concatenate([ggnn_out, feat_embed], axis=-1)

        if cfg.label_style == "graph":
            pooled = GlobalAttentionPool(
                dtype=dtype, impl=cfg.pool_impl, name="pooling"
            )(
                out, batch.node_graph, batch.node_mask, batch.n_graphs
            )
            if cfg.encoder_mode:
                return pooled
            return self._head(pooled)[:, 0]

        # Node-level label styles skip pooling (ggnn.py:100-102).
        if cfg.encoder_mode:
            return out
        return self._head(out)[:, 0]

    @nn.compact_name_scope
    def _head(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        for i in range(cfg.num_output_layers):
            last = i == cfg.num_output_layers - 1
            x = nn.Dense(1 if last else cfg.out_dim, name=f"output_{i}")(x)
            if not last:
                x = nn.relu(x)
        return x


def init_flowgnn(
    config: FlowGNNConfig, batch: GraphBatch, seed: int = 0
) -> Dict:
    model = FlowGNN(config)
    params = model.init(jax.random.PRNGKey(seed), batch)
    return params

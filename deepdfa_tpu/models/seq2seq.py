"""RoBERTa-encoder seq2seq: the reference's ``model_type=roberta``
generation path, TPU-native.

Re-design of CodeT5/models.py:195-408 (``Seq2Seq`` = RoBERTa encoder +
6-layer torch ``nn.TransformerDecoder`` + tied lm head + hand-rolled
``Beam``): the encoder is our Flax :class:`RobertaEncoder`, the decoder a
causal transformer with cross-attention and a KV cache, embeddings shared
between encoder input, decoder input, and the lm head (the reference ties
``lm_head.weight`` to ``encoder.embeddings.word_embeddings``). Decoding
reuses models/t5_generate.py's generic greedy/beam (this class implements
the same encode/decode/decode_logits protocol).

Decoder block layout follows torch ``nn.TransformerDecoderLayer`` defaults
the reference relies on: post-LN residuals, ReLU FFN.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu.models.beam_fold import fold_beam_queries, unfold_beam_out
from deepdfa_tpu.models.transformer import EncoderConfig, RobertaEncoder


@dataclasses.dataclass(frozen=True)
class Seq2SeqConfig:
    """Encoder shape + decoder depth + special ids. ``decoder_start_token_id``
    is the CLS/sos id and ``eos_token_id`` the SEP id (models.py:34-35
    ``sos_id=tokenizer.cls_token_id, eos_id=tokenizer.sep_token_id``)."""

    encoder: EncoderConfig = dataclasses.field(default_factory=EncoderConfig)
    num_decoder_layers: int = 6
    decoder_ffn_dim: int = 2048  # torch TransformerDecoderLayer default
    max_target_positions: int = 512

    @property
    def vocab_size(self) -> int:
        return self.encoder.vocab_size

    @property
    def hidden_size(self) -> int:
        return self.encoder.hidden_size

    @property
    def pad_token_id(self) -> int:
        return self.encoder.pad_token_id

    @property
    def decoder_start_token_id(self) -> int:
        return 0  # <s> / CLS in the RoBERTa vocab

    @property
    def eos_token_id(self) -> int:
        return 2  # </s> / SEP in the RoBERTa vocab

    @classmethod
    def tiny(cls, vocab_size: int = 128) -> "Seq2SeqConfig":
        return cls(
            encoder=EncoderConfig.tiny(vocab_size),
            num_decoder_layers=2,
            decoder_ffn_dim=64,
            max_target_positions=32,
        )


class _DecoderAttention(nn.Module):
    """MHA with an optional decode cache: self-attention caches K/V by step,
    cross-attention caches the encoder projections (same scheme as
    models/t5.py T5Attention)."""

    cfg: Seq2SeqConfig
    causal: bool = False

    @nn.compact
    def __call__(self, x, kv, mask, deterministic, decode=False,
                 beam_anc=None, beam_gather_impl="take_along"):
        c = self.cfg
        h = c.encoder.num_heads
        d = c.hidden_size
        head_dim = d // h
        is_cross = kv is not None
        kv = x if kv is None else kv

        q = nn.Dense(d, name="q")(x)

        def split(t):
            return t.reshape(t.shape[0], t.shape[1], h, head_dim)

        q = split(q)
        cross_cached = decode and is_cross and self.has_variable("cache", "cross_k")
        if cross_cached:
            k = self.get_variable("cache", "cross_k")
            v = self.get_variable("cache", "cross_v")
        else:
            k = split(nn.Dense(d, name="k")(kv))
            v = split(nn.Dense(d, name="v")(kv))
            if decode and is_cross:
                self.variable("cache", "cross_k", lambda: k)
                self.variable("cache", "cross_v", lambda: v)

        if decode and not is_cross:
            is_init = not self.has_variable("cache", "cached_k")
            ck = self.variable("cache", "cached_k", jnp.zeros, k.shape, k.dtype)
            cv = self.variable("cache", "cached_v", jnp.zeros, v.shape, v.dtype)
            ci = self.variable("cache", "cache_index",
                               lambda: jnp.zeros((), jnp.int32))
            if not is_init:
                idx = ci.value
                ck.value = jax.lax.dynamic_update_slice(ck.value, k, (0, idx, 0, 0))
                cv.value = jax.lax.dynamic_update_slice(cv.value, v, (0, idx, 0, 0))
                ci.value = idx + 1
                k, v = ck.value, cv.value
                if beam_anc is not None:
                    # Batched-beam decode: physical cache rows, ancestry
                    # resolved at read time (models/t5.py ancestry_gather).
                    from deepdfa_tpu.models.t5 import ancestry_gather

                    k = ancestry_gather(k, beam_anc, beam_gather_impl)
                    v = ancestry_gather(v, beam_anc, beam_gather_impl)
                mask = (jnp.arange(k.shape[1]) <= idx)[None, None, None, :]

        # Beam-deduped cross K/V (models/beam_fold.py): the beam factor
        # folds into the query axis when K/V are stored once per batch row.
        fold = None
        if is_cross:
            q, fold = fold_beam_queries(q, k)

        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(head_dim)
        if self.causal and not decode and not is_cross:
            t = x.shape[1]
            causal = jnp.tril(jnp.ones((t, t), bool))
            mask = mask & causal[None, None]
        scores = scores + jnp.where(mask, 0.0, -1e9)
        weights = jax.nn.softmax(scores, axis=-1)
        weights = nn.Dropout(c.encoder.dropout_rate)(
            weights, deterministic=deterministic
        )
        out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        out = unfold_beam_out(out, fold)
        out = out.reshape(out.shape[0], out.shape[1], d)
        return nn.Dense(d, name="out")(out)


class _DecoderLayer(nn.Module):
    cfg: Seq2SeqConfig

    @nn.compact
    def __call__(self, x, self_mask, enc_out, enc_mask, deterministic,
                 decode=False, beam_anc=None, beam_gather_impl="take_along"):
        c = self.cfg
        eps = c.encoder.layer_norm_eps
        drop = c.encoder.dropout_rate
        attn = _DecoderAttention(c, causal=True, name="self_attn")(
            x, None, self_mask, deterministic, decode=decode,
            beam_anc=beam_anc, beam_gather_impl=beam_gather_impl,
        )
        attn = nn.Dropout(drop)(attn, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=eps, name="self_ln")(x + attn)

        cross = _DecoderAttention(c, name="cross_attn")(
            x, enc_out, enc_mask, deterministic, decode=decode
        )
        cross = nn.Dropout(drop)(cross, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=eps, name="cross_ln")(x + cross)

        ff = nn.Dense(c.decoder_ffn_dim, name="ffn_in")(x)
        ff = nn.relu(ff)
        ff = nn.Dense(c.hidden_size, name="ffn_out")(ff)
        ff = nn.Dropout(drop)(ff, deterministic=deterministic)
        return nn.LayerNorm(epsilon=eps, name="ffn_ln")(x + ff)


class _PositionCache(nn.Module):
    """Tracks the decoder position across cached decode steps (variables
    must be created in a compact method, hence this tiny submodule)."""

    @nn.compact
    def __call__(self, length: int, decode: bool):
        if not decode:
            return jnp.arange(length)
        is_init = not self.has_variable("cache", "idx")
        var = self.variable("cache", "idx", lambda: jnp.zeros((), jnp.int32))
        if is_init:
            return jnp.arange(length)
        pos = var.value + jnp.arange(length)
        var.value = var.value + length
        return pos


class RobertaSeq2Seq(nn.Module):
    """Implements the t5_generate decode protocol (encode / decode /
    decode_logits / logits) over a RoBERTa encoder."""

    cfg: Seq2SeqConfig

    def setup(self):
        c = self.cfg
        self.shared = nn.Embed(c.vocab_size, c.hidden_size, name="shared")
        self.encoder = RobertaEncoder(c.encoder, name="encoder")
        self.tgt_positions = nn.Embed(
            c.max_target_positions, c.hidden_size, name="tgt_positions"
        )
        self.layers = [
            _DecoderLayer(c, name=f"layer_{i}") for i in range(c.num_decoder_layers)
        ]
        self.pos_cache = _PositionCache(name="pos_cache")

    def encode(self, input_ids, attn_mask=None, deterministic: bool = True):
        if attn_mask is None:
            attn_mask = input_ids != self.cfg.pad_token_id
        # Shared embedding feeds the encoder via input_embeds (the tied-
        # weight scheme: one table for encoder input, decoder input, and the
        # lm head, models.py:212-217 tie_weights).
        hidden, _ = self.encoder(
            input_ids, attn_mask, deterministic=deterministic,
            input_embeds=self.shared(input_ids),
        )
        return hidden

    def decode(self, decoder_input_ids, decoder_mask, enc_out, enc_mask,
               deterministic: bool = True, decode: bool = False,
               beam_anc=None, beam_gather_impl: str = "take_along"):
        c = self.cfg
        x = self.shared(decoder_input_ids)
        positions = self.pos_cache(decoder_input_ids.shape[1], decode)
        x = x + self.tgt_positions(jnp.minimum(positions, c.max_target_positions - 1))

        self_mask = decoder_mask[:, None, None, :]
        cross_mask = enc_mask[:, None, None, :]
        for layer in self.layers:
            x = layer(x, self_mask, enc_out, cross_mask, deterministic,
                      decode=decode, beam_anc=beam_anc,
                      beam_gather_impl=beam_gather_impl)
        return x

    def logits(self, hidden):
        return hidden @ self.shared.embedding.T

    def decode_logits(self, decoder_input_ids, decoder_mask, enc_out, enc_mask,
                      deterministic: bool = True, decode: bool = False,
                      beam_anc=None, beam_gather_impl: str = "take_along"):
        hidden = self.decode(decoder_input_ids, decoder_mask, enc_out, enc_mask,
                             deterministic=deterministic, decode=decode,
                             beam_anc=beam_anc,
                             beam_gather_impl=beam_gather_impl)
        return self.logits(hidden)

    def __call__(self, input_ids, decoder_input_ids,
                 attn_mask=None, decoder_mask=None,
                 deterministic: bool = True):
        c = self.cfg
        if attn_mask is None:
            attn_mask = input_ids != c.pad_token_id
        if decoder_mask is None:
            decoder_mask = jnp.ones_like(decoder_input_ids, bool)
        enc_out = self.encode(input_ids, attn_mask, deterministic)
        return self.decode(decoder_input_ids, decoder_mask, enc_out, attn_mask,
                           deterministic)

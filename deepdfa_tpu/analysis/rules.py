"""Hazard rules over the taint / reaching-definitions facts.

Rule catalogue (each backed by a positive+negative fixture in
``tests/test_analysis.py``):

  GL001 tracer-host-sync     float()/int()/bool()/.item()/.tolist()/
                             np.asarray() on a traced value inside jit scope
                             — under trace these either fail or bake a
                             constant; on weakly-traced paths they force a
                             silent device→host sync.
  GL002 tracer-control-flow  Python ``if``/``while``/``assert`` branching on
                             a traced value inside jit scope (TracerBoolConversionError
                             at best, silently-baked branch at worst).
  GL003 tracer-fstring       f-string interpolation of a traced value inside
                             jit scope — formats the tracer repr at trace
                             time, not the runtime value.
  GL004 host-sync-in-step-loop  float()/int()/.item() on a jitted-step
                             result inside the loop that dispatches the step
                             — serializes host and device every iteration
                             (the pattern that kills 10-hour runs). Syncs
                             guarded by a ``n % k`` rate limiter and values
                             passing through explicit transfers
                             (jax.device_get / block_until_ready /
                             np.asarray) are accepted.
  GL005 impure-under-jit     time.*/np.random.*/stdlib random.*/print/open/
                             global mutation inside jit scope — executed
                             once at trace time, then constant-folded.
  GL006 jit-in-loop          jax.jit/pjit/shard_map *creation* inside a loop
                             body — a fresh wrapper (and usually a fresh
                             compile) per iteration.
  GL007 key-reuse            the same ``jax.random`` key definition consumed
                             by two ``jax.random.*`` calls, or by one call
                             in a deeper loop than every reaching definition
                             — identical random streams where independent
                             ones were intended.
  GL008 nonstatic-python-scalar  a traced value where Python needs a static
                             int (``range``, shape arguments) inside jit
                             scope — needs ``static_argnums`` or a host-side
                             value.
  GL009 swallowed-device-exception  a bare ``except:`` / ``except
                             Exception:`` that neither re-raises nor logs,
                             wrapped around jit'd or device calls — TPU
                             faults (preemption, XLA OOM, device errors)
                             vanish inside it, exactly the signals the
                             resilience layer (checkpoint fallback, retry,
                             rollback) needs to see.
  GL010 unchecked-json-ingest  a ``json.load``/``json.loads`` result that
                             flows into np/jnp array construction without
                             passing through a ``contracts.validate_*``
                             call — unvalidated foreign data becoming
                             model-feed arrays is exactly the fail-silent
                             path the data-contract layer
                             (deepdfa_tpu/contracts) exists to close:
                             out-of-range indices clamp inside segment ops
                             and poison gradients instead of failing.
  GL011 naive-wallclock-timing  a ``time.time()``/``perf_counter()``/
                             ``monotonic()`` delta wrapped around a jitted
                             dispatch (a step-shaped or jit-wrapped call)
                             with no ``block_until_ready``-class barrier in
                             between — XLA dispatches asynchronously, so
                             the delta measures dispatch, not execution:
                             the timing is a lie. Explicit transfers
                             (``jax.block_until_ready``, ``jax.device_get``,
                             ``np.asarray``) and telemetry span fencing
                             (``sp.fence(x)``) are accepted barriers.
  GL013 blocking-checkpoint-in-step  synchronous snapshot work inside a
                             step-shaped loop (a loop that also dispatches
                             jitted steps): ``pickle.dump``/``os.fsync``
                             inline, or ``save_*``/``maybe_save_periodic``
                             on a receiver whose reaching definitions
                             construct the synchronous
                             ``CheckpointManager`` — every save then stalls
                             the loop on a device→host copy plus fsync.
                             The async handoff
                             (``AsyncCheckpointManager`` /
                             ``make_checkpoint_manager``) is the fix;
                             receivers of unknown provenance (parameters,
                             factories) stay unflagged — precision over
                             recall, the empty-baseline contract.
  GL014 unbounded-metric-cardinality  a registry metric creation
                             (``.counter(...)``/``.gauge(...)``/
                             ``.histogram(...)``) whose name is formatted
                             from per-item loop data (an enclosing
                             for-loop's target interpolated into an
                             f-string/format/%%/concat, directly or one
                             assignment away) — every distinct item mints
                             a new metric, so the registry and the
                             Prometheus exposition grow without bound
                             (the classic label-cardinality explosion).
                             Names formatted from parameters or iterated
                             from static collections stay unflagged: the
                             caller bounds those.
  GL016 pallas-interpret-in-prod  a ``pl.pallas_call`` (or a module-local
                             kernel wrapper with an ``interpret``
                             parameter that forwards to one) whose
                             ``interpret`` argument is pinned to literal
                             ``True`` — directly, through a reaching
                             assignment, or through a module-level
                             constant — on an unconditional path in a
                             file importable outside ``tests/``. The
                             interpreter is the debugging surface; a
                             pinned ``interpret=True`` that ships runs
                             the kernel on the Pallas interpreter at a
                             silent ~100× slowdown. Dispatch guarded by
                             a caller-controlled conditional (the
                             ``impl == "interpret"`` switch idiom) and
                             ``interpret=`` values of unknown provenance
                             (parameters, computed expressions) stay
                             unflagged — precision over recall, the
                             empty-baseline contract.
  GL017 unsafe-signal-handler  a handler registered via ``signal.signal``
                             whose body does blocking work — I/O
                             (open/print/logging), lock-class calls
                             (``.acquire()``/``.wait()``/``.join()``/
                             ``with`` context managers), sleeps,
                             checkpoint saves, or jit dispatch — instead
                             of only setting a flag/event consumed on
                             the main path. Signal handlers run between
                             bytecodes on the main thread: a lock the
                             interrupted code already holds deadlocks,
                             logging re-enters its module locks, and a
                             jit dispatch can re-enter the runtime. The
                             preemption lifecycle's contract
                             (resilience/lifecycle.py) is exactly the
                             accepted shape: one attribute assignment,
                             everything else on the monitor/main path.
                             ``Event.set()`` and ``os.write`` (the
                             self-pipe wakeup) are the accepted
                             signal-safe idioms; handlers of unknown
                             provenance (parameters, dynamic lookups)
                             stay unflagged — precision over recall,
                             the empty-baseline contract.
  GL018 device-dispatch-under-shared-lock  a jitted/step-shaped dispatch
                             (or a ``block_until_ready`` wait) inside a
                             ``with <lock>:`` block whose lock is
                             module-level (``_LOCK = threading.Lock()``
                             at module scope) or class-level (assigned
                             in a class body, reached as
                             ``self._lock``/``cls._lock``) — the classic
                             way a "parallel" front-end quietly
                             serializes: every thread that shares the
                             lock waits out the full device execution,
                             so N replicas run at 1-replica throughput.
                             Hold shared locks for state mutation only
                             and hand work to the dispatch path through
                             a queue (the serve fleet's per-replica
                             batcher handoff is the accepted shape).
                             Instance locks created in ``__init__`` and
                             locks of unknown provenance (parameters,
                             locals) stay unflagged — precision over
                             recall, the empty-baseline contract.
  GL019 per-hypothesis-decode-dispatch  a jit-wrapped/step-shaped
                             dispatch inside a Python ``for`` loop over a
                             beam/hypothesis/decode-length axis (the loop
                             target or iterable names the axis:
                             ``for t in range(max_len)``, ``for hyp in
                             beams``) when a ``lax.scan``-able carry
                             exists — a name both assigned and read in
                             the loop body (``cache``, ``state``). Each
                             iteration then pays a fresh host dispatch of
                             a device program: the per-hypothesis decode
                             tax that held CodeT5 beam-10 12× under
                             greedy until ISSUE 13 folded the loop into
                             one batched ``lax.scan`` over the carry
                             (models/t5_generate.py is the accepted
                             shape). Loops with no carry (vmap-shaped
                             independent work), loops that ``break``/
                             ``return`` early (host-controlled exit the
                             carry can't express without while_loop
                             surgery), and data loops over batches stay
                             unflagged — precision over recall, the
                             empty-baseline contract.
  GL020 subprocess-without-trace-context  spawning a deepdfa entrypoint
                             (a ``Popen``/``run``-family call whose argv
                             names a ``deepdfa_tpu`` module — literally,
                             through a name assigned such a list, or via
                             a module-local argv-builder function)
                             without propagating the distributed trace
                             context into the child env: the child's
                             telemetry then lands in an orphan run
                             instead of a shard of the parent's, and a
                             cross-process drain becomes unauditable
                             (ISSUE 14). The accepted shapes: ``env=``
                             built by a ``*child_env``/``*trace_env``
                             helper (``telemetry.context.child_env`` or
                             a module-local wrapper whose body calls
                             one / references the
                             ``DEEPDFA_TRACE_CONTEXT`` literal), or any
                             env expression carrying that literal. A
                             ``ProcessPoolExecutor`` construction is the
                             fork-side of the same hazard: it must
                             install a trace-context ``initializer=``
                             (``context.init_forked_worker``) so forked
                             workers rebind to their own shard. Non-
                             deepdfa argvs and receivers of unknown
                             provenance stay unflagged — precision over
                             recall, the empty-baseline contract.
  GL021 per-step-kernel-launch-in-scan  a module-local ``pallas_call``
                             wrapper (a def whose body dispatches one)
                             called inside a ``lax.scan``/``fori_loop``
                             body when a persistent variant — a
                             module-local def or imported name whose
                             name says ``persistent`` — is importable
                             from the same module. The scan then pays
                             one kernel launch per step and round-trips
                             the carry through HBM between launches,
                             when the module already ships the
                             cross-step fusion that keeps it VMEM-
                             resident (the ISSUE-15 persistent unroll:
                             h once in, h_K once out, instead of 2×K
                             tile round-trips). Dispatching the
                             persistent variant itself, scan bodies of
                             unknown provenance (parameters, imported
                             step functions), and modules with no
                             persistent variant to offer stay unflagged
                             — precision over recall, the
                             empty-baseline contract.
  GL026 unjoined-distributed-exit  a hard process exit (``sys.exit`` /
                             ``os._exit``) lexically after a
                             ``jax.distributed.initialize`` in the same
                             function with no leave-through-the-barrier
                             call in scope (``jax.distributed.shutdown``,
                             ``sync_global_devices``, or the lifecycle
                             drain/preempt helpers): the exiting process
                             abandons the coordination service and every
                             peer blocked in a collective wedges until
                             its own timeout — the fleet-drain hazard
                             class (ISSUE 18; the accepted shape is
                             ``initialize`` + ``try/finally: shutdown``,
                             or routing the exit through
                             ``preempt_snapshot_exit``/the fleet drain
                             barrier). ``os._exit`` skips ``finally``
                             blocks, so only a barrier call lexically
                             BETWEEN the initialize and the exit counts
                             for it. Functions that never initialize,
                             and exits before the join, stay unflagged —
                             precision over recall, the empty-baseline
                             contract.
  GL027 unbounded-sample-accumulation  a sample list that only ever
                             grows feeding an order-statistic: an
                             ``append``/``extend`` on a receiver whose
                             visible construction is ``[]``/``list()``/
                             ``deque()`` without ``maxlen``, consumed by
                             a quantile-class call (``percentile``/
                             ``quantile``/``quantiles``/``median``/
                             ``latency_quantile``, or a subscripted
                             ``sorted(x)``) in the same scope, in a
                             long-lived context — a ``self`` attribute
                             appended outside ``__init__`` (the object
                             outlives the method) or a local appended
                             inside a ``while`` loop. A serving process
                             accumulating per-request samples this way
                             grows without bound until the quantile call
                             itself becomes the latency spike; the
                             blessed shapes are the registry Histogram's
                             preallocated ring, ``deque(maxlen=...)``,
                             the traffic observatory's fixed-bin
                             :class:`~deepdfa_tpu.telemetry.sketch.
                             ShapeSketch`, or any visible shrink
                             (``pop``/``clear``/``del x[..]``/slice
                             reassignment) on the same receiver.
                             Dict-subscript receivers and constructions
                             of unknown provenance stay unflagged —
                             precision over recall, the empty-baseline
                             contract.
  GL015 subprocess-without-timeout  an unbounded blocking wait on a child
                             process: ``.communicate()``/``.wait()`` with
                             no ``timeout=`` on a receiver whose reaching
                             construction is ``subprocess.Popen``, a
                             ``subprocess.run``-family one-shot with no
                             ``timeout=``, or a blocking pipe read
                             (``proc.stdout.read``/``os.read``) in a
                             child-process-owning function with no
                             ``select``-class deadline guard — a wedged
                             child then wedges the worker forever, the
                             hazard class the pooled Joern driver exists
                             to avoid (its reads run under a
                             ``select.select`` deadline loop and every
                             plain ``.wait()`` follows a ``.kill()``).
                             A ``.kill()``/``.terminate()`` on the same
                             receiver before the wait bounds it (reaping
                             a dead child returns); parameter receivers
                             of unknown provenance stay unflagged —
                             precision over recall, the empty-baseline
                             contract.

Jit scope is detected from decorators (``@jax.jit``, ``@partial(jax.jit,..)``,
pjit, shard_map), module-level ``jax.jit(fn)`` wraps of a local def, and the
repo convention that every def nested inside a ``make_*step`` factory is the
body of a jitted step.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from deepdfa_tpu.analysis.cfg import CFG, Node, assigned_names, build_cfg, node_exprs
from deepdfa_tpu.analysis.dataflow import (
    Fact,
    Taint,
    TaintAnalysis,
    _expr_text,
    reaching_definitions,
)

RULES: Dict[str, str] = {
    "GL000": "parse-error",
    "GL001": "tracer-host-sync",
    "GL002": "tracer-control-flow",
    "GL003": "tracer-fstring",
    "GL004": "host-sync-in-step-loop",
    "GL005": "impure-under-jit",
    "GL006": "jit-in-loop",
    "GL007": "key-reuse",
    "GL008": "nonstatic-python-scalar",
    "GL009": "swallowed-device-exception",
    "GL010": "unchecked-json-ingest",
    "GL011": "naive-wallclock-timing",
    "GL013": "blocking-checkpoint-in-step",
    "GL014": "unbounded-metric-cardinality",
    "GL015": "subprocess-without-timeout",
    "GL016": "pallas-interpret-in-prod",
    "GL017": "unsafe-signal-handler",
    "GL018": "device-dispatch-under-shared-lock",
    "GL019": "per-hypothesis-decode-dispatch",
    "GL020": "subprocess-without-trace-context",
    "GL021": "per-step-kernel-launch-in-scan",
    # GL022–GL025 are interprocedural: implemented in concurrency.py over
    # the callgraph.py whole-program model, not in _FunctionChecker.
    "GL022": "unguarded-shared-mutation-across-threads",
    "GL023": "lock-order-inversion",
    "GL024": "fork-unsafe-spawn",
    "GL025": "blocking-join-on-main-path",
    "GL026": "unjoined-distributed-exit",
    "GL027": "unbounded-sample-accumulation",
}

#: Bump when analysis semantics change in a way file hashes cannot see —
#: invalidates every incremental-cache entry.
ANALYSIS_VERSION = 1


def ruleset_fingerprint() -> str:
    """Cache key component: the registered rules + the analysis version.
    A rule added/renamed or a semantics bump invalidates cached results."""
    payload = f"{ANALYSIS_VERSION}|" + "|".join(
        f"{k}={v}" for k, v in sorted(RULES.items()))
    return hashlib.sha1(payload.encode()).hexdigest()[:16]

_JIT_NAMES = frozenset({
    "jax.jit", "jit", "jax.pjit", "pjit", "jax.experimental.pjit.pjit",
    "jax.shard_map", "shard_map", "jax.experimental.shard_map.shard_map",
})
_JIT_WRAPPER_SUFFIXES = ("jit_dp_step",)
_PARTIAL_NAMES = frozenset({"functools.partial", "partial"})
_MAKE_STEP_RE = re.compile(r"^_?make_.*step$")
_STEP_CALL_RE = re.compile(r"^(?!make_).*(step|_fn)$|^step$")
_HOST_CASTS = frozenset({"float", "int", "bool"})
_SYNC_METHODS = frozenset({"item", "tolist", "numpy"})
_NP_SYNC = frozenset({"numpy.asarray", "numpy.array"})
_CLEANERS = frozenset({
    "jax.device_get", "jax.block_until_ready", "numpy.asarray", "numpy.array",
    "jax.experimental.multihost_utils.process_allgather",
})
_SHAPE_FNS = frozenset({
    "jax.numpy.zeros", "jax.numpy.ones", "jax.numpy.full", "jax.numpy.empty",
    "jax.numpy.arange", "jax.numpy.eye", "numpy.zeros", "numpy.ones",
    "numpy.full", "numpy.empty", "numpy.arange", "numpy.eye",
})
_IMPURE_CALLS = frozenset({
    "time.time", "time.perf_counter", "time.monotonic", "time.process_time",
    "time.time_ns", "datetime.datetime.now", "open", "input", "print",
})
_IMPURE_PREFIXES = ("numpy.random.", "random.")
_KEY_PRODUCERS = frozenset({
    "PRNGKey", "key", "wrap_key_data", "key_data", "key_impl", "clone",
})
_BROAD_EXC = frozenset({
    "Exception", "BaseException", "builtins.Exception",
    "builtins.BaseException",
})
# A call through any of these counts as "the handler tells someone":
# logger-style attribute calls, stdlib warning/printing, traceback dumps.
_LOG_ATTRS = frozenset({
    "debug", "info", "warning", "warn", "error", "exception", "critical",
    "log",
})
_LOG_CALLS = frozenset({
    "print", "warnings.warn", "traceback.print_exc",
    "traceback.print_exception", "traceback.format_exc",
})
# GL010: json ingestion sources, array-construction sinks, and the
# contracts validators that clean the taint. Cleaner matching is by
# resolved dotted name, so every import spelling of each validator is
# enumerated (``from deepdfa_tpu.contracts import validate_example`` /
# ``from deepdfa_tpu.contracts.schema import ...`` / module-qualified).
_JSON_SOURCES = frozenset({"json.load", "json.loads"})
_ARRAY_SINKS = frozenset({
    "numpy.asarray", "numpy.array", "jax.numpy.asarray", "jax.numpy.array",
})
_VALIDATOR_FNS = (
    "validate_example", "validate_joern_nodes", "validate_joern_edges",
    "validate_cache_row", "load_examples_jsonl",
)
# GL011: wall-clock sources, and the barrier calls that make a delta
# around a jitted dispatch honest. ``fence`` is the telemetry span's
# explicit block_until_ready hook (deepdfa_tpu/telemetry/spans.py).
_CLOCK_CALLS = frozenset({
    "time.time", "time.perf_counter", "time.monotonic",
})
_BARRIER_ATTRS = frozenset({"fence", "block_until_ready"})
# GL013: inline serialization that blocks a step loop, the save-method
# shapes, and the one receiver class with positive synchronous evidence.
_BLOCKING_IO_CALLS = frozenset({"pickle.dump", "os.fsync"})
_SAVE_METHOD_RE = re.compile(r"^(save|save_[a-z0-9_]+|maybe_save_periodic)$")
_SYNC_MANAGER_LEAF = "CheckpointManager"
# GL014: the registry's metric-creating method names (the only metric
# factory in the repo — telemetry/registry.py).
_METRIC_FACTORY_ATTRS = frozenset({"counter", "gauge", "histogram"})
# GL015: the Popen construction leaf, the blocking-wait methods, the
# one-shot helpers that accept timeout=, the pipe-read shapes, the calls
# that bound a subsequent wait (a killed child reaps immediately), and
# the deadline guards that make a raw pipe read honest.
_POPEN_LEAF = "Popen"
_SUBPROCESS_ONESHOTS = frozenset({
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
})
_PIPE_STREAMS = frozenset({"stdout", "stderr"})
_PIPE_READS = frozenset({"read", "readline", "readlines"})
_PROC_KILLERS = frozenset({"kill", "terminate"})
_SELECT_GUARDS = frozenset({
    "select.select", "select.poll", "select.epoll", "select.kqueue",
    "selectors.DefaultSelector",
})
_PTY_OPEN = "pty.openpty"
# GL016: the pallas_call leaf (every import spelling resolves through the
# alias table to something ending in it).
_PALLAS_CALL_LEAF = "pallas_call"
# GL017: the handler-registration entry points, the blocking-work shapes
# a handler body must not contain, and the accepted signal-safe idioms
# (one attribute/flag assignment; Event.set(); os.write on a self-pipe).
_SIGNAL_REGISTER = frozenset({"signal.signal", "signal.sigaction"})

# GL026: joining and leaving a jax.distributed job. The joiners are the
# blessed ways out — the coordination-service shutdown, a cross-process
# barrier, or the lifecycle helpers that drain through one.
_DIST_INIT = frozenset({
    "jax.distributed.initialize", "distributed.initialize",
})
_DIST_JOINERS = frozenset({
    "jax.distributed.shutdown", "distributed.shutdown",
    "multihost_utils.sync_global_devices", "sync_global_devices",
    "jax.experimental.multihost_utils.sync_global_devices",
    "preempt_snapshot_exit", "lifecycle.preempt_snapshot_exit",
    "fleet_drain", "lifecycle.fleet_drain",
})
_HARD_EXITS = frozenset({"sys.exit", "os._exit"})

# GL027: order-statistic consumers — call leaves that need the whole
# sample, so an unbounded receiver feeding one never stops costing.
_QUANTILE_LEAVES = frozenset({
    "percentile", "quantile", "quantiles", "median", "latency_quantile",
})
_HANDLER_BLOCKING_CALLS = frozenset({
    "open", "print", "input", "os.fsync", "time.sleep", "json.dump",
    "json.dumps", "pickle.dump", "subprocess.run", "subprocess.Popen",
    "subprocess.call", "subprocess.check_call", "subprocess.check_output",
})
_HANDLER_BLOCKING_ATTRS = frozenset({
    "acquire", "wait", "join", "write", "flush", "put", "get", "send",
    "recv", "fsync", "dump", "commit", "drain", "sleep", "observe", "inc",
} | {"save", "save_best", "save_last", "save_preempt"} | _LOG_ATTRS)
_HANDLER_SAFE_CALLS = frozenset({"os.write", "signal.set_wakeup_fd",
                                 "signal.Signals"})
_HANDLER_SAFE_ATTRS = frozenset({"set"})
# GL018: lock constructor spellings (every import form resolves through
# the alias table) and the device-wait attribute that counts as dispatch
# held under the lock.
_LOCK_CONSTRUCTORS = frozenset({
    "threading.Lock", "threading.RLock", "Lock", "RLock",
    "multiprocessing.Lock", "multiprocessing.RLock",
})
_DEVICE_WAIT_CALLS = frozenset({"jax.block_until_ready"})
# GL019: identifier stems that name a beam/hypothesis/decode-length axis
# — matched against the loop target and the iterable's source text.
# Deliberately narrow (batch/epoch/step data loops must never match):
# the decode-loop vocabulary, not loop vocabulary in general.
_DECODE_AXIS_RE = re.compile(
    r"\b(beams?|num_beams|beam_size|hyps?|hypotheses|hypothesis|"
    r"max_len|max_length|max_target_length|max_new_tokens|decode_steps|"
    r"decode_len)\b", re.IGNORECASE)
# GL020: the deepdfa-entrypoint argv marker, the env-helper naming
# convention that counts as propagation, the env literal that proves it,
# and the initializer-name shapes accepted on a ProcessPoolExecutor.
_ENTRYPOINT_SUBSTR = "deepdfa_tpu"
_TRACE_ENV_KEY = "DEEPDFA_TRACE_CONTEXT"
_TRACE_ENV_HELPER_RE = re.compile(r"(child_env|trace_env)$")
_TRACE_INIT_RE = re.compile(r"(trace|context|init_forked)")
_PPE_LEAF = "ProcessPoolExecutor"
_INGEST_CLEANERS = frozenset(
    form
    for name in _VALIDATOR_FNS
    for form in (
        name,
        f"contracts.{name}",
        f"schema.{name}",
        f"ingest.{name}",
        f"deepdfa_tpu.contracts.{name}",
        f"deepdfa_tpu.contracts.schema.{name}",
        f"deepdfa_tpu.contracts.ingest.{name}",
    )
)


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    function: str
    message: str
    trace: Tuple[str, ...] = ()
    source_line: str = ""

    @property
    def name(self) -> str:
        return RULES[self.rule]

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity, stable across unrelated edits: the
        file, rule, enclosing function, and whitespace-normalized source of
        the offending line."""
        norm = "".join(self.source_line.split())
        key = "|".join((self.path.replace("\\", "/"), self.rule,
                        self.function, norm))
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def format(self) -> str:
        head = (f"{self.path}:{self.line}:{self.col} {self.rule} "
                f"{self.name}: {self.message}")
        chain = [f"    ↳ {step}" for step in self.trace]
        return "\n".join([head] + chain)


@dataclasses.dataclass
class _FuncInfo:
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    qualname: str
    parents: Tuple[str, ...]  # enclosing function names, outermost first
    parent: Optional["_FuncInfo"] = None  # enclosing function, if any


class _Module:
    def __init__(self, path: str, tree: ast.Module, lines: List[str]):
        self.path = path
        self.tree = tree
        self.lines = lines
        self.aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        self.module_defs = {
            n.name for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        # GL017: name -> def node (first definition wins), so a handler
        # passed to signal.signal by name — module function or method —
        # can have its body inspected.
        self.def_nodes: Dict[str, ast.AST] = {}
        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.name not in self.def_nodes:
                self.def_nodes[n.name] = n
        # GL016 facts: module-level ``NAME = True`` constants (a pinned
        # interpret flag one module-constant hop away), and "kernel
        # wrappers" — module defs with an ``interpret`` parameter whose
        # body calls pallas_call directly, mapped to that parameter's
        # positional index (-1: keyword-only).
        self.true_constants: Set[str] = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Constant) \
                    and stmt.value.value is True:
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.true_constants.add(t.id)
        # One pass serves both rules: GL021's kernel dispatchers (any def
        # whose body calls pallas_call) are a superset of GL016's kernel
        # wrappers (those that ALSO take an ``interpret`` parameter).
        self.kernel_wrappers: Dict[str, int] = {}
        self.kernel_dispatchers: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            calls_pallas = any(
                isinstance(sub, ast.Call)
                and (dotted := self.resolve(sub.func)) is not None
                and dotted.rsplit(".", 1)[-1] == _PALLAS_CALL_LEAF
                for sub in ast.walk(node)
            )
            if not calls_pallas:
                continue
            self.kernel_dispatchers.add(node.name)
            a = node.args
            positional = [x.arg for x in a.posonlyargs + a.args]
            if "interpret" in positional:
                self.kernel_wrappers[node.name] = positional.index(
                    "interpret")
            elif "interpret" in [x.arg for x in a.kwonlyargs]:
                self.kernel_wrappers[node.name] = -1
        # GL021's other fact: the "persistent variants" whose
        # availability makes a per-step launch inside a scan a finding —
        # module defs or imported names whose leaf name says persistent
        # (the ops/fused_gnn.persistent_unroll shape).
        self.persistent_variants: Set[str] = {
            name
            for name in set(self.module_defs) | set(self.aliases)
            if "persistent" in name.lower()
        }
        # GL018 facts: shared-lock definitions. Module-level
        # ``NAME = threading.Lock()`` assignments and class-body
        # ``attr = threading.Lock()`` assignments (reached later as
        # ``self.attr``/``cls.attr``) — the two lock scopes every thread
        # in the process shares. Instance locks built in ``__init__``
        # are NOT collected: per-object locks are the batcher-handoff
        # idiom, not the fleet-wide serialization hazard.
        def _is_lock_ctor(value: ast.expr) -> bool:
            return (isinstance(value, ast.Call)
                    and self.resolve(value.func) in _LOCK_CONSTRUCTORS)

        self.module_locks: Set[str] = set()
        for stmt in tree.body:
            if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
        # Per-CLASS lock attrs, not a module-wide name pool: `self._lock`
        # only counts as the shared class-level lock inside the class
        # that declares `_lock = Lock()` in its body — another class's
        # instance lock of the same name must stay unflagged.
        self.class_locks: Dict[str, Set[str]] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            attrs = {
                t.id
                for stmt in node.body
                if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value)
                for t in stmt.targets
                if isinstance(t, ast.Name)
            }
            if attrs:
                self.class_locks[node.name] = attrs
        # GL020 facts: module defs that BUILD a deepdfa-entrypoint argv
        # (a list/tuple literal holding a "deepdfa_tpu…" string somewhere
        # in their body — the chaos `_fit_argv` shape; docstrings that
        # merely mention the package never sit in a list literal), and
        # module defs that count as trace-env helpers (their body calls
        # a *child_env/*trace_env function or carries the
        # DEEPDFA_TRACE_CONTEXT literal — the chaos `_child_env` shape).
        self.entrypoint_builders: Set[str] = set()
        self.trace_env_helpers: Set[str] = set()
        for name, dn in self.def_nodes.items():
            for sub in ast.walk(dn):
                if isinstance(sub, (ast.List, ast.Tuple)) and any(
                    isinstance(el, ast.Constant)
                    and isinstance(el.value, str)
                    and _ENTRYPOINT_SUBSTR in el.value
                    for el in sub.elts
                ):
                    self.entrypoint_builders.add(name)
                if isinstance(sub, ast.Constant) \
                        and sub.value == _TRACE_ENV_KEY:
                    self.trace_env_helpers.add(name)
                if isinstance(sub, ast.Call):
                    dotted = self.resolve(sub.func)
                    if dotted is not None and _TRACE_ENV_HELPER_RE.search(
                            dotted.rsplit(".", 1)[-1]):
                        self.trace_env_helpers.add(name)
        # Local defs wrapped by jax.jit(...) / jit_dp_step(...) anywhere in
        # the module: their bodies run under trace.
        self.jit_wrapped: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and node.args:
                dotted = self.resolve(node.func)
                if dotted is None:
                    continue
                if dotted in _JIT_NAMES or dotted.endswith(_JIT_WRAPPER_SUFFIXES):
                    arg = node.args[0]
                    if isinstance(arg, ast.Name) and arg.id in self.module_defs:
                        self.jit_wrapped.add(arg.id)

    def resolve(self, expr: ast.expr) -> Optional[str]:
        parts: List[str] = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        dotted = self.aliases.get(expr.id, expr.id)
        return ".".join([dotted] + list(reversed(parts)))

    def source_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


def _collect_functions(tree: ast.Module) -> List[_FuncInfo]:
    out: List[_FuncInfo] = []

    def visit(node: ast.AST, qual: Tuple[str, ...], parents: Tuple[str, ...],
              parent_fi: Optional[_FuncInfo]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = qual + (child.name,)
                fi = _FuncInfo(child, ".".join(q), parents, parent_fi)
                out.append(fi)
                visit(child, q, parents + (child.name,), fi)
            elif isinstance(child, ast.ClassDef):
                visit(child, qual + (child.name,), parents, parent_fi)
            else:
                visit(child, qual, parents, parent_fi)

    visit(tree, (), (), None)
    return out


def _is_jit_decorated(mod: _Module, fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        dotted = mod.resolve(target)
        if dotted in _JIT_NAMES:
            return True
        if isinstance(dec, ast.Call) and dotted in _PARTIAL_NAMES:
            for arg in dec.args:
                if mod.resolve(arg) in _JIT_NAMES:
                    return True
    return False


def _is_jit_scope(mod: _Module, fi: _FuncInfo) -> bool:
    # Jit scope propagates into nested helpers: a local def inside a jitted
    # function is traced when called, so its hazards are just as real.
    cur: Optional[_FuncInfo] = fi
    while cur is not None:
        if _is_jit_decorated(mod, cur.node) or cur.node.name in mod.jit_wrapped:
            return True
        cur = cur.parent
    return any(_MAKE_STEP_RE.match(p) for p in fi.parents)


def _params_of(fn: ast.AST) -> List[str]:
    a = fn.args
    names = [x.arg for x in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def _fmt_trace(taints: FrozenSet[Taint]) -> Tuple[str, ...]:
    best = min(taints, key=lambda t: (len(t.trace), t.trace))
    return tuple(f"line {line}: {what}" for line, what in best.trace)


def _is_none_check(test: ast.expr) -> bool:
    """``x is None`` / ``x is not None`` — static optionality checks on a
    traced argument are trace-time decisions, not data-dependent control
    flow."""
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    )


def _guarded_by_modulo(node: Node) -> bool:
    for test in node.guard_tests:
        for sub in ast.walk(test):
            if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Mod):
                return True
    return False


# ---------------------------------------------------------------------------
# Per-function checks
# ---------------------------------------------------------------------------


class _FunctionChecker:
    def __init__(self, mod: _Module, fi: _FuncInfo, jit_scope: bool):
        self.mod = mod
        self.fi = fi
        self.jit_scope = jit_scope
        self.cfg = build_cfg(fi.node)
        self.findings: List[Finding] = []

    def _report(self, rule: str, at: ast.AST, message: str,
                taints: FrozenSet[Taint] = frozenset()) -> None:
        line = getattr(at, "lineno", 0)
        self.findings.append(Finding(
            rule=rule, path=self.mod.path, line=line,
            col=getattr(at, "col_offset", 0), function=self.fi.qualname,
            message=message,
            trace=_fmt_trace(taints) if taints else (),
            source_line=self.mod.source_line(line),
        ))

    def run(self) -> List[Finding]:
        if self.jit_scope:
            self._check_jit_scope()
        else:
            self._check_step_loops()
            self._check_naive_timing()
            self._check_blocking_checkpoint()
        self._check_jit_in_loop()
        self._check_key_reuse()
        self._check_swallowed_exceptions()
        self._check_unchecked_ingest()
        self._check_metric_cardinality()
        self._check_subprocess_timeout()
        self._check_trace_context()
        self._check_pallas_interpret()
        self._check_signal_handlers()
        self._check_lock_dispatch()
        if not self.jit_scope:
            self._check_per_hypothesis_dispatch()
            self._check_scan_kernel_launch()
            self._check_distributed_exit()
            self._check_sample_accumulation()
        return self.findings

    # -- jit-scope rules (GL001/2/3/5/8) -------------------------------------

    def _check_jit_scope(self) -> None:
        fn = self.fi.node
        analysis = TaintAnalysis(
            self.mod.resolve,
            cleaners=frozenset(),  # inside jit nothing "cleans" a tracer
            seed_params={
                p: f"'{p}' is a traced argument of jitted {fn.name}()"
                for p in _params_of(fn)
            },
        )
        facts = analysis.solve(self.cfg)
        global_names = {
            n for s in ast.walk(fn) if isinstance(s, ast.Global)
            for n in s.names
        }
        for node in self.cfg.nodes:
            fact = facts.get(node.idx, {})
            if node.kind in ("if", "while"):
                test = node.stmt.test
                taints = analysis.taint_of(test, fact, node)
                if taints and not _is_none_check(test):
                    self._report(
                        "GL002", test,
                        f"Python `{node.kind}` on traced value "
                        f"`{_expr_text(test)}` — use lax.cond/lax.while_loop "
                        "or jnp.where",
                        taints)
            if isinstance(node.stmt, ast.Assert):
                taints = analysis.taint_of(node.stmt.test, fact, node)
                if taints:
                    self._report(
                        "GL002", node.stmt,
                        f"assert on traced value "
                        f"`{_expr_text(node.stmt.test)}` — use "
                        "checkify/debug.check", taints)
            if global_names:
                hard, soft = assigned_names(node)
                mutated = global_names & set(hard + soft)
                if mutated:
                    self._report(
                        "GL005", node.stmt,
                        f"mutation of global `{sorted(mutated)[0]}` under "
                        "jit — side effects run once at trace time")
            for expr in node_exprs(node):
                self._scan_jit_expr(expr, fact, node, analysis)

    def _scan_jit_expr(self, root: ast.expr, fact: Fact, node: Node,
                       analysis: TaintAnalysis) -> None:
        for sub in ast.walk(root):
            if isinstance(sub, ast.FormattedValue):
                taints = analysis.taint_of(sub.value, fact, node)
                if taints:
                    self._report(
                        "GL003", sub,
                        f"f-string interpolates traced value "
                        f"`{_expr_text(sub.value)}` — under jit this formats "
                        "the tracer, not the runtime value (use "
                        "jax.debug.print)", taints)
            if not isinstance(sub, ast.Call):
                continue
            dotted = self.mod.resolve(sub.func)
            args = list(sub.args) + [kw.value for kw in sub.keywords]
            arg_taints = analysis._union(args, fact, node)
            if dotted in _HOST_CASTS and arg_taints:
                self._report(
                    "GL001", sub,
                    f"{dotted}() on traced value forces a host sync / trace "
                    "error under jit — keep it on device (jnp ops) or move "
                    "it outside jit", arg_taints)
            elif dotted in _NP_SYNC and arg_taints:
                self._report(
                    "GL001", sub,
                    f"{dotted.replace('numpy', 'np')}() on traced value "
                    "under jit — use jnp.asarray or move the transfer "
                    "outside jit", arg_taints)
            elif (isinstance(sub.func, ast.Attribute)
                  and sub.func.attr in _SYNC_METHODS):
                recv = analysis.taint_of(sub.func.value, fact, node)
                if recv:
                    self._report(
                        "GL001", sub,
                        f".{sub.func.attr}() on traced value "
                        f"`{_expr_text(sub.func.value)}` under jit — host "
                        "syncs don't belong in traced code", recv)
            if dotted == "range" and arg_taints:
                self._report(
                    "GL008", sub,
                    "range() over a traced value — Python loops need a "
                    "static trip count (static_argnums, or lax.fori_loop)",
                    arg_taints)
            elif dotted in _SHAPE_FNS and sub.args:
                shape_taint = analysis.taint_of(sub.args[0], fact, node)
                if shape_taint:
                    self._report(
                        "GL008", sub,
                        f"traced value as the shape argument of {dotted} — "
                        "shapes must be static under jit (static_argnums)",
                        shape_taint)
            if dotted is not None and (
                    dotted in _IMPURE_CALLS
                    or dotted.startswith(_IMPURE_PREFIXES)):
                self._report(
                    "GL005", sub,
                    f"impure call {dotted}() under jit — runs once at trace "
                    "time and is baked into the compiled program (use "
                    "jax.random / jax.debug instead)")

    # -- step-loop host-sync rule (GL004) ------------------------------------

    def _check_step_loops(self) -> None:
        def seed(node: Node, call: ast.Call) -> Optional[str]:
            if not node.loop_stack:
                return None
            func = call.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None)
            if name is not None and _STEP_CALL_RE.match(name):
                return f"result of step call {name}(…) is a device value"
            return None

        analysis = TaintAnalysis(self.mod.resolve, seed_call=seed,
                                 cleaners=_CLEANERS)
        facts = analysis.solve(self.cfg)
        for node in self.cfg.nodes:
            if not node.loop_stack:
                continue
            fact = facts.get(node.idx, {})
            for expr in node_exprs(node):
                for sub in ast.walk(expr):
                    if not isinstance(sub, ast.Call):
                        continue
                    dotted = self.mod.resolve(sub.func)
                    is_cast = dotted in ("float", "int")
                    is_item = (isinstance(sub.func, ast.Attribute)
                               and sub.func.attr == "item")
                    if not (is_cast or is_item):
                        continue
                    target = (sub.args if is_cast
                              else [sub.func.value])
                    taints = analysis._union(list(target), fact, node)
                    live = frozenset(
                        t for t in taints if t.seed_loop in node.loop_stack
                    )
                    if live and not _guarded_by_modulo(node):
                        sync = (f"{dotted}()" if is_cast else ".item()")
                        self._report(
                            "GL004", sub,
                            f"{sync} on a jitted-step result inside the step "
                            "loop — blocks dispatch every iteration; "
                            "accumulate on device and read once after the "
                            "loop (or rate-limit with a `% k` guard)", live)

    # -- naive wall-clock timing (GL011) -------------------------------------

    def _is_dispatch_call(self, call: ast.Call) -> bool:
        """Does this call dispatch jitted work? Step-shaped names (the
        make_*step protocol) and module-level jit-wrapped defs count —
        the same dispatch heuristics GL004/GL009 use."""
        func = call.func
        name = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        return name is not None and (
            name in self.mod.jit_wrapped or bool(_STEP_CALL_RE.match(name))
        )

    def _is_barrier_call(self, call: ast.Call) -> bool:
        """Explicit transfers, span fencing, and the host syncs GL004
        itself defines (float()/int()/.item()/.tolist()/.numpy() force a
        device wait) all make a following clock read honest."""
        dotted = self.mod.resolve(call.func)
        if dotted in _CLEANERS or (dotted in _HOST_CASTS and call.args):
            return True
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr in (_BARRIER_ATTRS | _SYNC_METHODS))

    def _check_naive_timing(self) -> None:
        """``t0 = clock(); ...step(...)...; clock() - t0`` with no barrier
        between: under async dispatch the delta times the *dispatch*, not
        the work. Lexical line-interval analysis — clock-var definitions,
        dispatch calls, and barrier calls are bucketed by line, and a
        delta is flagged when its interval back to the nearest t0
        definition contains a dispatch but no barrier."""
        clock_defs: Dict[str, List[int]] = {}
        dispatch_lines: List[int] = []
        barrier_lines: List[int] = []
        deltas: List[Tuple[ast.AST, str, int]] = []
        for node in _walk_skip_defs(self.fi.node.body):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if (self.mod.resolve(node.value.func) in _CLOCK_CALLS
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Name)):
                    clock_defs.setdefault(node.targets[0].id, []).append(
                        node.lineno)
            if isinstance(node, ast.Call):
                if self._is_dispatch_call(node):
                    dispatch_lines.append(node.lineno)
                if self._is_barrier_call(node):
                    barrier_lines.append(node.lineno)
            if (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)
                    and isinstance(node.right, ast.Name)):
                left_is_clock = (
                    isinstance(node.left, ast.Call)
                    and self.mod.resolve(node.left.func) in _CLOCK_CALLS
                ) or isinstance(node.left, ast.Name)
                if left_is_clock:
                    deltas.append((node, node.right.id, node.lineno))
        for at, var, line in deltas:
            defs = [d for d in clock_defs.get(var, []) if d < line]
            if not defs:
                continue
            t0 = max(defs)
            if (any(t0 < d < line for d in dispatch_lines)
                    and not any(t0 <= b <= line for b in barrier_lines)):
                self._report(
                    "GL011", at,
                    f"wall-clock delta over `{var}` (defined line {t0}) "
                    "wraps a jitted dispatch with no block_until_ready/"
                    "device_get barrier in between — async dispatch makes "
                    "this time the dispatch, not the execution; fence the "
                    "result (jax.block_until_ready / telemetry span "
                    ".fence) before reading the clock")

    # -- blocking checkpoint in the step loop (GL013) ------------------------

    def _sync_manager_def_line(self, name: str, node: Node, defs) -> Optional[int]:
        """The construction line when ``name``'s reaching definitions
        include a synchronous ``CheckpointManager(...)`` call; None for
        parameters, factories, and the Async manager (unknown provenance
        stays unflagged — flagging a parameter would force every caller
        to prove a negative)."""
        for d in defs.get(node.idx, {}).get(name, frozenset()):
            stmt = self.cfg.nodes[d].stmt
            if (not isinstance(stmt, ast.Assign)
                    or not isinstance(stmt.value, ast.Call)):
                continue
            dotted = self.mod.resolve(stmt.value.func)
            if dotted is not None and dotted.rsplit(".", 1)[-1] == _SYNC_MANAGER_LEAF:
                return getattr(stmt, "lineno", 0)
        return None

    def _check_blocking_checkpoint(self) -> None:
        """Synchronous snapshot work inside a step-shaped loop: the loop
        both dispatches jitted steps and serializes/fsyncs inline, so
        every save stalls dispatch for the full device→host copy + write.
        The fix is an async handoff (AsyncCheckpointManager) — a save on
        a receiver constructed as the synchronous manager, or a bare
        ``pickle.dump``/``os.fsync``, is the hazard."""
        dispatch_loops: Set[int] = set()
        for node in self.cfg.nodes:
            for expr in node_exprs(node):
                for sub in ast.walk(expr):
                    if isinstance(sub, ast.Call) and self._is_dispatch_call(sub):
                        dispatch_loops.update(node.loop_stack)
        if not dispatch_loops:
            return
        defs = None
        for node in self.cfg.nodes:
            if not set(node.loop_stack) & dispatch_loops:
                continue
            for expr in node_exprs(node):
                for sub in ast.walk(expr):
                    if not isinstance(sub, ast.Call):
                        continue
                    dotted = self.mod.resolve(sub.func)
                    if dotted in _BLOCKING_IO_CALLS:
                        self._report(
                            "GL013", sub,
                            f"{dotted}(…) inside the step loop — inline "
                            "serialization/fsync blocks dispatch every "
                            "iteration; hand the write to an async writer "
                            "(AsyncCheckpointManager / a writer thread) "
                            "and keep only the device→host copy start on "
                            "the loop")
                        continue
                    if (isinstance(sub.func, ast.Attribute)
                            and _SAVE_METHOD_RE.match(sub.func.attr)
                            and isinstance(sub.func.value, ast.Name)):
                        if defs is None:
                            defs = reaching_definitions(self.cfg)
                        line = self._sync_manager_def_line(
                            sub.func.value.id, node, defs)
                        if line is not None:
                            self._report(
                                "GL013", sub,
                                f".{sub.func.attr}() on a synchronous "
                                f"CheckpointManager (constructed line "
                                f"{line}) inside the step loop — the save "
                                "blocks the loop on device→host copy + "
                                "fsync; use AsyncCheckpointManager / "
                                "make_checkpoint_manager for the async "
                                "handoff")

    # -- subprocess without timeout (GL015) ----------------------------------

    def _popen_provenance(self) -> Tuple[Dict[str, int], bool, bool,
                                         Dict[str, List[int]]]:
        """Function-wide lexical facts for GL015: receiver texts assigned
        a ``subprocess.Popen(...)`` construction (Name or attribute
        targets — the ``self._proc`` idiom), whether the function owns
        child-process machinery at all (a Popen or ``pty.openpty`` call),
        whether a ``select``-class deadline guard is present, and the
        lines where each receiver is killed/terminated."""
        receivers: Dict[str, int] = {}
        child_ctx = False
        select_guard = False
        killers: Dict[str, List[int]] = {}
        for node in _walk_skip_defs(self.fi.node.body):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                dotted = self.mod.resolve(node.value.func)
                if dotted is not None \
                        and dotted.rsplit(".", 1)[-1] == _POPEN_LEAF:
                    for t in node.targets:
                        receivers[_expr_text(t)] = node.lineno
            if not isinstance(node, ast.Call):
                continue
            dotted = self.mod.resolve(node.func)
            if dotted is not None:
                if dotted.rsplit(".", 1)[-1] == _POPEN_LEAF \
                        or dotted == _PTY_OPEN:
                    child_ctx = True
                if dotted in _SELECT_GUARDS:
                    select_guard = True
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _PROC_KILLERS:
                killers.setdefault(_expr_text(node.func.value),
                                   []).append(node.lineno)
        return receivers, child_ctx, select_guard, killers

    def _is_popen_receiver(self, value: ast.expr,
                           receivers: Dict[str, int]) -> bool:
        if _expr_text(value) in receivers:
            return True
        # The direct chain: subprocess.Popen(...).communicate()
        if isinstance(value, ast.Call):
            dotted = self.mod.resolve(value.func)
            return (dotted is not None
                    and dotted.rsplit(".", 1)[-1] == _POPEN_LEAF)
        return False

    def _check_subprocess_timeout(self) -> None:
        """Unbounded blocking waits on child processes — the hazard class
        the pooled Joern driver must never reintroduce: a long-lived
        worker blocked forever on a wedged child wedges its pool slot.
        Every wait needs a deadline (``timeout=``, a ``select`` loop, or
        a preceding kill); receivers the function did not construct stay
        unflagged (the caller owns their lifecycle)."""
        receivers, child_ctx, select_guard, killers = \
            self._popen_provenance()
        for node in _walk_skip_defs(self.fi.node.body):
            if not isinstance(node, ast.Call):
                continue
            dotted = self.mod.resolve(node.func)
            if dotted in _SUBPROCESS_ONESHOTS:
                if not any(kw.arg == "timeout" for kw in node.keywords):
                    self._report(
                        "GL015", node,
                        f"{dotted}(…) without timeout= — a wedged child "
                        "blocks this call forever; pass timeout= and "
                        "handle subprocess.TimeoutExpired")
                continue
            if dotted == "os.read" and child_ctx and not select_guard:
                self._report(
                    "GL015", node,
                    "os.read(…) in a child-process-owning function with "
                    "no select/poll deadline guard — a silent child "
                    "blocks the read forever; wrap it in a "
                    "select.select(..., timeout) deadline loop (the "
                    "joern_session._read_until_prompt idiom)")
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if (func.attr in _PIPE_READS
                    and isinstance(func.value, ast.Attribute)
                    and func.value.attr in _PIPE_STREAMS
                    and not select_guard
                    and self._is_popen_receiver(func.value.value,
                                                receivers)):
                self._report(
                    "GL015", node,
                    f"blocking .{func.value.attr}.{func.attr}() on a "
                    "Popen pipe with no select/poll deadline guard — a "
                    "silent child blocks the worker forever; read under "
                    "a select deadline loop or use .communicate("
                    "timeout=...)")
                continue
            if func.attr not in ("wait", "communicate"):
                continue
            if not self._is_popen_receiver(func.value, receivers):
                continue
            has_timeout = any(kw.arg == "timeout" for kw in node.keywords)
            if func.attr == "wait":
                has_timeout = has_timeout or bool(node.args)
            else:
                has_timeout = has_timeout or len(node.args) >= 2
            if has_timeout:
                continue
            base = _expr_text(func.value)
            if any(line <= node.lineno
                   for line in killers.get(base, [])):
                continue  # reaping a killed child returns promptly
            self._report(
                "GL015", node,
                f".{func.attr}() with no timeout= on the Popen child "
                f"constructed line {receivers.get(base, node.lineno)} — "
                "a wedged child blocks the worker forever; pass "
                "timeout= (handling subprocess.TimeoutExpired) or kill "
                "the child first")

    # -- subprocess without trace context (GL020) ----------------------------

    def _gl020_env_ok(self, env_expr: ast.expr,
                      env_names: Set[str]) -> bool:
        """Does this ``env=`` expression propagate the trace context?
        Accepted: any expression whose source carries the
        DEEPDFA_TRACE_CONTEXT literal, a call to a ``*child_env``/
        ``*trace_env`` helper (the blessed propagation point — including
        module-local wrappers whose body does either), or a name
        assigned one of those function-wide."""
        if _TRACE_ENV_KEY in _expr_text(env_expr):
            return True
        if isinstance(env_expr, ast.Call):
            dotted = self.mod.resolve(env_expr.func) \
                or _expr_text(env_expr.func)
            leaf = dotted.rsplit(".", 1)[-1]
            if _TRACE_ENV_HELPER_RE.search(leaf) \
                    or leaf in self.mod.trace_env_helpers:
                return True
        if isinstance(env_expr, ast.Name) and env_expr.id in env_names:
            return True
        return False

    def _gl020_is_entrypoint_argv(self, expr: ast.expr,
                                  argv_names: Set[str]) -> bool:
        """Is this argv a deepdfa entrypoint: a literal list/tuple naming
        a deepdfa_tpu module, a name assigned one function-wide, or a
        call to a module-local argv builder?"""
        if isinstance(expr, (ast.List, ast.Tuple)):
            return any(isinstance(el, ast.Constant)
                       and isinstance(el.value, str)
                       and _ENTRYPOINT_SUBSTR in el.value
                       for el in expr.elts)
        if isinstance(expr, ast.Name):
            return expr.id in argv_names
        if isinstance(expr, ast.Call):
            dotted = self.mod.resolve(expr.func) or _expr_text(expr.func)
            return dotted.rsplit(".", 1)[-1] in self.mod.entrypoint_builders
        return False

    def _check_trace_context(self) -> None:
        """Deepdfa entrypoint spawns must carry the distributed trace
        context (ISSUE 14): a child started without
        ``DEEPDFA_TRACE_CONTEXT`` writes its telemetry into an orphan
        run, and the cross-process timeline the chaos/drain audits read
        silently loses a participant. ProcessPoolExecutor is the fork
        flavor: without a trace-context initializer the forked workers'
        events die in copied rings."""
        argv_names: Set[str] = set()
        env_names: Set[str] = set()
        for node in _walk_skip_defs(self.fi.node.body):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            argv_hit = (isinstance(v, (ast.List, ast.Tuple)) and any(
                isinstance(el, ast.Constant) and isinstance(el.value, str)
                and _ENTRYPOINT_SUBSTR in el.value for el in v.elts))
            env_hit = self._gl020_env_ok(v, env_names)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if argv_hit:
                        argv_names.add(t.id)
                    if env_hit:
                        env_names.add(t.id)
        for node in _walk_skip_defs(self.fi.node.body):
            if not isinstance(node, ast.Call):
                continue
            dotted = self.mod.resolve(node.func)
            if dotted is None:
                continue
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf == _PPE_LEAF:
                init_kw = next((kw.value for kw in node.keywords
                                if kw.arg == "initializer"), None)
                init_name = (self.mod.resolve(init_kw)
                             or _expr_text(init_kw)) if init_kw is not None \
                    else ""
                if init_kw is None or not _TRACE_INIT_RE.search(init_name):
                    self._report(
                        "GL020", node,
                        "ProcessPoolExecutor without a trace-context "
                        "initializer — forked workers' telemetry dies in "
                        "copied rings; pass initializer=telemetry.context"
                        ".init_forked_worker so each worker rebinds to "
                        "its own shard of the active run")
                continue
            if leaf != _POPEN_LEAF and dotted not in _SUBPROCESS_ONESHOTS:
                continue
            argv = node.args[0] if node.args else None
            if argv is None \
                    or not self._gl020_is_entrypoint_argv(argv, argv_names):
                continue
            env_kw = next((kw.value for kw in node.keywords
                           if kw.arg == "env"), None)
            if env_kw is not None and self._gl020_env_ok(env_kw, env_names):
                continue
            self._report(
                "GL020", node,
                "deepdfa entrypoint spawned without propagating "
                "DEEPDFA_TRACE_CONTEXT into the child env — its telemetry "
                "lands in an orphan run instead of a shard of this one; "
                "build the env with telemetry.context.child_env(process) "
                "(or a module-local *child_env wrapper)")

    # -- unsafe signal handler (GL017) ---------------------------------------

    def _resolve_handler_body(self, handler: ast.expr) -> Optional[ast.AST]:
        """The def node a ``signal.signal`` handler argument names:
        inline lambda, module function, or a method referenced as
        ``self._handler`` / ``obj.handler``. Unknown provenance
        (parameters, dynamic lookups, restored previous handlers like
        ``signal.SIG_DFL``) resolves to None — unflagged."""
        if isinstance(handler, ast.Lambda):
            return handler
        if isinstance(handler, ast.Name):
            return self.mod.def_nodes.get(handler.id)
        if isinstance(handler, ast.Attribute):
            return self.mod.def_nodes.get(handler.attr)
        return None

    def _handler_blocking_work(self, body: ast.AST
                               ) -> Optional[Tuple[ast.AST, str]]:
        """First piece of blocking work in a handler body, or None for
        the accepted flag-only shape. Nested defs are skipped: work a
        handler merely *defines* doesn't run in signal context."""
        skip: Set[int] = set()
        for sub in ast.walk(body):
            if sub is not body and isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
                for inner in ast.walk(sub):
                    skip.add(id(inner))
        for sub in ast.walk(body):
            if id(sub) in skip:
                continue
            if isinstance(sub, ast.With):
                # A `with` in signal context is (almost always) a lock or
                # span acquire — the deadlock shape when the interrupted
                # code already holds it.
                return sub, "context-manager acquire (`with`)"
            if not isinstance(sub, ast.Call):
                continue
            dotted = self.mod.resolve(sub.func)
            if dotted in _HANDLER_SAFE_CALLS:
                continue
            if dotted in _HANDLER_BLOCKING_CALLS:
                return sub, f"{dotted}()"
            if dotted is not None:
                leaf = dotted.rsplit(".", 1)[-1]
                if leaf in self.mod.jit_wrapped \
                        or (leaf not in _HANDLER_SAFE_CALLS
                            and _STEP_CALL_RE.match(leaf)
                            and leaf in self.mod.module_defs):
                    return sub, f"jit dispatch ({dotted})"
            if isinstance(sub.func, ast.Attribute):
                attr = sub.func.attr
                if attr in _HANDLER_SAFE_ATTRS:
                    continue
                if attr in _HANDLER_BLOCKING_ATTRS:
                    return sub, f".{attr}()"
        return None

    def _check_signal_handlers(self) -> None:
        """GL017: a signal handler must only set a flag — handlers run
        between bytecodes on the main thread, so I/O, locks, and jit
        dispatch inside one deadlock or re-enter exactly when the
        process is being preempted (the moment the drain machinery
        exists for)."""
        nested: Set[int] = set()
        for child in ast.walk(self.fi.node):
            if child is not self.fi.node and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for inner in ast.walk(child):
                    nested.add(id(inner))
        for sub in ast.walk(self.fi.node):
            if id(sub) in nested or not isinstance(sub, ast.Call):
                continue  # nested defs get their own checker pass
            dotted = self.mod.resolve(sub.func)
            if dotted not in _SIGNAL_REGISTER or len(sub.args) < 2:
                continue
            body = self._resolve_handler_body(sub.args[1])
            if body is None:
                continue
            hit = self._handler_blocking_work(body)
            if hit is not None:
                node, what = hit
                name = getattr(body, "name", "<lambda>")
                self._report(
                    "GL017", node,
                    f"signal handler {name!r} does blocking work ({what}) "
                    "inside the handler body; set a flag/event in the "
                    "handler and consume it on the main path "
                    "(resilience/lifecycle.py is the reference shape)",
                )

    # -- unjoined distributed exit (GL026) -----------------------------------

    def _check_distributed_exit(self) -> None:
        """GL026: a function that joins a ``jax.distributed`` job and
        then hard-exits (``sys.exit``/``os._exit``) without leaving
        through the barrier. The exiting process abandons the
        coordination service mid-job; every peer blocked in a collective
        wedges until its own timeout — the hazard class the fleet drain
        choreography exists for. Lexical reaching, not CFG: the accepted
        idiom is ``initialize`` + ``try/finally: shutdown``, where the
        shutdown line FOLLOWS the exit — so for ``sys.exit`` any barrier
        call after the initialize joins. ``os._exit`` skips ``finally``
        blocks: only a barrier call lexically between the initialize and
        the exit counts for it."""
        init_lines: List[int] = []
        joiner_lines: List[int] = []
        exits: List[Tuple[ast.Call, str]] = []
        for sub in ast.walk(self.fi.node):
            if not isinstance(sub, ast.Call):
                continue
            dotted = self.mod.resolve(sub.func)
            if dotted in _DIST_INIT:
                init_lines.append(sub.lineno)
            elif dotted in _DIST_JOINERS:
                joiner_lines.append(sub.lineno)
            elif dotted in _HARD_EXITS:
                exits.append((sub, dotted))
        if not init_lines or not exits:
            return
        first_init = min(init_lines)
        for node, dotted in exits:
            if node.lineno <= first_init:
                continue  # exit before the join: never entered the job
            if dotted == "os._exit":
                joined = any(first_init <= ln <= node.lineno
                             for ln in joiner_lines)
                how = ("a barrier call between the initialize and the "
                       "exit (os._exit skips finally blocks)")
            else:
                joined = any(ln >= first_init for ln in joiner_lines)
                how = ("jax.distributed.shutdown in a finally, or "
                       "routing through preempt_snapshot_exit/the fleet "
                       "drain barrier")
            if not joined:
                self._report(
                    "GL026", node,
                    f"{dotted}() after jax.distributed.initialize (line "
                    f"{first_init}) with no leave-through-the-barrier "
                    "call in scope: the exiting process abandons the "
                    "coordination service and peers wedge in their next "
                    f"collective; use {how}",
                )

    # -- unbounded sample accumulation (GL027) -------------------------------

    def _check_sample_accumulation(self) -> None:
        """GL027: a sample list that only ever grows feeding an
        order-statistic. Quantiles need the whole sample, so the natural
        first draft — append every observation, ``np.percentile`` on
        demand — leaks in any long-lived context: a serving process's
        per-request latency list grows until the sort inside the
        quantile call IS the latency spike. The repo's blessed shapes
        are bounded by construction (the registry Histogram's
        preallocated ring, ``deque(maxlen=...)``, the traffic
        observatory's fixed-bin ShapeSketch), so an unbounded receiver
        that is appended in a long-lived scope, visibly constructed as
        ``[]``/``list()``/``deque()``, consumed by a quantile-class
        call, and never shrunk is a finding. Long-lived means: a
        ``self`` attribute appended outside ``__init__``, or a local
        appended inside a ``while`` loop. Dict-subscript receivers and
        unknown-provenance constructions stay unflagged — precision
        over recall, the empty-baseline contract."""
        fn = self.fi.node
        in_init = fn.name == "__init__"

        def key_of(expr: ast.expr) -> Optional[str]:
            if isinstance(expr, ast.Name):
                return expr.id
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return f"self.{expr.attr}"
            return None

        whiles = [w for w in ast.walk(fn) if isinstance(w, ast.While)]

        def in_while(call: ast.Call) -> bool:
            return any(w.lineno < call.lineno <= (w.end_lineno or w.lineno)
                       for w in whiles)

        appends: Dict[str, ast.Call] = {}  # first grow site per receiver
        for sub in ast.walk(fn):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in ("append", "extend")):
                continue
            key = key_of(sub.func.value)
            if key is None:
                continue
            if key.startswith("self."):
                if in_init:
                    continue  # setup-time fill, not steady-state growth
            elif not in_while(sub):
                continue  # straight-line local: dies with the call
            appends.setdefault(key, sub)
        if not appends:
            return

        for key, call in sorted(appends.items(),
                                key=lambda kv: kv[1].lineno):
            scope = (self._enclosing_class() if key.startswith("self.")
                     else fn)
            if scope is None:
                continue
            facts = self._sample_facts(scope, key)
            if (facts["unbounded"] and not facts["bounded"]
                    and not facts["shrinks"] and facts["consumed"]):
                self._report(
                    "GL027", call,
                    f"{key} only ever grows ({call.func.attr} here, no "
                    "pop/clear/slice trim in scope) and feeds "
                    f"{facts['consumer']} — an unbounded sample "
                    "accumulation in a long-lived scope; use the "
                    "registry Histogram ring, deque(maxlen=...), or a "
                    "telemetry.sketch.ShapeSketch (bounded bins, exact "
                    "merges)",
                )

    def _enclosing_class(self) -> Optional[ast.ClassDef]:
        target = self.fi.node
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.ClassDef) and any(
                    sub is target for sub in ast.walk(node)):
                return node
        return None

    def _sample_facts(self, scope: ast.AST, key: str) -> Dict[str, object]:
        """GL027 evidence for one receiver over one scope (the function
        for locals, the whole class for ``self`` attrs): how it was
        constructed, whether anything shrinks it, and which
        order-statistic call consumes it."""
        def matches(expr: ast.expr) -> bool:
            if isinstance(expr, ast.Name):
                return key == expr.id
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return key == f"self.{expr.attr}"
            return False

        facts: Dict[str, object] = {"unbounded": False, "bounded": False,
                                    "shrinks": False, "consumed": False,
                                    "consumer": ""}
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Assign):
                v = sub.value
                for t in sub.targets:
                    if matches(t):
                        if isinstance(v, ast.List):
                            facts["unbounded"] = True
                        elif isinstance(v, ast.Call):
                            ctor = self.mod.resolve(v.func)
                            if ctor in ("list", "collections.deque",
                                        "deque"):
                                if any(kw.arg == "maxlen"
                                       for kw in v.keywords):
                                    facts["bounded"] = True
                                else:
                                    facts["unbounded"] = True
                        elif (isinstance(v, ast.Subscript)
                                and matches(v.value)):
                            facts["shrinks"] = True  # x = x[-n:]
                    elif isinstance(t, ast.Subscript) and matches(t.value):
                        facts["shrinks"] = True  # x[:] = ... trim
            elif isinstance(sub, ast.Delete):
                for t in sub.targets:
                    if isinstance(t, ast.Subscript) and matches(t.value):
                        facts["shrinks"] = True
            elif isinstance(sub, ast.Call):
                f = sub.func
                if (isinstance(f, ast.Attribute) and matches(f.value)
                        and f.attr in ("pop", "popleft", "clear")):
                    facts["shrinks"] = True
                dotted = self.mod.resolve(f)
                leaf = dotted.rsplit(".", 1)[-1] if dotted else ""
                if leaf in _QUANTILE_LEAVES and any(
                        matches(a) for a in list(sub.args)
                        + [kw.value for kw in sub.keywords]):
                    facts["consumed"] = True
                    facts["consumer"] = f"{dotted}()"
            elif isinstance(sub, ast.Subscript):
                v = sub.value
                if (isinstance(v, ast.Call)
                        and self.mod.resolve(v.func) == "sorted"
                        and v.args and matches(v.args[0])):
                    facts["consumed"] = True
                    facts["consumer"] = "a subscripted sorted()"
        return facts

    # -- pallas interpret pinned in prod (GL016) -----------------------------

    def _pinned_true(self, expr: ast.expr, node: Node,
                     defs) -> "Tuple[bool, str]":
        """Is this ``interpret`` argument pinned to literal True?
        Covers the direct literal, a reaching in-function assignment of
        True, and a module-level ``NAME = True`` constant. Parameters and
        computed expressions are unknown provenance — the caller owns
        them — and stay unpinned."""
        if isinstance(expr, ast.Constant):
            return expr.value is True, "literal True"
        if not isinstance(expr, ast.Name):
            return False, ""
        if expr.id in _params_of(self.fi.node):
            return False, ""
        sites = defs.get(node.idx, {}).get(expr.id, frozenset())
        real = [d for d in sites if self.cfg.nodes[d].stmt is not None]
        if real:
            pinned = all(
                isinstance(self.cfg.nodes[d].stmt, ast.Assign)
                and isinstance(self.cfg.nodes[d].stmt.value, ast.Constant)
                and self.cfg.nodes[d].stmt.value.value is True
                for d in real
            )
            return pinned, (
                f"`{expr.id}` pinned True at line "
                f"{min(self.cfg.nodes[d].line for d in real)}")
        if expr.id in self.mod.true_constants:
            return True, f"module constant `{expr.id}` = True"
        return False, ""

    @staticmethod
    def _caller_gated(node: Node) -> bool:
        """An enclosing ``if`` whose test reads any name is treated as a
        caller-controlled dispatch (the ``impl == "interpret"`` switch
        idiom) — the pin is then an explicit mode choice, not a shipped
        debug flag."""
        return any(
            any(isinstance(n, ast.Name) for n in ast.walk(t))
            for t in node.guard_tests
        )

    def _check_pallas_interpret(self) -> None:
        """pallas_call/kernel-wrapper dispatch with ``interpret`` pinned
        True on an unconditional, importable-outside-tests path — the
        shipped-debug-flag class: the kernel silently runs on the Pallas
        interpreter at ~100× the compiled latency, and nothing crashes to
        say so."""
        parts = re.split(r"[\\/]", self.mod.path)
        if "tests" in parts:
            return
        defs = reaching_definitions(self.cfg)
        for node in self.cfg.nodes:
            for expr in node_exprs(node):
                for sub in ast.walk(expr):
                    if not isinstance(sub, ast.Call):
                        continue
                    dotted = self.mod.resolve(sub.func)
                    target = next(
                        (kw.value for kw in sub.keywords
                         if kw.arg == "interpret"), None)
                    is_pallas = (
                        dotted is not None
                        and dotted.rsplit(".", 1)[-1] == _PALLAS_CALL_LEAF)
                    wrapper = (
                        sub.func.id if isinstance(sub.func, ast.Name)
                        and sub.func.id in self.mod.kernel_wrappers
                        else None)
                    if wrapper is not None and target is None:
                        idx = self.mod.kernel_wrappers[wrapper]
                        if 0 <= idx < len(sub.args) and not any(
                                isinstance(a, ast.Starred)
                                for a in sub.args[:idx + 1]):
                            target = sub.args[idx]
                    if target is None or not (is_pallas or wrapper):
                        continue
                    pinned, how = self._pinned_true(target, node, defs)
                    if not pinned or self._caller_gated(node):
                        continue
                    what = (f"{dotted}(…)" if is_pallas
                            else f"kernel wrapper {wrapper}(…)")
                    self._report(
                        "GL016", sub,
                        f"{what} with interpret pinned True ({how}) on an "
                        "unconditional path importable outside tests/ — "
                        "the Pallas interpreter is a ~100x slowdown that "
                        "ships silently; gate interpreted dispatch behind "
                        "a caller-chosen impl switch (the tile_spmm "
                        "_dispatch idiom) or drop the pin")

    # -- recompilation (GL006) -----------------------------------------------

    def _check_jit_in_loop(self) -> None:
        for node in self.cfg.nodes:
            if not node.loop_stack:
                continue
            for expr in node_exprs(node):
                # A jit inside a lambda BODY is deferred, not created per
                # iteration — exclude those subtrees before scanning
                # (ast.walk has no skip, so collect them up front).
                deferred = {
                    id(n)
                    for lam in ast.walk(expr) if isinstance(lam, ast.Lambda)
                    for n in ast.walk(lam.body)
                }
                for sub in ast.walk(expr):
                    if isinstance(sub, ast.Call) and id(sub) not in deferred:
                        dotted = self.mod.resolve(sub.func)
                        if dotted in _JIT_NAMES:
                            self._report(
                                "GL006", sub,
                                f"{dotted}(…) created inside a loop — a "
                                "fresh wrapper (and compile cache entry) "
                                "per iteration; hoist the jit out of the "
                                "loop")

    # -- PRNG key reuse (GL007) ----------------------------------------------

    def _check_key_reuse(self) -> None:
        defs = reaching_definitions(self.cfg)
        consumers: Dict[Tuple[str, int], List[Node]] = {}
        depth_flagged: Set[int] = set()
        for node in self.cfg.nodes:
            for expr in node_exprs(node):
                for sub in ast.walk(expr):
                    if not isinstance(sub, ast.Call):
                        continue
                    dotted = self.mod.resolve(sub.func)
                    if (dotted is None
                            or not dotted.startswith("jax.random.")
                            or dotted.rsplit(".", 1)[1] in _KEY_PRODUCERS):
                        continue
                    key_args = [a for a in sub.args[:1]
                                if isinstance(a, ast.Name)]
                    key_args += [kw.value for kw in sub.keywords
                                 if kw.arg == "key"
                                 and isinstance(kw.value, ast.Name)]
                    for arg in key_args:
                        sites = defs.get(node.idx, {}).get(
                            arg.id, frozenset((self.cfg.entry,)))
                        for d in sites:
                            consumers.setdefault((arg.id, d), []).append(node)
                        # Loop-constant key: every reaching def sits outside
                        # the consumer's innermost loop.
                        if node.loop_stack and node.idx not in depth_flagged:
                            if all(self.cfg.nodes[d].loop_depth < node.loop_depth
                                   for d in sites):
                                depth_flagged.add(node.idx)
                                self._report(
                                    "GL007", sub,
                                    f"PRNG key `{arg.id}` is defined outside "
                                    "this loop but consumed inside it — the "
                                    "same key (and random stream) repeats "
                                    "every iteration; fold_in the loop "
                                    "index or split per iteration")
        for (name, d), nodes in consumers.items():
            distinct = sorted({n.idx for n in nodes})
            if len(distinct) < 2:
                continue
            lines = sorted({n.line for n in nodes})
            at = next(n for n in nodes if n.idx == distinct[1])
            def_line = self.cfg.nodes[d].line or "argument"
            self._report(
                "GL007", at.stmt if at.stmt is not None else self.fi.node,
                f"PRNG key `{name}` (defined line {def_line}) feeds "
                f"{len(distinct)} jax.random consumers (lines "
                f"{', '.join(map(str, lines))}) — reused keys give "
                "identical streams; jax.random.split per consumer")


    # -- unchecked json ingestion (GL010) ------------------------------------

    def _check_unchecked_ingest(self) -> None:
        """json.load(s) results must pass a contracts.validate_* call
        before reaching array construction (np/jnp asarray/array) — the
        data-contract boundary rule. Runs in every scope: foreign data is
        foreign whether or not the function is jitted."""

        def seed(node: Node, call: ast.Call) -> Optional[str]:
            if self.mod.resolve(call.func) in _JSON_SOURCES:
                return "result of json.load(s)(…) is unvalidated ingest data"
            return None

        analysis = TaintAnalysis(self.mod.resolve, seed_call=seed,
                                 cleaners=_INGEST_CLEANERS)
        facts = analysis.solve(self.cfg)
        for node in self.cfg.nodes:
            fact = facts.get(node.idx, {})
            for expr in node_exprs(node):
                for sub in ast.walk(expr):
                    if not isinstance(sub, ast.Call):
                        continue
                    dotted = self.mod.resolve(sub.func)
                    if dotted not in _ARRAY_SINKS:
                        continue
                    args = list(sub.args) + [kw.value for kw in sub.keywords]
                    taints = analysis._union(args, fact, node)
                    if taints:
                        self._report(
                            "GL010", sub,
                            f"json-ingested data flows into {dotted}() "
                            "without a contracts.validate_* check — route "
                            "it through deepdfa_tpu.contracts (schema "
                            "validation + quarantine) before it becomes a "
                            "model-feed array", taints)

    # -- unbounded metric cardinality (GL014) --------------------------------

    @staticmethod
    def _interpolated_names(expr: ast.expr) -> Tuple[List[ast.Name], bool]:
        """(names interpolated into ``expr``, is-a-formatted-string).

        Covers the string-building shapes a metric name can take:
        f-strings, ``.format(...)``, ``%`` formatting, and ``+`` concat.
        """
        names: List[ast.Name] = []
        if isinstance(expr, ast.JoinedStr):
            for v in expr.values:
                if isinstance(v, ast.FormattedValue):
                    names += [n for n in ast.walk(v.value)
                              if isinstance(n, ast.Name)]
            return names, True
        if (isinstance(expr, ast.Call)
                and isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "format"):
            for a in list(expr.args) + [kw.value for kw in expr.keywords]:
                names += [n for n in ast.walk(a) if isinstance(n, ast.Name)]
            return names, True
        if isinstance(expr, ast.BinOp) and isinstance(expr.op,
                                                      (ast.Mod, ast.Add)):
            names += [n for n in ast.walk(expr) if isinstance(n, ast.Name)]
            return names, True
        return names, False

    @staticmethod
    def _is_static_collection(expr: ast.expr) -> bool:
        """A literal tuple/list/set of constants: iterating one bounds
        the loop target by the code, not the data (the documented GL014
        negative — formatted or not)."""
        return (isinstance(expr, (ast.Tuple, ast.List, ast.Set))
                and all(isinstance(e, ast.Constant) for e in expr.elts))

    def _enclosing_loop_targets(self, node: Node) -> Dict[str, int]:
        """{name: line} of every enclosing for-loop's iteration target
        (static-literal iterables exempt — their targets are bounded)."""
        targets: Dict[str, int] = {}
        for h in node.loop_stack:
            head = self.cfg.nodes[h]
            if isinstance(head.stmt, (ast.For, ast.AsyncFor)):
                if self._is_static_collection(head.stmt.iter):
                    continue
                for n in ast.walk(head.stmt.target):
                    if isinstance(n, ast.Name):
                        targets[n.id] = head.line
        return targets

    def _check_metric_cardinality(self) -> None:
        """Registry metric creation named from per-item loop data: every
        distinct item mints a new metric, so the registry (and the
        Prometheus exposition built from it) grows with the data instead
        of the code — the label-cardinality explosion. Parameters and
        static-collection iteration stay unflagged: those names are
        bounded by the caller, and flagging them would force every
        snapshot mirror to prove a negative (precision over recall, the
        empty-baseline contract)."""
        defs = None
        for node in self.cfg.nodes:
            if not node.loop_stack:
                continue
            loop_targets = self._enclosing_loop_targets(node)
            if not loop_targets:
                continue
            for expr in node_exprs(node):
                for sub in ast.walk(expr):
                    if not (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr in _METRIC_FACTORY_ATTRS):
                        continue
                    name_arg = sub.args[0] if sub.args else next(
                        (kw.value for kw in sub.keywords
                         if kw.arg == "name"), None)
                    if name_arg is None:
                        continue
                    target: Optional[str] = None
                    names, formatted = self._interpolated_names(name_arg)
                    if formatted:
                        target = next((n.id for n in names
                                       if n.id in loop_targets), None)
                    elif isinstance(name_arg, ast.Name):
                        # One hop: the name was built from a loop target
                        # by an assignment inside the same loop.
                        if defs is None:
                            defs = reaching_definitions(self.cfg)
                        sites = defs.get(node.idx, {}).get(
                            name_arg.id, frozenset())
                        for d in sites:
                            stmt = self.cfg.nodes[d].stmt
                            if (not isinstance(stmt, ast.Assign)
                                    or not set(self.cfg.nodes[d].loop_stack)
                                    & set(node.loop_stack)):
                                continue
                            nm, fm = self._interpolated_names(stmt.value)
                            target = next(
                                (n.id for n in nm if n.id in loop_targets),
                                None) if fm else None
                            if target is not None:
                                break
                    if target is not None:
                        self._report(
                            "GL014", sub,
                            f".{sub.func.attr}() metric name formatted "
                            f"from loop item `{target}` (loop target, "
                            f"line {loop_targets[target]}) — every "
                            "distinct item creates a new metric series "
                            "(unbounded cardinality); use a bounded "
                            "enumeration for the name and put per-item "
                            "detail in event attrs")

    # -- device dispatch under a shared lock (GL018) -------------------------

    def _shared_lock_desc(self, expr: ast.expr) -> Optional[str]:
        """Human description when ``expr`` names a module- or
        class-level lock; None for instance locks, parameters, and
        anything of unknown provenance (unflagged — the caller bounds
        those). ``self._lock``/``cls._lock`` matches only when a class
        on THIS function's lexical path declares the attr in its class
        body; ``SomeClass._lock`` only when SomeClass does."""
        if isinstance(expr, ast.Name) and expr.id in self.mod.module_locks:
            return f"module-level lock `{expr.id}`"
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)):
            base, attr = expr.value.id, expr.attr
            if base in ("self", "cls"):
                if any(attr in self.mod.class_locks.get(seg, ())
                       for seg in self.fi.qualname.split(".")):
                    return f"class-level lock `{base}.{attr}`"
            elif attr in self.mod.class_locks.get(base, ()):
                return f"class-level lock `{base}.{attr}`"
        return None

    def _is_device_dispatch_or_wait(self, call: ast.Call) -> bool:
        """Step-shaped/jit-wrapped dispatch (the GL004/GL011 heuristics)
        or an explicit device wait (block_until_ready) — either one held
        under a shared lock serializes every sharer on the device."""
        if self._is_dispatch_call(call):
            return True
        dotted = self.mod.resolve(call.func)
        if dotted in _DEVICE_WAIT_CALLS:
            return True
        return (isinstance(call.func, ast.Attribute)
                and call.func.attr == "block_until_ready")

    def _check_lock_dispatch(self) -> None:
        """``with <shared lock>: ...step(...)...`` — the classic way a
        "parallel" front-end serializes on one replica: every transport
        or pump thread that shares the lock waits out the full device
        execution before its own work starts, so replicated engines run
        at single-engine throughput. Shared locks are for state
        mutation; dispatch belongs outside the critical section, fed by
        a queue (the per-replica batcher handoff)."""
        for node in _walk_skip_defs(self.fi.node.body):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                desc = self._shared_lock_desc(item.context_expr)
                if desc is None:
                    continue
                for sub in _walk_skip_defs(node.body):
                    if (isinstance(sub, ast.Call)
                            and self._is_device_dispatch_or_wait(sub)):
                        self._report(
                            "GL018", sub,
                            f"jitted/step-shaped dispatch under {desc} — "
                            "every thread sharing this lock serializes "
                            "on the device execution (a 'parallel' "
                            "front-end at 1-replica throughput); hold "
                            "the lock only for state mutation and hand "
                            "work to the dispatch path through a queue")

    # -- per-hypothesis decode dispatch (GL019) ------------------------------

    @staticmethod
    def _loop_carry_names(loop: ast.For) -> List[str]:
        """Names both (hard-)assigned and read inside the loop body —
        the lax.scan carry shape (``logits, cache = step(cache, tok)``).
        The loop target itself is the axis, never the carry."""
        targets: Set[str] = {
            n.id for n in ast.walk(loop.target) if isinstance(n, ast.Name)
        }
        assigned: Set[str] = set()
        read: Set[str] = set()
        for sub in _walk_skip_defs(loop.body):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            assigned.add(n.id)
            elif isinstance(sub, ast.AugAssign) and isinstance(
                    sub.target, ast.Name):
                assigned.add(sub.target.id)
                read.add(sub.target.id)
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                read.add(sub.id)
        return sorted((assigned & read) - targets)

    def _check_per_hypothesis_dispatch(self) -> None:
        """``for t in range(max_len): _, cache = step(cache, ...)`` — the
        hand-rolled decode loop: one host dispatch per token/hypothesis
        where a single lax.scan over the carry would keep the device
        saturated (the 12× beam-10 cliff ISSUE 13 closed). Flags only
        loops whose axis vocabulary is decode-shaped AND that carry
        state; no-carry loops and early-`break` loops stay unflagged."""
        for node in _walk_skip_defs(self.fi.node.body):
            if not isinstance(node, ast.For):
                continue
            axis_text = " ".join(
                [n.id for n in ast.walk(node.target)
                 if isinstance(n, ast.Name)]
                + [_expr_text(node.iter)])
            m = _DECODE_AXIS_RE.search(axis_text)
            if not m:
                continue
            if any(isinstance(sub, (ast.Break, ast.Return))
                   for sub in _walk_skip_defs(node.body)):
                continue  # host-controlled early exit: not scan-able as-is
            carry = self._loop_carry_names(node)
            if not carry:
                continue  # independent per-item work: vmap's job, not scan's
            for sub in _walk_skip_defs(node.body):
                if isinstance(sub, ast.Call) and self._is_dispatch_call(sub):
                    self._report(
                        "GL019", sub,
                        f"jit-wrapped/step-shaped dispatch inside a "
                        f"Python loop over a decode axis (`{m.group(0)}`) "
                        f"with scan-able carry `{carry[0]}` — every "
                        "iteration pays a fresh host dispatch (the "
                        "per-hypothesis decode tax); fold the loop into "
                        "the program as one lax.scan over the carry "
                        "(models/t5_generate.py's batched beam is the "
                        "accepted shape)")
                    break  # one finding per loop: the loop is the hazard

    # -- per-step kernel launch in a scan (GL021) ----------------------------

    _SCAN_LOOP_LEAVES = frozenset({"scan", "fori_loop"})

    def _scan_body_nodes(self, call: ast.Call) -> "List[ast.AST]":
        """The AST to inspect for a lax.scan / lax.fori_loop call's body
        function: the lambda body inline, or the named module-local def's
        body. Receivers of unknown provenance (parameters, attributes of
        imported objects) return nothing — the caller owns those."""
        leaf = None
        dotted = self.mod.resolve(call.func)
        if dotted is not None and "lax" in dotted.split("."):
            leaf = dotted.rsplit(".", 1)[-1]
        if leaf not in self._SCAN_LOOP_LEAVES:
            return []
        if leaf == "scan":
            body = call.args[0] if call.args else next(
                (kw.value for kw in call.keywords if kw.arg == "f"), None)
        else:  # fori_loop(lower, upper, body_fun, init_val)
            body = call.args[2] if len(call.args) > 2 else next(
                (kw.value for kw in call.keywords
                 if kw.arg == "body_fun"), None)
        if isinstance(body, ast.Lambda):
            return [body.body]
        if isinstance(body, ast.Name):
            # Scope-aware lookup: a local def in THIS function shadows
            # any same-named def elsewhere in the module (the module-wide
            # first-definition-wins table would inspect the wrong body).
            local = next(
                (n for n in ast.walk(self.fi.node)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                 and n is not self.fi.node and n.name == body.id),
                None)
            if local is not None:
                return list(local.body)
            # Otherwise only a module-TOP-LEVEL def resolves — a nested
            # def inside some other function is not in scope here.
            top = next(
                (n for n in self.mod.tree.body
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                 and n.name == body.id),
                None)
            if top is not None:
                return list(top.body)
        return []

    def _check_scan_kernel_launch(self) -> None:
        """A module-local pallas_call wrapper dispatched per scan step
        when the same module ships a persistent cross-step variant: the
        scan pays a kernel launch per step and round-trips the carry
        through HBM between launches — the exact traffic the persistent
        unroll exists to delete (ISSUE 15). One finding per loop body."""
        if not (self.mod.kernel_dispatchers
                and self.mod.persistent_variants):
            return
        variant = sorted(self.mod.persistent_variants)[0]
        for node in _walk_skip_defs(self.fi.node.body):
            if not isinstance(node, ast.Call):
                continue
            body_nodes = self._scan_body_nodes(node)
            if not body_nodes:
                continue
            for stmt in body_nodes:
                hit = next(
                    (sub for sub in ast.walk(stmt)
                     if isinstance(sub, ast.Call)
                     and isinstance(sub.func, ast.Name)
                     and sub.func.id in self.mod.kernel_dispatchers
                     # Dispatching the persistent variant itself IS the
                     # accepted shape, not the hazard.
                     and "persistent" not in sub.func.id.lower()),
                    None)
                if hit is not None:
                    self._report(
                        "GL021", hit,
                        f"per-step kernel launch: `{hit.func.id}(…)` (a "
                        "module-local pallas_call wrapper) dispatched "
                        "inside a lax.scan/fori_loop body while "
                        f"`{variant}` is importable from this module — "
                        "the scan pays one kernel launch per step and "
                        "round-trips the carry through HBM between "
                        "launches; dispatch the persistent K-step "
                        "variant instead (ops/fused_gnn.persistent_"
                        "unroll is the accepted shape)")
                    break  # one finding per loop: the loop is the hazard

    # -- swallowed device exceptions (GL009) ---------------------------------

    def _is_broad_handler(self, handler: ast.ExceptHandler) -> bool:
        if handler.type is None:  # bare except
            return True
        types = (handler.type.elts if isinstance(handler.type, ast.Tuple)
                 else [handler.type])
        return any(self.mod.resolve(t) in _BROAD_EXC for t in types)

    def _handler_swallows(self, handler: ast.ExceptHandler) -> bool:
        """No re-raise and no logging anywhere in the handler body
        (nested defs excluded: a deferred function is not this handler's
        error path)."""
        for sub in _walk_skip_defs(handler.body):
            if isinstance(sub, ast.Raise):
                return False
            if not isinstance(sub, ast.Call):
                continue
            dotted = self.mod.resolve(sub.func)
            if dotted is not None and (
                    dotted in _LOG_CALLS or dotted.startswith("logging.")):
                return False
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _LOG_ATTRS):
                return False
        return True

    def _try_has_device_call(self, body: List[ast.stmt]) -> bool:
        """Does the guarded block dispatch jit'd or device work? jax.*
        calls (jnp resolves through the alias table), module-level
        jit-wrapped defs, and step-shaped calls (the make_*step protocol)
        all count."""
        for sub in _walk_skip_defs(body):
            if not isinstance(sub, ast.Call):
                continue
            dotted = self.mod.resolve(sub.func)
            if dotted is not None and (dotted == "jax"
                                       or dotted.startswith("jax.")):
                return True
            func = sub.func
            name = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else None)
            if name is not None and (name in self.mod.jit_wrapped
                                     or _STEP_CALL_RE.match(name)):
                return True
        return False

    def _check_swallowed_exceptions(self) -> None:
        # Only Trys belonging directly to THIS function: nested defs carry
        # their own checker pass.
        for node in _walk_skip_defs(self.fi.node.body):
            if not isinstance(node, ast.Try):
                continue
            if not self._try_has_device_call(node.body):
                continue
            for handler in node.handlers:
                if (self._is_broad_handler(handler)
                        and self._handler_swallows(handler)):
                    what = ("except:" if handler.type is None
                            else "except Exception:")
                    self._report(
                        "GL009", handler,
                        f"broad `{what}` swallows errors around jit'd/"
                        "device calls (no re-raise, no logging) — TPU "
                        "faults the resilience layer must see (preemption, "
                        "XLA OOM, device errors) vanish here; log the "
                        "exception or re-raise")


def _walk_skip_defs(nodes):
    """ast.walk over a statement list that does NOT descend into nested
    function/class definitions (they are analyzed as their own scopes)."""
    stack = list(nodes)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            stack.append(child)


# ---------------------------------------------------------------------------
# Module entry point
# ---------------------------------------------------------------------------


def analyze_source(path: str, source: Optional[str] = None) -> List[Finding]:
    """All findings for one Python file (``source`` overrides reading
    ``path`` — the test-fixture hook)."""
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        # A file the analyzer cannot parse is itself a (new) finding — a
        # broken file must fail the gate, not silently skip analysis.
        return [Finding(
            rule="GL000", path=path, line=e.lineno or 0, col=0,
            function="<module>", message=f"unparseable file: {e.msg}",
            source_line="")]
    mod = _Module(path, tree, source.splitlines())
    findings: List[Finding] = []
    for fi in _collect_functions(tree):
        checker = _FunctionChecker(mod, fi, _is_jit_scope(mod, fi))
        findings.extend(checker.run())
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings

"""Package walker + baseline diffing for graftlint.

``run_analysis`` walks the given paths (default: the ``deepdfa_tpu``
package), analyzes every ``.py`` file, and diffs the findings against a
committed baseline-suppressions file so CI fails only on NEW findings.

Baseline entries are keyed by a line-number-free fingerprint (file, rule,
function, normalized source line — ``Finding.fingerprint``), so unrelated
edits above a suppressed finding don't resurrect it; identical fingerprints
are count-aware, so *adding a second copy* of a suppressed hazard still
fails. Regenerate with ``--write-baseline`` after deliberate suppressions.
"""

from __future__ import annotations

import collections
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from deepdfa_tpu.analysis.rules import Finding, analyze_source

BASELINE_VERSION = 1


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_paths() -> List[str]:
    return [os.path.join(repo_root(), "deepdfa_tpu")]


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "configs", "lint_baseline.json")


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git", "_build")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def collect_findings(paths: Sequence[str],
                     root: Optional[str] = None) -> List[Finding]:
    return _findings_for_files(iter_python_files(paths), root)


def _findings_for_files(files: Sequence[str],
                        root: Optional[str] = None) -> List[Finding]:
    root = root or repo_root()
    findings: List[Finding] = []
    for path in files:
        rel = os.path.relpath(path, root)
        if rel.startswith(".."):  # outside the root: keep absolute
            rel = path
        findings.extend(analyze_source(rel, source=_read(path)))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return f.read()


def load_baseline(path: str) -> Dict[str, int]:
    """fingerprint -> allowed count. A missing file is an empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    counts: Dict[str, int] = collections.Counter(
        entry["fingerprint"] for entry in doc.get("suppressions", [])
    )
    return dict(counts)


def write_baseline(findings: Sequence[Finding], path: str) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "generated_by": "deepdfa_tpu.cli analyze-code --write-baseline",
        "suppressions": [
            {
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "name": f.name,
                "file": f.path,
                "function": f.function,
                # informational only — the fingerprint is the key
                "line": f.line,
                "source": f.source_line,
            }
            for f in findings
        ],
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], Dict[str, int]]:
    """(new findings, stale fingerprints with unused counts).

    Findings are suppressed fingerprint-by-fingerprint up to the baselined
    count; the (n+1)-th identical finding is NEW. Leftover counts are stale
    entries worth pruning from the baseline."""
    remaining = dict(baseline)
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
        else:
            new.append(f)
    stale = {fp: n for fp, n in remaining.items() if n > 0}
    return new, stale


def run_analysis(
    paths: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
    write_baseline_file: bool = False,
    root: Optional[str] = None,
) -> Dict:
    """The analyze-code engine. Returns a JSON-able report:

    ``{"files", "findings" (all), "new" (non-baselined), "stale_suppressions",
    "exit_code"}`` — exit_code 1 iff new findings exist (and we're not
    regenerating the baseline)."""
    paths = list(paths) if paths else default_paths()
    baseline_path = baseline_path or default_baseline_path()
    files = iter_python_files(paths)
    findings = _findings_for_files(files, root=root)
    if write_baseline_file:
        write_baseline(findings, baseline_path)
        return {
            "files": len(files),
            "findings": [_as_dict(f) for f in findings],
            "new": [],
            "stale_suppressions": {},
            "baseline": baseline_path,
            "baseline_written": True,
            "exit_code": 0,
        }
    baseline = load_baseline(baseline_path)
    new, stale = apply_baseline(findings, baseline)
    return {
        "files": len(files),
        "findings": [_as_dict(f) for f in findings],
        "new": [_as_dict(f) for f in new],
        "new_findings": new,
        "stale_suppressions": stale,
        "baseline": baseline_path,
        "exit_code": 1 if new else 0,
    }


def _as_dict(f: Finding) -> Dict:
    return {
        "rule": f.rule,
        "name": f.name,
        "path": f.path,
        "line": f.line,
        "col": f.col,
        "function": f.function,
        "message": f.message,
        "trace": list(f.trace),
        "fingerprint": f.fingerprint,
    }


def format_report(report: Dict, verbose: bool = False) -> str:
    """Human-readable lint output (the non-``--json`` CLI surface)."""
    lines: List[str] = []
    new = report.get("new_findings", [])
    for f in new:
        lines.append(f.format())
    n_baselined = len(report["findings"]) - len(new)
    summary = (
        f"graftlint: {len(new)} new finding{'s' if len(new) != 1 else ''} "
        f"({n_baselined} baselined, {report['files']} files)"
    )
    if report.get("baseline_written"):
        summary = (
            f"graftlint: baseline regenerated with "
            f"{len(report['findings'])} suppressions -> {report['baseline']}"
        )
    lines.append(summary)
    if report.get("stale_suppressions"):
        lines.append(
            f"graftlint: {sum(report['stale_suppressions'].values())} stale "
            "suppression(s) no longer match any finding — regenerate the "
            "baseline to prune them"
        )
    if verbose and not report.get("baseline_written"):
        for f in report["findings"]:
            lines.append(
                f"  [all] {f['path']}:{f['line']} {f['rule']} {f['message']}"
            )
    return "\n".join(lines)

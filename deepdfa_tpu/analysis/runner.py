"""Package walker + baseline diffing for graftlint.

``run_analysis`` walks the given paths (default: the ``deepdfa_tpu``
package), analyzes every ``.py`` file, and diffs the findings against a
committed baseline-suppressions file so CI fails only on NEW findings.

Two phases since the interprocedural lift: a **per-file** phase (the
GL001–GL021 rules plus a :class:`callgraph.ModuleSummary` per file — both
pure functions of one file's content, so both cache under the file's
sha256 + the rule-registry fingerprint), then a **whole-program** phase
that composes all summaries into a :class:`callgraph.Program` and runs
the GL022–GL025 concurrency rules. The program phase is cheap (no AST
work, just graph composition) and always runs — on a warm
``--incremental`` pass only changed files and their import-graph
dependents repeat the per-file phase.

Baseline entries are keyed by a line-number-free fingerprint (file, rule,
function, normalized source line — ``Finding.fingerprint``), so unrelated
edits above a suppressed finding don't resurrect it; identical fingerprints
are count-aware, so *adding a second copy* of a suppressed hazard still
fails. Regenerate with ``--write-baseline`` after deliberate suppressions.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from deepdfa_tpu.analysis import callgraph
from deepdfa_tpu.analysis.concurrency import analyze_concurrency
from deepdfa_tpu.analysis.rules import (
    Finding, analyze_source, ruleset_fingerprint,
)

BASELINE_VERSION = 1


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_paths() -> List[str]:
    return [os.path.join(repo_root(), "deepdfa_tpu")]


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "configs", "lint_baseline.json")


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git", "_build")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return sorted(set(out))


def default_cache_path() -> str:
    return os.path.join(repo_root(), ".graftlint_cache.json")


def collect_findings(paths: Sequence[str],
                     root: Optional[str] = None) -> List[Finding]:
    """Per-file (intraprocedural) findings only — the legacy surface;
    ``run_analysis``/``analyze_files`` add the program phase."""
    root = root or repo_root()
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        rel = _rel(path, root)
        findings.extend(analyze_source(rel, source=_read(path)))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _rel(path: str, root: str) -> str:
    rel = os.path.relpath(path, root)
    if rel.startswith(".."):  # outside the root: keep absolute
        rel = path
    return rel.replace("\\", "/")


def _read(path: str) -> str:
    with open(path, encoding="utf-8") as f:
        return f.read()


def analyze_files(sources: Dict[str, str]) -> List[Finding]:
    """Full analysis (per-file rules + GL022–GL025 program phase) over an
    in-memory ``{path: source}`` program — the multi-file fixture hook."""
    findings: List[Finding] = []
    summaries: List[callgraph.ModuleSummary] = []
    split: Dict[str, List[str]] = {}
    for path in sorted(sources):
        src = sources[path]
        split[path.replace("\\", "/")] = src.splitlines()
        findings.extend(analyze_source(path, source=src))
        summary = callgraph.summarize_module(path, src)
        if summary is not None:
            summaries.append(summary)

    def lookup(path: str, line: int) -> str:
        lines = split.get(path, [])
        return lines[line - 1] if 0 < line <= len(lines) else ""

    findings.extend(analyze_concurrency(callgraph.Program(summaries), lookup))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---------------------------------------------------------------------------
# Incremental cache: per-file findings + summaries keyed on content hash
# ---------------------------------------------------------------------------


def _finding_to_cache(f: Finding) -> Dict:
    return {
        "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
        "function": f.function, "message": f.message,
        "trace": list(f.trace), "source_line": f.source_line,
    }


def _finding_from_cache(d: Dict) -> Finding:
    return Finding(
        rule=d["rule"], path=d["path"], line=d["line"], col=d["col"],
        function=d["function"], message=d["message"],
        trace=tuple(d.get("trace", ())), source_line=d.get("source_line", ""))


def _load_cache(path: str) -> Dict:
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {"version": "", "files": {}}
    if doc.get("version") != ruleset_fingerprint():
        return {"version": "", "files": {}}  # registry changed: all stale
    if not isinstance(doc.get("files"), dict):
        return {"version": "", "files": {}}
    return doc


def _save_cache(path: str, entries: Dict[str, Dict]) -> None:
    doc = {"version": ruleset_fingerprint(), "files": entries}
    try:
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
    except OSError:
        pass  # a read-only checkout just runs cold every time


def load_baseline(path: str) -> Dict[str, int]:
    """fingerprint -> allowed count. A missing file is an empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    counts: Dict[str, int] = collections.Counter(
        entry["fingerprint"] for entry in doc.get("suppressions", [])
    )
    return dict(counts)


def write_baseline(findings: Sequence[Finding], path: str) -> None:
    doc = {
        "version": BASELINE_VERSION,
        "generated_by": "deepdfa_tpu.cli analyze-code --write-baseline",
        "suppressions": [
            {
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "name": f.name,
                "file": f.path,
                "function": f.function,
                # informational only — the fingerprint is the key
                "line": f.line,
                "source": f.source_line,
            }
            for f in findings
        ],
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: Dict[str, int]
) -> Tuple[List[Finding], Dict[str, int]]:
    """(new findings, stale fingerprints with unused counts).

    Findings are suppressed fingerprint-by-fingerprint up to the baselined
    count; the (n+1)-th identical finding is NEW. Leftover counts are stale
    entries worth pruning from the baseline."""
    remaining = dict(baseline)
    new: List[Finding] = []
    for f in findings:
        fp = f.fingerprint
        if remaining.get(fp, 0) > 0:
            remaining[fp] -= 1
        else:
            new.append(f)
    stale = {fp: n for fp, n in remaining.items() if n > 0}
    return new, stale


def run_analysis(
    paths: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
    write_baseline_file: bool = False,
    root: Optional[str] = None,
    incremental: bool = False,
    cache_path: Optional[str] = None,
) -> Dict:
    """The analyze-code engine. Returns a JSON-able report:

    ``{"files", "findings" (all), "new" (non-baselined), "stale_suppressions",
    "exit_code"}`` — exit_code 1 iff new findings exist (and we're not
    regenerating the baseline). With ``incremental=True``, per-file results
    for content-unchanged files come from the cache (which a cold run
    primes) and ``"reanalyzed"`` lists the files that actually re-ran the
    per-file phase: changed files plus their direct import-graph
    dependents. The GL022–GL025 program phase always runs — it is graph
    composition over the (cached) summaries, not AST work."""
    paths = list(paths) if paths else default_paths()
    baseline_path = baseline_path or default_baseline_path()
    root = root or repo_root()
    cache_path = cache_path or default_cache_path()
    files = iter_python_files(paths)

    cache = _load_cache(cache_path) if incremental else \
        {"version": "", "files": {}}
    cached_files: Dict[str, Dict] = cache["files"]
    entries: Dict[str, Dict] = {}
    findings: List[Finding] = []
    summaries: Dict[str, callgraph.ModuleSummary] = {}
    abs_of: Dict[str, str] = {}
    changed: List[str] = []

    def analyze_one(rel: str, path: str) -> Dict:
        source = _read(path)
        digest = hashlib.sha256(source.encode()).hexdigest()
        file_findings = analyze_source(rel, source=source)
        summary = callgraph.summarize_module(rel, source)
        return {
            "sha256": digest,
            "findings": [_finding_to_cache(f) for f in file_findings],
            "summary": summary.to_dict() if summary is not None else None,
        }

    for path in files:
        rel = _rel(path, root)
        abs_of[rel] = path
        entry = cached_files.get(rel)
        if entry is not None:
            digest = hashlib.sha256(_read(path).encode()).hexdigest()
            if digest != entry.get("sha256"):
                entry = None
        if entry is None:
            entry = analyze_one(rel, path)
            changed.append(rel)
        entries[rel] = entry

    # a changed file invalidates its direct import-graph dependents: their
    # per-file results cannot change (per-file analysis sees one file), but
    # the contract is that an edit re-checks everything that imports it.
    if incremental and changed:
        probe = callgraph.Program([
            callgraph.ModuleSummary.from_dict(e["summary"])
            for e in entries.values() if e.get("summary")])
        dependents: List[str] = []
        for rel in changed:
            for dep in probe.importers_of(rel):
                if dep in entries and dep not in changed and \
                        dep not in dependents:
                    dependents.append(dep)
        for rel in dependents:
            entries[rel] = analyze_one(rel, abs_of[rel])
        reanalyzed = sorted(changed + dependents)
    else:
        reanalyzed = sorted(changed)

    for rel in sorted(entries):
        entry = entries[rel]
        findings.extend(_finding_from_cache(d) for d in entry["findings"])
        if entry.get("summary"):
            summaries[rel] = callgraph.ModuleSummary.from_dict(
                entry["summary"])

    _save_cache(cache_path, entries)

    program = callgraph.Program(list(summaries.values()))
    line_cache: Dict[str, List[str]] = {}

    def lookup(rel_path: str, line: int) -> str:
        if rel_path not in line_cache:
            try:
                line_cache[rel_path] = _read(
                    abs_of.get(rel_path, rel_path)).splitlines()
            except OSError:
                line_cache[rel_path] = []
        lines = line_cache[rel_path]
        return lines[line - 1] if 0 < line <= len(lines) else ""

    findings.extend(analyze_concurrency(program, lookup))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    if write_baseline_file:
        write_baseline(findings, baseline_path)
        return {
            "files": len(files),
            "reanalyzed": reanalyzed,
            "findings": [_as_dict(f) for f in findings],
            "new": [],
            "stale_suppressions": {},
            "baseline": baseline_path,
            "baseline_written": True,
            "exit_code": 0,
        }
    baseline = load_baseline(baseline_path)
    new, stale = apply_baseline(findings, baseline)
    return {
        "files": len(files),
        "reanalyzed": reanalyzed,
        "findings": [_as_dict(f) for f in findings],
        "new": [_as_dict(f) for f in new],
        "new_findings": new,
        "stale_suppressions": stale,
        "baseline": baseline_path,
        "exit_code": 1 if new else 0,
    }


def _as_dict(f: Finding) -> Dict:
    return {
        "rule": f.rule,
        "name": f.name,
        "path": f.path,
        "line": f.line,
        "col": f.col,
        "function": f.function,
        "message": f.message,
        "trace": list(f.trace),
        "fingerprint": f.fingerprint,
    }


def format_report(report: Dict, verbose: bool = False) -> str:
    """Human-readable lint output (the non-``--json`` CLI surface)."""
    lines: List[str] = []
    new = report.get("new_findings", [])
    for f in new:
        lines.append(f.format())
    n_baselined = len(report["findings"]) - len(new)
    summary = (
        f"graftlint: {len(new)} new finding{'s' if len(new) != 1 else ''} "
        f"({n_baselined} baselined, {report['files']} files)"
    )
    if report.get("baseline_written"):
        summary = (
            f"graftlint: baseline regenerated with "
            f"{len(report['findings'])} suppressions -> {report['baseline']}"
        )
    lines.append(summary)
    if report.get("stale_suppressions"):
        lines.append(
            f"graftlint: {sum(report['stale_suppressions'].values())} stale "
            "suppression(s) no longer match any finding — regenerate the "
            "baseline to prune them"
        )
    if verbose and not report.get("baseline_written"):
        for f in report["findings"]:
            lines.append(
                f"  [all] {f['path']}:{f['line']} {f['rule']} {f['message']}"
            )
    return "\n".join(lines)

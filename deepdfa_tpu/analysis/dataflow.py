"""Forward dataflow solvers over the ``cfg`` graphs.

Two analyses drive the rules:

- **Reaching definitions** — the textbook kill/gen pass (the same analysis
  the reproduced paper's models learn to emulate; here it runs for real over
  our own sources). Facts map ``name -> frozenset(def node ids)``.
- **Taint** — which names (transitively) hold values derived from a set of
  seeds: jit-scope parameters, or the results of jitted-step calls inside a
  loop. Facts map ``name -> frozenset(Taint)`` where each ``Taint`` carries
  the def-use chain that propagated it (for the report) and the loop that
  seeded it (so a sink can be scoped to "the same loop as the step call").

Both run a worklist to a fixpoint in reverse post-order; joins are key-wise
unions, so termination is by finite fact height (defs and traces are drawn
from the finite node set — traces are capped and compared structurally).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Dict, FrozenSet, List, Optional, Tuple

from deepdfa_tpu.analysis.cfg import (
    CFG,
    Node,
    assigned_names,
    deleted_names,
    node_exprs,
)

# ---------------------------------------------------------------------------
# Generic forward worklist
# ---------------------------------------------------------------------------

Fact = Dict[str, FrozenSet]


def _join(a: Fact, b: Fact) -> Fact:
    if not a:
        return dict(b)
    out = dict(a)
    for k, v in b.items():
        cur = out.get(k)
        out[k] = v if cur is None else (cur | v)
    return out


def solve_forward(
    cfg: CFG,
    transfer: Callable[[Node, Fact], Fact],
    entry_fact: Optional[Fact] = None,
) -> Dict[int, Fact]:
    """Fixpoint in-facts per node id."""
    in_facts: Dict[int, Fact] = {cfg.entry: dict(entry_fact or {})}
    out_facts: Dict[int, Fact] = {}
    order = cfg.rpo()
    pos = {nid: i for i, nid in enumerate(order)}
    work = list(order)
    in_work = set(work)
    while work:
        work.sort(key=lambda n: pos.get(n, 0), reverse=True)
        nid = work.pop()
        in_work.discard(nid)
        node = cfg.nodes[nid]
        fact: Fact = {}
        if nid == cfg.entry:
            fact = dict(entry_fact or {})
        for p in node.preds:
            if p in out_facts:
                fact = _join(fact, out_facts[p])
        in_facts[nid] = fact
        new_out = transfer(node, fact)
        if out_facts.get(nid) != new_out:
            out_facts[nid] = new_out
            for s in node.succs:
                if s not in in_work:
                    in_work.add(s)
                    work.append(s)
    return in_facts


# ---------------------------------------------------------------------------
# Reaching definitions
# ---------------------------------------------------------------------------


def reaching_definitions(cfg: CFG) -> Dict[int, Dict[str, FrozenSet[int]]]:
    """For each node: the def sites (node ids) of every name reaching it."""

    def transfer(node: Node, fact: Fact) -> Fact:
        hard, soft = assigned_names(node)
        if not hard and not soft and not isinstance(node.stmt, ast.Delete):
            return fact
        out = dict(fact)
        for name in hard:
            out[name] = frozenset((node.idx,))
        for name in soft:
            out[name] = out.get(name, frozenset()) | {node.idx}
        for name in deleted_names(node):
            out.pop(name, None)
        return out

    return solve_forward(cfg, transfer)


# ---------------------------------------------------------------------------
# Taint
# ---------------------------------------------------------------------------

_TRACE_CAP = 8

#: Attribute reads that yield static (host) metadata, not traced values.
_STATIC_ATTRS = frozenset(
    {"shape", "dtype", "ndim", "size", "aval", "sharding", "device"}
)

#: Builtins whose result is a host value regardless of argument taint.
#: float/int/bool are the *sinks* the rules flag — their result is a host
#: scalar, so taint must not cascade past them (one finding per sync).
_UNTAINTED_RESULT_CALLS = frozenset(
    {"float", "int", "bool", "str", "len", "repr", "format", "isinstance",
     "hasattr", "getattr", "type", "id", "print"}
)

#: Mutating method calls that propagate argument taint onto the receiver.
_MUTATORS = frozenset({"append", "extend", "add", "update", "insert",
                       "setdefault", "__setitem__"})


@dataclasses.dataclass(frozen=True)
class Taint:
    seed_loop: Optional[int]  # loop-head node id the seed fired in (None = whole function)
    trace: Tuple[Tuple[int, str], ...]  # (line, "what happened") def-use chain

    def extended(self, line: int, what: str) -> "Taint":
        if len(self.trace) >= _TRACE_CAP:
            return self
        return Taint(self.seed_loop, self.trace + ((line, what),))


def _expr_text(expr: ast.AST, limit: int = 60) -> str:
    try:
        text = ast.unparse(expr)
    except Exception:  # pragma: no cover — unparse covers all exprs we build
        text = type(expr).__name__
    return text if len(text) <= limit else text[: limit - 1] + "…"


class TaintAnalysis:
    """Configurable taint propagation.

    ``seed_call(node, call)``: return a reason string when ``call`` is a
    taint *source* at ``node`` (e.g. a jitted-step invocation); the Taint is
    seeded with the node's innermost loop. ``cleaners``: dotted call names
    whose result is host-side (explicit syncs like ``jax.device_get``).
    ``resolve``: maps an expression to its dotted name (import-alias aware,
    provided by rules.py).
    """

    def __init__(
        self,
        resolve: Callable[[ast.expr], Optional[str]],
        seed_call: Optional[Callable[[Node, ast.Call], Optional[str]]] = None,
        cleaners: FrozenSet[str] = frozenset(),
        seed_params: Optional[Dict[str, str]] = None,
    ):
        self.resolve = resolve
        self.seed_call = seed_call
        self.cleaners = cleaners
        self.seed_params = seed_params or {}

    # -- expression evaluation ------------------------------------------------

    def taint_of(self, expr: ast.expr, fact: Fact,
                 node: Optional[Node] = None) -> FrozenSet[Taint]:
        if isinstance(expr, ast.Name):
            return fact.get(expr.id, frozenset())
        if isinstance(expr, ast.Attribute):
            if expr.attr in _STATIC_ATTRS:
                return frozenset()
            return self.taint_of(expr.value, fact, node)
        if isinstance(expr, ast.Call):
            return self._taint_of_call(expr, fact, node)
        if isinstance(expr, ast.BoolOp):
            return self._union(expr.values, fact, node)
        if isinstance(expr, ast.BinOp):
            return self.taint_of(expr.left, fact, node) | self.taint_of(
                expr.right, fact, node)
        if isinstance(expr, ast.UnaryOp):
            return self.taint_of(expr.operand, fact, node)
        if isinstance(expr, ast.Compare):
            return self._union([expr.left] + list(expr.comparators), fact, node)
        if isinstance(expr, ast.IfExp):
            return self._union([expr.test, expr.body, expr.orelse], fact, node)
        if isinstance(expr, ast.Subscript):
            return self.taint_of(expr.value, fact, node) | self.taint_of(
                expr.slice, fact, node)
        if isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
            return self._union(expr.elts, fact, node)
        if isinstance(expr, ast.Dict):
            return self._union(
                [e for e in list(expr.keys) + list(expr.values) if e is not None],
                fact, node)
        if isinstance(expr, ast.Starred):
            return self.taint_of(expr.value, fact, node)
        if isinstance(expr, ast.NamedExpr):
            return self.taint_of(expr.value, fact, node)
        if isinstance(expr, ast.Slice):
            return self._union(
                [e for e in (expr.lower, expr.upper, expr.step) if e is not None],
                fact, node)
        if isinstance(expr, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            srcs = self._union([g.iter for g in expr.generators], fact, node)
            return srcs | self.taint_of(expr.elt, fact, node)
        if isinstance(expr, ast.DictComp):
            srcs = self._union([g.iter for g in expr.generators], fact, node)
            return srcs | self.taint_of(expr.key, fact, node) | self.taint_of(
                expr.value, fact, node)
        # Constants, f-strings (host str result), lambdas, etc.
        return frozenset()

    def _union(self, exprs: List[ast.expr], fact: Fact,
               node: Optional[Node]) -> FrozenSet[Taint]:
        out: FrozenSet[Taint] = frozenset()
        for e in exprs:
            out |= self.taint_of(e, fact, node)
        return out

    def _taint_of_call(self, call: ast.Call, fact: Fact,
                       node: Optional[Node]) -> FrozenSet[Taint]:
        dotted = self.resolve(call.func)
        if dotted in self.cleaners:
            return frozenset()
        if dotted in _UNTAINTED_RESULT_CALLS:
            return frozenset()
        if self.seed_call is not None and node is not None:
            reason = self.seed_call(node, call)
            if reason is not None:
                seed_loop = node.loop_stack[-1] if node.loop_stack else None
                return frozenset(
                    (Taint(seed_loop, ((node.line, reason),)),)
                )
        args = list(call.args) + [kw.value for kw in call.keywords]
        out = self._union(args, fact, node)
        # A method call on a tainted object returns tainted (e.g.
        # ``x.astype(...)``); a plain function keeps only argument taint.
        if isinstance(call.func, ast.Attribute):
            out |= self.taint_of(call.func.value, fact, node)
        return out

    # -- transfer -------------------------------------------------------------

    def entry_fact(self, cfg: CFG) -> Fact:
        fact: Fact = {}
        line = getattr(cfg.func, "lineno", 0)
        for name, reason in self.seed_params.items():
            fact[name] = frozenset((Taint(None, ((line, reason),)),))
        return fact

    def transfer(self, node: Node, fact: Fact) -> Fact:
        s = node.stmt
        if s is None:
            return fact
        hard, soft = assigned_names(node)
        if not hard and not soft and not isinstance(s, (ast.Delete, ast.Expr)):
            return fact
        out = dict(fact)
        rhs: Optional[ast.expr] = None
        if isinstance(s, ast.Assign):
            rhs = s.value
        elif isinstance(s, ast.AnnAssign):
            rhs = s.value
        elif isinstance(s, ast.AugAssign):
            rhs = s.value
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            rhs = s.iter
        taint: FrozenSet[Taint] = frozenset()
        if rhs is not None:
            taint = self.taint_of(rhs, fact, node)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            taint = self._union(
                [item.context_expr for item in s.items], fact, node)
        if taint:
            what = _expr_text(rhs if rhs is not None else s)
            lhs = ", ".join(hard) if hard else (soft[0] if soft else "?")
            taint = frozenset(
                t.extended(node.line, f"{lhs} ← {what}") for t in taint
            )
        for name in hard:
            if taint:
                out[name] = taint
            else:
                out.pop(name, None)  # rebound clean: kill
        for name in soft:
            if taint:
                out[name] = out.get(name, frozenset()) | taint
        # Mutator method calls taint their receiver: ``acc.append(loss)``.
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            call = s.value
            if (isinstance(call.func, ast.Attribute)
                    and call.func.attr in _MUTATORS
                    and isinstance(call.func.value, ast.Name)):
                arg_taint = self._union(
                    list(call.args) + [kw.value for kw in call.keywords],
                    fact, node)
                if arg_taint:
                    recv = call.func.value.id
                    arg_taint = frozenset(
                        t.extended(node.line,
                                   f"{recv}.{call.func.attr}({_expr_text(call.args[0]) if call.args else ''})")
                        for t in arg_taint)
                    out[recv] = out.get(recv, frozenset()) | arg_taint
        for name in deleted_names(node):
            out.pop(name, None)
        # Walrus defs inside owned expressions.
        for expr in node_exprs(node):
            for sub in ast.walk(expr):
                if isinstance(sub, ast.NamedExpr) and isinstance(sub.target, ast.Name):
                    t = self.taint_of(sub.value, fact, node)
                    if t:
                        out[sub.target.id] = t
        return out

    def solve(self, cfg: CFG) -> Dict[int, Fact]:
        return solve_forward(cfg, self.transfer, self.entry_fact(cfg))

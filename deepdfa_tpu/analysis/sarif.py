"""SARIF 2.1.0 export for graftlint findings.

``cli analyze-code --sarif <path>`` writes one run in the static-analysis
interchange format every major CI renders as inline annotations. The
mapping is deliberately minimal and standard: one ``rule`` per registered
GLxxx id, one ``result`` per finding with a file/line/column region, level
``error`` for findings NOT covered by the committed baseline and ``note``
for baselined ones, and the finding's trace steps as the message's
continuation lines. The JSON report and the baseline diff are unchanged —
SARIF is a second serialization of the same run.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

from deepdfa_tpu.analysis.rules import RULES

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def report_to_sarif(report: Dict) -> Dict:
    """One SARIF ``run`` from a ``run_analysis`` report dict."""
    new_fps = {f["fingerprint"] for f in report.get("new", [])}
    rules_used: List[str] = sorted({f["rule"]
                                    for f in report.get("findings", [])})
    rule_index = {rid: i for i, rid in enumerate(rules_used)}
    results = []
    for f in report.get("findings", []):
        message = f["message"]
        if f.get("trace"):
            message = "\n".join([message] + list(f["trace"]))
        results.append({
            "ruleId": f["rule"],
            "ruleIndex": rule_index[f["rule"]],
            "level": ("error" if f["fingerprint"] in new_fps else "note"),
            "message": {"text": message},
            "partialFingerprints": {"graftlint/v1": f["fingerprint"]},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {
                        "uri": f["path"].replace(os.sep, "/"),
                    },
                    "region": {
                        "startLine": max(1, int(f["line"])),
                        "startColumn": int(f["col"]) + 1,
                    },
                },
                "logicalLocations": [{
                    "name": f["function"],
                    "kind": "function",
                }],
            }],
        })
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "graftlint",
                    "informationUri":
                        "https://github.com/deepdfa-tpu/deepdfa-tpu",
                    "rules": [{
                        "id": rid,
                        "name": RULES.get(rid, rid),
                        "shortDescription": {"text": RULES.get(rid, rid)},
                    } for rid in rules_used],
                },
            },
            "results": results,
        }],
    }


def write_sarif(report: Dict, path: str) -> None:
    doc = report_to_sarif(report)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")

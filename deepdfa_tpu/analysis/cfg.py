"""Intra-procedural control-flow graphs over Python ``ast`` statements.

One CFG node per simple statement; ``if``/``while``/``for``/``with`` get a
head node owning just their test/iter/items expression, with the nested
bodies flattened into their own nodes. This is the granularity the forward
solvers in ``dataflow.py`` run at — fine enough for def-use chains with
real line numbers, coarse enough that a whole package solves in well under
a second.

Every node records the stack of enclosing loop-head node ids
(``loop_stack``), which the rules use to scope "inside the step loop"
facts, and the chain of enclosing ``if`` tests (``guard_tests``), which the
host-sync rule uses to recognize rate-limited (``n % k == 0``-guarded)
syncs.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: Statement classes that get a dedicated head node whose "owned"
#: expressions exclude the nested bodies (those become their own nodes).
_HEAD_KINDS = (ast.If, ast.While, ast.For, ast.AsyncFor, ast.With,
               ast.AsyncWith, ast.Try)


@dataclasses.dataclass
class Node:
    idx: int
    stmt: Optional[ast.AST]  # None for the synthetic entry/exit
    kind: str  # "entry" | "exit" | "stmt" | "if" | "while" | "for" | "with" | "try"
    line: int
    loop_stack: Tuple[int, ...]  # enclosing loop-head node ids, outermost first
    guard_tests: Tuple[ast.expr, ...]  # enclosing if-tests, outermost first
    succs: Set[int] = dataclasses.field(default_factory=set)
    preds: Set[int] = dataclasses.field(default_factory=set)

    @property
    def loop_depth(self) -> int:
        return len(self.loop_stack)


class CFG:
    def __init__(self, func: Optional[ast.AST], nodes: List[Node],
                 entry: int, exit_: int):
        self.func = func
        self.nodes = nodes
        self.entry = entry
        self.exit = exit_

    def rpo(self) -> List[int]:
        """Reverse post-order from entry — the forward-solver visit order."""
        seen: Set[int] = set()
        order: List[int] = []
        stack: List[Tuple[int, Iterable[int]]] = [(self.entry, iter(sorted(self.nodes[self.entry].succs)))]
        seen.add(self.entry)
        while stack:
            nid, it = stack[-1]
            advanced = False
            for s in it:
                if s not in seen:
                    seen.add(s)
                    stack.append((s, iter(sorted(self.nodes[s].succs))))
                    advanced = True
                    break
            if not advanced:
                order.append(nid)
                stack.pop()
        return list(reversed(order))


class _Builder:
    def __init__(self) -> None:
        self.nodes: List[Node] = []
        self._loops: List[Tuple[int, List[int]]] = []  # (head idx, break nodes)
        self._guards: List[ast.expr] = []
        self._exits: List[int] = []  # return/raise nodes -> exit

    def _new(self, stmt: Optional[ast.AST], kind: str) -> int:
        idx = len(self.nodes)
        self.nodes.append(Node(
            idx=idx, stmt=stmt, kind=kind,
            line=getattr(stmt, "lineno", 0),
            loop_stack=tuple(h for h, _ in self._loops),
            guard_tests=tuple(self._guards),
        ))
        return idx

    def _link(self, preds: Iterable[int], nid: int) -> None:
        for p in preds:
            self.nodes[p].succs.add(nid)
            self.nodes[nid].preds.add(p)

    def _seq(self, stmts: List[ast.stmt], preds: Set[int]) -> Set[int]:
        for stmt in stmts:
            if not preds:
                # Unreachable code after return/break still gets nodes (its
                # defs must exist for the solver maps) but no inbound edges.
                pass
            preds = self._stmt(stmt, preds)
        return preds

    def _stmt(self, stmt: ast.stmt, preds: Set[int]) -> Set[int]:
        if isinstance(stmt, ast.If):
            head = self._new(stmt, "if")
            self._link(preds, head)
            self._guards.append(stmt.test)
            then_out = self._seq(stmt.body, {head})
            else_out = self._seq(stmt.orelse, {head}) if stmt.orelse else {head}
            self._guards.pop()
            return then_out | else_out
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            kind = "while" if isinstance(stmt, ast.While) else "for"
            head = self._new(stmt, kind)
            self._link(preds, head)
            self._loops.append((head, []))
            body_out = self._seq(stmt.body, {head})
            self._link(body_out, head)  # back edge
            _, breaks = self._loops.pop()
            out = {head} | set(breaks)
            if stmt.orelse:
                out = self._seq(stmt.orelse, {head}) | set(breaks)
            return out
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self._new(stmt, "with")
            self._link(preds, head)
            return self._seq(stmt.body, {head})
        if isinstance(stmt, ast.Try):
            head = self._new(stmt, "try")
            self._link(preds, head)
            first_body = len(self.nodes)
            body_out = self._seq(stmt.body, {head})
            body_nodes = set(range(first_body, len(self.nodes))) | {head}
            out = set(body_out)
            if stmt.orelse:
                out = self._seq(stmt.orelse, out)
            for handler in stmt.handlers:
                # Any statement in the body may raise into the handler.
                h_out = self._seq(handler.body, set(body_nodes))
                out |= h_out
            if stmt.finalbody:
                out = self._seq(stmt.finalbody, out)
            return out
        if isinstance(stmt, ast.Break):
            nid = self._new(stmt, "stmt")
            self._link(preds, nid)
            if self._loops:
                self._loops[-1][1].append(nid)
            return set()
        if isinstance(stmt, ast.Continue):
            nid = self._new(stmt, "stmt")
            self._link(preds, nid)
            if self._loops:
                self.nodes[nid].succs.add(self._loops[-1][0])
                self.nodes[self._loops[-1][0]].preds.add(nid)
            return set()
        if isinstance(stmt, (ast.Return, ast.Raise)):
            nid = self._new(stmt, "stmt")
            self._link(preds, nid)
            self._exits.append(nid)
            return set()
        # Everything else — including nested FunctionDef/ClassDef, whose
        # bodies are analyzed as their own CFGs — is one linear node.
        nid = self._new(stmt, "stmt")
        self._link(preds, nid)
        return {nid}


def build_cfg(func: ast.AST, body: Optional[List[ast.stmt]] = None) -> CFG:
    """CFG for a function (or any statement list via ``body``)."""
    b = _Builder()
    entry = b._new(None, "entry")
    stmts = body if body is not None else list(getattr(func, "body", []))
    out = b._seq(stmts, {entry})
    exit_ = b._new(None, "exit")
    b._link(out | set(b._exits), exit_)
    return CFG(func, b.nodes, entry, exit_)


# ---------------------------------------------------------------------------
# Per-node expression / definition accessors
# ---------------------------------------------------------------------------


def node_exprs(node: Node) -> List[ast.expr]:
    """The expressions a node *owns* (excluding nested statement bodies)."""
    s = node.stmt
    if s is None:
        return []
    if isinstance(s, ast.If) or isinstance(s, ast.While):
        return [s.test]
    if isinstance(s, (ast.For, ast.AsyncFor)):
        return [s.iter]
    if isinstance(s, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in s.items]
    if isinstance(s, ast.Try):
        return []
    if isinstance(s, _HEAD_KINDS):  # pragma: no cover — exhaustive above
        return []
    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # Decorators/defaults evaluate at def time; the body is its own CFG.
        return list(s.decorator_list) + [
            d for d in (s.args.defaults + s.args.kw_defaults) if d is not None
        ]
    if isinstance(s, ast.ClassDef):
        return list(s.decorator_list) + list(s.bases)
    out: List[ast.expr] = []
    for child in ast.iter_child_nodes(s):
        if isinstance(child, ast.expr):
            out.append(child)
    return out


def _target_names(target: ast.expr, out: List[str]) -> None:
    if isinstance(target, ast.Name):
        out.append(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            _target_names(elt, out)
    elif isinstance(target, ast.Starred):
        _target_names(target.value, out)
    # Attribute/Subscript targets mutate an object — handled as soft defs.


def _base_name(expr: ast.expr) -> Optional[str]:
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def assigned_names(node: Node) -> Tuple[List[str], List[str]]:
    """(hard defs, soft defs) a node introduces.

    Hard defs rebind a plain name (kill + gen for the solvers); soft defs
    mutate through an attribute/subscript target or augment in place (gen
    without kill).
    """
    s = node.stmt
    hard: List[str] = []
    soft: List[str] = []
    if s is None:
        return hard, soft
    if isinstance(s, ast.Assign):
        for t in s.targets:
            _target_names(t, hard)
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                base = _base_name(t)
                if base:
                    soft.append(base)
    elif isinstance(s, ast.AnnAssign) and s.value is not None:
        _target_names(s.target, hard)
        if isinstance(s.target, (ast.Attribute, ast.Subscript)):
            base = _base_name(s.target)
            if base:
                soft.append(base)
    elif isinstance(s, ast.AugAssign):
        if isinstance(s.target, ast.Name):
            soft.append(s.target.id)
        else:
            base = _base_name(s.target)
            if base:
                soft.append(base)
    elif isinstance(s, (ast.For, ast.AsyncFor)):
        _target_names(s.target, hard)
    elif isinstance(s, (ast.With, ast.AsyncWith)):
        for item in s.items:
            if item.optional_vars is not None:
                _target_names(item.optional_vars, hard)
    elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        hard.append(s.name)
    elif isinstance(s, ast.Import):
        for a in s.names:
            hard.append((a.asname or a.name).split(".")[0])
    elif isinstance(s, ast.ImportFrom):
        for a in s.names:
            hard.append(a.asname or a.name)
    # Walrus targets anywhere in the owned expressions are hard defs too.
    for expr in node_exprs(node):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.NamedExpr) and isinstance(sub.target, ast.Name):
                hard.append(sub.target.id)
    return hard, soft


def deleted_names(node: Node) -> List[str]:
    s = node.stmt
    if isinstance(s, ast.Delete):
        out: List[str] = []
        for t in s.targets:
            _target_names(t, out)
        return out
    return []

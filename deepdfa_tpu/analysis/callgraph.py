"""Whole-program layer for graftlint: import graph, call graph, summaries.

The per-function rules in ``rules.py`` see one CFG at a time; every hazard
the multi-process serving arc is about to create — fork-after-thread,
lock-order inversion across modules, a module global mutated from a pump
thread AND the main path — spans functions. This module lifts the analysis:

* :func:`summarize_module` — one pure, JSON-serializable
  :class:`ModuleSummary` per file (so the incremental cache can persist it
  keyed on the file's content hash): imports, module globals, lock
  definitions (the GL018 provenance facts: module-level and class-body
  ``threading.Lock()``/``RLock()``, plus ``self.x = Lock()`` instance
  locks), and a :class:`FunctionSummary` per function — calls made, locks
  held at each, threads/processes spawned, shared names read/written,
  unbounded joins, and calls that can block forever.
* :class:`Program` — composes the summaries: resolves call sites to
  function ids, memoizes reachability closures, validates lock ids, and
  derives the thread model (every spawn target's closure) and the
  main-path reachability set that ``concurrency.py`` checks GL022–GL025
  against.

Resolution is deliberately conservative — the empty-baseline contract:
a call we cannot attribute (dynamic dispatch, a callable stored in a
variable, ``**kwargs`` trampolines) produces NO edge rather than a guess,
and an acquisition through a lock we cannot identify marks the region
"unknown" (``?``), which suppresses race findings under it instead of
manufacturing them. What IS resolved: bare names to module/nested defs,
``self.meth``/``cls.meth`` to methods of the lexically enclosing class,
absolute dotted names through the per-module import alias tables
(including one re-export hop through package ``__init__`` files), and
``obj.meth`` only when exactly one class in the whole program defines
``meth`` and the name is not in the ubiquitous-method stoplist.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = [
    "ModuleSummary", "FunctionSummary", "Program", "summarize_module",
    "modname_for_path",
]

#: Unidentifiable lock sentinel: a non-call ``with`` context we could not
#: resolve (a local ``lock = Lock()``, an attribute of unknown provenance).
#: Regions under it are *possibly* guarded — GL022 skips writes under it.
UNKNOWN_LOCK = "?"

_LOCK_CONSTRUCTORS = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
}
_THREAD_CTORS = frozenset({"threading.Thread"})
_TPE = "concurrent.futures.ThreadPoolExecutor"
_PPE = "concurrent.futures.ProcessPoolExecutor"
_MP_PROCESS_LEAF = "Process"
_MUTATOR_METHODS = frozenset({
    "append", "extend", "add", "update", "insert", "setdefault", "pop",
    "popitem", "remove", "discard", "clear", "put", "appendleft",
})
#: Child-side fork re-init helpers (``telemetry.context.init_forked_worker``
#: is the repo's blessed shape — GL020 precedent): a fork-class spawn whose
#: target or initializer reaches one is considered re-initialized.
_REINIT_RE = re.compile(r"init_forked|forked_worker|fork_reinit|reinit_fork")
#: ``obj.meth()`` unique-method fallback never fires for these — too many
#: unrelated classes (stdlib included) define them.
_METHOD_STOPLIST = frozenset({
    "get", "put", "set", "close", "run", "start", "join", "wait", "result",
    "submit", "append", "add", "update", "pop", "items", "values", "keys",
    "read", "write", "send", "recv", "open", "clear", "copy", "flush",
    "acquire", "release", "encode", "decode", "format", "strip", "split",
})


def modname_for_path(path: str) -> str:
    """Dotted module name for a (repo-relative) file path.

    ``deepdfa_tpu/telemetry/spans.py`` → ``deepdfa_tpu.telemetry.spans``;
    package ``__init__.py`` files name the package itself. Paths outside
    any package (test fixtures) degrade to their stem — still unique
    within one program, which is all resolution needs.
    """
    norm = path.replace("\\", "/").strip("/")
    if norm.endswith(".py"):
        norm = norm[:-3]
    parts = [p for p in norm.split("/") if p not in ("", ".")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else "<module>"


# ---------------------------------------------------------------------------
# Summary dataclasses (all JSON-round-trippable for the incremental cache)
# ---------------------------------------------------------------------------


def _asdict_list(items: List[Any]) -> List[Dict[str, Any]]:
    return [dataclasses.asdict(i) for i in items]


@dataclasses.dataclass
class CallSite:
    callee: str              # alias-resolved dotted text, or raw expr text
    line: int
    locks: List[str]         # lock-id candidates held lexically at the call
    after_thread_spawn: bool = False


@dataclasses.dataclass
class SpawnSite:
    kind: str                # thread | process | process_pool | fork | popen_preexec
    target: str              # alias-resolved target text ("" when unknown)
    line: int
    locks: List[str]
    start_method: str = ""   # fork | spawn | forkserver | default | unknown
    initializer: str = ""    # process-pool initializer (resolved text)
    after_thread_spawn: bool = False


@dataclasses.dataclass
class LockAcquire:
    lock: str                # lock-id candidate
    line: int
    held: List[str]          # candidates already held when acquiring


@dataclasses.dataclass
class SharedAccess:
    name: str                # shared-id candidate (modname.NAME / modname.Cls.attr)
    line: int
    locks: List[str]
    write: bool


@dataclasses.dataclass
class JoinSite:
    kind: str                # join | result
    receiver: str            # receiver expr text
    target: str              # resolved spawn-target text ("" unknown)
    line: int
    timeout: bool            # a timeout/arg bounds the wait


@dataclasses.dataclass
class BlockingCall:
    what: str                # e.g. ".get()", ".wait()", "serve_forever"
    line: int


@dataclasses.dataclass
class FunctionSummary:
    qualname: str
    line: int
    cls: str = ""            # lexically enclosing class name ("" = free fn)
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    spawns: List[SpawnSite] = dataclasses.field(default_factory=list)
    locks: List[LockAcquire] = dataclasses.field(default_factory=list)
    accesses: List[SharedAccess] = dataclasses.field(default_factory=list)
    joins: List[JoinSite] = dataclasses.field(default_factory=list)
    blocking: List[BlockingCall] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FunctionSummary":
        return cls(
            qualname=d["qualname"], line=d["line"], cls=d.get("cls", ""),
            calls=[CallSite(**c) for c in d.get("calls", [])],
            spawns=[SpawnSite(**s) for s in d.get("spawns", [])],
            locks=[LockAcquire(**a) for a in d.get("locks", [])],
            accesses=[SharedAccess(**a) for a in d.get("accesses", [])],
            joins=[JoinSite(**j) for j in d.get("joins", [])],
            blocking=[BlockingCall(**b) for b in d.get("blocking", [])],
        )


@dataclasses.dataclass
class ModuleSummary:
    path: str
    modname: str
    imports: List[str]                     # dotted candidates this module imports
    aliases: Dict[str, str]                # local name -> dotted (re-export hops)
    module_globals: List[str]
    mutable_globals: List[str]             # globals bound to mutable objects
    module_locks: Dict[str, str]           # name -> Lock | RLock
    classes: List[str]
    class_attrs: Dict[str, List[str]]      # class -> class-body attr names
    class_locks: Dict[str, List[str]]      # class -> lock attrs (class/instance)
    thread_subclasses: List[str]
    class_thread_attrs: Dict[str, Dict[str, str]]  # cls -> attr -> target text
    functions: Dict[str, FunctionSummary]  # qualname -> summary

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["functions"] = {q: fs.to_dict() for q, fs in self.functions.items()}
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModuleSummary":
        return cls(
            path=d["path"], modname=d["modname"],
            imports=list(d.get("imports", [])),
            aliases=dict(d.get("aliases", {})),
            module_globals=list(d.get("module_globals", [])),
            mutable_globals=list(d.get("mutable_globals", [])),
            module_locks=dict(d.get("module_locks", {})),
            classes=list(d.get("classes", [])),
            class_attrs={k: list(v) for k, v in d.get("class_attrs", {}).items()},
            class_locks={k: list(v) for k, v in d.get("class_locks", {}).items()},
            thread_subclasses=list(d.get("thread_subclasses", [])),
            class_thread_attrs={k: dict(v) for k, v
                                in d.get("class_thread_attrs", {}).items()},
            functions={q: FunctionSummary.from_dict(f)
                       for q, f in d.get("functions", {}).items()},
        )


# ---------------------------------------------------------------------------
# Module summarization
# ---------------------------------------------------------------------------


def _dotted(expr: ast.AST) -> Optional[str]:
    """``a.b.c`` attribute chains to text; None for anything else."""
    parts: List[str] = []
    node = expr
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_mutable_init(value: ast.AST) -> bool:
    return isinstance(value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.DictComp, ast.SetComp, ast.Call))


class _ModuleScan:
    """One pass over a module AST building its :class:`ModuleSummary`."""

    def __init__(self, path: str, tree: ast.Module):
        self.path = path.replace("\\", "/")
        self.modname = modname_for_path(path)
        self.is_package = self.path.endswith("__init__.py")
        self.summary = ModuleSummary(
            path=self.path, modname=self.modname, imports=[], aliases={},
            module_globals=[], mutable_globals=[], module_locks={},
            classes=[], class_attrs={}, class_locks={},
            thread_subclasses=[], class_thread_attrs={}, functions={},
        )
        self._scan_imports(tree)
        self._scan_toplevel(tree)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(node, qual=node.name, cls="")
            elif isinstance(node, ast.ClassDef):
                self._scan_class(node)
        self._aggregate_class_facts()

    # -- imports ----------------------------------------------------------

    def _relative_base(self, level: int) -> str:
        parts = self.modname.split(".")
        if not self.is_package:
            parts = parts[:-1]
        up = level - 1
        if up:
            parts = parts[:-up] if up < len(parts) else []
        return ".".join(parts)

    def _scan_imports(self, tree: ast.Module) -> None:
        al = self.summary.aliases
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        al[a.asname] = a.name
                    else:
                        al[a.name.split(".")[0]] = a.name.split(".")[0]
                    self.summary.imports.append(a.name)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    rel = self._relative_base(node.level)
                    base = f"{rel}.{base}".strip(".") if base else rel
                if not base:
                    continue
                self.summary.imports.append(base)
                for a in node.names:
                    if a.name == "*":
                        continue
                    al[a.asname or a.name] = f"{base}.{a.name}"
                    self.summary.imports.append(f"{base}.{a.name}")

    def resolve(self, expr: ast.AST) -> Optional[str]:
        """Alias-resolved dotted text of a Name/Attribute chain."""
        text = _dotted(expr)
        if text is None:
            return None
        head, _, rest = text.partition(".")
        mapped = self.summary.aliases.get(head)
        if mapped:
            return f"{mapped}.{rest}" if rest else mapped
        return text

    # -- module top level -------------------------------------------------

    def _scan_toplevel(self, tree: ast.Module) -> None:
        s = self.summary
        for node in tree.body:
            targets: List[ast.AST] = []
            value: Optional[ast.AST] = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            elif isinstance(node, ast.AugAssign):
                targets, value = [node.target], node.value
            for t in targets:
                names = ([e for e in t.elts if isinstance(e, ast.Name)]
                         if isinstance(t, ast.Tuple) else
                         ([t] if isinstance(t, ast.Name) else []))
                for n in names:
                    if n.id not in s.module_globals:
                        s.module_globals.append(n.id)
                    if value is not None and _is_mutable_init(value) \
                            and n.id not in s.mutable_globals:
                        kind = self._lock_ctor_kind(value)
                        if kind:
                            s.module_locks[n.id] = kind
                        else:
                            s.mutable_globals.append(n.id)

    def _lock_ctor_kind(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            name = self.resolve(value.func)
            if name in _LOCK_CONSTRUCTORS:
                return _LOCK_CONSTRUCTORS[name]
            if name in ("Lock", "RLock"):  # from threading import Lock
                mapped = self.summary.aliases.get(name, "")
                if mapped.startswith("threading."):
                    return name
        return None

    # -- classes ----------------------------------------------------------

    def _scan_class(self, node: ast.ClassDef) -> None:
        s = self.summary
        s.classes.append(node.name)
        attrs: List[str] = []
        locks: List[str] = []
        for b in node.bases:
            base = self.resolve(b)
            if base in _THREAD_CTORS or base == "Thread" and \
                    self.summary.aliases.get("Thread", "").startswith("threading"):
                s.thread_subclasses.append(node.name)
            elif base in s.thread_subclasses:
                s.thread_subclasses.append(node.name)
        for stmt in node.body:
            if isinstance(stmt, ast.Assign):
                value = stmt.value
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        attrs.append(t.id)
                        if self._lock_ctor_kind(value):
                            locks.append(t.id)
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name) and stmt.value is not None:
                attrs.append(stmt.target.id)
                if self._lock_ctor_kind(stmt.value):
                    locks.append(stmt.target.id)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(stmt, qual=f"{node.name}.{stmt.name}",
                                    cls=node.name)
        s.class_attrs[node.name] = attrs
        if locks:
            s.class_locks[node.name] = locks

    def _aggregate_class_facts(self) -> None:
        """Fold instance-lock and thread-attr binds out of method bodies
        into class-level maps (``self._lock = Lock()`` in ``__init__`` is
        the GL018-exempt idiom; ``self._t = Thread(target=...)`` is how the
        checkpoint writer binds its thread)."""
        s = self.summary
        for fs in s.functions.values():
            if not fs.cls:
                continue
            for recv, kind in getattr(fs, "_lock_binds", []):
                if recv.startswith("self."):
                    attr = recv[5:]
                    s.class_locks.setdefault(fs.cls, [])
                    if attr not in s.class_locks[fs.cls]:
                        s.class_locks[fs.cls].append(attr)
            for recv, target in getattr(fs, "_thread_binds", []):
                if recv.startswith("self."):
                    s.class_thread_attrs.setdefault(fs.cls, {})[recv[5:]] = \
                        target

    # -- functions --------------------------------------------------------

    def _scan_function(self, node: ast.AST, qual: str, cls: str) -> None:
        # nested defs are summarized by _FunctionScanNested as the body scan
        # reaches them, each under its dotted qualname.
        self.summary.functions[qual] = _FunctionScan(self, node, qual,
                                                     cls).run()


class _FunctionScan:
    """Summarize one function body; nested defs get their own summaries."""

    def __init__(self, mod: _ModuleScan, node: ast.AST, qual: str, cls: str):
        self.mod = mod
        self.node = node
        self.fs = FunctionSummary(qualname=qual, line=node.lineno, cls=cls)
        self.qual = qual
        self.cls = cls
        self.global_decls: Set[str] = set()
        self.local_names: Set[str] = set()
        self.pools: Dict[str, str] = {}        # var -> thread | process
        self.ctx_methods: Dict[str, str] = {}  # var -> fork | spawn | ...
        self.thread_vars: Dict[str, str] = {}  # var/attr text -> target text
        self.future_vars: Dict[str, str] = {}  # var -> submitted target text
        self.future_lists: Dict[str, str] = {} # list var -> submitted target
        self.thread_lists: Dict[str, str] = {} # list var -> thread target
        self.killed: Set[str] = set()          # receivers .kill()/.terminate()d
        self._lock_binds: List[Tuple[str, str]] = []
        self._thread_binds: List[Tuple[str, str]] = []
        self._prescan()

    def _prescan(self) -> None:
        args = self.node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            self.local_names.add(a.arg)
        for n in _walk_skip_nested(self.node):
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                self.global_decls.update(n.names)
            elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                self.local_names.add(n.id)
        self.local_names -= self.global_decls

    def run(self) -> FunctionSummary:
        self._visit_block(self.node.body, held=())
        first = min((s.line for s in self.fs.spawns if s.kind == "thread"),
                    default=None)
        if first is not None:
            for c in self.fs.calls:
                c.after_thread_spawn = c.line > first
            for s in self.fs.spawns:
                s.after_thread_spawn = s.line > first
        # expose binds to the module aggregation pass
        self.fs._lock_binds = self._lock_binds      # type: ignore[attr-defined]
        self.fs._thread_binds = self._thread_binds  # type: ignore[attr-defined]
        return self.fs

    # -- statements -------------------------------------------------------

    def _visit_block(self, stmts: List[ast.stmt], held: Tuple[str, ...]) -> None:
        for stmt in stmts:
            self._visit_stmt(stmt, held)

    def _visit_stmt(self, stmt: ast.stmt, held: Tuple[str, ...]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _FunctionScanNested(self.mod, stmt, f"{self.qual}.{stmt.name}",
                                self.cls)
            return
        if isinstance(stmt, ast.ClassDef):
            return  # function-local classes: out of model
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._visit_assign(stmt, held)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._visit_with(stmt, held)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter, held)
            self._map_loop_target(stmt)
            self._visit_block(stmt.body, held)
            self._visit_block(stmt.orelse, held)
            return
        if isinstance(stmt, ast.While):
            self._scan_expr(stmt.test, held)
            self._visit_block(stmt.body, held)
            self._visit_block(stmt.orelse, held)
            return
        if isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, held)
            self._visit_block(stmt.body, held)
            self._visit_block(stmt.orelse, held)
            return
        if isinstance(stmt, ast.Try):
            self._visit_block(stmt.body, held)
            for h in stmt.handlers:
                self._visit_block(h.body, held)
            self._visit_block(stmt.orelse, held)
            self._visit_block(stmt.finalbody, held)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._scan_expr(child, held)

    def _visit_assign(self, stmt: ast.stmt, held: Tuple[str, ...]) -> None:
        value = getattr(stmt, "value", None)
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        flat: List[ast.AST] = []
        for t in targets:
            flat.extend(t.elts if isinstance(t, ast.Tuple) else [t])
        if value is not None:
            self._bind_provenance(flat, value)
            self._scan_expr(value, held)
        for t in flat:
            if isinstance(t, ast.Name):
                name = t.id
                if name in self.global_decls or (
                        isinstance(stmt, ast.AugAssign)
                        and name not in self.local_names
                        and name in self.mod.summary.module_globals):
                    self._record_write(self._global_id(name), t.lineno, held)
            elif isinstance(t, ast.Subscript):
                self._record_container_write(t.value, t.lineno, held)
            # plain attribute stores (obj.x = v) rebind per-object state —
            # not shared-by-class/module state; out of model.

    def _bind_provenance(self, targets: List[ast.AST], value: ast.AST) -> None:
        """Track what a binding makes of its name: a thread, a pool, a
        future, an mp context, a list of threads/futures."""
        recvs = []
        for t in targets:
            text = _dotted(t)
            if text:
                recvs.append(text)
        if not recvs:
            return
        info = self._classify_value(value)
        if info is None:
            return
        kind, payload = info
        for recv in recvs:
            if kind == "thread":
                self.thread_vars[recv] = payload
                self._thread_binds.append((recv, payload))
            elif kind == "lock":
                self._lock_binds.append((recv, payload))
            elif kind == "pool":
                self.pools[recv] = payload
            elif kind == "ctx":
                self.ctx_methods[recv] = payload
            elif kind == "future":
                self.future_vars[recv] = payload
            elif kind == "future_list":
                self.future_lists[recv] = payload
            elif kind == "thread_list":
                self.thread_lists[recv] = payload

    def _classify_value(self, value: ast.AST) -> Optional[Tuple[str, str]]:
        if isinstance(value, ast.Call):
            name = self.resolve_call_name(value.func)
            if name in _THREAD_CTORS:
                return ("thread", self._thread_target(value))
            if name and self._thread_subclass(name):
                return ("thread", f"{name}.run")
            if self.mod._lock_ctor_kind(value):
                return ("lock", "")
            if name == _TPE or (name or "").endswith("ThreadPoolExecutor"):
                return ("pool", "thread")
            if name == _PPE or (name or "").endswith("ProcessPoolExecutor"):
                return ("pool", "process")
            if name and name.endswith(".get_context") and value.args and \
                    isinstance(value.args[0], ast.Constant):
                return ("ctx", str(value.args[0].value))
            if isinstance(value.func, ast.Attribute) and \
                    value.func.attr == "submit":
                base = _dotted(value.func.value)
                if base in self.pools and value.args:
                    tgt = self.resolve_call_name(value.args[0]) or ""
                    return ("future", tgt)
        elif isinstance(value, ast.ListComp):
            elt = value.elt
            if isinstance(elt, ast.Call):
                info = self._classify_value(elt)
                if info and info[0] == "future":
                    return ("future_list", info[1])
                if info and info[0] == "thread":
                    return ("thread_list", info[1])
        elif isinstance(value, ast.List):
            for elt in value.elts:
                if isinstance(elt, ast.Call):
                    info = self._classify_value(elt)
                    if info and info[0] == "thread":
                        return ("thread_list", info[1])
        return None

    def _map_loop_target(self, stmt: ast.stmt) -> None:
        it = _dotted(stmt.iter) if isinstance(stmt.iter, (ast.Name, ast.Attribute)) else None
        tgt = stmt.target
        if it is None or not isinstance(tgt, ast.Name):
            return
        if it in self.future_lists:
            self.future_vars[tgt.id] = self.future_lists[it]
        elif it in self.thread_lists:
            self.thread_vars[tgt.id] = self.thread_lists[it]

    def _visit_with(self, stmt: ast.stmt, held: Tuple[str, ...]) -> None:
        new_held = list(held)
        for item in stmt.items:
            ctx = item.context_expr
            if isinstance(ctx, ast.Call):
                # not a lock (span(), open(), pool ctor, ...) — but the
                # expression itself may spawn/bind (with PPE(...) as pool:)
                self._scan_expr(ctx, tuple(new_held))
                if item.optional_vars is not None:
                    self._bind_provenance(
                        [item.optional_vars] if not isinstance(
                            item.optional_vars, ast.Tuple)
                        else list(item.optional_vars.elts), ctx)
            elif isinstance(ctx, (ast.Name, ast.Attribute)):
                lock_id = self._lock_id(ctx)
                self.fs.locks.append(LockAcquire(
                    lock=lock_id, line=ctx.lineno, held=list(new_held)))
                new_held.append(lock_id)
            else:
                self._scan_expr(ctx, tuple(new_held))
        self._visit_block(stmt.body, tuple(new_held))

    # -- lock / shared-name identity --------------------------------------

    def _global_id(self, name: str) -> str:
        return f"{self.mod.modname}.{name}"

    def _lock_id(self, expr: ast.AST) -> str:
        s = self.mod.summary
        text = _dotted(expr)
        if text is None:
            return UNKNOWN_LOCK
        if "." not in text:
            if text in s.module_locks:
                return f"{s.modname}.{text}"
            mapped = s.aliases.get(text)
            if mapped and "." in mapped:
                return mapped  # cross-module import; validated in Program
            return UNKNOWN_LOCK
        head, _, attr = text.partition(".")
        if head in ("self", "cls") and self.cls and "." not in attr:
            if attr in s.class_locks.get(self.cls, ()):
                return f"{s.modname}.{self.cls}.{attr}"
            # unresolved instance attr: possibly a lock bound elsewhere
            return UNKNOWN_LOCK
        resolved = self.mod.resolve(expr)
        return resolved if resolved and "." in resolved else UNKNOWN_LOCK

    def resolve_call_name(self, func: ast.AST) -> Optional[str]:
        """Resolved dotted text for a callee; self./cls. kept as prefix."""
        text = _dotted(func)
        if text is None:
            return None
        if text.startswith("self.") or text.startswith("cls."):
            return text
        return self.mod.resolve(func) or text

    # -- expressions ------------------------------------------------------

    def _scan_expr(self, expr: ast.AST, held: Tuple[str, ...]) -> None:
        for node in _walk_expr(expr):
            if isinstance(node, ast.Call):
                self._visit_call(node, held)
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in self.mod.summary.mutable_globals and \
                        node.id not in self.local_names:
                    self.fs.accesses.append(SharedAccess(
                        name=self._global_id(node.id), line=node.lineno,
                        locks=list(held), write=False))

    def _visit_call(self, call: ast.Call, held: Tuple[str, ...]) -> None:
        func = call.func
        name = self.resolve_call_name(func)
        attr = func.attr if isinstance(func, ast.Attribute) else None
        recv_text = _dotted(func.value) if isinstance(func, ast.Attribute) else None

        if self._spawn_site(call, name, attr, recv_text, held):
            return
        if attr in ("kill", "terminate") and recv_text:
            self.killed.add(recv_text)
        if attr == "join" and recv_text is not None:
            self._join_site(call, recv_text, held)
        elif attr == "result" and recv_text is not None:
            tgt = self.future_vars.get(recv_text)
            if tgt is not None:
                self.fs.joins.append(JoinSite(
                    kind="result", receiver=recv_text, target=tgt,
                    line=call.lineno, timeout=_has_timeout(call)))
        if attr in ("get", "wait") and not call.args and \
                not _has_timeout(call):
            self.fs.blocking.append(BlockingCall(
                what=f".{attr}()", line=call.lineno))
        elif attr == "serve_forever":
            self.fs.blocking.append(BlockingCall(
                what="serve_forever", line=call.lineno))

        if attr in _MUTATOR_METHODS and recv_text:
            self._mutation_site(recv_text, call.lineno, held)

        if name:
            self.fs.calls.append(CallSite(
                callee=name, line=call.lineno, locks=list(held)))

    def _spawn_site(self, call: ast.Call, name: Optional[str],
                    attr: Optional[str], recv_text: Optional[str],
                    held: Tuple[str, ...]) -> bool:
        if name in _THREAD_CTORS or (name and self._thread_subclass(name)):
            target = (self._thread_target(call) if name in _THREAD_CTORS
                      else f"{name}.run")
            self.fs.spawns.append(SpawnSite(
                kind="thread", target=target, line=call.lineno,
                locks=list(held)))
            return True
        if name == _PPE or (name or "").endswith("ProcessPoolExecutor"):
            self.fs.spawns.append(SpawnSite(
                kind="process_pool", target="", line=call.lineno,
                locks=list(held),
                start_method=self._pool_start_method(call),
                initializer=self._kw_name(call, "initializer")))
            return True
        if name == "os.fork":
            self.fs.spawns.append(SpawnSite(
                kind="fork", target="", line=call.lineno, locks=list(held),
                start_method="fork"))
            return True
        if attr == _MP_PROCESS_LEAF and recv_text:
            method = ""
            if recv_text in self.ctx_methods:
                method = self.ctx_methods[recv_text]
            elif name in ("multiprocessing.Process",):
                method = "default"
            if method:
                self.fs.spawns.append(SpawnSite(
                    kind="process", target=self._thread_target(call),
                    line=call.lineno, locks=list(held), start_method=method))
                return True
        if name == "subprocess.Popen" or (name or "").endswith(".Popen") \
                or name == "Popen":
            pre = self._kw_name(call, "preexec_fn")
            if pre and pre != "None":
                self.fs.spawns.append(SpawnSite(
                    kind="popen_preexec", target=pre, line=call.lineno,
                    locks=list(held), start_method="fork"))
                return True
            return False  # plain Popen: fork+exec, out of the fork model
        if attr == "submit" and recv_text in self.pools and call.args:
            tgt = self.resolve_call_name(call.args[0]) or ""
            kind = self.pools[recv_text]
            self.fs.spawns.append(SpawnSite(
                kind="thread" if kind == "thread" else "pool_submit",
                target=tgt, line=call.lineno, locks=list(held)))
            return False  # submit is also a call-shaped fact; keep scanning
        return False

    def _join_site(self, call: ast.Call, recv_text: str,
                   held: Tuple[str, ...]) -> None:
        target = self.thread_vars.get(recv_text)
        if target is None and recv_text.startswith("self.") and self.cls:
            target = self.mod.summary.class_thread_attrs.get(
                self.cls, {}).get(recv_text[5:])
        if target is None:
            # look ahead: binds recorded later in the module pass (a join
            # in close() on a thread bound in __init__) resolve during the
            # program phase through class_thread_attrs; locals only here.
            return
        if recv_text in self.killed:
            return  # kill-then-join is the bounded GL015 shape
        self.fs.joins.append(JoinSite(
            kind="join", receiver=recv_text, target=target,
            line=call.lineno, timeout=_has_timeout(call)))

    def _mutation_site(self, recv_text: str, line: int,
                       held: Tuple[str, ...]) -> None:
        s = self.mod.summary
        if "." not in recv_text:
            if recv_text in s.module_globals and \
                    recv_text not in self.local_names:
                self._record_write(self._global_id(recv_text), line, held)
            return
        head, _, attr = recv_text.partition(".")
        if head in ("self", "cls") and self.cls and "." not in attr:
            if attr in s.class_attrs.get(self.cls, ()):
                self._record_write(f"{s.modname}.{self.cls}.{attr}",
                                   line, held)
            return
        mapped = s.aliases.get(head)
        if mapped and "." not in attr.partition(".")[2]:
            self._record_write(f"{mapped}.{attr}", line, held)

    def _record_container_write(self, base: ast.AST, line: int,
                                held: Tuple[str, ...]) -> None:
        text = _dotted(base)
        if text:
            self._mutation_site_for_subscript(text, line, held)

    def _mutation_site_for_subscript(self, text: str, line: int,
                                     held: Tuple[str, ...]) -> None:
        s = self.mod.summary
        if "." not in text:
            if text in s.module_globals and text not in self.local_names:
                self._record_write(self._global_id(text), line, held)
            return
        self._mutation_site(text, line, held)

    def _record_write(self, shared_id: str, line: int,
                      held: Tuple[str, ...]) -> None:
        self.fs.accesses.append(SharedAccess(
            name=shared_id, line=line, locks=list(held), write=True))

    # -- helpers ----------------------------------------------------------

    def _thread_subclass(self, name: str) -> bool:
        s = self.mod.summary
        leaf = name.rsplit(".", 1)[-1]
        return leaf in s.thread_subclasses and (
            "." not in name or name == f"{s.modname}.{leaf}" or name == leaf)

    def _thread_target(self, call: ast.Call) -> str:
        for kw in call.keywords:
            if kw.arg == "target":
                return self.resolve_call_name(kw.value) or ""
        return ""

    def _pool_start_method(self, call: ast.Call) -> str:
        for kw in call.keywords:
            if kw.arg == "mp_context":
                v = kw.value
                if isinstance(v, ast.Call) and v.args and \
                        isinstance(v.args[0], ast.Constant):
                    return str(v.args[0].value)
                text = _dotted(v)
                if text and text in self.ctx_methods:
                    return self.ctx_methods[text]
                return "unknown"
        return "default"

    def _kw_name(self, call: ast.Call, kw_name: str) -> str:
        for kw in call.keywords:
            if kw.arg == kw_name:
                if isinstance(kw.value, ast.Constant):
                    return str(kw.value.value)
                return self.resolve_call_name(kw.value) or ""
        return ""


class _FunctionScanNested(_FunctionScan):
    """Nested def: summarize into the module like any other function."""

    def __init__(self, mod: _ModuleScan, node: ast.AST, qual: str, cls: str):
        super().__init__(mod, node, qual, cls)
        mod.summary.functions[qual] = self.run()


def _walk_skip_nested(func_node: ast.AST):
    """Walk a function body without descending into nested defs/classes."""
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _walk_expr(expr: ast.AST):
    """Walk an expression tree, skipping Lambda bodies (deferred code)."""
    stack = [expr]
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _has_timeout(call: ast.Call) -> bool:
    if call.args:
        return True
    return any(kw.arg == "timeout" and not (
        isinstance(kw.value, ast.Constant) and kw.value.value is None)
        for kw in call.keywords)


def summarize_module(path: str, source: str) -> Optional[ModuleSummary]:
    """Concurrency summary of one file; None when it does not parse
    (rules.py already reports GL000 for that)."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    return _ModuleScan(path, tree).summary


# ---------------------------------------------------------------------------
# Program: composition + resolution + closures
# ---------------------------------------------------------------------------


class Program:
    """All module summaries composed into one resolvable call graph."""

    def __init__(self, modules: List[ModuleSummary]):
        self.modules: Dict[str, ModuleSummary] = {
            m.modname: m for m in sorted(modules, key=lambda m: m.path)}
        self.by_path: Dict[str, ModuleSummary] = {
            m.path: m for m in self.modules.values()}
        self.functions: Dict[str, Tuple[ModuleSummary, FunctionSummary]] = {}
        self._method_index: Dict[str, List[str]] = {}
        for m in self.modules.values():
            for q, fs in m.functions.items():
                fid = f"{m.modname}:{q}"
                self.functions[fid] = (m, fs)
                leaf = q.rsplit(".", 1)[-1]
                if fs.cls:
                    self._method_index.setdefault(leaf, []).append(fid)
        self._edges: Dict[str, List[str]] = {}
        self._closures: Dict[str, Set[str]] = {}
        self._lock_kinds: Dict[str, str] = {}
        for m in self.modules.values():
            for name, kind in m.module_locks.items():
                self._lock_kinds[f"{m.modname}.{name}"] = kind
            for cls, attrs in m.class_locks.items():
                for a in attrs:
                    self._lock_kinds[f"{m.modname}.{cls}.{a}"] = "Lock"
        self._lock_alias_cache: Dict[str, Optional[str]] = {}

    # -- import graph ------------------------------------------------------

    def importers_of(self, path: str) -> Set[str]:
        """Paths of modules that import the module at ``path`` (direct
        reverse edges — what an incremental edit must re-analyze)."""
        target = self.by_path.get(path)
        if target is None:
            return set()
        out: Set[str] = set()
        name = target.modname
        for m in self.modules.values():
            if m.path == path:
                continue
            for imp in m.imports:
                if imp == name or imp.startswith(name + "."):
                    out.add(m.path)
                    break
        return out

    # -- lock identity -----------------------------------------------------

    def lock_id(self, candidate: str) -> Optional[str]:
        """Validate a summarize-time lock candidate against known lock
        definitions, following one import/re-export alias hop. None for
        candidates that name no known lock (``?`` stays ``?``-like)."""
        if candidate == UNKNOWN_LOCK:
            return None
        if candidate in self._lock_alias_cache:
            return self._lock_alias_cache[candidate]
        result: Optional[str] = None
        if candidate in self._lock_kinds:
            result = candidate
        else:
            resolved = self._resolve_dotted_value(candidate)
            if resolved in self._lock_kinds:
                result = resolved
        self._lock_alias_cache[candidate] = result
        return result

    def lock_kind(self, lock_id: str) -> str:
        return self._lock_kinds.get(lock_id, "Lock")

    def held_locks(self, candidates: List[str]) -> Tuple[Set[str], bool]:
        """(validated lock ids, had_unknown) for a held-candidates list."""
        out: Set[str] = set()
        unknown = False
        for c in candidates:
            if c == UNKNOWN_LOCK:
                unknown = True
                continue
            lid = self.lock_id(c)
            if lid:
                out.add(lid)
            else:
                unknown = True
        return out, unknown

    def _resolve_dotted_value(self, dotted: str, hops: int = 3) -> Optional[str]:
        """Resolve ``pkg.sub.NAME`` through module membership and package
        ``__init__`` re-export aliases to its defining module's id."""
        for _ in range(hops):
            mod, leaf = self._split_known_module(dotted)
            if mod is None:
                return None
            if leaf in mod.module_locks or leaf in mod.module_globals:
                return f"{mod.modname}.{leaf}"
            mapped = mod.aliases.get(leaf)
            if mapped is None or mapped == dotted:
                return None
            dotted = mapped
        return None

    def _split_known_module(
            self, dotted: str) -> Tuple[Optional[ModuleSummary], str]:
        """Longest known-module prefix of ``dotted``; (module, rest)."""
        parts = dotted.split(".")
        for i in range(len(parts) - 1, 0, -1):
            name = ".".join(parts[:i])
            if name in self.modules:
                return self.modules[name], ".".join(parts[i:])
        return None, dotted

    # -- shared-id identity ------------------------------------------------

    def shared_id(self, candidate: str) -> Optional[str]:
        """Validate a shared-name candidate (modname.NAME or
        modname.Cls.attr) against known module globals / class attrs,
        following re-export hops for cross-module mutations."""
        mod, rest = self._split_known_module(candidate)
        if mod is None:
            return None
        if "." not in rest:
            if rest in mod.module_globals:
                return f"{mod.modname}.{rest}"
            mapped = mod.aliases.get(rest)
            if mapped:
                resolved = self._resolve_dotted_value(mapped)
                return self.shared_id(resolved) if resolved else None
            return None
        cls, _, attr = rest.partition(".")
        if attr in mod.class_attrs.get(cls, ()):
            return f"{mod.modname}.{cls}.{attr}"
        return None

    # -- call resolution ---------------------------------------------------

    def resolve_callee(self, mod: ModuleSummary, fs: FunctionSummary,
                       raw: str) -> Optional[str]:
        if not raw:
            return None
        if raw.startswith("self.") or raw.startswith("cls."):
            attr = raw.split(".", 1)[1]
            if "." in attr or not fs.cls:
                return None
            fid = f"{mod.modname}:{fs.cls}.{attr}"
            return fid if fid in self.functions else None
        if "." not in raw:
            # innermost lexical scope first: own nested defs, then sibling
            # nested defs up the enclosing-function chain, then module level
            scope = fs.qualname.split(".")
            for depth in range(len(scope), -1, -1):
                if fs.cls and depth == 1:
                    continue  # class bodies are not an enclosing scope
                prefix = ".".join(scope[:depth] + [raw])
                fid = f"{mod.modname}:{prefix}"
                if fid in self.functions:
                    return fid
            if raw in mod.classes:
                init = f"{mod.modname}:{raw}.__init__"
                return init if init in self.functions else None
            return None
        known, rest = self._split_known_module(raw)
        if known is not None:
            return self._resolve_in_module(known, rest)
        # obj.meth(): unique-method fallback, stoplisted
        leaf = raw.rsplit(".", 1)[-1]
        if leaf in _METHOD_STOPLIST or leaf.startswith("__"):
            return None
        cands = self._method_index.get(leaf, [])
        return cands[0] if len(cands) == 1 else None

    def _resolve_in_module(self, mod: ModuleSummary,
                           rest: str, hops: int = 3) -> Optional[str]:
        if not rest:
            return None
        fid = f"{mod.modname}:{rest}"
        if fid in self.functions:
            return fid
        head = rest.split(".")[0]
        if head in mod.classes:
            if "." not in rest:
                init = f"{mod.modname}:{rest}.__init__"
                return init if init in self.functions else None
            return None
        mapped = mod.aliases.get(head)
        if mapped and hops > 0:
            full = mapped + rest[len(head):]
            known, new_rest = self._split_known_module(full)
            if known is not None:
                return self._resolve_in_module(known, new_rest, hops - 1)
        return None

    def edges_of(self, fid: str) -> List[str]:
        if fid in self._edges:
            return self._edges[fid]
        mod, fs = self.functions[fid]
        out: List[str] = []
        seen: Set[str] = set()
        for c in fs.calls:
            r = self.resolve_callee(mod, fs, c.callee)
            if r and r not in seen:
                seen.add(r)
                out.append(r)
        self._edges[fid] = out
        return out

    def closure(self, fid: str) -> Set[str]:
        """All functions reachable from ``fid`` through resolved calls,
        including itself."""
        if fid in self._closures:
            return self._closures[fid]
        result: Set[str] = set()
        stack = [fid]
        while stack:
            cur = stack.pop()
            if cur in result or cur not in self.functions:
                continue
            result.add(cur)
            stack.extend(self.edges_of(cur))
        self._closures[fid] = result
        return result

    # -- thread / main models ---------------------------------------------

    def resolve_spawn_target(self, mod: ModuleSummary, fs: FunctionSummary,
                             spawn: SpawnSite) -> Optional[str]:
        return self.resolve_callee(mod, fs, spawn.target)

    def thread_entries(self) -> List[Tuple[str, str, SpawnSite, str]]:
        """Every resolvable thread spawn: (entry_fid, spawner_fid, site,
        description)."""
        out = []
        for fid, (mod, fs) in sorted(self.functions.items()):
            for s in fs.spawns:
                if s.kind != "thread":
                    continue
                entry = self.resolve_spawn_target(mod, fs, s)
                if entry:
                    desc = f"{mod.path}:{s.line}"
                    out.append((entry, fid, s, desc))
        return out

    def main_reachable(self) -> Set[str]:
        """Functions reachable without passing through a thread target:
        the 'main path'. Seed = every function that is not inside any
        thread entry's closure; the closure of the seed adds the shared
        helpers both worlds call."""
        in_thread: Set[str] = set()
        for entry, _, _, _ in self.thread_entries():
            in_thread |= self.closure(entry)
        seed = [fid for fid in self.functions if fid not in in_thread]
        out: Set[str] = set()
        for fid in seed:
            out |= self.closure(fid)
        return out

    # -- derived facts for the rules --------------------------------------

    def closure_locks(self, fid: str) -> Set[str]:
        """Validated lock ids acquired anywhere in ``fid``'s closure."""
        out: Set[str] = set()
        for f in self.closure(fid):
            _, fs = self.functions[f]
            for la in fs.locks:
                lid = self.lock_id(la.lock)
                if lid:
                    out.add(lid)
        return out

    def closure_blocks_forever(self, fid: str) -> Optional[str]:
        """A 'can block forever' witness in ``fid``'s closure, or None."""
        for f in sorted(self.closure(fid)):
            mod, fs = self.functions[f]
            if fs.blocking:
                b = fs.blocking[0]
                return f"{b.what} at {mod.path}:{b.line} in {fs.qualname}"
        return None

    def closure_spawns_thread(self, fid: str) -> bool:
        return any(s.kind == "thread"
                   for f in self.closure(fid)
                   for s in self.functions[f][1].spawns)

    def calls_reinit_helper(self, fid: Optional[str]) -> bool:
        """Does the closure of ``fid`` call a fork re-init helper
        (``init_forked_worker``-shaped name)?"""
        if fid is None:
            return False
        for f in self.closure(fid):
            _, fs = self.functions[f]
            for c in fs.calls:
                if _REINIT_RE.search(c.callee.rsplit(".", 1)[-1]):
                    return True
        return False

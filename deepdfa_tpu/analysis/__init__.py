"""graftlint: dataflow-analysis-based static checking for JAX/TPU hazards.

The paper this repo reproduces trains models to *emulate* dataflow analysis;
this package runs the real thing over our own sources. A reaching-definitions
/ taint solver (``dataflow.py``) over intra-procedural CFGs (``cfg.py``)
drives hazard rules (``rules.py``) for the failure modes that cost TPU runs:
silent host-device syncs in jitted or step-loop code, tracer leaks into
Python control flow, recompilation triggers, impurity under ``jit``, and
``jax.random`` key reuse. On top, a whole-program layer (``callgraph.py``
summaries composed into a call graph, ``concurrency.py`` rules) checks the
concurrency hazards no per-function view can see: cross-thread races on
module globals, lock-order inversion cycles, fork-after-thread spawns, and
unbounded joins on targets that can block forever. ``runner.py`` walks the
package (with an optional content-hash incremental cache), diffs against a
committed baseline, and reports only new findings with the def-use chain
that triggered each one.

Entry points: ``python -m deepdfa_tpu.cli analyze-code`` / ``scripts/lint.sh``.
Everything here is stdlib-only (``ast``) — no jax import, so the linter runs
anywhere in milliseconds.
"""

from deepdfa_tpu.analysis.rules import Finding, analyze_source  # noqa: F401
from deepdfa_tpu.analysis.runner import analyze_files, run_analysis  # noqa: F401

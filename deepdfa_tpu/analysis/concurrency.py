"""GL022–GL025: interprocedural concurrency rules over the Program model.

Consumes :class:`~deepdfa_tpu.analysis.callgraph.Program` (per-function
summaries composed into a call graph) and emits the same
:class:`~deepdfa_tpu.analysis.rules.Finding` records the intraprocedural
rules do, so the baseline diff, the CLI, and SARIF export are unchanged.

The four hazards — each one a failure mode the multi-process serving arc
walks straight into:

* **GL022 unguarded-shared-mutation-across-threads** — a module global or
  class-body attribute written from two execution contexts (at least one a
  spawned-thread closure) with no common lock across all writes. Shared
  state is module globals and class-body attrs ONLY: instance attributes
  and locals are per-object/per-frame, and flagging them would trade the
  empty-baseline contract for noise. A write under an *unidentifiable*
  lock (``with lock:`` on a local or unknown attr) marks the name
  possibly-guarded and suppresses the finding — precision over recall.
* **GL023 lock-order-inversion** — a cycle in the interprocedural lock
  acquisition graph: edges from lexically nested ``with`` regions plus
  edges from locks acquired anywhere in the closure of a call made while
  a lock is held. Same-lock re-entry is a different hazard (and fine for
  RLock) — self-edges are excluded.
* **GL024 fork-unsafe-spawn** — a fork-class spawn (``os.fork``,
  fork/default-method ``multiprocessing``, ``Popen(preexec_fn=...)``)
  reachable after a thread exists, or while a known lock is held (the
  child inherits the locked lock). Plain ``Popen`` is exempt (fork+exec
  resets the child); ``spawn``/``forkserver`` start methods are exempt;
  and a child entry or pool initializer whose closure calls a
  ``init_forked_worker``-shaped re-init helper is the repo's blessed
  shape (GL020 precedent) and is exempt.
* **GL025 blocking-join-on-main-path** — an unbounded ``.join()`` /
  ``.result()`` on a thread or future whose target's reachable closure
  can block forever (a no-timeout ``.get()``/``.wait()``,
  ``serve_forever``). A timeout argument, a kill-then-join sequence, or
  a target with no blocking witness all stay unflagged.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from deepdfa_tpu.analysis.callgraph import (
    _REINIT_RE, FunctionSummary, ModuleSummary, Program,
)
from deepdfa_tpu.analysis.rules import Finding

__all__ = ["analyze_concurrency"]

_FORK_KINDS = frozenset({"fork", "process", "process_pool", "popen_preexec"})
_SAFE_START_METHODS = frozenset({"spawn", "forkserver"})


def _mk(rule: str, mod: ModuleSummary, fs: FunctionSummary, line: int,
        message: str, trace: Tuple[str, ...],
        line_lookup) -> Finding:
    return Finding(
        rule=rule, path=mod.path, line=line, col=0,
        function=fs.qualname, message=message, trace=trace,
        source_line=line_lookup(mod.path, line))


def analyze_concurrency(program: Program, line_lookup) -> List[Finding]:
    """All GL022–GL025 findings for one composed program.

    ``line_lookup(path, lineno) -> str`` supplies the source line for the
    fingerprint (the runner reads files lazily; fixtures pass a dict-backed
    lookup).
    """
    findings: List[Finding] = []
    findings.extend(_check_shared_mutation(program, line_lookup))
    findings.extend(_check_lock_order(program, line_lookup))
    findings.extend(_check_fork_safety(program, line_lookup))
    findings.extend(_check_blocking_joins(program, line_lookup))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


# ---------------------------------------------------------------------------
# GL022: unguarded shared mutation across threads
# ---------------------------------------------------------------------------


def _check_shared_mutation(program: Program, line_lookup) -> List[Finding]:
    entries = program.thread_entries()
    thread_members: Dict[str, List[Tuple[str, str]]] = {}  # fid -> [(entry, where)]
    for entry, _spawner, _site, desc in entries:
        for fid in program.closure(entry):
            thread_members.setdefault(fid, []).append((entry, desc))
    main = program.main_reachable()

    # shared id -> write records (contexts, validated locks, unknown?, site)
    writes: Dict[str, List[dict]] = {}
    for fid, (mod, fs) in sorted(program.functions.items()):
        contexts: Set[str] = set()
        for entry, desc in thread_members.get(fid, ()):
            contexts.add(f"thread {entry.split(':', 1)[1]} (spawned {desc})")
        if fid in main:
            contexts.add("main path")
        if not contexts:
            continue
        for acc in fs.accesses:
            if not acc.write:
                continue
            sid = program.shared_id(acc.name)
            if sid is None:
                continue
            locks, unknown = program.held_locks(acc.locks)
            writes.setdefault(sid, []).append({
                "contexts": contexts, "locks": locks, "unknown": unknown,
                "mod": mod, "fs": fs, "line": acc.line,
                "in_thread": fid in thread_members,
            })

    findings: List[Finding] = []
    for sid in sorted(writes):
        recs = writes[sid]
        all_contexts: Set[str] = set()
        for r in recs:
            all_contexts |= r["contexts"]
        thread_ctx = sorted(c for c in all_contexts if c != "main path")
        if len(all_contexts) < 2 or not thread_ctx:
            continue
        if any(r["unknown"] for r in recs):
            continue  # possibly guarded by a lock we cannot identify
        common = set.intersection(*(r["locks"] for r in recs))
        if common:
            continue
        site = min((r for r in recs if r["in_thread"]), default=recs[0],
                   key=lambda r: (r["mod"].path, r["line"]))
        others = [f"{r['mod'].path}:{r['line']}" for r in recs
                  if r is not site]
        trace = tuple(
            [f"contexts writing {sid}: " + "; ".join(sorted(all_contexts))]
            + ([f"other write sites: {', '.join(others)}"] if others else []))
        findings.append(_mk(
            "GL022", site["mod"], site["fs"], site["line"],
            f"shared name {sid} is written from "
            f"{len(all_contexts)} execution contexts "
            f"({len(thread_ctx)} thread) with no common lock",
            trace, line_lookup))
    return findings


# ---------------------------------------------------------------------------
# GL023: lock-order inversion
# ---------------------------------------------------------------------------


def _check_lock_order(program: Program, line_lookup) -> List[Finding]:
    # edge (A, B): A held while B acquired; keep the first witness site
    edges: Dict[Tuple[str, str], dict] = {}

    def add_edge(a: str, b: str, mod: ModuleSummary, fs: FunctionSummary,
                 line: int, note: str) -> None:
        if a == b:
            return  # same-lock re-entry is not an ordering inversion
        edges.setdefault((a, b), {
            "mod": mod, "fs": fs, "line": line, "note": note})

    for fid, (mod, fs) in sorted(program.functions.items()):
        for la in fs.locks:
            inner = program.lock_id(la.lock)
            if inner is None:
                continue
            held, _ = program.held_locks(la.held)
            for outer in held:
                add_edge(outer, inner, mod, fs, la.line,
                         f"nested with-regions in {fs.qualname}")
        for c in fs.calls:
            held, _ = program.held_locks(c.locks)
            if not held:
                continue
            callee = program.resolve_callee(mod, fs, c.callee)
            if callee is None:
                continue
            for inner in program.closure_locks(callee):
                for outer in held:
                    add_edge(outer, inner, mod, fs, c.line,
                             f"{fs.qualname} holds it while calling "
                             f"{callee.split(':', 1)[1]}")

    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())

    findings: List[Finding] = []
    for scc in _tarjan_sccs(graph):
        if len(scc) < 2:
            continue
        cycle = _cycle_through(sorted(scc), graph)
        first = edges.get((cycle[0], cycle[1])) or next(
            iter(edges[e] for e in edges if e[0] in scc and e[1] in scc))
        path = " -> ".join(cycle + [cycle[0]])
        trace = []
        for a, b in zip(cycle, cycle[1:] + [cycle[0]]):
            e = edges.get((a, b))
            if e:
                trace.append(f"{a} held while acquiring {b} "
                             f"({e['mod'].path}:{e['line']}; {e['note']})")
        findings.append(_mk(
            "GL023", first["mod"], first["fs"], first["line"],
            f"lock acquisition order cycle: {path}",
            tuple(trace), line_lookup))
    return findings


def _tarjan_sccs(graph: Dict[str, Set[str]]) -> List[Set[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[Set[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (the lock graph is tiny, but no recursion limits)
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc: Set[str] = set()
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.add(w)
                    if w == node:
                        break
                sccs.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return sccs


def _cycle_through(nodes: List[str], graph: Dict[str, Set[str]]) -> List[str]:
    """A concrete cycle visiting nodes of one SCC, starting at the
    lexicographically smallest (deterministic finding text)."""
    start = nodes[0]
    scc = set(nodes)
    path = [start]
    seen = {start}
    cur = start
    while True:
        nxt = None
        for cand in sorted(graph.get(cur, ())):
            if cand == start and len(path) > 1:
                return path
            if cand in scc and cand not in seen:
                nxt = cand
                break
        if nxt is None:
            return path
        path.append(nxt)
        seen.add(nxt)
        cur = nxt


# ---------------------------------------------------------------------------
# GL024: fork-unsafe spawn
# ---------------------------------------------------------------------------


def _fork_sites(program: Program):
    for fid, (mod, fs) in sorted(program.functions.items()):
        for s in fs.spawns:
            if s.kind in _FORK_KINDS and \
                    s.start_method not in _SAFE_START_METHODS:
                yield fid, mod, fs, s


def _check_fork_safety(program: Program, line_lookup) -> List[Finding]:
    entries = program.thread_entries()
    thread_closure: Set[str] = set()
    thread_descs: Dict[str, str] = {}
    for entry, _spawner, _site, desc in entries:
        for fid in program.closure(entry):
            thread_closure.add(fid)
            thread_descs.setdefault(fid, f"{entry.split(':', 1)[1]} "
                                          f"(spawned {desc})")

    # call sites that can only execute after a thread exists: an earlier
    # intra-function thread spawn, or an earlier call whose closure spawns
    caller_after_thread: Dict[str, List[Tuple[str, str, int]]] = {}
    for fid, (mod, fs) in sorted(program.functions.items()):
        first_thread: Optional[int] = min(
            (s.line for s in fs.spawns if s.kind == "thread"), default=None)
        for c in fs.calls:
            callee = program.resolve_callee(mod, fs, c.callee)
            if callee is None:
                continue
            if callee != fid and program.closure_spawns_thread(callee):
                line = c.line
                if first_thread is None or line < first_thread:
                    first_thread = line
        if first_thread is None:
            continue
        for c in fs.calls:
            if c.line <= first_thread:
                continue
            callee = program.resolve_callee(mod, fs, c.callee)
            if callee is None:
                continue
            for member in program.closure(callee):
                caller_after_thread.setdefault(member, []).append(
                    (fid, mod.path, first_thread))

    findings: List[Finding] = []
    for fid, mod, fs, s in _fork_sites(program):
        child = program.resolve_callee(mod, fs, s.target) if s.target else None
        init = (program.resolve_callee(mod, fs, s.initializer)
                if s.initializer else None)
        blessed = (
            program.calls_reinit_helper(child)
            or program.calls_reinit_helper(init)
            or bool(s.target and _REINIT_RE.search(s.target))
            or bool(s.initializer and _REINIT_RE.search(s.initializer)))
        if blessed:
            continue

        reasons: List[str] = []
        first_thread = min(
            (sp.line for sp in fs.spawns
             if sp.kind == "thread" and sp.line < s.line), default=None)
        if s.after_thread_spawn or first_thread is not None:
            reasons.append(
                f"a thread is spawned earlier in {fs.qualname} "
                f"(line {first_thread})")
        elif fid in thread_closure:
            reasons.append(
                f"reachable from thread target {thread_descs[fid]}")
        elif fid in caller_after_thread:
            caller, cpath, tline = caller_after_thread[fid][0]
            reasons.append(
                f"reached from {caller.split(':', 1)[1]} after it has "
                f"spawned a thread ({cpath}:{tline})")
        locks, _ = program.held_locks(s.locks)
        if locks:
            reasons.append(
                f"forked while holding {', '.join(sorted(locks))} — the "
                f"child inherits the locked lock")
        if not reasons:
            continue
        kind_desc = {
            "fork": "os.fork()",
            "process": "fork-method multiprocessing.Process",
            "process_pool": "fork-method ProcessPoolExecutor",
            "popen_preexec": "Popen with preexec_fn",
        }[s.kind]
        findings.append(_mk(
            "GL024", mod, fs, s.line,
            f"{kind_desc} is fork-unsafe here: {reasons[0]}",
            tuple(reasons[1:]) + (
                "fix: use a spawn start method, move the fork before any "
                "thread exists, or re-init the child with an "
                "init_forked_worker-style helper",),
            line_lookup))
    return findings


# ---------------------------------------------------------------------------
# GL025: blocking join on the main path
# ---------------------------------------------------------------------------


def _check_blocking_joins(program: Program, line_lookup) -> List[Finding]:
    findings: List[Finding] = []
    for fid, (mod, fs) in sorted(program.functions.items()):
        for j in fs.joins:
            if j.timeout:
                continue
            target = program.resolve_callee(mod, fs, j.target)
            if target is None:
                continue
            witness = program.closure_blocks_forever(target)
            if witness is None:
                continue
            what = ".join()" if j.kind == "join" else ".result()"
            findings.append(_mk(
                "GL025", mod, fs, j.line,
                f"unbounded {what} on {j.receiver}: its target "
                f"{target.split(':', 1)[1]} can block forever",
                (f"blocking witness: {witness}",
                 "fix: pass a timeout (and escalate on expiry) or bound "
                 "the target's own waits"),
                line_lookup))
    return findings

"""The corrupt-corpus gauntlet: a seeded fuzzer proving the contracts.

``poison_corpus`` damages a synthetic corpus across every corruption class
in :data:`CORRUPTIONS` (one victim row per class, chosen by a seeded RNG —
the ``resilience/inject.py`` seeding convention: same seed, same plan,
same damage) and writes

- ``corpus.jsonl``       — the poisoned corpus (checksummed rows);
- ``clean_subset.jsonl`` — the pre-corruption originals of every row that
  SHOULD survive ingestion (fatally-corrupted victims removed, repairable
  victims restored) — the bit-for-bit reference corpus for the chaos
  scenario's determinism gate;
- a corruption *plan* mapping each class to its victim and the reason code
  the quarantine manifest must record.

``validate_corpus`` is the ``cli validate <cache-dir>`` engine;
``smoke`` is the seconds-long self-test wired into ``scripts/test.sh``.
"""

from __future__ import annotations

import copy
import json
import random
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deepdfa_tpu.contracts.ingest import load_examples_jsonl
from deepdfa_tpu.contracts.quarantine import (
    DIRNAME as QUARANTINE_DIRNAME,
    Quarantine,
    read_manifest,
)
from deepdfa_tpu.contracts.schema import CHECKSUM_KEY, row_checksum
from deepdfa_tpu.core.config import ALL_SUBKEYS

#: Node cap the gauntlet corpora are validated under (the oversize class
#: multiplies past it; every clean synthetic graph sits far below it).
GAUNTLET_MAX_NODES = 512


def _first_subkey(row) -> str:
    return next(iter(row["feats"]))


# Each corruption: (level, mutate, expected_reason, expected_repair)
#   level "row"  — mutates the parsed row; the checksum is RE-computed
#                  after (damage predates the cache write, so the digest
#                  is consistent and the schema validator must catch it);
#   level "post" — mutates the row AFTER checksumming (bitrot after the
#                  write: the digest check must catch it);
#   level "line" — mutates the serialized line text (torn writes).
# expected_reason None => the row must survive; expected_repair names the
# repair code the loader must apply.


def _c_truncate(line: str, rng: random.Random) -> str:
    return line[: max(len(line) // 2, 1)]


def _c_dangling(row, rng):
    k = rng.randrange(len(row["senders"]))
    row["senders"][k] = int(row["num_nodes"]) + 3
    return row


def _c_negative_feature(row, rng):
    key = _first_subkey(row)
    row["feats"][key][rng.randrange(len(row["feats"][key]))] = -5
    return row


def _c_nan_feature(row, rng):
    key = _first_subkey(row)
    feats = [float(v) for v in row["feats"][key]]
    feats[rng.randrange(len(feats))] = float("nan")
    row["feats"][key] = feats
    return row


def _c_feat_length(row, rng):
    key = _first_subkey(row)
    row["feats"][key] = row["feats"][key][:-1]
    return row


def _c_duplicate_node_id(row, rng):
    ids = row["node_ids"]
    ids[1 % len(ids)] = ids[0]
    return row


def _c_label_domain(row, rng):
    row["label"] = 7
    return row


def _c_empty_graph(row, rng):
    row["num_nodes"] = 0
    for key in ("senders", "receivers", "vuln", "df_in", "df_out",
                "node_ids"):
        if key in row:
            row[key] = []
    row["feats"] = {k: [] for k in row["feats"]}
    return row


def _c_oversize_graph(row, rng):
    row["num_nodes"] = GAUNTLET_MAX_NODES * 10
    return row


def _c_mistyped_field(row, rng):
    row["senders"] = "not-an-edge-list"
    return row


def _c_missing_subkey(row, rng):
    row["feats"].pop(_first_subkey(row))
    return row


def _c_checksum(row, rng):
    # "post" level: flips content under an already-recorded digest.
    row["label"] = 1 - int(row["label"])
    return row


def _c_float_feats(row, rng):
    key = _first_subkey(row)
    row["feats"][key] = [float(v) for v in row["feats"][key]]
    return row


def _c_float_label(row, rng):
    row["label"] = float(row["label"])
    return row


CORRUPTIONS: Dict[str, Tuple[str, Callable, Optional[str], Optional[str]]] = {
    "truncated_json":    ("line", _c_truncate,          "truncated_json", None),
    "dangling_endpoint": ("row",  _c_dangling,          "dangling_endpoint", None),
    "negative_feature":  ("row",  _c_negative_feature,  "negative_feature", None),
    "nan_feature":       ("row",  _c_nan_feature,       "nan_feature", None),
    "feat_length":       ("row",  _c_feat_length,       "feat_length", None),
    "duplicate_node_id": ("row",  _c_duplicate_node_id, "duplicate_node_id", None),
    "label_domain":      ("row",  _c_label_domain,      "label_domain", None),
    "empty_graph":       ("row",  _c_empty_graph,       "empty_graph", None),
    "oversize_graph":    ("row",  _c_oversize_graph,    "oversize_graph", None),
    "mistyped_field":    ("row",  _c_mistyped_field,    "mistyped_field", None),
    "missing_subkey":    ("row",  _c_missing_subkey,    "missing_subkey", None),
    "checksum_mismatch": ("post", _c_checksum,          "checksum_mismatch", None),
    # Repairable classes: the loader must fix these in place, exactly.
    "float_feats":       ("row",  _c_float_feats,       None, "float_field"),
    "float_label":       ("row",  _c_float_label,       None, "float_field"),
}


def _rows_from_examples(examples: Sequence[Dict]) -> List[Dict]:
    """JSON-able rows via THE shared row encoder (ingest.encode_row),
    re-id'd to their corpus position (so a quarantine manifest ``item_id``
    equals the line index for every class, including unparseable lines)
    and carrying explicit ``node_ids``."""
    from deepdfa_tpu.contracts.ingest import encode_row

    rows: List[Dict] = []
    for i, ex in enumerate(examples):
        row = encode_row(ex)
        row["id"] = i
        row.setdefault("node_ids", list(range(int(row["num_nodes"]))))
        rows.append(row)
    return rows


def poison_corpus(
    examples: Sequence[Dict],
    out_dir: str | Path,
    seed: int = 0,
    classes: Optional[Sequence[str]] = None,
) -> Dict:
    """Write ``corpus.jsonl`` (poisoned) + ``clean_subset.jsonl`` under
    ``out_dir``; returns the corruption plan.

    One victim row per class, victims distinct, chosen by
    ``random.Random(seed)``. Raises when the corpus is too small to host
    every class (each needs its own victim).
    """
    classes = list(classes) if classes is not None else list(CORRUPTIONS)
    unknown = set(classes) - set(CORRUPTIONS)
    if unknown:
        raise ValueError(f"unknown corruption classes {sorted(unknown)}")
    rows = _rows_from_examples(examples)
    if len(rows) <= len(classes):
        raise ValueError(
            f"corpus of {len(rows)} rows cannot host {len(classes)} "
            "corruption classes plus clean survivors")
    rng = random.Random(seed)
    victims = rng.sample(range(len(rows)), len(classes))
    victim_of = dict(zip(classes, victims))

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    plan: List[Dict] = []
    poisoned_lines: List[str] = []
    clean_lines: List[str] = []
    index_to_class = {idx: cls for cls, idx in victim_of.items()}
    for i, row in enumerate(rows):
        clean_text = None
        cls = index_to_class.get(i)
        if cls is None:
            text = json.dumps(
                dict(row, **{CHECKSUM_KEY: row_checksum(row)}))
            clean_text = text
        else:
            level, fn, reason, repair = CORRUPTIONS[cls]
            bad = copy.deepcopy(row)
            if level == "row":
                bad = fn(bad, rng)
                bad[CHECKSUM_KEY] = row_checksum(bad)
                text = json.dumps(bad)
            elif level == "post":
                bad[CHECKSUM_KEY] = row_checksum(bad)
                bad = fn(bad, rng)
                text = json.dumps(bad)
            else:  # line
                text = fn(json.dumps(
                    dict(bad, **{CHECKSUM_KEY: row_checksum(bad)})), rng)
            if reason is None:
                # Repairable: the original belongs in the clean subset.
                clean_text = json.dumps(
                    dict(row, **{CHECKSUM_KEY: row_checksum(row)}))
            plan.append({"class": cls, "index": i, "id": row["id"],
                         "expected_reason": reason,
                         "expected_repair": repair})
        poisoned_lines.append(text)
        if clean_text is not None:
            clean_lines.append(clean_text)

    (out_dir / "corpus.jsonl").write_text(
        "\n".join(poisoned_lines) + "\n", encoding="utf-8")
    (out_dir / "clean_subset.jsonl").write_text(
        "\n".join(clean_lines) + "\n", encoding="utf-8")
    plan_doc = {"seed": seed, "classes": classes, "n_rows": len(rows),
                "victims": sorted(plan, key=lambda p: p["index"])}
    with open(out_dir / "poison_plan.json", "w", encoding="utf-8") as f:
        json.dump(plan_doc, f, indent=1)
    return plan_doc


def check_manifest(plan: Dict, manifest: List[Dict],
                   loaded_ids: Sequence[int]) -> Dict:
    """Grade a quarantine manifest against a corruption plan.

    Every fatal victim must appear exactly once with its expected reason;
    no clean (or repairable) row may be quarantined; every repairable
    victim must have survived into ``loaded_ids``.
    """
    fatal = {p["index"]: p for p in plan["victims"]
             if p["expected_reason"] is not None}
    repairable = [p for p in plan["victims"] if p["expected_reason"] is None]
    by_item: Dict[int, List[Dict]] = {}
    for entry in manifest:
        by_item.setdefault(int(entry["item_id"]), []).append(entry)

    missing = [i for i in fatal if i not in by_item]
    wrong_reason = [
        {"index": i, "want": fatal[i]["expected_reason"],
         "got": [e["reason"] for e in by_item[i]]}
        for i in fatal if i in by_item
        and [e["reason"] for e in by_item[i]] != [fatal[i]["expected_reason"]]
    ]
    false_quarantines = sorted(set(by_item) - set(fatal))
    loaded = set(int(i) for i in loaded_ids)
    repairs_lost = [p["index"] for p in repairable
                    if p["index"] not in loaded]
    ok = not (missing or wrong_reason or false_quarantines or repairs_lost)
    return {"ok": ok, "missing": missing, "wrong_reason": wrong_reason,
            "false_quarantines": false_quarantines,
            "repairs_lost": repairs_lost,
            "fatal_victims": len(fatal),
            "repairable_victims": len(repairable)}


# ---------------------------------------------------------------------------
# cli validate
# ---------------------------------------------------------------------------


def validate_corpus(
    target: str | Path,
    subkeys: Sequence[str] = ALL_SUBKEYS,
    max_nodes: Optional[int] = None,
    quarantine_root: Optional[str | Path] = None,
) -> Dict:
    """Validate a corpus file or cache directory (every ``*.jsonl`` under
    it, the quarantine directory excluded). Returns the ``cli validate``
    report; ``exit_code`` 1 when anything was quarantined (fail-closed:
    a dirty cache should fail a pipeline gate, not pass silently)."""
    target = Path(target)
    if target.is_dir():
        files = sorted(
            p for p in target.rglob("*.jsonl")
            if QUARANTINE_DIRNAME not in p.parts
        )
    else:
        files = [target]
    if not files:
        raise FileNotFoundError(f"no .jsonl corpus under {target}")
    reports = []
    total_quarantined = 0
    by_reason: Dict[str, int] = {}
    for path in files:
        sink = Quarantine(quarantine_root) if quarantine_root is not None \
            else Quarantine(path.parent / QUARANTINE_DIRNAME)
        _, rep = load_examples_jsonl(path, subkeys, max_nodes=max_nodes,
                                     quarantine=sink)
        reports.append(rep)
        total_quarantined += rep["quarantined"]
        for reason, count in rep["by_reason"].items():
            by_reason[reason] = by_reason.get(reason, 0) + count
    return {
        "files": [r["path"] for r in reports],
        "rows": sum(r["lines"] for r in reports),
        "loaded": sum(r["loaded"] for r in reports),
        "repaired": sum(r["repaired"] for r in reports),
        "quarantined": total_quarantined,
        "by_reason": dict(sorted(by_reason.items())),
        "reports": reports,
        "exit_code": 1 if total_quarantined else 0,
    }


def smoke(out_dir: Optional[str | Path] = None, n_examples: int = 24,
          seed: int = 0) -> Dict:
    """Seconds-long self-test (the ``cli validate --smoke`` engine): poison
    a tiny synthetic corpus across EVERY corruption class, ingest it, and
    grade the quarantine manifest. ``ok`` only when every class was
    repaired or quarantined under its expected reason code with zero false
    quarantines."""
    import tempfile

    from deepdfa_tpu.core.config import FeatureSpec
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    tmp = Path(out_dir) if out_dir is not None else Path(
        tempfile.mkdtemp(prefix="contracts_smoke_"))
    tmp.mkdir(parents=True, exist_ok=True)
    feature = FeatureSpec(limit_all=20, limit_subkeys=20)
    examples = synthetic_bigvul(n_examples, feature, positive_fraction=0.5,
                                seed=seed)
    plan = poison_corpus(examples, tmp, seed=seed)
    sink = Quarantine(tmp / QUARANTINE_DIRNAME)
    loaded, report = load_examples_jsonl(
        tmp / "corpus.jsonl", ALL_SUBKEYS,
        max_nodes=GAUNTLET_MAX_NODES, quarantine=sink)
    grade = check_manifest(plan, read_manifest(sink.root),
                           [ex["id"] for ex in loaded])
    n_fatal = grade["fatal_victims"]
    survived = report["loaded"] == n_examples - n_fatal
    repaired = report["repaired"] >= grade["repairable_victims"]
    ok = bool(grade["ok"] and survived and repaired)
    return {
        "ok": ok,
        "classes": len(plan["classes"]),
        "n_examples": n_examples,
        "ingest": {k: v for k, v in report.items() if k != "reports"},
        "grade": grade,
        "out_dir": str(tmp),
        "exit_code": 0 if ok else 1,
    }

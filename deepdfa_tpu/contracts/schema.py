"""Typed data contracts for every ingestion boundary.

The reference pipeline parses Joern output and cached JSONL with no schema
enforcement; malformed graphs either crash a multi-hour export or — worse —
flow into the batcher, where out-of-range edge endpoints clamp inside the
masked segment ops and silently poison gradients. Here every boundary
(Joern ``nodes/edges`` JSON → CPG → cached JSONL → ``batch_graphs`` inputs →
serve admission) routes through ONE validator family with a reason-coded
taxonomy:

- **fatal** reasons reject the item (:class:`ContractError`); ingestion
  loaders move it to the quarantine sink (``contracts/quarantine.py``)
  instead of letting it reach the model;
- **repairable** reasons are fixed in place *exactly* (e.g. integral floats
  cast back to ints — a JSON round-trip artifact), recorded via the
  ``repairs`` out-param, and never change the semantic content of the item
  (the corrupt-corpus gauntlet's bit-for-bit acceptance gate rests on
  repairs being value-preserving).

Validators double as graftlint GL010 *cleaners*: a ``json.load(s)`` result
that reaches ``np.asarray`` without passing through a
``contracts.validate_*`` call is a lint finding (analysis/rules.py).
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from deepdfa_tpu.core.metrics import IngestStats

#: Process-global per-boundary ingest counters (snapshot via
#: ``contracts.STATS.snapshot()`` — the ``cli validate`` report body).
#: Opt-in at validator level (``stats=`` param): the bulk loader counts
#: locally and merges once per corpus, so the per-row hot path stays
#: lock-free; serve admission passes STATS per request.
STATS = IngestStats()

#: Reason-code taxonomy: code -> severity. Fatal reasons quarantine the
#: item; repairable reasons are fixed in place (value-preserving) and only
#: counted. The corrupt-corpus gauntlet asserts every corruption class maps
#: to exactly one of these codes.
REASONS: Dict[str, str] = {
    "truncated_json": "fatal",     # line does not parse as JSON
    "checksum_mismatch": "fatal",  # cache row fails its __sha1__ digest
    "mistyped_field": "fatal",     # non-coercible field type
    "missing_field": "fatal",      # required field absent
    "missing_subkey": "fatal",     # a required feature subkey absent
    "empty_graph": "fatal",        # num_nodes < 1
    "oversize_graph": "fatal",     # num_nodes > the configured cap
    "edge_shape": "fatal",         # senders/receivers not equal-length 1-d
    "dangling_endpoint": "fatal",  # edge endpoint < 0 or >= num_nodes
    "feat_length": "fatal",        # per-node array not shaped (num_nodes,)
    "negative_feature": "fatal",   # feature index < 0
    "nan_feature": "fatal",        # non-finite feature value
    "label_domain": "fatal",       # label / vuln bit outside {0, 1}
    "duplicate_node_id": "fatal",  # node id repeats in an export
    "no_method_node": "fatal",     # Joern graph without a METHOD node
    "bad_source": "fatal",         # scan source text fails the API contract
    "joern_failure": "fatal",      # CPG extraction gave up after retries
    "float_field": "repairable",   # integral floats / bools cast back exactly
}

FATAL_REASONS = frozenset(r for r, sev in REASONS.items() if sev == "fatal")
REPAIRABLE_REASONS = frozenset(
    r for r, sev in REASONS.items() if sev == "repairable"
)

#: Key carrying a cache row's content digest (``row_checksum`` of the row
#: without this key). Absent on pipeline exports; written by the
#: checksummed cache writers (etl/cache.py, contracts/ingest.py).
CHECKSUM_KEY = "__sha1__"


class ContractError(ValueError):
    """A fatal data-contract violation at an ingestion boundary.

    Subclasses :class:`ValueError` so pre-contract callers that caught
    ValueError (batcher overflow handling, the Joern parser's callers) keep
    working. Carries the taxonomy ``reason`` code, the ``boundary`` it was
    detected at, the ``item_id`` (when known), and a bounded ``fragment``
    of the offending data — everything the quarantine manifest records.
    """

    def __init__(self, reason: str, message: str, *,
                 boundary: str = "example",
                 item_id=None, fragment: Optional[str] = None):
        if reason not in REASONS:
            raise ValueError(f"unknown contract reason {reason!r}")
        super().__init__(message)
        self.reason = reason
        self.boundary = boundary
        self.item_id = item_id
        self.fragment = fragment


def fragment_of(value, limit: int = 160) -> str:
    """Bounded repr of the offending data for the quarantine manifest."""
    try:
        text = json.dumps(value, default=repr)
    except (TypeError, ValueError):
        text = repr(value)
    return text if len(text) <= limit else text[: limit - 1] + "…"


def row_checksum(row: Mapping) -> str:
    """Content digest of one cache row (the :data:`CHECKSUM_KEY` value):
    sha1 over the canonical JSON of the row without the digest key."""
    payload = {k: v for k, v in row.items() if k != CHECKSUM_KEY}
    canon = json.dumps(payload, sort_keys=True, separators=(",", ":"),
                       default=repr)
    return hashlib.sha1(canon.encode("utf-8")).hexdigest()


# ---------------------------------------------------------------------------
# Field coercion
# ---------------------------------------------------------------------------


def _int_array(
    value,
    what: str,
    *,
    boundary: str,
    item_id,
    repairs: Optional[List[str]],
    dtype=np.int32,
) -> np.ndarray:
    """Coerce one per-node/per-edge field to an int array.

    Int/uint input passes through (cast only when the dtype differs — the
    hot path for already-typed arrays is copy-free); bool and *integral*
    float input is a repairable JSON artifact and casts back exactly;
    non-integral floats, NaN/inf, strings, and ragged objects are fatal.
    """
    try:
        arr = np.asarray(value)
    except (TypeError, ValueError) as e:
        raise ContractError(
            "mistyped_field", f"malformed graph payload: {what}: {e}",
            boundary=boundary, item_id=item_id, fragment=fragment_of(value))

    def check_range(a):
        # astype wraps silently past the target dtype's range — a corrupt
        # 64-bit edge endpoint must not wrap back INTO valid range and
        # slip past the endpoint check (the silent-poisoning class again).
        info = np.iinfo(dtype)
        if a.size and (int(a.min()) < info.min or int(a.max()) > info.max):
            raise ContractError(
                "mistyped_field",
                f"malformed graph payload: {what} exceeds the "
                f"{np.dtype(dtype).name} range",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of([int(a.min()), int(a.max())]))

    kind = arr.dtype.kind
    if kind in "iu":
        if arr.dtype == dtype:
            return arr
        check_range(arr)
        return arr.astype(dtype)
    if kind == "b":
        if repairs is not None and arr.size:
            repairs.append("float_field")
        return arr.astype(dtype)
    if kind == "f":
        if not np.all(np.isfinite(arr)):
            raise ContractError(
                "nan_feature", f"{what} has non-finite entries",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(np.asarray(value).tolist()))
        if arr.size and not np.array_equal(arr, np.trunc(arr)):
            raise ContractError(
                "mistyped_field",
                f"malformed graph payload: {what} has non-integral values",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(np.asarray(value).tolist()))
        check_range(arr)
        if repairs is not None and arr.size:
            repairs.append("float_field")
        return arr.astype(dtype)
    raise ContractError(
        "mistyped_field",
        f"malformed graph payload: {what} is not numeric "
        f"(dtype {arr.dtype})",
        boundary=boundary, item_id=item_id, fragment=fragment_of(value))


def _int_scalar(value, what: str, *, boundary: str, item_id,
                repairs: Optional[List[str]] = None) -> int:
    if isinstance(value, bool):
        if repairs is not None:
            repairs.append("float_field")
        return int(value)
    if isinstance(value, float):
        if not np.isfinite(value) or value != int(value):
            raise ContractError(
                "mistyped_field",
                f"malformed graph payload: {what} is not an integer",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(value))
        if repairs is not None:
            repairs.append("float_field")
        return int(value)
    try:
        return int(value)
    except (TypeError, ValueError):
        raise ContractError(
            "mistyped_field",
            f"malformed graph payload: {what} is not an integer",
            boundary=boundary, item_id=item_id, fragment=fragment_of(value))


# ---------------------------------------------------------------------------
# The example contract (cached JSONL rows, batch_graphs inputs, serve
# admission payloads)
# ---------------------------------------------------------------------------


def validate_example(
    graph: Mapping,
    subkeys: Sequence[str],
    *,
    with_label: bool = False,
    max_nodes: Optional[int] = None,
    boundary: str = "example",
    item_id=None,
    repairs: Optional[List[str]] = None,
    stats: Optional[IngestStats] = None,
) -> Dict:
    """Validate + canonicalize one graph example; raises
    :class:`ContractError` (fatal reasons) or returns the normalized dict.

    ``with_label=False`` is the serve-admission shape (no labels exist at
    scoring time; ``vuln`` comes back zeroed) and reproduces the historic
    HTTP-400 message classes byte-for-byte where they existed.
    ``with_label=True`` is the training/cache shape: ``vuln`` is required,
    ``label`` defaults to ``vuln.max()``, and the optional export fields
    (``df_in``/``df_out``/``project``/``node_ids``/``node_lines``) are
    validated and passed through.

    ``repairs``: optional list collecting repairable reason codes applied
    (value-preserving casts only). ``max_nodes``: oversize cap (checked
    before per-field shapes so an oversize corruption reads as
    ``oversize_graph``, not a shape mismatch).
    """
    if stats is not None:
        stats.bump(boundary, "seen")
    try:
        out = _validate_example(
            graph, subkeys, with_label=with_label, max_nodes=max_nodes,
            boundary=boundary, item_id=item_id, repairs=repairs)
    except ContractError as e:
        if stats is not None:
            stats.bump(boundary, "rejected")
            stats.bump(boundary, f"reason:{e.reason}")
        raise
    if stats is not None:
        stats.bump(boundary, "valid")
        if repairs:
            stats.bump(boundary, "repaired")
            for r in set(repairs):
                stats.bump(boundary, f"repair:{r}")
    return out


def _validate_example(graph, subkeys, *, with_label, max_nodes, boundary,
                      item_id, repairs) -> Dict:
    if not isinstance(graph, Mapping):
        raise ContractError(
            "mistyped_field",
            f"malformed graph payload: expected an object, got "
            f"{type(graph).__name__}",
            boundary=boundary, item_id=item_id, fragment=fragment_of(graph))

    def require(field):
        if field not in graph:
            # Historic serve text: KeyError('num_nodes') stringifies to
            # "'num_nodes'", so the legacy 400 read
            # "malformed graph payload: 'num_nodes'". Preserved.
            raise ContractError(
                "missing_field", f"malformed graph payload: '{field}'",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(sorted(graph)))
        return graph[field]

    n = _int_scalar(require("num_nodes"), "num_nodes",
                    boundary=boundary, item_id=item_id, repairs=repairs)
    if n < 1:
        raise ContractError(
            "empty_graph", "graph needs at least one node",
            boundary=boundary, item_id=item_id,
            fragment=fragment_of({"num_nodes": n}))
    if max_nodes is not None and n > max_nodes:
        raise ContractError(
            "oversize_graph",
            f"graph has {n} nodes, over the {max_nodes}-node cap",
            boundary=boundary, item_id=item_id,
            fragment=fragment_of({"num_nodes": n}))

    senders = _int_array(require("senders"), "senders", boundary=boundary,
                         item_id=item_id, repairs=repairs)
    receivers = _int_array(require("receivers"), "receivers",
                           boundary=boundary, item_id=item_id,
                           repairs=repairs)
    if senders.shape != receivers.shape or senders.ndim != 1:
        raise ContractError(
            "edge_shape", "senders/receivers must be equal-length 1-d",
            boundary=boundary, item_id=item_id,
            fragment=fragment_of({"senders": list(senders.shape),
                                  "receivers": list(receivers.shape)}))
    if len(senders) and (int(senders.min()) < 0 or int(receivers.min()) < 0
                         or int(senders.max()) >= n
                         or int(receivers.max()) >= n):
        raise ContractError(
            "dangling_endpoint", "edge endpoint out of range",
            boundary=boundary, item_id=item_id,
            fragment=fragment_of({
                "num_nodes": n,
                "senders": [int(senders.min()), int(senders.max())]
                if len(senders) else [],
                "receivers": [int(receivers.min()), int(receivers.max())]
                if len(receivers) else [],
            }))

    raw_feats = require("feats")
    if not isinstance(raw_feats, Mapping):
        raise ContractError(
            "mistyped_field",
            "malformed graph payload: feats must be an object",
            boundary=boundary, item_id=item_id,
            fragment=fragment_of(raw_feats))
    feats: Dict[str, np.ndarray] = {}
    for key in list(subkeys) + [k for k in raw_feats if k not in subkeys]:
        if key not in raw_feats:
            raise ContractError(
                "missing_subkey", f"missing feature subkey {key!r}",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(sorted(raw_feats)))
        arr = _int_array(raw_feats[key], f"feats[{key!r}]",
                         boundary=boundary, item_id=item_id, repairs=repairs)
        if arr.shape != (n,):
            raise ContractError(
                "feat_length", f"feats[{key!r}] must have shape ({n},)",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of({key: list(arr.shape)}))
        if arr.size and int(arr.min()) < 0:
            raise ContractError(
                "negative_feature", f"feats[{key!r}] has negative entries",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of({key: int(arr.min())}))
        feats[key] = arr

    out: Dict = {"num_nodes": n, "senders": senders, "receivers": receivers,
                 "feats": feats}
    if "id" in graph:
        out["id"] = _int_scalar(graph["id"], "id", boundary=boundary,
                                item_id=item_id, repairs=repairs)

    if not with_label:
        out["vuln"] = np.zeros(n, np.int32)  # labels don't exist at serve
        return out

    vuln = _int_array(require("vuln"), "vuln", boundary=boundary,
                      item_id=item_id, repairs=repairs)
    if vuln.shape != (n,):
        raise ContractError(
            "feat_length", f"vuln must have shape ({n},)",
            boundary=boundary, item_id=item_id,
            fragment=fragment_of(list(vuln.shape)))
    if vuln.size and (int(vuln.min()) < 0 or int(vuln.max()) > 1):
        raise ContractError(
            "label_domain", "vuln bits must be in {0, 1}",
            boundary=boundary, item_id=item_id,
            fragment=fragment_of([int(vuln.min()), int(vuln.max())]))
    out["vuln"] = vuln

    if "label" in graph:
        label = _int_scalar(graph["label"], "label", boundary=boundary,
                            item_id=item_id, repairs=repairs)
    else:
        label = int(vuln.max(initial=0))
    if label not in (0, 1):
        raise ContractError(
            "label_domain", f"label {label} outside {{0, 1}}",
            boundary=boundary, item_id=item_id,
            fragment=fragment_of(graph.get("label")))
    out["label"] = label

    for key in ("df_in", "df_out"):
        if key in graph:
            arr = _int_array(graph[key], key, boundary=boundary,
                             item_id=item_id, repairs=repairs)
            if arr.shape != (n,):
                raise ContractError(
                    "feat_length", f"{key} must have shape ({n},)",
                    boundary=boundary, item_id=item_id,
                    fragment=fragment_of(list(arr.shape)))
            out[key] = arr
    if "node_ids" in graph:
        ids = _int_array(graph["node_ids"], "node_ids", boundary=boundary,
                         item_id=item_id, repairs=repairs, dtype=np.int64)
        if ids.shape != (n,):
            raise ContractError(
                "feat_length", f"node_ids must have shape ({n},)",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(list(ids.shape)))
        if len(np.unique(ids)) != n:
            raise ContractError(
                "duplicate_node_id", "node_ids contains duplicates",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(np.asarray(graph["node_ids"]).tolist()))
        out["node_ids"] = ids
    if "node_lines" in graph:
        lines = _int_array(graph["node_lines"], "node_lines",
                           boundary=boundary, item_id=item_id,
                           repairs=repairs)
        if lines.shape != (n,):
            raise ContractError(
                "feat_length", f"node_lines must have shape ({n},)",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(list(lines.shape)))
        out["node_lines"] = lines
    if "project" in graph:
        out["project"] = graph["project"]
    return out


# ---------------------------------------------------------------------------
# The Joern export contract (raw nodes/edges JSON)
# ---------------------------------------------------------------------------


def validate_joern_nodes(nodes_json, *, boundary: str = "joern",
                         item_id=None,
                         stats: Optional[IngestStats] = None):
    """Validate a Joern ``.nodes.json`` payload: a list of property objects,
    each carrying an int-coercible ``id``, ids unique across the export.
    Returns the payload (the GL010 cleaner contract)."""
    if stats is not None:
        stats.bump(boundary, "seen")
    try:
        if not isinstance(nodes_json, list):
            raise ContractError(
                "mistyped_field",
                f"joern nodes export is {type(nodes_json).__name__}, "
                "expected a list",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(nodes_json))
        seen_ids = set()
        for rec in nodes_json:
            if not isinstance(rec, Mapping):
                raise ContractError(
                    "mistyped_field",
                    f"joern node record is {type(rec).__name__}, "
                    "expected an object",
                    boundary=boundary, item_id=item_id,
                    fragment=fragment_of(rec))
            if "id" not in rec:
                raise ContractError(
                    "missing_field",
                    "joern node record without an 'id' field",
                    boundary=boundary, item_id=item_id,
                    fragment=fragment_of(rec))
            nid = _int_scalar(rec["id"], "node id", boundary=boundary,
                              item_id=item_id)
            if nid in seen_ids:
                raise ContractError(
                    "duplicate_node_id",
                    f"joern export repeats node id {nid}",
                    boundary=boundary, item_id=item_id,
                    fragment=fragment_of(rec))
            seen_ids.add(nid)
    except ContractError as e:
        if stats is not None:
            stats.bump(boundary, "rejected")
            stats.bump(boundary, f"reason:{e.reason}")
        raise
    if stats is not None:
        stats.bump(boundary, "valid")
    return nodes_json


def validate_joern_edges(edges_json, *, boundary: str = "joern",
                         item_id=None,
                         stats: Optional[IngestStats] = None):
    """Validate a Joern ``.edges.json`` payload: a list of
    ``[inNode, outNode, etype, ...]`` rows with int-coercible endpoints and
    a string edge type. Returns the payload."""
    if stats is not None:
        stats.bump(boundary, "seen")
    try:
        if not isinstance(edges_json, list):
            raise ContractError(
                "mistyped_field",
                f"joern edges export is {type(edges_json).__name__}, "
                "expected a list",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(edges_json))
        for row in edges_json:
            if (not isinstance(row, (list, tuple)) or len(row) < 3
                    or not isinstance(row[2], str)):
                raise ContractError(
                    "mistyped_field",
                    "joern edge row is not [inNode, outNode, etype, ...]",
                    boundary=boundary, item_id=item_id,
                    fragment=fragment_of(row))
            _int_scalar(row[0], "edge inNode", boundary=boundary,
                        item_id=item_id)
            _int_scalar(row[1], "edge outNode", boundary=boundary,
                        item_id=item_id)
    except ContractError as e:
        if stats is not None:
            stats.bump(boundary, "rejected")
            stats.bump(boundary, f"reason:{e.reason}")
        raise
    if stats is not None:
        stats.bump(boundary, "valid")
    return edges_json


# ---------------------------------------------------------------------------
# The cache-row contract (checksummed JSONL rows)
# ---------------------------------------------------------------------------


def validate_cache_row(row, *, boundary: str = "cache", item_id=None,
                       stats: Optional[IngestStats] = None) -> Dict:
    """Validate one parsed cache/JSONL row: must be an object; when it
    carries a :data:`CHECKSUM_KEY` digest, the digest must match the row's
    canonical content (bitrot detection). Returns the row WITHOUT the
    digest key."""
    if stats is not None:
        stats.bump(boundary, "seen")
    try:
        if not isinstance(row, Mapping):
            raise ContractError(
                "mistyped_field",
                f"cache row is {type(row).__name__}, expected an object",
                boundary=boundary, item_id=item_id, fragment=fragment_of(row))
        if CHECKSUM_KEY in row:
            want = row[CHECKSUM_KEY]
            got = row_checksum(row)
            if got != want:
                raise ContractError(
                    "checksum_mismatch",
                    f"cache row digest {got[:12]} != recorded "
                    f"{str(want)[:12]}",
                    boundary=boundary, item_id=item_id,
                    fragment=fragment_of(
                        {k: row[k] for k in list(row)[:4]}))
            row = {k: v for k, v in row.items() if k != CHECKSUM_KEY}
    except ContractError as e:
        if stats is not None:
            stats.bump(boundary, "rejected")
            stats.bump(boundary, f"reason:{e.reason}")
        raise
    if stats is not None:
        stats.bump(boundary, "valid")
    return dict(row)


# ---------------------------------------------------------------------------
# The scan-source contract (the POST /scan API edge, where attacker-
# controlled raw C source enters the pipeline)
# ---------------------------------------------------------------------------


#: Upper bound on one scan item's source text. Single functions are a few
#: KB; a megabyte of "function" is either a mistake or an attempt to feed
#: the Joern pool unbounded work.
MAX_SOURCE_BYTES = 262_144


def validate_scan_source(source, *, boundary: str = "scan", item_id=None,
                         max_bytes: int = MAX_SOURCE_BYTES,
                         stats: Optional[IngestStats] = None) -> str:
    """Validate one raw-source scan item (reason code ``bad_source``).

    The source must be a non-empty text string, free of NUL bytes (Joern
    reads it back from a file; an embedded NUL truncates what the parser
    sees vs. what was hashed), decodable to UTF-8, and bounded in size —
    the scan cache keys and the Joern pool's per-item budget both assume
    function-sized inputs. Returns the source unchanged.
    """
    if stats is not None:
        stats.bump(boundary, "seen")
    try:
        if not isinstance(source, str):
            raise ContractError(
                "bad_source",
                f"scan source is {type(source).__name__}, expected a string",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(source))
        if not source.strip():
            raise ContractError(
                "bad_source", "scan source is empty",
                boundary=boundary, item_id=item_id)
        if "\x00" in source:
            raise ContractError(
                "bad_source", "scan source contains NUL bytes",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(source[:64]))
        try:
            size = len(source.encode("utf-8"))
        except UnicodeEncodeError as e:
            raise ContractError(
                "bad_source", f"scan source is not encodable: {e}",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(source[:64]))
        if size > max_bytes:
            raise ContractError(
                "bad_source",
                f"scan source is {size} bytes > cap {max_bytes}",
                boundary=boundary, item_id=item_id,
                fragment=fragment_of(source[:64]))
    except ContractError as e:
        if stats is not None:
            stats.bump(boundary, "rejected")
            stats.bump(boundary, f"reason:{e.reason}")
        raise
    if stats is not None:
        stats.bump(boundary, "valid")
    return source

"""Data contracts enforced at every ingestion boundary.

One validator family (schema.py) guards Joern JSON → CPG → cached JSONL →
``batch_graphs`` inputs → serve admission; violations carry a reason code
from the :data:`~deepdfa_tpu.contracts.schema.REASONS` taxonomy and land in
the fail-closed quarantine sink (quarantine.py) instead of the model. The
corrupt-corpus gauntlet (gauntlet.py) proves the property end to end:
every seeded corruption class is repaired or quarantined, never trained on.
"""

from deepdfa_tpu.contracts.ingest import (
    load_examples_jsonl,
    write_examples_jsonl,
)
from deepdfa_tpu.contracts.quarantine import (
    Quarantine,
    quarantine_dir,
    read_manifest,
)
from deepdfa_tpu.contracts.schema import (
    CHECKSUM_KEY,
    ContractError,
    FATAL_REASONS,
    REASONS,
    REPAIRABLE_REASONS,
    STATS,
    row_checksum,
    validate_cache_row,
    validate_example,
    validate_joern_edges,
    validate_joern_nodes,
    validate_scan_source,
)

__all__ = [
    "CHECKSUM_KEY",
    "ContractError",
    "FATAL_REASONS",
    "REASONS",
    "REPAIRABLE_REASONS",
    "STATS",
    "Quarantine",
    "load_examples_jsonl",
    "quarantine_dir",
    "read_manifest",
    "row_checksum",
    "validate_cache_row",
    "validate_example",
    "validate_joern_edges",
    "validate_joern_nodes",
    "validate_scan_source",
    "write_examples_jsonl",
]

"""Validated JSONL ingestion: the ONE loader for cached graph corpora.

``cli.load_dataset``, ``data/combined.py``'s graph source, the gauntlet,
and ``cli validate`` all read exported examples through
:func:`load_examples_jsonl`, so the contract (schema.py) and the fail-closed
quarantine posture (quarantine.py) hold at every consumer:

- a line that does not parse is quarantined as ``truncated_json``;
- a row whose ``__sha1__`` digest mismatches is ``checksum_mismatch``;
- a row violating the example schema quarantines under its reason code;
- repairable violations (integral-float casts) are fixed in place,
  counted, and the item is kept — repairs are value-preserving, so a
  repaired corpus trains bit-for-bit like its clean original.

The loader never raises mid-corpus: one poisoned row costs that row, not
the run (the reference drops ~4% of Big-Vul functions to malformed graphs;
silently crashing on them would lose the other 96%).

Performance design (the bench gate: ``ingest_validate_overhead_pct`` < 5%
versus the raw pre-contracts loader). Naive per-row validation cost ~90%:
~10 numpy reduction dispatches per row dwarf the actual O(n) work at CFG
sizes. The loader is therefore two-tier:

1. a **structural fast path** per row — exact-type probes (``type(x) is
   int``; ``bool`` fails an exact-type probe and routes to the slow path),
   ``len()`` shape checks, required-subkey presence, python-level
   ``max()`` upper-bound checks on the parsed lists (C loop, no numpy
   dispatch), and ONE ``np.asarray`` over a merged per-row buffer whose
   slices become the example's senders/receivers/vuln/feats views — one
   conversion dispatch where the raw loader paid seven, which more than
   funds the validation work;
2. a **corpus-level negativity pass** — the merged buffers concatenate
   once per corpus and a single ``min()`` proves every edge endpoint,
   vuln bit, and feature index non-negative; a violation rescans per-row
   and routes offenders through the precise validator
   (schema.validate_example) for their exact reason code and quarantine.

Rows that miss the fast path (checksummed rows, float-typed fields, any
structural oddity) take the full validator — fidelity where it matters,
raw-loader speed on the clean common case. Known fast-path limit: a
*single* non-integral float spliced mid-array (not at either probed end)
casts like the raw loader casted; whole-array float fields — the JSON
round-trip artifact and the gauntlet's corruption class — are caught and
repaired, and checksummed corpora always get the full per-element
validator.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from deepdfa_tpu.contracts.quarantine import Quarantine, quarantine_dir
from deepdfa_tpu.contracts.schema import (
    CHECKSUM_KEY,
    ContractError,
    IngestStats,
    STATS,
    row_checksum,
    validate_cache_row,
    validate_example,
)

logger = logging.getLogger(__name__)


def encode_row(ex: Mapping) -> Dict:
    """One example as a JSON-able row (numpy arrays to lists) — THE row
    encoder, shared by :func:`write_examples_jsonl` and the gauntlet's
    corpus writer so the fuzzer can only ever damage rows the real writer
    would produce."""
    row: Dict = {}
    for k, v in ex.items():
        if isinstance(v, np.ndarray):
            row[k] = v.tolist()
        elif isinstance(v, Mapping):
            row[k] = {kk: (vv.tolist()
                           if isinstance(vv, np.ndarray) else vv)
                      for kk, vv in v.items()}
        elif isinstance(v, (np.integer,)):
            row[k] = int(v)
        else:
            row[k] = v
    return row


def write_examples_jsonl(examples: Sequence[Mapping], path: str | Path,
                         checksum: bool = True) -> int:
    """Write graph examples as JSONL (numpy arrays to lists); with
    ``checksum`` each row carries its ``__sha1__`` content digest so
    bitrot is detectable at load. Returns rows written."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    n = 0
    with open(path, "w", encoding="utf-8") as f:
        for ex in examples:
            row = encode_row(ex)
            if checksum:
                row[CHECKSUM_KEY] = row_checksum(row)
            f.write(json.dumps(row) + "\n")
            n += 1
    return n


class _FastMiss(Exception):
    """Row needs the full per-row validator (not necessarily bad)."""


_PER_NODE_OPTIONAL = ("df_in", "df_out", "node_ids", "node_lines")


def _fast_example(doc, subkeys, max_nodes,
                  line_index) -> Tuple[Dict, np.ndarray]:
    """The structural fast path: validate + normalize one parsed row, or
    raise ``_FastMiss`` to defer to the full validator. Returns
    ``(example, merged_buffer)``; the buffer (layout: senders, receivers,
    vuln, feats values) feeds the corpus-level negativity pass, and the
    example's arrays are slice views of it — one conversion dispatch per
    row."""
    if type(doc) is not dict or CHECKSUM_KEY in doc:
        raise _FastMiss
    n = doc.get("num_nodes")
    if type(n) is not int or n < 1:
        raise _FastMiss
    if max_nodes is not None and n > max_nodes:
        raise _FastMiss
    s = doc.get("senders")
    r = doc.get("receivers")
    if type(s) is not list or type(r) is not list or len(s) != len(r):
        raise _FastMiss
    if s and (type(s[0]) is not int or type(s[-1]) is not int
              or type(r[0]) is not int or type(r[-1]) is not int):
        raise _FastMiss
    feats = doc.get("feats")
    if type(feats) is not dict:
        raise _FastMiss
    for key in subkeys:
        if key not in feats:
            raise _FastMiss
    vuln = doc.get("vuln")
    if type(vuln) is not list or len(vuln) != n:
        raise _FastMiss
    if type(vuln[0]) is not int or type(vuln[-1]) is not int:
        raise _FastMiss
    for key in _PER_NODE_OPTIONAL:
        if key in doc:
            v = doc[key]
            if type(v) is not list or len(v) != n:
                raise _FastMiss
    if "node_ids" in doc and len(set(doc["node_ids"])) != n:
        raise _FastMiss  # duplicate_node_id — the slow path names it
    if "id" in doc:
        if type(doc["id"]) is not int:
            raise _FastMiss
    else:
        doc["id"] = line_index
    if "label" in doc:
        # Exact-type probe: 1.0 and True compare equal to 1 but need the
        # slow path's float_field repair (the two tiers must agree).
        lab = doc["label"]
        if type(lab) is not int or lab not in (0, 1):
            raise _FastMiss
    e = len(s)
    merged = s + r + vuln
    try:
        # Upper bounds python-side on the parsed lists (a C loop, no numpy
        # dispatch; TypeError on mixed types -> slow path). Lower bounds
        # ride the corpus-level min over the merged buffers.
        if s and (max(s) >= n or max(r) >= n):
            raise _FastMiss
        if max(vuln) > 1:
            raise _FastMiss
        feat_views: Dict[str, slice] = {}
        off = 2 * e + n
        for key, v in feats.items():
            if type(v) is not list or len(v) != n:
                raise _FastMiss
            if v and (type(v[0]) is not int or type(v[-1]) is not int):
                raise _FastMiss
            merged += v
            feat_views[key] = slice(off, off + n)
            off += n
        # ONE conversion per row (the raw loader paid one per field).
        # numpy itself rejects NaN-to-int and non-numeric input.
        buf = np.asarray(merged, np.int32)
    except (TypeError, ValueError, OverflowError):
        raise _FastMiss
    doc["senders"] = buf[:e]
    doc["receivers"] = buf[e:2 * e]
    doc["vuln"] = buf[2 * e:2 * e + n]
    doc["feats"] = {k: buf[sl] for k, sl in feat_views.items()}
    return doc, buf


def load_examples_jsonl(
    path: str | Path,
    subkeys: Sequence[str],
    *,
    max_nodes: Optional[int] = None,
    quarantine: Optional[Quarantine] = None,
    boundary: str = "cache",
    stats: Optional[IngestStats] = None,
) -> Tuple[List[Dict], Dict]:
    """Load a graph-example JSONL corpus through the full contract.

    Returns ``(examples, report)``: the surviving normalized examples (the
    ``batch_graphs`` input schema — int32 arrays, ``id`` defaulting to the
    line index, ``label`` defaulting to ``vuln.max()``) and a report dict
    with per-reason quarantine counts. ``quarantine`` defaults to the
    ``quarantine/`` sibling of ``path``; pass an explicit sink to redirect.
    """
    path = Path(path)
    sink = quarantine if quarantine is not None else Quarantine(
        quarantine_dir(path))
    target = stats if stats is not None else STATS

    examples: List[Dict] = []
    fast_bufs: List[np.ndarray] = []
    fast_positions: List[int] = []
    fast_lines: List[str] = []
    repaired = 0
    n_lines = 0

    def slow_validate(doc, line, item_id) -> Optional[Dict]:
        """The precise per-row path; returns the example or quarantines."""
        nonlocal repaired
        repairs: List[str] = []
        try:
            row = validate_cache_row(doc, boundary=boundary,
                                     item_id=item_id)
            ex = validate_example(
                row, subkeys, with_label=True, max_nodes=max_nodes,
                boundary=boundary, item_id=item_id, repairs=repairs)
        except ContractError as err:
            target.bump(boundary, f"reason:{err.reason}")
            sink.put(err, raw=line)
            return None
        if repairs:
            repaired += 1
            for rep in set(repairs):
                target.bump(boundary, f"repair:{rep}")
        return ex

    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            try:
                doc = json.loads(line)
            except json.JSONDecodeError as e:
                if not line.strip():
                    continue  # blank line, not a violation
                n_lines += 1
                target.bump(boundary, "reason:truncated_json")
                sink.put(ContractError(
                    "truncated_json", f"line {i}: {e}", boundary=boundary,
                    item_id=i, fragment=line.strip()[:160]), raw=line)
                continue
            n_lines += 1
            try:
                ex, buf = _fast_example(doc, subkeys, max_nodes, i)
            except _FastMiss:
                item_id = doc.get("id", i) if isinstance(doc, Mapping) else i
                ex = slow_validate(doc, line, item_id)
                if ex is None:
                    continue
                ex.setdefault("id", i)
            else:
                fast_positions.append(len(examples))
                fast_bufs.append(buf)
                fast_lines.append(line)
            examples.append(ex)

    # Corpus-level negativity pass: one concat + one min proves every
    # fast-path edge endpoint, vuln bit, and feature index >= 0 (upper
    # bounds were checked per row). Violators re-run the precise validator
    # for their reason code (dangling_endpoint / label_domain /
    # negative_feature) and quarantine.
    if fast_bufs:
        allcat = (np.concatenate(fast_bufs) if len(fast_bufs) > 1
                  else fast_bufs[0])
        if allcat.size and int(allcat.min()) < 0:
            drop = set()
            for pos, buf, line in zip(fast_positions, fast_bufs,
                                      fast_lines):
                if buf.size and int(buf.min()) < 0:
                    ex = examples[pos]
                    if slow_validate(ex, line, ex.get("id", pos)) is None:
                        drop.add(pos)
            examples = [ex for i, ex in enumerate(examples)
                        if i not in drop]

    # Label default for fast-path rows that carried none (the raw loader's
    # setdefault semantics; exports always write a label).
    for ex in examples:
        if "label" not in ex:
            ex["label"] = int(ex["vuln"].max()) if len(ex["vuln"]) else 0

    target.bump(boundary, "seen", n_lines)
    target.bump(boundary, "valid", len(examples))
    target.bump(boundary, "rejected", n_lines - len(examples))
    if repaired:
        target.bump(boundary, "repaired", repaired)

    report = {
        "path": str(path),
        "lines": n_lines,
        "loaded": len(examples),
        "repaired": repaired,
        "fast_path": len(fast_bufs),
        **sink.report(),
    }
    if sink.total:
        logger.warning(
            "ingest %s: %d/%d rows quarantined (%s) -> %s", path,
            sink.total, n_lines, dict(sink.counts), sink.root)
    return examples, report

"""Fail-closed quarantine sink for contract-violating items.

Bad items never reach the model and never abort the corpus: each one is
recorded under ``<cache>/quarantine/`` with

- ``manifest.jsonl`` — one line per quarantined item: ``{"item_id",
  "boundary", "reason", "fragment", "ordinal"}`` (ordinal = quarantine
  order, so a manifest diff is stable across runs of the same corpus);
- ``items.jsonl`` — the raw offending payload (the JSONL line as read, or
  a JSON dump of the structured item) for post-mortem repair.

Writes are append-only line writes (the same posture as the reference's
``failed_joern.txt``): a crash mid-quarantine loses at most one line, and
two processes quarantining into the same directory interleave whole lines.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from pathlib import Path
from typing import Dict, List

from deepdfa_tpu.contracts.schema import ContractError, fragment_of

MANIFEST_NAME = "manifest.jsonl"
ITEMS_NAME = "items.jsonl"
DIRNAME = "quarantine"


def quarantine_dir(cache_path: str | Path) -> Path:
    """The quarantine root for a cache file or directory: the
    ``quarantine/`` sibling of a file, or child of a directory."""
    p = Path(cache_path)
    root = p if p.is_dir() else p.parent
    return root / DIRNAME


class Quarantine:
    """Append-only quarantine sink rooted at one directory."""

    def __init__(self, root: str | Path):
        self.root = Path(root)
        self.counts: collections.Counter = collections.Counter()
        self._ordinal = 0
        # One sink may be fed from concurrent transport threads (the
        # serve HTTP server handles each POST /scan on its own thread);
        # ordinal assignment + the two appends must stay one atom or the
        # manifest<->items ordinal join breaks.
        self._lock = threading.Lock()

    @property
    def manifest_path(self) -> Path:
        return self.root / MANIFEST_NAME

    @property
    def items_path(self) -> Path:
        return self.root / ITEMS_NAME

    def put(self, error: ContractError, raw=None) -> None:
        """Record one violation. ``raw``: the offending payload as read
        (a JSONL line string or a structured item); defaults to the
        error's own fragment."""
        with self._lock:
            self.root.mkdir(parents=True, exist_ok=True)
            entry = {
                "ordinal": self._ordinal,
                "item_id": error.item_id,
                "boundary": error.boundary,
                "reason": error.reason,
                "message": str(error),
                "fragment": error.fragment,
            }
            with open(self.manifest_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(entry) + "\n")
            with open(self.items_path, "a", encoding="utf-8") as f:
                f.write(json.dumps({
                    "ordinal": self._ordinal,
                    "item_id": error.item_id,
                    "raw": raw if isinstance(raw, str) else fragment_of(
                        raw if raw is not None else error.fragment,
                        limit=4096),
                }) + "\n")
            self._ordinal += 1
            self.counts[error.reason] += 1
        # Trace-visible quarantine: the run report counts these from
        # events.jsonl alone (import deferred — contracts stays importable
        # standalone; the hook is a no-op without an active run).
        from deepdfa_tpu import telemetry

        telemetry.event("quarantine", boundary=error.boundary,
                        reason=error.reason, item_id=error.item_id)

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def report(self) -> Dict:
        return {"quarantined": self.total,
                "by_reason": dict(sorted(self.counts.items())),
                "dir": str(self.root)}


def read_manifest(root: str | Path) -> List[Dict]:
    """All manifest entries under a quarantine root (empty when none)."""
    path = Path(root) / MANIFEST_NAME if Path(root).name != MANIFEST_NAME \
        else Path(root)
    if not path.exists():
        return []
    out: List[Dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def clear(root: str | Path) -> None:
    """Remove a quarantine directory's record files (a fresh-run reset —
    the gauntlet starts each soak from an empty manifest)."""
    for name in (MANIFEST_NAME, ITEMS_NAME):
        p = Path(root) / name
        if p.exists():
            os.remove(p)

"""Experiment launcher / model zoo.

Parity with the reference's sweep entry (CodeT5/sh/run_exp.py:1-167 →
exp_with_args.sh:1-100): one command resolves (task, sub_task, model_tag)
into the reference's per-task hyperparameters (source/target length, epochs,
patience, the model-tag-dependent batch size and learning rate), lays out
the run directory (models/summary/results), and dispatches to this
framework's trainers in-process — there is no bash indirection to a second
script because the trainers are importable.

  python -m deepdfa_tpu.exp --task defect --model_tag codet5_base \
      --data synthetic --res_dir results

Model zoo tags (run_exp.py:146-147): roberta, codebert, unixcoder,
codet5_small, codet5_base, codet5_large. Tasks (run_exp.py:148): summarize,
concode, translate, refine, defect, clone, multi_task.

Real datasets plug in through ``--data <dir>`` holding the CodeT5-format
files the data loaders consume; ``--data synthetic`` runs the whole sweep on
generated data (the generalized sample mode) so launcher plumbing is
testable without the archives.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time
from typing import Dict, Optional

TASKS = ("summarize", "concode", "translate", "refine", "defect", "clone",
         "multi_task")
MODEL_TAGS = ("roberta", "codebert", "unixcoder", "codet5_small",
              "codet5_base", "codet5_large")


@dataclasses.dataclass
class ExpConfig:
    task: str
    sub_task: str
    model_tag: str
    batch_size: int
    learning_rate: float  # absolute (the reference passes lr in units of 1e-5)
    source_length: int
    target_length: int
    patience: int
    epochs: int
    seed: int = 0


def get_sub_tasks(task: str):
    """run_exp.py:132-141."""
    return {
        "summarize": ["ruby", "javascript", "go", "python", "java", "php"],
        "translate": ["java-cs", "cs-java"],
        "refine": ["small", "medium"],
    }.get(task, ["none"])


def resolve(task: str, sub_task: str = "none", model_tag: str = "codet5_base",
            seed: int = 0) -> ExpConfig:
    """The reference's task/model hyperparameter table
    (run_exp.py:19-97 get_args_by_task_model)."""
    if task == "translate":
        src_len, trg_len, epoch, patience = 320, 256, 100, 5
    elif task == "summarize":
        src_len, trg_len, epoch, patience = 256, 128, 15, 2
    elif task == "refine":
        src_len = 130 if sub_task == "small" else 240
        trg_len = 120 if sub_task == "small" else 240
        epoch, patience = 50, 5
    elif task == "concode":
        src_len, trg_len, epoch, patience = 320, 150, 30, 3
    elif task == "defect":
        src_len, trg_len, epoch, patience = 512, 3, 10, 2
    elif task == "clone":
        src_len, trg_len, epoch, patience = 400, 400, 1, 2
    elif task == "multi_task":
        src_len = trg_len = -1
        epoch, patience = -1, -1
    else:
        raise ValueError(f"unknown task {task!r}")

    # Batch-size rules per model tag (run_exp.py:79-91).
    if "codet5_small" in model_tag:
        bs = 32
        if task in ("summarize", "translate") or (task == "refine" and sub_task == "small"):
            bs = 64
        elif task == "clone":
            bs = 25
    elif "codet5_large" in model_tag:
        bs = 8
    else:
        bs = 32
        if task == "translate":
            bs = 25
        elif task == "summarize":
            bs = 48
        elif task == "clone":
            bs = 16 if model_tag in ("codebert", "roberta") else 10

    lr = 5
    if task == "concode":
        lr = 10
    elif task == "defect":
        lr = 2
    return ExpConfig(
        task=task, sub_task=sub_task, model_tag=model_tag, batch_size=bs,
        learning_rate=lr * 1e-5, source_length=src_len, target_length=trg_len,
        patience=patience, epochs=epoch, seed=seed,
    )


def _t5_config(model_tag: str, tiny: bool):
    from deepdfa_tpu.models.t5 import T5Config

    if tiny:
        return T5Config.tiny()
    return {
        "codet5_small": T5Config.codet5_small,
        "codet5_base": T5Config.codet5_base,
        "codet5_large": T5Config.codet5_large,
    }[model_tag]()


def build_model(cfg: ExpConfig, tiny: bool = False, generation: bool = False):
    """Model-zoo construction: codet5_* tags build T5; encoder tags
    (roberta/codebert/unixcoder) build the RoBERTa Seq2Seq for generation
    tasks (reference models.py:195-408) and the LineVul classifier
    otherwise."""
    if cfg.model_tag.startswith("codet5"):
        from deepdfa_tpu.models.t5 import T5Model

        return T5Model(_t5_config(cfg.model_tag, tiny))
    from deepdfa_tpu.models.transformer import EncoderConfig

    enc = EncoderConfig.tiny() if tiny else EncoderConfig()
    if generation:
        from deepdfa_tpu.models.seq2seq import RobertaSeq2Seq, Seq2SeqConfig

        s2s = Seq2SeqConfig.tiny(enc.vocab_size) if tiny else Seq2SeqConfig(encoder=enc)
        return RobertaSeq2Seq(s2s)
    from deepdfa_tpu.models.linevul import LineVul

    return LineVul(enc)


def run_experiment(
    cfg: ExpConfig,
    data: str = "synthetic",
    res_dir: str = "results",
    tiny: bool = False,
    overrides: Optional[Dict] = None,
    pretrained: Optional[str] = None,
    tokenizer: Optional[str] = None,
    flowgnn: Optional[str] = None,
    beam_size: int = 10,
) -> Dict:
    """Run one experiment end to end; returns the result record written to
    ``<res_dir>/<task>_<sub_task>_<model_tag>/result.json`` (res_fn,
    run_exp.py:106-108)."""
    import numpy as np

    from deepdfa_tpu.core.config import TransformerTrainConfig

    run_name = f"{cfg.task}_{cfg.sub_task}_{cfg.model_tag}"
    out_dir = os.path.join(res_dir, run_name)
    os.makedirs(out_dir, exist_ok=True)

    tcfg = TransformerTrainConfig(
        batch_size=cfg.batch_size,
        eval_batch_size=cfg.batch_size,
        learning_rate=cfg.learning_rate,
        max_epochs=max(cfg.epochs, 1),
        early_stop_patience=cfg.patience if cfg.patience > 0 else None,
        seed=cfg.seed,
    )
    for k, v in (overrides or {}).items():
        tcfg = dataclasses.replace(tcfg, **{k: v})

    t0 = time.time()
    if pretrained and data != "synthetic" and tokenizer is None:
        # Without real tokenizer assets, dataset directories encode with
        # the hashing tokenizer, whose ids bear no relation to the BPE
        # vocabulary a checkpoint's embeddings were trained on —
        # fine-tuning would start from scrambled embeddings while the
        # record claims a pretrained run. Pass --tokenizer with the
        # checkpoint's assets to combine them.
        raise NotImplementedError(
            "--pretrained with --data <dir> needs the checkpoint's BPE "
            "tokenizer (--tokenizer <assets>); the hashing fallback's ids "
            "don't match the checkpoint vocabulary"
        )
    tok = None
    if tokenizer is not None:
        if data == "synthetic":
            # Synthetic data is random ids — recording a tokenizer the run
            # never used would misstate how the data was encoded.
            raise ValueError(
                "--tokenizer only applies to --data <dir> runs; it has no "
                "effect on synthetic data"
            )
        from deepdfa_tpu.data.text import load_bpe_tokenizer

        tok = load_bpe_tokenizer(tokenizer)
    if flowgnn and cfg.task != "defect":
        # The reference threads flowgnn_model only through the defect runner
        # (run_exp.py:7-16 → run_defect.py:160-246).
        raise ValueError("--flowgnn only applies to --task defect")
    if cfg.task == "defect":
        result = _run_defect(cfg, tcfg, data, tiny, pretrained, tok,
                             flowgnn=flowgnn, out_dir=out_dir)
    elif cfg.task == "clone":
        result = _run_clone(cfg, tcfg, data, tiny, tok, pretrained=pretrained,
                            out_dir=out_dir)
    elif cfg.task == "multi_task":
        result = _run_multitask(cfg, tcfg, data, tiny, pretrained=pretrained,
                                tok=tok, out_dir=out_dir,
                                beam_size=beam_size)
    else:  # generation family: summarize / translate / refine / concode
        result = _run_gen(cfg, tcfg, data, tiny, pretrained, tok,
                          out_dir=out_dir, beam_size=beam_size)
    result["seconds"] = round(time.time() - t0, 2)
    result["config"] = dataclasses.asdict(cfg)
    if pretrained:
        result["pretrained"] = pretrained
    if tokenizer:
        result["tokenizer"] = tokenizer
    if flowgnn:
        result["flowgnn"] = flowgnn

    res_fn = os.path.join(res_dir, run_name, "result.json")
    with open(res_fn, "w") as f:
        json.dump(result, f, indent=1)
    return result


def _tokenize_fn(tok):
    return lambda s: tok.convert_tokens_to_ids(tok.tokenize(s))


def _save_best(out_dir: Optional[str], state, epoch: int,
               metric_name: Optional[str] = None,
               metric: Optional[float] = None) -> None:
    """Persist the selected state's params (the reference keeps
    checkpoint-best-* dirs per run, run_gen.py:280-300, run_defect.py:
    383-405; params-only like fit-text so restore never depends on the
    optimizer tree). Restore pattern: CheckpointManager(dir).restore("best",
    {"params": fresh_init_params})."""
    if out_dir is None:
        return
    import jax

    from deepdfa_tpu.train.checkpoint import CheckpointManager

    CheckpointManager(out_dir).save_best(
        {"params": jax.device_get(state.params)}, epoch,
        metrics={metric_name: metric} if metric_name else None,
    )


from deepdfa_tpu.data.text import check_tok_vocab as _check_tok_vocab


def _gen_data_from_dir(cfg: ExpConfig, data_dir: str, vocab: int,
                       pad_id: int, eos_id: int, tok=None,
                       splits=("train", "dev"), source_prefix: str = ""):
    """Per-split arrays from a CodeT5-format dataset directory
    (the reference's layout, CodeT5/utils.py get_filenames). ``tok``:
    trained BPE assets (--tokenizer); defaults to the hashing tokenizer —
    vocab assets are not redistributable here; etl/tokenizer_train.py
    produces a real BPE to swap in. ``source_prefix``: the multi-task
    "{task} {sub_task}: " marker (_utils.py:24-28)."""
    import dataclasses as _dc

    from deepdfa_tpu.data.seq2seq import (
        READERS,
        encode_examples,
        get_filenames,
    )
    from deepdfa_tpu.data.text import HashingT5Tokenizer

    _check_tok_vocab(tok, vocab, pad_id=pad_id, eos_id=eos_id)
    if tok is None:
        tok = HashingT5Tokenizer(vocab)
    out = []
    for split in splits:
        ex = READERS[cfg.task](
            get_filenames(data_dir, cfg.task, cfg.sub_task, split)
        )
        if source_prefix:
            ex = [_dc.replace(e, source=source_prefix + e.source) for e in ex]
        out.append(
            encode_examples(
                ex, _tokenize_fn(tok), cfg.source_length, cfg.target_length,
                pad_id=pad_id, eos_id=eos_id,
            )
        )
    return out


def _toy_gen_data(n, vocab, src_len, trg_len, seed):
    import numpy as np

    rng = np.random.RandomState(seed)
    src = rng.randint(3, vocab, size=(n, min(src_len, 16))).astype(np.int32)
    tgt = src[:, : min(trg_len, 8)][:, ::-1].copy()  # learnable reverse task
    return {"source_ids": src, "target_ids": tgt}


def _load_pretrained_for(cfg, pretrained: str):
    """(model-ready config, nested init_params) for a model tag + HF dir.

    Nesting matches each model's submodule layout: DefectModel holds its
    stack under "t5", LineVul under "roberta", RobertaSeq2Seq under
    "encoder" (+ the tied "shared" table); the trainers graft the subtree
    onto a fresh init (text_loop._merge_params).
    """
    from deepdfa_tpu.models.pretrained import load_pretrained

    kind, mcfg, conv = load_pretrained(pretrained)
    want = "t5" if cfg.model_tag.startswith("codet5") else "roberta"
    if kind != want:
        raise ValueError(
            f"model_tag {cfg.model_tag!r} needs a {want} checkpoint, "
            f"{pretrained} holds {kind}"
        )
    return kind, mcfg, conv


def _split_exists(data_dir: str, task: str, sub_task: str, split: str) -> bool:
    from deepdfa_tpu.data.seq2seq import get_filenames

    return all(
        os.path.exists(p)
        for p in get_filenames(data_dir, task, sub_task, split).split(",")
    )


def _run_gen(cfg, tcfg, data, tiny, pretrained=None, tok=None, out_dir=None,
             beam_size=10):
    """``beam_size``: dev/test decoding width (the reference's --beam_size,
    run_gen.py:79,108 — default 10)."""
    from deepdfa_tpu.train.gen_loop import fit_gen

    init_params = None
    if pretrained:
        kind, mcfg, conv = _load_pretrained_for(cfg, pretrained)
        if kind == "t5":
            from deepdfa_tpu.models.t5 import T5Model

            model = T5Model(mcfg)
            init_params = conv  # T5Model IS the converted tree
        else:
            from deepdfa_tpu.models.seq2seq import RobertaSeq2Seq, Seq2SeqConfig

            model = RobertaSeq2Seq(Seq2SeqConfig(encoder=mcfg))
            # The seq2seq encoder is fed input_embeds from the shared table
            # (tie_weights, models.py:212-217), so it never creates a
            # word_embeddings param — that table seeds "shared" instead,
            # and the rest of the encoder subtree grafts as-is.
            enc_tree = dict(conv["params"])
            word = enc_tree.pop("word_embeddings")
            init_params = {"params": {
                "encoder": enc_tree,
                "shared": {"embedding": word["embedding"]},
            }}
    else:
        model = build_model(cfg, tiny=tiny, generation=True)
    vocab = model.cfg.vocab_size
    testd = None
    if data == "synthetic":
        train = _toy_gen_data(64, vocab, cfg.source_length, cfg.target_length, cfg.seed)
        evald = _toy_gen_data(16, vocab, cfg.source_length, cfg.target_length, cfg.seed + 1)
        max_tgt = 8
    else:
        splits = ["train", "dev"]
        # The paper's number comes from the test split evaluated with the
        # best checkpoint after training (run_gen.py:370-395); read it when
        # the directory ships one.
        has_test = _split_exists(data, cfg.task, cfg.sub_task, "test")
        if has_test:
            splits.append("test")
        parts = _gen_data_from_dir(
            cfg, data, vocab, model.cfg.pad_token_id,
            getattr(model.cfg, "eos_token_id", 2), tok=tok,
            splits=tuple(splits),
        )
        train, evald = parts[0], parts[1]
        testd = parts[2] if has_test else None
        max_tgt = cfg.target_length
    # BLEU scores over decoded text when the tokenizer can decode (real BPE
    # assets); over token ids otherwise. CodeBLEU (the concode metric,
    # run_gen.py:152-154) additionally needs parseable source text.
    decode_fn = getattr(tok, "decode", None) if tok is not None else None
    out = fit_gen(model, train, evald, tcfg, max_target_length=max_tgt,
                  beam_size=beam_size, init_params=init_params,
                  task=cfg.task, decode_fn=decode_fn, output_dir=out_dir,
                  codebleu_lang="java" if (cfg.task == "concode"
                                           and decode_fn) else None)
    _save_best(out_dir, out["state"], out["best_epoch"],
               "bleu_em", out["bleu_em"])
    result = {"eval_loss": float(out["eval_loss"]),
              "exact_match": float(out["exact_match"]),
              "bleu": float(out["bleu"]),
              "bleu_em": float(out["bleu_em"]),
              # Which space the BLEU n-grams were scored in: decoded subword
              # text (comparable to reference numbers) vs raw token-id
              # strings (self-consistent for selection only — synthetic/
              # hashing runs have no invertible tokenizer).
              "bleu_space": "text" if decode_fn else "ids",
              "best_epoch": int(out["best_epoch"])}
    if "codebleu" in out:
        result["codebleu"] = float(out["codebleu"])
    if testd is not None:
        from deepdfa_tpu.train.gen_loop import (
            _ids_to_text,
            bleu_for_task,
            evaluate_gen,
        )

        ev = evaluate_gen(model, out["state"], testd, tcfg, max_tgt,
                          beam_size=beam_size, return_preds=True)
        pad, eos = model.cfg.pad_token_id, model.cfg.eos_token_id
        preds = _ids_to_text(ev["pred_ids"], pad, eos, decode_fn)
        golds = _ids_to_text(testd["target_ids"][: len(preds)], pad, eos,
                             decode_fn)
        result["test"] = {
            "eval_loss": float(ev["eval_loss"]),
            "exact_match": float(ev["exact_match"]),
            "bleu": float(bleu_for_task(cfg.task, golds, preds)),
        }
        if cfg.task == "concode" and decode_fn:
            # CodeBLEU is concode's paper-reported test metric
            # (run_gen.py:152-154,386-391).
            from deepdfa_tpu.eval.codebleu import get_codebleu

            result["test"]["codebleu"] = float(
                get_codebleu(golds, preds, "java")["codebleu"]
            )
        if out_dir:
            from deepdfa_tpu.train.gen_loop import _dump_gen_predictions

            srcs = _ids_to_text(testd["source_ids"][: len(preds)], pad, eos,
                                decode_fn)
            _dump_gen_predictions(out_dir, "test_best", preds, golds, srcs)
    return result


def _run_defect(cfg, tcfg, data, tiny, pretrained=None, tok=None,
                flowgnn=None, out_dir=None):
    """Defect classification — DefectModel (eos-pooled T5) for codet5 tags,
    encoder classifier otherwise; both train through fit_text.

    ``pretrained``: HF checkpoint dir; the converted stack grafts onto the
    fresh init (the reference's from_pretrained flow, run_defect.py:155-158,
    linevul_main.py:605-621) — the task head always trains from scratch.

    ``flowgnn``: graph source spec — activates the DeepDFA-combined model
    the way ``--flowgnn_data``/``--flowgnn_model`` do in the reference
    (run_defect.py:160-246): graphs join text rows by example id, rows
    whose graph is missing are masked.
    """
    import numpy as np

    from deepdfa_tpu.train.text_loop import fit_text

    gcfg = None
    if flowgnn:
        from deepdfa_tpu.core.config import FeatureSpec, FlowGNNConfig

        feature = (FeatureSpec(limit_all=20, limit_subkeys=20) if tiny
                   else FeatureSpec())
        gcfg = FlowGNNConfig(
            feature=feature, encoder_mode=True, label_style="graph",
            **({"hidden_dim": 4, "n_steps": 2} if tiny else
               # run_defect.py:215-217 hardcodes hidden 32 / 5 steps.
               {"hidden_dim": 32, "n_steps": 5}),
        )
    rng = np.random.RandomState(cfg.seed)
    n, seq = 64, 16
    init_params = None
    if cfg.model_tag.startswith("codet5"):
        from deepdfa_tpu.models.t5 import DefectModel

        if pretrained:
            _, t5cfg, conv = _load_pretrained_for(cfg, pretrained)
            init_params = {"params": {"t5": conv["params"]}}
        else:
            t5cfg = _t5_config(cfg.model_tag, tiny)
        model = DefectModel(t5cfg, graph_config=gcfg)
        vocab, pad_id, style = t5cfg.vocab_size, t5cfg.pad_token_id, "t5"
        # The T5 classifier pools at the config's eos id, so the tokenizer
        # must agree on it (checked in _defect_data_from_dir).
        eos_id = t5cfg.eos_token_id
        ids = rng.randint(3, vocab, size=(n, seq)).astype(np.int32)
        ids[:, -1] = t5cfg.eos_token_id  # single-eos invariant (_utils.py:34)
    else:
        from deepdfa_tpu.models.linevul import LineVul
        from deepdfa_tpu.models.transformer import EncoderConfig

        if pretrained:
            _, enc, conv = _load_pretrained_for(cfg, pretrained)
            init_params = {"params": {"roberta": conv["params"]}}
        else:
            enc = EncoderConfig.tiny() if tiny else EncoderConfig()
        # auto = flash kernels on TPU, blockwise elsewhere (attention impls
        # don't touch the param tree, so pretrained grafts are unaffected).
        enc = dataclasses.replace(enc, attention_impl="auto")
        model = LineVul(enc, graph_config=gcfg)
        vocab, pad_id, style = enc.vocab_size, enc.pad_token_id, "roberta"
        eos_id = None  # the encoder classifier pools at [CLS], not eos
        ids = rng.randint(2, vocab, size=(n, seq)).astype(np.int32)
    if data == "synthetic":
        data_d = {
            "input_ids": ids,
            "labels": (rng.rand(n) < 0.5).astype(np.int32),
            "index": np.arange(n),
        }
        splits = {"train": np.arange(int(n * 0.8)),
                  "val": np.arange(int(n * 0.8), n)}
    else:
        data_d, splits = _defect_data_from_dir(cfg, data, vocab, style, tok,
                                               pad_id=pad_id, eos_id=eos_id)
    graphs_by_id = subkeys = budget = None
    if flowgnn:
        from deepdfa_tpu.core.config import subkeys_for
        from deepdfa_tpu.data.combined import (
            graph_join_and_budget,
            load_graph_source,
        )

        if flowgnn.startswith("synthetic") and data != "synthetic":
            # Synthetic graph ids are positional (0..N-1); a real dataset's
            # idx ids would join to nothing and every row would train
            # masked.
            raise ValueError(
                "--flowgnn synthetic only pairs with --data synthetic; "
                "point --flowgnn at the dataset's graph cache"
            )
        spec = (f"synthetic:{len(data_d['labels'])}"
                if flowgnn.startswith("synthetic") else flowgnn)
        gexamples = load_graph_source(spec, gcfg.feature, seed=cfg.seed)
        subkeys = subkeys_for(gcfg.feature)
        graphs_by_id, budget = graph_join_and_budget(
            gexamples, max(tcfg.batch_size, tcfg.eval_batch_size)
        )
    best_state, hist = fit_text(model, data_d, splits, tcfg, pad_id=pad_id,
                                init_params=init_params,
                                graphs_by_id=graphs_by_id,
                                subkeys=subkeys, graph_budget=budget)
    _save_best(out_dir, best_state, hist["best_epoch"],
               "val_f1", hist["best_val_f1"])
    result = {"best_val_f1": hist["best_val_f1"],
              "best_epoch": hist["best_epoch"]}
    if len(splits.get("test", ())):
        import jax

        from deepdfa_tpu.train.text_loop import (
            evaluate_text,
            make_text_eval_step,
        )

        ev = evaluate_text(
            jax.jit(make_text_eval_step(model)), best_state, data_d,
            splits["test"], tcfg, graphs_by_id, subkeys, budget,
            pad_id=pad_id,
        )
        result["test"] = {"loss": float(ev["loss"]), **ev["metrics"],
                          "num_missing": int(ev["num_missing"])}
    return result


def _defect_data_from_dir(cfg: ExpConfig, data_dir: str, vocab: int,
                          style: str, tok=None, pad_id=None, eos_id=None):
    """Defect train/valid JSONL ({idx, code|func, target} — the schema our
    export writes and the reference reads) into one fit_text data dict with
    train/val split indices."""
    import numpy as np

    from deepdfa_tpu.data.seq2seq import get_filenames, read_defect_examples
    from deepdfa_tpu.data.text import (
        HashingCodeTokenizer,
        HashingT5Tokenizer,
        encode_dataset,
    )

    _check_tok_vocab(tok, vocab, pad_id=pad_id, eos_id=eos_id)
    if tok is None:
        tok = (HashingT5Tokenizer if style == "t5"
               else HashingCodeTokenizer)(vocab)
    splits = ["train", "dev"]
    if _split_exists(data_dir, "defect", cfg.sub_task, "test"):
        # The reference tests from the best checkpoint after training
        # (run_defect.py:418-446) — that number is what the paper reports.
        splits.append("test")
    parts = []
    for split in splits:
        codes, labels, idx = read_defect_examples(
            get_filenames(data_dir, "defect", cfg.sub_task, split)
        )
        rows = [{"code": c, "label": l, "id": i}
                for c, l, i in zip(codes, labels, idx)]
        parts.append(encode_dataset(rows, tok, block_size=cfg.source_length,
                                    style=style))
    data_d = {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}
    bounds = np.cumsum([0] + [len(p["labels"]) for p in parts])
    out = {"train": np.arange(bounds[0], bounds[1]),
           "val": np.arange(bounds[1], bounds[2])}
    if len(parts) == 3:
        out["test"] = np.arange(bounds[2], bounds[3])
    return data_d, out


def _clone_model_and_init(cfg, tiny, pretrained):
    """CloneModel (always T5-stacked, CodeT5/models.py:64-122) with an
    optional pretrained t5 subtree grafting onto the fresh head
    (run_clone.py from_pretrained)."""
    from deepdfa_tpu.models.t5 import CloneModel

    init_params = None
    if pretrained:
        from deepdfa_tpu.models.pretrained import load_pretrained

        kind, t5cfg, conv = load_pretrained(pretrained)
        if kind != "t5":
            raise ValueError(
                f"the clone model is T5-stacked and needs a t5 checkpoint; "
                f"{pretrained} holds {kind}"
            )
        init_params = {"params": {"t5": conv["params"]}}
    else:
        tag = (cfg.model_tag if cfg.model_tag.startswith("codet5")
               else "codet5_base")
        t5cfg = _t5_config(tag, tiny)
    return CloneModel(t5cfg), t5cfg, init_params


def _run_clone(cfg, tcfg, data, tiny, tok=None, pretrained=None,
               out_dir=None):
    if data == "synthetic":
        return _fit_clone_synthetic(cfg, tcfg, tiny, pretrained,
                                    out_dir=out_dir)

    from deepdfa_tpu.data.seq2seq import get_filenames, read_clone_examples
    from deepdfa_tpu.data.text import HashingT5Tokenizer
    from deepdfa_tpu.train.clone_loop import (
        encode_clone_pairs,
        evaluate_clone,
        fit_clone,
    )

    model, t5cfg, init_params = _clone_model_and_init(cfg, tiny, pretrained)
    _check_tok_vocab(tok, t5cfg.vocab_size, pad_id=t5cfg.pad_token_id,
                     eos_id=t5cfg.eos_token_id)
    if tok is None:
        tok = HashingT5Tokenizer(t5cfg.vocab_size)
    # BigCloneBench layout: {root}/clone/{train,valid}.txt index +
    # {root}/clone/data.jsonl code table (CodeT5/utils.py, _utils.py:283-305).
    code_table = os.path.join(data, "clone", "data.jsonl")
    # Each half gets source_length tokens ([N, 2L] pair concat,
    # CodeT5/_utils.py:64-72).
    splits = ["train", "dev"]
    if _split_exists(data, "clone", cfg.sub_task, "test"):
        splits.append("test")
    sets = {}
    for split in splits:
        pairs = read_clone_examples(
            get_filenames(data, "clone", cfg.sub_task, split), code_table
        )
        sets[split] = encode_clone_pairs(
            pairs, _tokenize_fn(tok), cfg.source_length,
            pad_id=t5cfg.pad_token_id, eos_id=t5cfg.eos_token_id,
        )
    out = fit_clone(model, sets["train"], sets["dev"], tcfg,
                    init_params=init_params)
    _save_best(out_dir, out["state"], -1, "val_f1", out["best_f1"])
    result = {"best_f1": out["best_f1"], "eval_metrics": out["eval_metrics"]}
    if "test" in sets:
        # run_clone evaluates the test index with the selected state.
        result["test"] = evaluate_clone(model, out["state"].params,
                                        sets["test"], tcfg)
    return result


def _fit_clone_synthetic(cfg, tcfg, tiny, pretrained=None, out_dir=None):
    import numpy as np

    from deepdfa_tpu.train.clone_loop import fit_clone

    model, t5cfg, init_params = _clone_model_and_init(cfg, tiny, pretrained)
    rng = np.random.RandomState(cfg.seed)
    n, seq = 48, 12

    def pair(clone):
        a = rng.randint(3, t5cfg.vocab_size, size=seq)
        b = a.copy() if clone else rng.randint(3, t5cfg.vocab_size, size=seq)
        return np.concatenate([a, b])

    labels = (rng.rand(n) < 0.5).astype(np.int32)
    src = np.stack([pair(bool(l)) for l in labels]).astype(np.int32)
    train = {"source_ids": src[: int(n * 0.75)], "labels": labels[: int(n * 0.75)]}
    evald = {"source_ids": src[int(n * 0.75):], "labels": labels[int(n * 0.75):]}
    out = fit_clone(model, train, evald, tcfg, init_params=init_params)
    _save_best(out_dir, out["state"], -1, "val_f1", out["best_f1"])
    return {"best_f1": out["best_f1"], "eval_metrics": out["eval_metrics"]}


def _multitask_dir_data(data: str, vocab: int, pad_id: int,
                        eos_id: int, tok, seed: int):
    """(task_data, eval_data) dicts from whatever generation tasks a
    CodeT5-layout directory ships — the run_multi_gen.py data assembly
    (each task's source carries its "{task} {sub_task}: " prefix,
    _utils.py:24-28), composed from the single-task readers."""
    task_data, eval_data = {}, {}
    for task in ("summarize", "translate", "refine", "concode"):
        for sub in get_sub_tasks(task):
            if not (_split_exists(data, task, sub, "train")
                    and _split_exists(data, task, sub, "dev")):
                continue
            sub_cfg = resolve(task, sub, "codet5_small", seed=seed)
            prefix = (f"{task} {sub}: " if sub != "none" else f"{task}: ")
            train, dev = _gen_data_from_dir(
                sub_cfg, data, vocab, pad_id, eos_id, tok=tok,
                source_prefix=prefix,
            )
            name = f"{task}_{sub}" if sub != "none" else task
            task_data[name], eval_data[name] = train, dev
    if not task_data:
        raise ValueError(
            f"no multi-task training data under {data!r} (want the CodeT5 "
            "layout: summarize/<lang>/, translate/, refine/<size>/, "
            "concode/)"
        )
    return task_data, eval_data


def _run_multitask(cfg, tcfg, data, tiny, pretrained=None, tok=None,
                   out_dir=None, beam_size=None):
    from deepdfa_tpu.train.gen_loop import fit_gen_multitask

    init_params = None
    if pretrained:
        from deepdfa_tpu.models.pretrained import load_pretrained
        from deepdfa_tpu.models.t5 import T5Model

        kind, mcfg, conv = load_pretrained(pretrained)
        if kind != "t5":
            raise ValueError(
                f"multi_task trains the T5 stack and needs a t5 checkpoint; "
                f"{pretrained} holds {kind}"
            )
        model = T5Model(mcfg)
        init_params = conv  # T5Model IS the converted tree
    else:
        tag = (cfg.model_tag if cfg.model_tag.startswith("codet5")
               else "codet5_small")
        model = build_model(
            dataclasses.replace(cfg, model_tag=tag), tiny=tiny,
            generation=True,
        )
    vocab = model.cfg.vocab_size
    if data == "synthetic":
        tasks = {
            name: _toy_gen_data(32, vocab, 16, 8, cfg.seed + i)
            for i, name in enumerate(("summarize", "translate"))
        }
        evals = {
            name: _toy_gen_data(8, vocab, 16, 8, cfg.seed + 10 + i)
            for i, name in enumerate(("summarize", "translate"))
        }
        max_steps, max_tgt = 40, 8
    else:
        tasks, evals = _multitask_dir_data(
            data, vocab, model.cfg.pad_token_id,
            model.cfg.eos_token_id, tok, cfg.seed,
        )
        total = sum(len(t["source_ids"]) for t in tasks.values())
        epochs = tcfg.max_epochs if tcfg.max_epochs > 0 else 1
        max_steps = max(epochs * -(-total // tcfg.batch_size), 1)
        max_tgt = max(t["target_ids"].shape[1] for t in evals.values())
    # Dev decoding beam: run_multi_gen.py's eval_bleu generates with a
    # fixed num_beams=5 (:110) — NOT run_gen's --beam_size — so the CLI
    # flag (default 10, a run_gen.py default) is ignored here.
    del beam_size
    # BLEU over decoded text when the tokenizer can decode, over token ids
    # otherwise (the _run_gen rule) — selection must score the same space
    # single-task runs report.
    decode_fn = getattr(tok, "decode", None) if tok is not None else None
    # --patience N > 0 reaches fit_gen_multitask as
    # tcfg.early_stop_patience, which overrides the per-task table for
    # every task. --patience 0 (disable) became early_stop_patience=None
    # at tcfg construction — indistinguishable there from "unset", which
    # keeps the reference's table — so the disable is passed explicitly.
    patience = ({name: None for name in evals} if cfg.patience == 0
                else None)
    out = fit_gen_multitask(model, tasks, evals, tcfg, max_steps=max_steps,
                            max_target_length=max_tgt, beam_size=5,
                            init_params=init_params, decode_fn=decode_fn,
                            patience=patience)
    # checkpoint-last at the run root + per-task checkpoint-best-bleu dirs
    # (run_multi_gen.py:334-357, :465-470).
    _save_best(out_dir, out["state"], -1)
    if out_dir:
        import types

        for name, params in out["best_params"].items():
            if params is None:
                continue
            _save_best(os.path.join(out_dir, "checkpoint-best-bleu", name),
                       types.SimpleNamespace(params=params),
                       int(out["tasks"][name].get("step", -1)),
                       "bleu_em", out["tasks"][name].get("bleu_em"))
    return {"tasks": out["tasks"], "history": out["history"],
            "bleu_space": "text" if decode_fn else "ids"}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="deepdfa_tpu.exp")
    parser.add_argument("--task", choices=TASKS, default="defect")
    parser.add_argument("--sub_task", default="none")
    parser.add_argument("--model_tag", choices=MODEL_TAGS, default="codet5_base")
    parser.add_argument("--data", default="synthetic")
    parser.add_argument("--res_dir", default="results")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--tiny", action="store_true",
                        help="tiny model shapes (smoke tests)")
    parser.add_argument("--epochs", type=int, default=None,
                        help="override the task table's epoch count")
    parser.add_argument("--patience", type=int, default=None,
                        help="override the task table's early-stop "
                             "patience; 0 disables early stopping "
                             "(multi_task: disables the per-task patience "
                             "table)")
    parser.add_argument("--pretrained", default=None,
                        help="HF checkpoint dir to fine-tune from "
                             "(from_pretrained parity, run_defect.py:155-158)")
    parser.add_argument("--tokenizer", default=None,
                        help="trained tokenizer assets (tokenizer.json or "
                             "the vocab/merges pair etl/tokenizer_train.py "
                             "writes) for --data encoding; required to "
                             "combine --pretrained with --data")
    parser.add_argument("--beam_size", type=int, default=10,
                        help="dev/test decoding beam for the generation "
                             "tasks (run_gen.py:79 default)")
    parser.add_argument("--flowgnn", default=None,
                        help="graph source (synthetic | dbize cache dir | "
                             "etl export .jsonl) activating the DeepDFA-"
                             "combined defect model (run_defect.py "
                             "--flowgnn_data/--flowgnn_model)")
    args = parser.parse_args(argv)

    if args.sub_task not in get_sub_tasks(args.task):
        parser.error(f"sub_task {args.sub_task!r} invalid for {args.task!r} "
                     f"(choose from {get_sub_tasks(args.task)})")
    cfg = resolve(args.task, args.sub_task, args.model_tag, seed=args.seed)
    if args.patience is not None:
        cfg = dataclasses.replace(cfg, patience=args.patience)
    overrides = {"max_epochs": args.epochs} if args.epochs else None
    result = run_experiment(
        cfg, data=args.data, res_dir=args.res_dir, tiny=args.tiny,
        overrides=overrides, pretrained=args.pretrained,
        tokenizer=args.tokenizer, flowgnn=args.flowgnn,
        beam_size=args.beam_size,
    )
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

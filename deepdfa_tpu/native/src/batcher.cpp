// Native padded-graph batcher: the host-side hot loop of the input
// pipeline.
//
// The reference leans on DGL's C++ dgl.batch to splice graphs per step
// (DDFA/sastvd/linevd/datamodule.py:110-141); the TPU rebuild batches into
// fixed budgets (deepdfa_tpu/graphs/batch.py) and this kernel does the
// per-graph offsetting/scatter in C++ so feeding 8 chips doesn't bottleneck
// on a Python loop.
//
// Inputs are the per-graph arrays concatenated back-to-back; outputs are the
// zero-initialized padded batch arrays. Returns 0 on success or -(gi+1) if
// graph gi would overflow the node/edge budget.

#include <cstdint>
#include <cstring>

extern "C" {

int32_t batch_fill(int32_t n_graphs,
                   const int32_t* num_nodes,       // [n_graphs]
                   const int32_t* num_edges,       // [n_graphs] (pre-self-loop)
                   const int32_t* senders_cat,     // [sum(num_edges)]
                   const int32_t* receivers_cat,
                   const int32_t* vuln_cat,        // [sum(num_nodes)]
                   const int32_t* feats_cat,       // [n_subkeys, sum(num_nodes)]
                   int32_t n_subkeys,
                   int32_t add_self_loops,
                   int32_t max_nodes, int32_t max_edges,
                   int32_t* feats_out,             // [n_subkeys, max_nodes]
                   int32_t* vuln_out,              // [max_nodes]
                   int32_t* senders_out,           // [max_edges]
                   int32_t* receivers_out,
                   int32_t* node_graph,            // [max_nodes]
                   uint8_t* node_mask,             // [max_nodes]
                   uint8_t* edge_mask) {           // [max_edges]
  int64_t total_nodes = 0, total_edges = 0;
  for (int32_t g = 0; g < n_graphs; ++g) {
    total_nodes += num_nodes[g];
    total_edges += num_edges[g];
  }

  int32_t node_off = 0, edge_off = 0;
  int64_t in_node = 0, in_edge = 0;
  for (int32_t g = 0; g < n_graphs; ++g) {
    const int32_t n = num_nodes[g];
    const int32_t e_in = num_edges[g];
    const int32_t e = e_in + (add_self_loops ? n : 0);
    if (node_off + n > max_nodes || edge_off + e > max_edges) return -(g + 1);

    for (int32_t k = 0; k < n_subkeys; ++k) {
      std::memcpy(feats_out + (int64_t)k * max_nodes + node_off,
                  feats_cat + (int64_t)k * total_nodes + in_node,
                  n * sizeof(int32_t));
    }
    std::memcpy(vuln_out + node_off, vuln_cat + in_node, n * sizeof(int32_t));
    for (int32_t i = 0; i < e_in; ++i) {
      senders_out[edge_off + i] = senders_cat[in_edge + i] + node_off;
      receivers_out[edge_off + i] = receivers_cat[in_edge + i] + node_off;
    }
    if (add_self_loops) {
      for (int32_t i = 0; i < n; ++i) {
        senders_out[edge_off + e_in + i] = node_off + i;
        receivers_out[edge_off + e_in + i] = node_off + i;
      }
    }
    for (int32_t i = 0; i < n; ++i) node_graph[node_off + i] = g;
    std::memset(node_mask + node_off, 1, n);
    std::memset(edge_mask + edge_off, 1, e);

    node_off += n;
    edge_off += e;
    in_node += n;
    in_edge += e_in;
  }
  return 0;
}

}  // extern "C"

// Native worklist reaching-definitions solver.
//
// The reference gets its training-time dataflow solutions from Joern's
// Scala ReachingDefProblem solver (DDFA/storage/external/get_dataflow_output.sc:37-55)
// and keeps a pure-Python checker (DDFA/code_gnn/analysis/dataflow.py:103-181).
// This is the TPU-native framework's production solver: a C++ bitset
// worklist over the CFG, bit-identical to the Python oracle in
// deepdfa_tpu/etl/reaching.py (the fixpoint of a monotone union/mask system
// is unique, so agreement is exact, not approximate).
//
// Graph encoding (prepared by the Python caller):
//   n            dense CFG node count (0..n-1)
//   succ/pred    CSR adjacency (indptr int32[n+1], indices int32[m])
//   gen_var[i]   variable id this node defines, or -1 (identity of a
//                definition is its node index; variable ids are interned
//                strings)
// Outputs: packed uint64 bitsets, `words` words per node, definition d's
// bit is (rank of d among gen nodes) — in_bits/out_bits are the IN/OUT sets
// of the fixpoint.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

int32_t reachdef_words(int32_t n_nodes, const int32_t* gen_var) {
  int32_t ndefs = 0;
  for (int32_t i = 0; i < n_nodes; ++i) ndefs += gen_var[i] >= 0;
  return ndefs ? (ndefs + 63) / 64 : 1;
}

void reachdef_solve(int32_t n,
                    const int32_t* succ_indptr, const int32_t* succ_indices,
                    const int32_t* pred_indptr, const int32_t* pred_indices,
                    const int32_t* gen_var,
                    uint64_t* in_bits, uint64_t* out_bits, int32_t words) {
  // Definition rank per node (-1 if the node defines nothing).
  std::vector<int32_t> def_rank(n, -1);
  int32_t ndefs = 0;
  int32_t max_var = -1;
  for (int32_t i = 0; i < n; ++i) {
    if (gen_var[i] >= 0) {
      def_rank[i] = ndefs++;
      if (gen_var[i] > max_var) max_var = gen_var[i];
    }
  }

  // Per-variable kill mask: every definition of that variable.
  std::vector<uint64_t> var_mask((size_t)(max_var + 1) * words, 0);
  for (int32_t i = 0; i < n; ++i) {
    if (gen_var[i] >= 0) {
      uint64_t* m = var_mask.data() + (size_t)gen_var[i] * words;
      m[def_rank[i] >> 6] |= 1ull << (def_rank[i] & 63);
    }
  }

  std::memset(in_bits, 0, (size_t)n * words * sizeof(uint64_t));
  std::memset(out_bits, 0, (size_t)n * words * sizeof(uint64_t));

  // FIFO worklist seeded with every node in index order (matches the
  // Python deque; the fixpoint is order-independent anyway).
  std::vector<int32_t> queue(n);
  std::vector<uint8_t> queued(n, 1);
  for (int32_t i = 0; i < n; ++i) queue[i] = i;
  size_t head = 0;

  std::vector<uint64_t> in_n(words), out_n(words);
  while (head < queue.size()) {
    int32_t u = queue[head++];
    queued[u] = 0;

    // IN[u] = union of OUT[p]
    std::memset(in_n.data(), 0, words * sizeof(uint64_t));
    for (int32_t e = pred_indptr[u]; e < pred_indptr[u + 1]; ++e) {
      const uint64_t* po = out_bits + (size_t)pred_indices[e] * words;
      for (int32_t w = 0; w < words; ++w) in_n[w] |= po[w];
    }
    std::memcpy(in_bits + (size_t)u * words, in_n.data(),
                words * sizeof(uint64_t));

    // OUT[u] = GEN[u] | (IN[u] \ KILL[u]); KILL = other defs of u's var.
    if (gen_var[u] >= 0) {
      const uint64_t* vm = var_mask.data() + (size_t)gen_var[u] * words;
      for (int32_t w = 0; w < words; ++w) out_n[w] = in_n[w] & ~vm[w];
      out_n[def_rank[u] >> 6] |= 1ull << (def_rank[u] & 63);
    } else {
      std::memcpy(out_n.data(), in_n.data(), words * sizeof(uint64_t));
    }

    uint64_t* uo = out_bits + (size_t)u * words;
    bool changed = std::memcmp(uo, out_n.data(), words * sizeof(uint64_t)) != 0;
    if (changed) {
      std::memcpy(uo, out_n.data(), words * sizeof(uint64_t));
      for (int32_t e = succ_indptr[u]; e < succ_indptr[u + 1]; ++e) {
        int32_t s = succ_indices[e];
        if (!queued[s]) {
          queued[s] = 1;
          queue.push_back(s);
        }
      }
    }
  }
}

}  // extern "C"

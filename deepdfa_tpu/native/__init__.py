"""Native (C++) runtime components, built on demand with g++ and bound via
ctypes.

The reference's runtime leans on native code throughout — DGL's C++ graph
batching kernels, Joern's Scala dataflow solver (SURVEY §2.2 N1/N4). The
TPU rebuild keeps that split: JAX/XLA/Pallas own the accelerator, and the
host-side hot paths live here:

- ``reachdef.cpp``   — bitset worklist reaching-definitions solver
  (production path; the pure-Python ``etl/reaching.py`` is the oracle)
- ``batcher.cpp``    — padded graph batch assembly feeding the device

Build: one shared library compiled from every ``src/*.cpp`` on first use,
cached under ``_build/`` keyed by a source+flags hash. No pybind11 (not in
the image): plain ``extern "C"`` + ctypes. If no C++ toolchain is available
the callers fall back to their Python implementations (``available()``).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

_SRC_DIR = Path(__file__).resolve().parent / "src"
_BUILD_DIR = Path(__file__).resolve().parent / "_build"
_CXX = os.environ.get("CXX", "g++")
_CXXFLAGS = ["-O3", "-std=c++17", "-fPIC", "-shared", "-Wall"]

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_error: Optional[str] = None


def _source_hash() -> str:
    h = hashlib.sha256()
    h.update(" ".join([_CXX] + _CXXFLAGS).encode())
    for src in sorted(_SRC_DIR.glob("*.cpp")):
        h.update(src.name.encode())
        h.update(src.read_bytes())
    return h.hexdigest()[:16]


def _build() -> Path:
    _BUILD_DIR.mkdir(exist_ok=True)
    out = _BUILD_DIR / f"libdeepdfa_native_{_source_hash()}.so"
    if out.exists():
        return out
    sources = sorted(str(p) for p in _SRC_DIR.glob("*.cpp"))
    tmp = out.with_suffix(f".so.tmp{os.getpid()}")  # unique per builder
    cmd = [_CXX, *_CXXFLAGS, "-o", str(tmp), *sources]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed: {' '.join(cmd)}\n{proc.stderr}")
    os.replace(tmp, out)  # atomic: concurrent builders race benignly
    return out


def _load() -> ctypes.CDLL:
    global _lib, _lib_error
    with _lock:
        if _lib is not None:
            return _lib
        if _lib_error is not None:
            raise RuntimeError(_lib_error)
        try:
            lib = ctypes.CDLL(str(_build()))
        except Exception as e:  # toolchain missing, build error, bad .so
            _lib_error = str(e)
            raise RuntimeError(_lib_error) from e

        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")

        lib.reachdef_words.restype = ctypes.c_int32
        lib.reachdef_words.argtypes = [ctypes.c_int32, i32p]
        lib.reachdef_solve.restype = None
        lib.reachdef_solve.argtypes = [
            ctypes.c_int32, i32p, i32p, i32p, i32p, i32p, u64p, u64p,
            ctypes.c_int32,
        ]
        lib.batch_fill.restype = ctypes.c_int32
        lib.batch_fill.argtypes = [
            ctypes.c_int32, i32p, i32p, i32p, i32p, i32p, i32p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            i32p, i32p, i32p, i32p, i32p, u8p, u8p,
        ]
        _lib = lib
        return _lib


def available() -> bool:
    """True if the native library loads (builds) on this host."""
    try:
        _load()
        return True
    except RuntimeError:
        return False


def build_error() -> Optional[str]:
    if _lib is None and _lib_error is None:
        available()
    return _lib_error


# ---------------------------------------------------------------------------
# Reaching definitions
# ---------------------------------------------------------------------------

def solve_reaching(
    n: int,
    succ_indptr: np.ndarray,
    succ_indices: np.ndarray,
    pred_indptr: np.ndarray,
    pred_indices: np.ndarray,
    gen_var: np.ndarray,
) -> Tuple[List[List[int]], List[List[int]]]:
    """Run the C++ solver over a dense-indexed CFG.

    ``gen_var[i]`` is the interned variable id node i defines (-1 if none).
    Returns (in_defs, out_defs): per node, the sorted list of *defining node
    indices* whose definitions reach it.
    """
    lib = _load()
    gen_var = np.ascontiguousarray(gen_var, np.int32)
    words = int(lib.reachdef_words(n, gen_var)) if n else 1
    in_bits = np.zeros((max(n, 1), words), np.uint64)
    out_bits = np.zeros((max(n, 1), words), np.uint64)
    if n:
        lib.reachdef_solve(
            n,
            np.ascontiguousarray(succ_indptr, np.int32),
            np.ascontiguousarray(succ_indices, np.int32),
            np.ascontiguousarray(pred_indptr, np.int32),
            np.ascontiguousarray(pred_indices, np.int32),
            gen_var,
            in_bits,
            out_bits,
            words,
        )
    def_nodes = np.flatnonzero(gen_var >= 0)

    def unpack(bits: np.ndarray) -> List[List[int]]:
        # [n, words] uint64 -> per-node defining-node index lists
        u8 = bits.view(np.uint8)
        expanded = np.unpackbits(u8, axis=1, bitorder="little")
        out = []
        for i in range(n):
            ranks = np.flatnonzero(expanded[i, : len(def_nodes)])
            out.append(def_nodes[ranks].tolist())
        return out

    return unpack(in_bits)[:n], unpack(out_bits)[:n]


# ---------------------------------------------------------------------------
# Graph batching
# ---------------------------------------------------------------------------

def fill_batch(
    graphs,
    n_graphs: int,
    max_nodes: int,
    max_edges: int,
    subkeys,
    add_self_loops: bool = True,
) -> Dict[str, np.ndarray]:
    """Assemble the padded batch arrays for ``graphs`` natively.

    Same contract as the Python loop in graphs/batch.py:batch_graphs; raises
    ValueError on budget overflow with the same message shape.
    """
    lib = _load()
    num_nodes = np.array([int(g["num_nodes"]) for g in graphs], np.int32)
    num_edges = np.array([len(g["senders"]) for g in graphs], np.int32)
    cat = lambda key, dt: (
        np.concatenate([np.asarray(g[key], dt) for g in graphs])
        if graphs else np.zeros(0, dt)
    )
    senders_cat = cat("senders", np.int32)
    receivers_cat = cat("receivers", np.int32)
    vuln_cat = cat("vuln", np.int32)
    total_nodes = int(num_nodes.sum())
    feats_cat = np.zeros((len(subkeys), total_nodes), np.int32)
    for ki, k in enumerate(subkeys):
        off = 0
        for g in graphs:
            n = int(g["num_nodes"])
            feats_cat[ki, off : off + n] = np.asarray(g["feats"][k], np.int32)
            off += n

    out = {
        "feats": np.zeros((len(subkeys), max_nodes), np.int32),
        "vuln": np.zeros(max_nodes, np.int32),
        "senders": np.zeros(max_edges, np.int32),
        "receivers": np.zeros(max_edges, np.int32),
        "node_graph": np.zeros(max_nodes, np.int32),
        "node_mask": np.zeros(max_nodes, np.uint8),
        "edge_mask": np.zeros(max_edges, np.uint8),
    }
    rc = lib.batch_fill(
        len(graphs), num_nodes, num_edges, senders_cat, receivers_cat,
        vuln_cat, feats_cat, len(subkeys), int(add_self_loops),
        max_nodes, max_edges,
        out["feats"], out["vuln"], out["senders"], out["receivers"],
        out["node_graph"], out["node_mask"], out["edge_mask"],
    )
    if rc < 0:
        gi = -rc - 1
        node_off = int(num_nodes[:gi].sum())
        edge_off = int((num_edges[:gi] + (num_nodes[:gi] if add_self_loops else 0)).sum())
        e = int(num_edges[gi]) + (int(num_nodes[gi]) if add_self_loops else 0)
        raise ValueError(
            f"graph {gi} overflows budget "
            f"(nodes {node_off}+{num_nodes[gi]}/{max_nodes}, "
            f"edges {edge_off}+{e}/{max_edges})"
        )
    return out

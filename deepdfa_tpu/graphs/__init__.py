from deepdfa_tpu.graphs.batch import (
    GraphBatch,
    batch_graphs,
    graph_label_from_nodes,
    pad_budget_for,
)
from deepdfa_tpu.graphs.segment import (
    segment_max,
    segment_softmax,
    segment_sum,
)

__all__ = [
    "GraphBatch",
    "batch_graphs",
    "graph_label_from_nodes",
    "pad_budget_for",
    "segment_max",
    "segment_softmax",
    "segment_sum",
]

"""Segment ops: the XLA-native replacement for DGL's message-passing kernels.

DGL implements gather/scatter message passing in CUDA (GatedGraphConv's SpMM,
GlobalAttentionPooling's per-graph softmax). On TPU the same computation is
expressed with static-shape segment reductions; the kernels in
``deepdfa_tpu.ops`` specialize the hot paths further.

Scatter is the slow lane on TPU — XLA serializes it, and a traced train step
spends most of its fixed cost in the pooling/embedding scatters (measured on
v5e: ~60-190 us per scatter/gather fusion vs ~15 us for an equivalent-size
matmul; bench.py module docstring). :func:`segment_onehot` is the dense
escape hatch: a [num_segments, n] assignment matrix turns masked segment
sums into MXU matmuls whose backward is also a matmul, no scatter anywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def segment_sum(data: jnp.ndarray, segment_ids: jnp.ndarray, num_segments: int) -> jnp.ndarray:
    """Sum rows of ``data`` into ``num_segments`` buckets by ``segment_ids``.

    ``num_segments`` must be static for XLA. Padding contract: callers zero
    out padded rows *before* calling (padded ids point at slot 0, so unmasked
    garbage would accumulate there) — see the masked message step in
    ``models/flowgnn.py`` and ``segment_softmax``'s ``mask`` argument.
    """
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_max(
    data: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    initial: float = -jnp.inf,
) -> jnp.ndarray:
    out = jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
    # Empty segments come back as -inf; replace with `initial` when requested.
    if initial != -jnp.inf:
        out = jnp.where(jnp.isneginf(out), initial, out)
    return out


def segment_onehot(
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: jnp.ndarray | None = None,
    dtype: jnp.dtype = jnp.float32,
) -> jnp.ndarray:
    """Dense assignment matrix ``M`` [num_segments, n]: ``M @ x`` equals the
    masked ``segment_sum(x)`` — as one MXU matmul instead of a scatter, with
    a matmul transpose (not a gather) as its autodiff backward.

    ``M`` itself is structural: build it under ``stop_gradient`` semantics
    (boolean comparisons carry no gradient) and reuse it for every reduction
    over the same batch layout.
    """
    m = segment_ids[None, :] == jnp.arange(num_segments)[:, None]
    if mask is not None:
        m = m & mask[None, :]
    return m.astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def onehot_take(
    table: jnp.ndarray, idx: jnp.ndarray, precision=None
) -> jnp.ndarray:
    """``table[idx]`` whose BACKWARD is an assignment-matrix matmul instead
    of XLA's scatter-add.

    The forward gather is cheap on TPU; the grad-accumulation scatter is
    not (serialized — ~60 us per table per step in the traced GNN train
    step vs ~15 us for the equivalent dense dot; swapping it for
    ``onehot.T @ g`` measured 0.83 -> 0.61 ms/step end to end, bench.py).

    ``precision`` applies to the backward dot. The cotangent arrives f32
    regardless of training dtype (autodiff of the lookup's downstream
    cast), so the caller picks: DEFAULT for bf16 training (one bf16
    rounding of g, f32 MXU accumulation — no coarser than the surrounding
    compute), HIGHEST for f32 runs (exact like the scatter).
    """
    return jnp.take(table, idx, axis=0)


def _onehot_take_fwd(table, idx, precision=None):
    # Zero-width marker carries the table's static row count and dtype into
    # the backward without holding the table itself alive.
    marker = jnp.zeros((table.shape[0], 0), table.dtype)
    return jnp.take(table, idx, axis=0), (idx, marker)


def _onehot_take_bwd(precision, res, g):
    import numpy as np

    idx, marker = res
    num = marker.shape[0]
    # Match jnp.take's default (mode="fill") semantics exactly, as the
    # scatter-add backward of the "take" oracle does: negative indices wrap
    # pythonically, out-of-range indices contribute NOTHING (they match no
    # row of the assignment matrix — take's forward filled them with NaN
    # and its backward drops their cotangents). Any index rank flattens
    # against the matching flattened cotangent rows.
    flat_idx = jnp.ravel(idx)
    flat_idx = jnp.where(flat_idx < 0, flat_idx + num, flat_idx)
    flat_g = g.reshape(flat_idx.shape[0], -1)
    onehot = segment_onehot(flat_idx, num, dtype=g.dtype)
    dtable = jax.lax.dot_general(
        onehot, flat_g, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=precision,
    ).astype(marker.dtype)
    return dtable, np.zeros(idx.shape, jax.dtypes.float0)


onehot_take.defvjp(_onehot_take_fwd, _onehot_take_bwd)


def segment_softmax(
    logits: jnp.ndarray,
    segment_ids: jnp.ndarray,
    num_segments: int,
    mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Numerically-stable softmax within each segment.

    This is the TPU equivalent of DGL ``GlobalAttentionPooling``'s
    ``dgl.softmax_nodes``: gate logits are normalized over the nodes of each
    graph. ``mask`` zeroes padded rows so they get zero weight.
    """
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    # max per segment, broadcast back
    seg_max = jax.ops.segment_max(logits, segment_ids, num_segments=num_segments)
    seg_max = jnp.where(jnp.isneginf(seg_max), 0.0, seg_max)
    shifted = logits - seg_max[segment_ids]
    exp = jnp.exp(shifted)
    if mask is not None:
        exp = jnp.where(mask, exp, 0.0)
    denom = jax.ops.segment_sum(exp, segment_ids, num_segments=num_segments)
    denom = jnp.where(denom > 0, denom, 1.0)
    return exp / denom[segment_ids]

"""Static-shape graph batching.

DGL batches arbitrary-size graphs dynamically (``dgl.batch``,
reference: DDFA/sastvd/linevd/datamodule.py:110-141, dataset.py:76). XLA
compiles one program per shape, so here a batch is a fixed budget of
``n_graphs`` graph slots, ``max_nodes`` node slots and ``max_edges`` edge
slots; real entries are marked by masks and padding is inert under the masked
segment ops. Budgets are rounded to a small set of buckets so eval traffic
causes a handful of compiles, not one per batch.

Self-loop semantics: the reference bakes self-loops into its cached graphs
(``dgl.add_self_loop``, DDFA/sastvd/scripts/dbize_graphs.py:25); here
``batch_graphs(add_self_loops=True)`` applies the same transformation at
batch-build time so upstream storage stays loop-free.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence

import jax.numpy as jnp
import numpy as np
from flax import struct

from deepdfa_tpu.contracts.schema import ContractError



@struct.dataclass
class GraphBatch:
    """A padded batch of graphs (a pytree; all leaves static-shape).

    node_feats  : dict subkey -> int32[max_nodes] abstract-dataflow indices
                  (0 = not-a-definition, 1.. = vocab; reference
                  DDFA/sastvd/scripts/dbize_absdf.py:35-43)
    node_vuln   : int32[max_nodes] per-node vulnerability label (_VULN)
    senders     : int32[max_edges] source node slot of each edge
    receivers   : int32[max_edges] destination node slot of each edge
    node_graph  : int32[max_nodes] graph slot each node belongs to
    node_mask   : bool[max_nodes]
    edge_mask   : bool[max_edges]
    graph_mask  : bool[n_graphs]
    graph_ids   : int32[n_graphs] original example ids (host bookkeeping,
                  -1 for empty slots)
    """

    node_feats: Dict[str, jnp.ndarray]
    node_vuln: jnp.ndarray
    senders: jnp.ndarray
    receivers: jnp.ndarray
    node_graph: jnp.ndarray
    node_mask: jnp.ndarray
    edge_mask: jnp.ndarray
    graph_mask: jnp.ndarray
    graph_ids: jnp.ndarray
    # Optional block-sparse adjacency (ops/tile_spmm.TileAdjacency) for the
    # Pallas MXU message-passing path; None → XLA segment ops.
    tile_adj: Optional[Any] = None
    # Optional block-banded adjacency (ops/band_spmm.BandAdjacency): the
    # fully-parallel batched-matmul message path (message_impl="band").
    band_adj: Optional[Any] = None
    # Optional per-node dataflow-solution bits (_DF_IN/_DF_OUT analogues,
    # reference base_module.py:83-95): int32[max_nodes], built when the
    # examples carry "df_in"/"df_out" (batch_graphs(with_dataflow=True)).
    node_df_in: Optional[jnp.ndarray] = None
    node_df_out: Optional[jnp.ndarray] = None

    @property
    def n_graphs(self) -> int:
        return self.graph_mask.shape[0]

    @property
    def max_nodes(self) -> int:
        return self.node_mask.shape[0]

    @property
    def max_edges(self) -> int:
        return self.edge_mask.shape[0]


def graph_label_from_nodes(batch: GraphBatch, impl: str = "auto") -> jnp.ndarray:
    """Graph-level label = max node ``_VULN`` over real nodes.

    Parity with the reference's per-graph label extraction
    (DDFA/code_gnn/models/base_module.py:87-88: ``g.ndata["_VULN"].max()``
    per unbatched graph). Padded nodes are routed through value 0 so an
    all-padding slot yields label 0 (and is excluded by graph_mask anyway).

    On TPU, computed as a masked broadcast-compare + row max instead of a
    segment_max: XLA serializes TPU scatters, and this per-step scatter-max
    cost ~70 us in the traced train step (bench.py module docstring); the
    dense [n_graphs, max_nodes] reduce fuses into one cheap kernel. Off-TPU
    the O(n) segment_max stays (CPU eval hosts should not pay the
    O(n_graphs * max_nodes) zero-fill) — the pool_impl/embed_impl backend
    gate, core/backend.py.
    """
    from deepdfa_tpu.core.backend import resolve_auto
    from deepdfa_tpu.graphs.segment import segment_max

    if resolve_auto(impl, tpu="dense", other="segment") == "segment":
        return segment_max(
            jnp.where(batch.node_mask,
                      batch.node_vuln.astype(jnp.float32), -jnp.inf),
            batch.node_graph, batch.n_graphs, initial=0.0,
        )
    vuln = jnp.where(batch.node_mask, batch.node_vuln, 0).astype(jnp.float32)
    member = (
        batch.node_graph[None, :]
        == jnp.arange(batch.n_graphs, dtype=batch.node_graph.dtype)[:, None]
    )
    return jnp.where(member, vuln[None, :], 0.0).max(axis=1)


# Bucket ladder top for padding budgets: beyond it, sizes stay exact (a
# pow2 round-up at tens of millions of slots doubles memory for nothing).
_BUCKET_TOP = 2 ** 21


def select_bucket(n: int, maximum: Optional[int] = None,
                  minimum: int = 16) -> int:
    """Round ``n`` up to the padding-bucket ladder (powers of two from
    ``minimum``).

    THE bucket-rounding rule, shared by training batching
    (:func:`pad_budget_for`, ladder base 16) and the serving micro-batcher
    (``deepdfa_tpu.serve``, slot ladder base 1) — one rule means one
    bounded set of compiled shapes across both paths. ``maximum`` caps the
    result (a serving slot count never exceeds the configured batch);
    ``n`` beyond the cap or the ladder top comes back unrounded so callers
    fail on their budget checks instead of silently over-allocating.
    """
    n = max(int(n), 1)
    if maximum is not None and n >= maximum:
        return max(n, maximum)
    if n > _BUCKET_TOP:
        return n
    b = minimum
    while b < n:
        b *= 2
    return b if maximum is None else min(b, maximum)


def slot_nodes_for(
    graphs: Sequence[Mapping], minimum: int = 16, tile: Optional[int] = None
) -> int:
    """The dense-slot size for ``slot_nodes`` packing: the padding-bucket
    ladder (:func:`select_bucket`) rounded up from the largest graph.

    The pow2 ladder is what makes the slots *(nodes × tile)-aligned* for
    free: with ``tile`` itself a power of two, either the slot divides the
    tile (several whole graphs per MXU row tile) or the tile divides the
    slot (one graph spanning whole tiles) — a graph can straddle at most
    ``ceil(slot/tile)`` adjacent tiles, which is exactly the band
    bandwidth the fused kernel's rolling window pays for. ``tile`` only
    enforces the power-of-two compatibility contract; it never widens the
    slot."""
    biggest = max((int(g["num_nodes"]) for g in graphs), default=1)
    slot = select_bucket(max(biggest, 1), minimum=minimum)
    if tile is not None and tile & (tile - 1):
        raise ValueError(f"tile {tile} is not a power of two")
    return slot


def pad_budget_for(
    graphs: Sequence[Mapping], n_graphs: int, add_self_loops: bool = True
) -> Dict[str, int]:
    """Pick bucketed node/edge budgets covering every graph in ``graphs``
    when packed ``n_graphs`` at a time (greedy order-preserving packing)."""
    max_nodes = 0
    max_edges = 0
    for start in range(0, len(graphs), n_graphs):
        chunk = graphs[start : start + n_graphs]
        nodes = sum(int(g["num_nodes"]) for g in chunk)
        edges = sum(len(g["senders"]) for g in chunk)
        if add_self_loops:
            edges += nodes
        max_nodes = max(max_nodes, nodes)
        max_edges = max(max_edges, edges)
    return {
        "n_graphs": n_graphs,
        "max_nodes": select_bucket(max(max_nodes, 1)),
        "max_edges": select_bucket(max(max_edges, 1)),
    }


def batch_graphs(
    graphs: Sequence[Mapping],
    n_graphs: int,
    max_nodes: int,
    max_edges: int,
    subkeys: Sequence[str],
    add_self_loops: bool = True,
    build_tile_adj: bool = False,
    tile: Optional[int] = None,  # None -> ops.tile_spmm.DEFAULT_TILE
    tile_pad_nz: Optional[int] = None,
    build_band_adj: bool = False,
    band_bandwidth: Optional[int] = None,
    impl: str = "auto",
    with_dataflow: bool = False,
    slot_nodes: Optional[int] = None,
    shape_series: Optional[str] = "train",
) -> "GraphBatch":
    """Pack up to ``n_graphs`` graphs into one padded batch (host-side).

    Each graph mapping needs: ``num_nodes``, ``senders``, ``receivers``,
    ``vuln`` (int[num_nodes]), ``feats`` (dict subkey -> int[num_nodes]), and
    optionally ``id``. Graphs that would overflow the node/edge budget raise —
    callers size budgets with :func:`pad_budget_for` or spill to the next
    batch upstream.

    ``slot_nodes``: dense-slot packing mode — graph ``gi`` occupies the
    fixed node range ``[gi*slot_nodes, (gi+1)*slot_nodes)`` instead of
    packing contiguously. Ragged per-graph shapes disappear behind one
    slot size from the :func:`select_bucket` ladder (:func:`slot_nodes_for`),
    which pins the band adjacency's bandwidth to ``ceil(slot/tile)`` tiles
    regardless of the batch mix — what the fused megakernel's rolling
    window is sized by. Slot packing trades node-slot occupancy for shape
    regularity; masked padding was already the batching model, so padded
    in-slot tails are inert exactly like padded batch tails.

    ``impl``: "native" (C++ batcher, deepdfa_tpu/native — the production
    input-pipeline path), "python" (numpy loop — the oracle), or "auto".
    Slot packing always takes the python path (a slot layout is an offset
    table, not a hot copy loop).

    ``shape_series``: traffic-observatory series prefix for the raw
    pre-bucket shapes in this batch (ISSUE 20). The default "train"
    records every packed graph's node/edge counts into the
    ``traffic_shape_train_*`` sketches plus the train-side pad ledger
    (elements used vs the padded node budget — the goodput denominator
    for fenced train rows in the roofline). Pass ``None`` on paths that
    are NOT training admission — the serve engine captures its own
    lanes at submit time and must not double-count here.
    """
    if len(graphs) > n_graphs:
        raise ValueError(f"{len(graphs)} graphs > {n_graphs} slots")
    if shape_series is not None and graphs:
        from deepdfa_tpu.telemetry import sketch as _traffic

        if _traffic.capture_enabled():
            used = 0
            for g in graphs:
                n = int(g["num_nodes"])
                used += n
                _traffic.observe_shape(
                    f"traffic_shape_{shape_series}_nodes", n)
                _traffic.observe_shape(
                    f"traffic_shape_{shape_series}_edges",
                    len(g["senders"]))
            _traffic.observe_train_pad(used, int(max_nodes))
    if slot_nodes is not None:
        if slot_nodes < 1:
            raise ValueError(f"slot_nodes {slot_nodes} < 1")
        if n_graphs * slot_nodes > max_nodes:
            raise ValueError(
                f"{n_graphs} slots of {slot_nodes} nodes exceed the "
                f"{max_nodes}-node budget")
        for gi, g in enumerate(graphs):
            if int(g["num_nodes"]) > slot_nodes:
                raise ValueError(
                    f"graph {gi} (id {g.get('id', '?')}): "
                    f"{int(g['num_nodes'])} nodes > slot_nodes {slot_nodes}")

    # Endpoint contract, enforced BEFORE node-offsetting (and before the
    # native batcher copies anything): a dangling endpoint used to clamp
    # inside the masked segment ops and silently poison gradients. The
    # check is allocation-free for valid input — np.asarray of an existing
    # array is a view, min/max are O(E) reads with scalar results.
    for gi, g in enumerate(graphs):
        n = int(g["num_nodes"])
        s = np.asarray(g["senders"])
        r = np.asarray(g["receivers"])
        if s.shape != r.shape or s.ndim != 1:
            raise ContractError(
                "edge_shape",
                f"graph {gi} (id {g.get('id', '?')}): senders/receivers "
                "must be equal-length 1-d",
                boundary="batch", item_id=g.get("id", gi))
        if s.size and (int(s.min()) < 0 or int(r.min()) < 0
                       or int(s.max()) >= n or int(r.max()) >= n):
            raise ContractError(
                "dangling_endpoint",
                f"graph {gi} (id {g.get('id', '?')}): edge endpoint out of "
                f"range for {n} nodes "
                f"(senders [{int(s.min())}, {int(s.max())}], receivers "
                f"[{int(r.min())}, {int(r.max())}])",
                boundary="batch", item_id=g.get("id", gi))

    graph_mask = np.zeros(n_graphs, bool)
    graph_ids = np.full(n_graphs, -1, np.int64)
    for gi, g in enumerate(graphs):
        graph_mask[gi] = True
        graph_ids[gi] = int(g.get("id", gi))

    if impl not in ("auto", "native", "python"):
        raise ValueError(f"unknown impl {impl!r}")
    use_native = False
    if slot_nodes is not None:
        if impl == "native":
            raise ValueError("slot_nodes packing has no native batcher path")
    elif impl in ("auto", "native"):
        from deepdfa_tpu import native as _native

        use_native = _native.available()
        if impl == "native" and not use_native:
            raise RuntimeError(f"native batcher unavailable: {_native.build_error()}")

    if use_native:
        from deepdfa_tpu import native as _native

        arrs = _native.fill_batch(
            graphs, n_graphs, max_nodes, max_edges, subkeys, add_self_loops
        )
        feats = {k: arrs["feats"][ki] for ki, k in enumerate(subkeys)}
        vuln = arrs["vuln"]
        senders = arrs["senders"]
        receivers = arrs["receivers"]
        node_graph = arrs["node_graph"]
        node_mask = arrs["node_mask"].astype(bool)
        edge_mask = arrs["edge_mask"].astype(bool)
    else:
        feats = {k: np.zeros(max_nodes, np.int32) for k in subkeys}
        vuln = np.zeros(max_nodes, np.int32)
        senders = np.zeros(max_edges, np.int32)
        receivers = np.zeros(max_edges, np.int32)
        node_graph = np.zeros(max_nodes, np.int32)
        node_mask = np.zeros(max_nodes, bool)
        edge_mask = np.zeros(max_edges, bool)

        node_off = 0
        edge_off = 0
        for gi, g in enumerate(graphs):
            if slot_nodes is not None:
                node_off = gi * slot_nodes
            n = int(g["num_nodes"])
            s = np.asarray(g["senders"], np.int32)
            r = np.asarray(g["receivers"], np.int32)
            if add_self_loops:
                loops = np.arange(n, dtype=np.int32)
                s = np.concatenate([s, loops])
                r = np.concatenate([r, loops])
            e = len(s)
            if node_off + n > max_nodes or edge_off + e > max_edges:
                raise ValueError(
                    f"graph {gi} overflows budget "
                    f"(nodes {node_off}+{n}/{max_nodes}, edges {edge_off}+{e}/{max_edges})"
                )
            for k in subkeys:
                feats[k][node_off : node_off + n] = np.asarray(g["feats"][k], np.int32)
            vuln[node_off : node_off + n] = np.asarray(g["vuln"], np.int32)
            senders[edge_off : edge_off + e] = s + node_off
            receivers[edge_off : edge_off + e] = r + node_off
            node_graph[node_off : node_off + n] = gi
            node_mask[node_off : node_off + n] = True
            edge_mask[edge_off : edge_off + e] = True
            node_off += n
            edge_off += e

    tile_adj = None
    if build_tile_adj:
        from deepdfa_tpu.ops.tile_spmm import DEFAULT_TILE, build_tile_adjacency

        tile_adj = build_tile_adjacency(
            senders, receivers, edge_mask, max_nodes,
            tile=tile if tile is not None else DEFAULT_TILE,
            pad_nz=tile_pad_nz,
        )

    band_adj = None
    if build_band_adj:
        from deepdfa_tpu.ops.band_spmm import build_band_adjacency
        from deepdfa_tpu.ops.tile_spmm import DEFAULT_TILE

        band_adj = build_band_adjacency(
            senders, receivers, edge_mask, max_nodes,
            tile=tile if tile is not None else DEFAULT_TILE,
            bandwidth=band_bandwidth,
        )

    df_in = df_out = None
    if with_dataflow:
        # Dataflow-solution bits ride outside the native batcher (a plain
        # offset copy, not worth a C++ path).
        df_in = np.zeros(max_nodes, np.int32)
        df_out = np.zeros(max_nodes, np.int32)
        off = 0
        for gi, g in enumerate(graphs):
            if slot_nodes is not None:
                # Slot packing moves every graph's node range; the
                # dataflow bits must land at the same slot offsets as the
                # node features or the labels silently shear off by the
                # accumulated in-slot padding.
                off = gi * slot_nodes
            n = int(g["num_nodes"])
            if "df_in" not in g or "df_out" not in g:
                raise ValueError(
                    "with_dataflow=True but example "
                    f"{g.get('id', '?')} carries no df_in/df_out bits — "
                    "re-run the ETL export (etl/pipeline.py attaches them) "
                    "or regenerate synthetic data"
                )
            df_in[off : off + n] = np.asarray(g["df_in"], np.int32)
            df_out[off : off + n] = np.asarray(g["df_out"], np.int32)
            off += n

    return GraphBatch(
        node_feats={k: jnp.asarray(v) for k, v in feats.items()},
        node_vuln=jnp.asarray(vuln),
        senders=jnp.asarray(senders),
        receivers=jnp.asarray(receivers),
        node_graph=jnp.asarray(node_graph),
        node_mask=jnp.asarray(node_mask),
        edge_mask=jnp.asarray(edge_mask),
        graph_mask=jnp.asarray(graph_mask),
        graph_ids=jnp.asarray(graph_ids),
        tile_adj=tile_adj,
        band_adj=band_adj,
        node_df_in=jnp.asarray(df_in) if df_in is not None else None,
        node_df_out=jnp.asarray(df_out) if df_out is not None else None,
    )


def batch_iterator(
    graphs: List[Mapping],
    n_graphs: int,
    max_nodes: int,
    max_edges: int,
    subkeys: Sequence[str],
    add_self_loops: bool = True,
    build_tile_adj: bool = False,
    tile: Optional[int] = None,  # None -> ops.tile_spmm.DEFAULT_TILE
    tile_pad_nz: Optional[int] = None,
    build_band_adj: bool = False,
    band_bandwidth: Optional[int] = None,
    with_dataflow: bool = False,
    slot_nodes: Optional[int] = None,
    shape_series: Optional[str] = "train",
):
    """Greedy packer: yields GraphBatches, spilling graphs that would
    overflow the budget into the next batch (static-shape replacement for
    DGL's GraphDataLoader). With ``build_tile_adj`` every batch carries the
    Pallas block-sparse adjacency (pin ``tile_pad_nz`` so all batches share
    one compiled kernel); ``build_band_adj`` likewise attaches the banded
    adjacency (pin ``band_bandwidth``). ``slot_nodes`` switches to
    dense-slot packing: each graph costs one fixed slot of the node budget
    (pin it — e.g. :func:`slot_nodes_for` over the whole corpus — so every
    batch shares one slot layout and one compiled fused-kernel shape)."""
    pending: List[Mapping] = []
    nodes = edges = 0
    kw = dict(
        add_self_loops=add_self_loops, build_tile_adj=build_tile_adj,
        tile=tile, tile_pad_nz=tile_pad_nz, build_band_adj=build_band_adj,
        band_bandwidth=band_bandwidth, with_dataflow=with_dataflow,
        slot_nodes=slot_nodes, shape_series=shape_series,
    )

    def _cost(g):
        n = int(g["num_nodes"])
        e = len(g["senders"]) + (n if add_self_loops else 0)
        return (n if slot_nodes is None else slot_nodes), e

    for g in graphs:
        n, e = _cost(g)
        if pending and (
            len(pending) >= n_graphs or nodes + n > max_nodes or edges + e > max_edges
        ):
            yield batch_graphs(pending, n_graphs, max_nodes, max_edges, subkeys, **kw)
            pending, nodes, edges = [], 0, 0
        if slot_nodes is not None and int(g["num_nodes"]) > slot_nodes:
            raise ValueError(
                f"single graph exceeds slot: {int(g['num_nodes'])} nodes > "
                f"slot_nodes {slot_nodes}")
        if n > max_nodes or e > max_edges:
            raise ValueError(f"single graph exceeds budget: {n} nodes / {e} edges")
        pending.append(g)
        nodes += n
        edges += e
    if pending:
        yield batch_graphs(pending, n_graphs, max_nodes, max_edges, subkeys, **kw)

from deepdfa_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    replicated,
    shard_concat,
)

__all__ = ["batch_sharding", "make_mesh", "replicated", "shard_concat"]

"""Ring attention: exact attention over sequence-sharded inputs.

The reference has no sequence parallelism at all — every input is truncated
to 512 tokens (SURVEY §5 "Long-context"). Here sequences shard over a
``seq`` mesh axis: each device holds a ``[B, T/n, H, D]`` slice of q/k/v,
and KV slices rotate around the ICI ring via ``ppermute`` while each device
folds the arriving chunk into its streaming-softmax state
(deepdfa_tpu/ops/attention.py). After ``n`` steps every query has attended
to every key — exact softmax attention with O(T/n) memory per device and
communication overlapped against the per-chunk matmuls by XLA's latency
hiding scheduler.

Two entry points:
  - :func:`ring_attention` — the per-shard collective body; call inside
    ``shard_map``/``pjit`` manual code with a named ``seq`` axis.
  - :func:`ring_attention_sharded` — wraps a global ``[B, T, H, D]`` array
    in ``jax.shard_map`` (manual only over the seq axis; batch/model axes
    stay under GSPMD auto partitioning).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepdfa_tpu.ops import attention as A
from deepdfa_tpu.parallel.mesh import SEQ_AXIS


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_mask: Optional[jnp.ndarray] = None,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
    block_size: int = 512,
) -> jnp.ndarray:
    """Per-shard ring attention. Arrays are the local sequence shard
    ``[B, Ts, H, D]`` (mask ``[B, Ts]``); must run under a mesh with
    ``axis_name`` manual (shard_map)."""
    # lax.axis_size is a newer-jax API; psum of a concrete 1 over the axis
    # is the 0.4.x-era idiom and resolves statically (no collective).
    _axis_size = getattr(jax.lax, "axis_size", None)
    n = (_axis_size(axis_name) if _axis_size is not None
         else jax.lax.psum(1, axis_name))
    idx = jax.lax.axis_index(axis_name)
    b, ts, h, d = q.shape
    qs = q  # scaling happens inside blockwise_attention

    perm = [(j, (j + 1) % n) for j in range(n)]
    mask = kv_mask if kv_mask is not None else jnp.ones((b, ts), bool)

    def body(i, carry):
        kk, vv, mm, state = carry
        # After i rotations each device holds the KV slice that originated
        # on shard (idx - i) mod n; its global offset positions the causal
        # comparison.
        src = jax.lax.rem(idx - i + n, n)
        state = A.blockwise_attention(
            qs, kk, vv, kv_mask=mm, causal=causal,
            q_offset=idx * ts, kv_offset=src * ts,
            block_size=block_size, state=state, return_state=True,
        )
        kk = jax.lax.ppermute(kk, axis_name, perm)
        vv = jax.lax.ppermute(vv, axis_name, perm)
        mm = jax.lax.ppermute(mm, axis_name, perm)
        return kk, vv, mm, state

    state = A.init_state(b, ts, h, d)
    # n is a static mesh property, so unroll: each step's ppermute overlaps
    # with the next step's compute under XLA's scheduler.
    carry = (k, v, mask, state)
    for i in range(n):
        carry = body(i, carry)
    _, _, _, state = carry
    return A.finalize_state(state, dtype=q.dtype)


def ring_attention_sharded(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_mask: Optional[jnp.ndarray] = None,
    causal: bool = False,
    mesh=None,
    axis_name: str = SEQ_AXIS,
    block_size: int = 512,
) -> jnp.ndarray:
    """Global-view ring attention: shards ``[B, T, H, D]`` over ``axis_name``
    and runs :func:`ring_attention` manually on each shard. Other mesh axes
    (data/model) remain auto-partitioned by GSPMD, so this composes with a
    dp×sp mesh inside one ``jit``.

    On the 0.4.x jax line partial-manual shard_map (``axis_names`` ⊂ mesh
    axes) is unsupported at the XLA level (IsManualSubgroup check failure),
    so the legacy path goes FULL-manual, sharding the batch axis over the
    data axis as well — semantics-preserving because the ring body is
    per-example over batch (its only collective is the seq-axis ppermute);
    it adds the constraint that the global batch divide the data-axis size,
    which every trainer batch already satisfies (the shard packers divide
    batches by ``n_data`` by construction)."""
    from deepdfa_tpu.parallel.mesh import DATA_AXIS, shard_map_compat

    partial_manual_ok = getattr(jax, "shard_map", None) is not None
    batch_axis = None if partial_manual_ok else DATA_AXIS
    spec_qkv = P(batch_axis, axis_name)
    spec_mask = P(batch_axis, axis_name)

    fn = partial(ring_attention, causal=causal, axis_name=axis_name,
                 block_size=block_size)
    mapped = shard_map_compat(
        fn,
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_mask),
        out_specs=spec_qkv,
        axis_names={axis_name} if partial_manual_ok else None,
        check_vma=False,
    )
    if kv_mask is None:
        kv_mask = jnp.ones(q.shape[:2], bool)
    # Partial-manual shard_map (axis_names ⊂ mesh axes) only traces under
    # jit; the jit wrapper inlines when an outer jit is already tracing and
    # covers eager callers (e.g. Flax model.init).
    return jax.jit(mapped)(q, k, v, kv_mask)

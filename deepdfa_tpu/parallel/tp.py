"""Tensor parallelism: parameter sharding rules over the mesh's ``model``
axis.

The reference never shards a model (SURVEY §2.3 — parity is pure dp), but
codet5-large at longer contexts wants its matmuls split across chips. Under
GSPMD that is a *data layout* choice, not a code change: place each
parameter with a NamedSharding and jit propagates the partitioning,
inserting the all-reduces a Megatron implementation writes by hand.

Rules follow the Megatron pairing so every attention/FFN block needs one
collective, not two:
  - q/k/v (and wi / wi_0 / wi_1) kernels: column-parallel — output feature
    dim sharded over ``model``;
  - o / wo kernels: row-parallel — input feature dim sharded (their
    matmul's contraction produces the partial sums the all-reduce joins);
  - embeddings, layer norms, biases, relative-position tables: replicated.

Works for both param trees in this repo (models/t5.py T5Model and
models/transformer.py RobertaEncoder) since the rules key on the owning
module name, not the tree shape.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepdfa_tpu.parallel.mesh import MODEL_AXIS

# Module names whose Dense kernel is column-parallel (shard dim 1) vs
# row-parallel (shard dim 0). T5Attention: q/k/v/o; T5FFN: wi*/wo;
# RobertaEncoder SelfAttention: query/key/value + attention_output;
# EncoderLayer FFN: intermediate/output.
_COLUMN = {"q", "k", "v", "wi", "wi_0", "wi_1", "query", "key", "value",
           "intermediate", "ffn_in"}
_ROW = {"o", "wo", "attention_output", "output", "out", "ffn_out"}


def _spec_for(path) -> P:
    names = [getattr(k, "key", None) for k in path]
    leaf = names[-1] if names else None
    owner = names[-2] if len(names) >= 2 else None
    if leaf == "kernel" and owner in _COLUMN:
        return P(None, MODEL_AXIS)
    if leaf == "kernel" and owner in _ROW:
        return P(MODEL_AXIS, None)
    if leaf == "bias" and owner in _COLUMN:
        return P(MODEL_AXIS)
    return P()  # replicated: embeddings, norms, heads, row-parallel biases


def tp_param_shardings(params: Any, mesh: Mesh) -> Any:
    """NamedSharding pytree matching ``params`` under the Megatron rules.

    ``jax.device_put(params, tp_param_shardings(params, mesh))`` + jitting
    the existing train step is the whole TP story; batches still shard over
    ``data``.
    """
    return jax.tree_util.tree_map_with_path(
        lambda path, _: NamedSharding(mesh, _spec_for(path)), params
    )


def shard_params(params: Any, mesh: Mesh) -> Any:
    return jax.device_put(params, tp_param_shardings(params, mesh))

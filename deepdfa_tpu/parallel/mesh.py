"""Device mesh + sharding layout.

The reference scales with DataParallel replication and NCCL DDP
(LineVul/linevul/linevul_main.py:165-166, CodeT5/run_defect.py:143-147). Here
parallelism is a single ``jax.sharding.Mesh`` with a ``data`` axis (ICI) and
a ``model`` axis reserved for tensor parallelism of the larger transformer
families; batches are sharded over ``data``, parameters replicated (or
sharded over ``model``), and XLA's GSPMD partitioner inserts the gradient
all-reduce that DDP did explicitly.

Alignment contract for graph batches: every leaf of a ``GraphBatch`` built by
:func:`shard_concat` has its leading axis divisible by the data-axis size,
and no graph's nodes/edges cross a shard boundary, so message passing is
collective-free within a step.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepdfa_tpu.graphs.batch import GraphBatch

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    n_seq: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """3-axis mesh (data, seq, model): dp over ``data``, ring/sequence
    parallelism over ``seq`` (ICI neighbors), tensor parallelism over
    ``model``. Unused axes have size 1 and cost nothing."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // (n_model * n_seq)
    use = np.asarray(devices[: n_data * n_seq * n_model]).reshape(
        n_data, n_seq, n_model
    )
    return Mesh(use, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for every GraphBatch leaf: leading axis over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_concat(shards: Sequence[GraphBatch]) -> GraphBatch:
    """Concatenate D equal-budget per-device batches into one device-aligned
    global batch.

    Node/graph indices in shard d are offset by d's cumulative budgets so the
    concatenated arrays form one consistent graph batch whose shard
    boundaries coincide with graph boundaries.
    """
    d = len(shards)
    b0 = shards[0]
    for b in shards:
        assert b.n_graphs == b0.n_graphs
        assert b.max_nodes == b0.max_nodes
        assert b.max_edges == b0.max_edges

    def cat(field, offsets=None):
        parts = []
        for i, b in enumerate(shards):
            arr = getattr(b, field)
            if offsets is not None:
                arr = arr + offsets[i]
            parts.append(arr)
        return np.concatenate([np.asarray(p) for p in parts])

    node_off = [i * b0.max_nodes for i in range(d)]
    graph_off = [i * b0.n_graphs for i in range(d)]
    import jax.numpy as jnp

    return GraphBatch(
        node_feats={
            k: jnp.asarray(
                np.concatenate([np.asarray(b.node_feats[k]) for b in shards])
            )
            for k in b0.node_feats
        },
        node_vuln=jnp.asarray(cat("node_vuln")),
        senders=jnp.asarray(cat("senders", node_off)),
        receivers=jnp.asarray(cat("receivers", node_off)),
        node_graph=jnp.asarray(cat("node_graph", graph_off)),
        node_mask=jnp.asarray(cat("node_mask")),
        edge_mask=jnp.asarray(cat("edge_mask")),
        graph_mask=jnp.asarray(cat("graph_mask")),
        graph_ids=jnp.asarray(cat("graph_ids")),
        # The Pallas tile adjacency is per-device state; a concatenated tile
        # list would not partition along the data axis, so sharded batches
        # carry no adjacency and models running on them must use
        # message_impl="segment" (the model raises otherwise).
        tile_adj=None,
    )


def host_shard_indices(
    indices,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
):
    """Per-host strided slice of an epoch's example indices, truncated so
    every host gets the SAME length — in multi-controller JAX all processes
    must run the same number of jitted steps or the collectives deadlock
    (the reason DistributedSampler pads to equal shards,
    reference CodeT5/run_defect.py:274-277).

    This is an *IO-sharding building block*, not wired into the training
    loops: a host feeding a globally-sharded step must assemble arrays with
    ``jax.make_array_from_process_local_data`` from its local slice, which
    is a multi-host input-pipeline concern the single-host loops here don't
    have. No-op on a single host.
    """
    pc = jax.process_count() if process_count is None else process_count
    if pc <= 1:
        return indices
    pi = jax.process_index() if process_index is None else process_index
    per_host = len(indices) // pc  # truncate: equal step counts on all hosts
    return indices[pi::pc][:per_host]

"""Device mesh + sharding layout.

The reference scales with DataParallel replication and NCCL DDP
(LineVul/linevul/linevul_main.py:165-166, CodeT5/run_defect.py:143-147). Here
parallelism is a single ``jax.sharding.Mesh`` with a ``data`` axis (ICI) and
a ``model`` axis reserved for tensor parallelism of the larger transformer
families; batches are sharded over ``data``, parameters replicated (or
sharded over ``model``), and XLA's GSPMD partitioner inserts the gradient
all-reduce that DDP did explicitly.

Alignment contract for graph batches: every leaf of a ``GraphBatch`` built by
:func:`shard_concat` has its leading axis divisible by the data-axis size,
and no graph's nodes/edges cross a shard boundary, so message passing is
collective-free within a step.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepdfa_tpu.graphs.batch import GraphBatch

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs,
                     check_vma: bool = True, axis_names=None):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes ``jax.shard_map(..., check_vma=, axis_names=)``;
    the 0.4.x line in this image only has
    ``jax.experimental.shard_map.shard_map(..., check_rep=, auto=)`` —
    where ``auto`` is the *complement* of ``axis_names``. One shim keeps
    every kernel call site version-agnostic.
    """
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = set(axis_names)
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)
    from jax.experimental.shard_map import shard_map as sm_legacy

    kwargs = {"check_rep": check_vma}
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - set(axis_names)
    return sm_legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     **kwargs)


def make_mesh(
    n_data: Optional[int] = None,
    n_model: int = 1,
    n_seq: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """3-axis mesh (data, seq, model): dp over ``data``, ring/sequence
    parallelism over ``seq`` (ICI neighbors), tensor parallelism over
    ``model``. Unused axes have size 1 and cost nothing."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // (n_model * n_seq)
    use = np.asarray(devices[: n_data * n_seq * n_model]).reshape(
        n_data, n_seq, n_model
    )
    return Mesh(use, (DATA_AXIS, SEQ_AXIS, MODEL_AXIS))


def replica_device_shards(
    n_replicas: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> "list[list[jax.Device]]":
    """Partition the device list into one shard per serving-engine replica
    (serve/fleet.py): replica ``i`` owns ``shards[i]`` and pins its params
    and micro-batches to ``shards[i][0]``.

    Contiguous blocks (the same locality order ``make_mesh`` uses, so a
    replica's shard is an ICI neighborhood, not a stripe across the
    fabric); a non-dividing device count spreads the remainder over the
    first shards — every device belongs to exactly one replica, none
    sit silently idle. With fewer devices than replicas the assignment
    degrades to round-robin — on a one-device host every replica shares
    it, which is exactly the single-process CPU test/CI topology.
    """
    if n_replicas < 1:
        raise ValueError("n_replicas must be >= 1")
    devices = list(devices if devices is not None else jax.devices())
    if not devices:
        raise ValueError("no devices to assign replicas to")
    if len(devices) >= n_replicas:
        per, rem = divmod(len(devices), n_replicas)
        shards, start = [], 0
        for i in range(n_replicas):
            width = per + (1 if i < rem else 0)
            shards.append(devices[start:start + width])
            start += width
        return shards
    return [[devices[i % len(devices)]] for i in range(n_replicas)]


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for every GraphBatch leaf: leading axis over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def snapshot_layout(mesh: Optional[Mesh]) -> dict:
    """The logical DP layout a checkpoint records (ISSUE 6): enough to
    decide, at restore time, whether the resuming topology matches the
    one that wrote the snapshot. ``mesh=None`` is the unsharded
    single-device loop (n_shards 1)."""
    n_shards = int(mesh.shape[DATA_AXIS]) if mesh is not None else 1
    return {
        "n_shards": n_shards,
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
    }


class ProcessCountMismatchError(RuntimeError):
    """A cross-process-count restore found the snapshot's shard set
    genuinely unrecoverable: a missing shard directory, manifest, or
    leaf file — something redistribution cannot reassemble the
    replicated tree from. A mere ``process_count`` change is NOT this
    error anymore: since ISSUE 18 :func:`check_layout_compatible`
    routes it to checkpoint redistribution
    (``CheckpointManager.redistribute`` — consolidate on the primary,
    broadcast to the fleet, re-shard onto the new mesh), and the resume
    proceeds. This error survives as the typed fail-loud for the cases
    where the bytes themselves are incomplete; the fix is to restore
    from another intact snapshot (the verified fallback does this
    automatically) or re-run the original fleet."""


# Resume strategies check_layout_compatible routes to (ISSUE 18).
RESUME_SAME = "same"
RESUME_RESHARD = "reshard"
RESUME_REDISTRIBUTE_FAST = "redistribute_fast"
RESUME_REDISTRIBUTE_CONSOLIDATE = "redistribute_consolidate"


def plan_resume(prev: Optional[dict], cur: dict) -> str:
    """Pick the resume strategy for a snapshot layout vs the live one.

    * ``same`` — identical logical layout (or nothing recorded to
      compare: pre-ISSUE-10 snapshots resume as before).
    * ``reshard`` — same process count, different shard/device count:
      the single-host elastic path (replicated ``device_put`` onto the
      new mesh, ``reshard_state``).
    * ``redistribute_fast`` — process count changed and the old shard
      set nests into the new one (``old % new == 0``, both > 1): leaf
      files re-home by hardlink, no array deserialization.
    * ``redistribute_consolidate`` — any other process-count change:
      the primary consolidates every shard into the replicated tree,
      broadcasts it, and re-shards onto the new topology.
    """
    if not prev:
        return RESUME_SAME
    prev_pc = prev.get("process_count")
    cur_pc = cur.get("process_count")
    if prev_pc is None or cur_pc is None or int(prev_pc) == int(cur_pc):
        if prev.get("n_shards") is not None \
                and int(prev.get("n_shards", 1)) != int(cur.get("n_shards", 1)):
            return RESUME_RESHARD
        return RESUME_SAME
    prev_pc, cur_pc = int(prev_pc), int(cur_pc)
    if prev_pc > 1 and cur_pc > 1 and prev_pc % cur_pc == 0:
        return RESUME_REDISTRIBUTE_FAST
    return RESUME_REDISTRIBUTE_CONSOLIDATE


def check_layout_compatible(prev: Optional[dict], cur: dict) -> str:
    """Route a resume across topologies (the multi-host half of the
    elastic-resume contract). Returns the strategy from
    :func:`plan_resume`; a ``process_count`` change routes to
    checkpoint redistribution instead of raising (the pre-ISSUE-18
    fail-loud). The typed :class:`ProcessCountMismatchError` is no
    longer raised here — it now marks genuinely unrecoverable shard
    sets and is raised by the consolidate/redistribute machinery in
    ``train/checkpoint.py`` when shard files are missing. Layouts
    without a recorded process count (pre-ISSUE-10 snapshots) route to
    ``same`` — there is nothing to compare against."""
    return plan_resume(prev, cur)


def reshard_state(state, mesh: Optional[Mesh]):
    """Topology-independent restore placement: put a restored (host-side)
    train state onto the *current* mesh, whatever mesh wrote it.

    Train states are replicated over the data axis, so resharding is a
    replicated ``device_put`` — the snapshot itself is topology-free
    (orbax restores to host numpy) and the DP width lives entirely in how
    the step functions shard their *batches*. A run checkpointed on 8
    devices therefore resumes on 1/2/4 (and vice versa): the batch math
    keeps the same global example order and budgets, only the per-shard
    packing (and hence floating-point reduction order) moves — metrics
    are bit-tracked when the shard count is unchanged and
    tolerance-bounded across reshapes (README "Elastic training").
    """
    host = jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)) if hasattr(x, "shape") else x,
        state,
    )
    if mesh is None:
        return jax.device_put(host)
    return jax.device_put(host, replicated(mesh))


def shard_concat(
    shards: Sequence[GraphBatch],
    base_shard: int = 0,
    tile_nz: Optional[int] = None,
    tile_dtype=None,
    band_bandwidth: Optional[int] = None,
    band_dtype=None,
) -> GraphBatch:
    """Concatenate D equal-budget per-device batches into one device-aligned
    global batch.

    Node/graph indices in shard d are offset by d's cumulative budgets so the
    concatenated arrays form one consistent graph batch whose shard
    boundaries coincide with graph boundaries.

    ``base_shard``: global index of the first shard — a host assembling only
    its local slice of a multi-controller batch must offset node/graph
    references by its global position, since the lifted array's indices are
    global (senders/receivers/node_graph address rows of the full batch).

    ``tile_nz``/``tile_dtype`` (and ``band_bandwidth``/``band_dtype`` for
    the banded path): common budget and vals dtype for the stacked
    adjacency; multi-controller callers pass the global maximum /
    globally-agreed dtype over all shards so every host's local stack
    shares one leaf shape AND dtype.
    """
    d = len(shards)
    b0 = shards[0]
    for b in shards:
        assert b.n_graphs == b0.n_graphs
        assert b.max_nodes == b0.max_nodes
        assert b.max_edges == b0.max_edges

    def cat(field, offsets=None):
        parts = []
        for i, b in enumerate(shards):
            arr = getattr(b, field)
            if offsets is not None:
                arr = arr + offsets[i]
            parts.append(arr)
        return np.concatenate([np.asarray(p) for p in parts])

    node_off = [(base_shard + i) * b0.max_nodes for i in range(d)]
    graph_off = [(base_shard + i) * b0.n_graphs for i in range(d)]
    import jax.numpy as jnp

    # Per-shard tile adjacencies stack along a leading device axis: the
    # global adjacency is block-diagonal over shards (no graph crosses a
    # shard boundary), so each device's kernel runs on its own tile list
    # under shard_map (ops.tile_spmm.tile_spmm_sharded).
    tile_adj = None
    if all(b.tile_adj is not None for b in shards):
        from deepdfa_tpu.ops.tile_spmm import stack_tile_adjacencies

        tile_adj = stack_tile_adjacencies(
            [b.tile_adj for b in shards], pad_nz=tile_nz,
            force_dtype=tile_dtype,
        )

    band_adj = None
    if all(b.band_adj is not None for b in shards):
        from deepdfa_tpu.ops.band_spmm import stack_band_adjacencies

        band_adj = stack_band_adjacencies(
            [b.band_adj for b in shards], bandwidth=band_bandwidth,
            force_dtype=band_dtype,
        )

    return GraphBatch(
        node_feats={
            k: jnp.asarray(
                np.concatenate([np.asarray(b.node_feats[k]) for b in shards])
            )
            for k in b0.node_feats
        },
        node_vuln=jnp.asarray(cat("node_vuln")),
        senders=jnp.asarray(cat("senders", node_off)),
        receivers=jnp.asarray(cat("receivers", node_off)),
        node_graph=jnp.asarray(cat("node_graph", graph_off)),
        node_mask=jnp.asarray(cat("node_mask")),
        edge_mask=jnp.asarray(cat("edge_mask")),
        graph_mask=jnp.asarray(cat("graph_mask")),
        graph_ids=jnp.asarray(cat("graph_ids")),
        tile_adj=tile_adj,
        band_adj=band_adj,
        node_df_in=(
            jnp.asarray(cat("node_df_in"))
            if all(b.node_df_in is not None for b in shards) else None
        ),
        node_df_out=(
            jnp.asarray(cat("node_df_out"))
            if all(b.node_df_out is not None for b in shards) else None
        ),
    )


def jit_dp_step(
    step_fn,
    mesh: Mesh,
    n_batch_args: int,
    n_out: int,
    batch_sizes: Sequence[int] = (),
    donate=(0,),
):
    """jit a ``(state, *batch_args) -> (state_or_scalar, ...)`` step
    data-parallel over the mesh: batch args shard on the data axis, state
    and outputs replicate, GSPMD inserts the gradient all-reduce. The one
    place the dp-jit recipe lives — the text/gen/clone trainers all use it.

    ``batch_sizes``: any batch sizes that must divide the data-axis extent
    (validated up front, not at the first sharded call).
    """
    d = int(mesh.shape[DATA_AXIS])
    for bs in batch_sizes:
        if bs % d:
            raise ValueError(
                f"batch size {bs} must divide the data-axis size {d}"
            )
    rep, dsh = replicated(mesh), batch_sharding(mesh)
    return jax.jit(
        step_fn,
        donate_argnums=donate,
        in_shardings=(rep,) + (dsh,) * n_batch_args,
        out_shardings=(rep,) * n_out,
    )


def local_shard_slice(
    n_shards: int,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> slice:
    """Which of a global batch's ``n_shards`` data shards this host feeds.

    Mesh construction order (``make_mesh`` reshapes ``jax.devices()``, which
    lists all processes' devices grouped by process index) puts contiguous
    data-axis blocks on each host, so host ``pi`` owns shards
    ``[pi*local : (pi+1)*local]``.
    """
    pc = jax.process_count() if process_count is None else process_count
    pi = jax.process_index() if process_index is None else process_index
    if n_shards % pc:
        raise ValueError(f"data shards {n_shards} not divisible by hosts {pc}")
    local = n_shards // pc
    return slice(pi * local, (pi + 1) * local)


def assemble_global_batch(local_batch, mesh: Mesh, sharding=None):
    """Multi-controller input assembly: lift each host's local batch shard
    into one global jax.Array per leaf via
    ``jax.make_array_from_process_local_data`` (the pjit-era replacement for
    the reference's DistributedSampler feeding per-rank tensors,
    CodeT5/run_defect.py:274-277). Identity on a single process.
    """
    if jax.process_count() == 1:
        return local_batch
    sh = sharding or batch_sharding(mesh)

    def lift(x):
        return jax.make_array_from_process_local_data(sh, np.asarray(x))

    return jax.tree_util.tree_map(lift, local_batch)

"""Declarative SLOs: burn-rate monitoring over metric snapshots.

One spec format, two evaluation surfaces:

* **Offline** — :func:`evaluate_report` checks a ``cli trace report``
  dict against the spec (``cli trace report --slo <spec>`` exits nonzero
  on breach): the post-hoc gate a perf PR or a smoke run cites.
* **Live** — :class:`SLOMonitor` consumes periodic metric snapshots (the
  serve pump feeds it the shared registry + engine stats once a second)
  and evaluates each objective as a *burn rate*: the fraction of
  observations inside ``window_s`` that violate the threshold. A rule
  breaches when its burn rate exceeds its error ``budget`` (default 0 —
  a single bad observation burns the whole budget, which is what
  ``compiles_after_warmup: 0`` means). Breaches emit ``slo.breach``
  telemetry events, bump ``slo_breach_total``, raise the ``slo_burning``
  gauge, and degrade ``/healthz`` — the hook the ROADMAP's adaptive
  flush policy will later consume.

Spec format (JSON or dict)::

    {"slos": [
        {"metric": "compiles.after_warmup", "max": 0},
        {"metric": "serve.request_ms_p99",  "max": 2000.0},
        {"metric": "serve_latency_ms.p99",  "max": 0.25,
         "window_s": 60, "budget": 0.1},
        {"metric": "queue_depth",           "max": 128}
    ]}

``metric`` is a dotted path into whatever snapshot the surface is fed —
a trace report offline, the merged registry+engine values live (registry
histograms expand, so ``serve_latency_ms.p99`` works). A metric absent
from the snapshot is *skipped*, not breached, unless ``"required": true``
— specs are shared across runs that exercise different subsystems.
"""

from __future__ import annotations

import collections
import copy
import json
import math
import os
import time
from typing import Any, Deque, Dict, List, Mapping, Optional, Tuple

# Built-in specs, selectable by name anywhere a spec path is accepted.
# "smoke": the serve-smoke / trace-report gate — zero post-warmup
# recompiles, zero telemetry drops, and a p99 bound generous enough for
# the shared-CPU CI host (the real latency SLO is a deployment concern;
# the smoke gate exists to catch blowouts, not to tune).
BUILTIN_SPECS: Dict[str, Dict[str, Any]] = {
    "smoke": {"slos": [
        {"metric": "compiles.after_warmup", "max": 0},
        {"metric": "telemetry_drops", "max": 0},
        {"metric": "serve.request_ms_p99", "max": 5000.0},
    ]},
    # The chaos soak injects faults and compiles many fresh programs on
    # purpose; its SLO gates the observability substrate itself (nothing
    # dropped) and end-to-end serve latency under faults.
    "chaos": {"slos": [
        {"metric": "telemetry_drops", "max": 0},
        {"metric": "serve.request_ms_p99", "max": 60000.0},
    ]},
}
BUILTIN_SPECS["default"] = BUILTIN_SPECS["smoke"]


def load_spec(spec: "str | Mapping[str, Any]") -> Dict[str, Any]:
    """A spec dict from a built-in name, a JSON file path, or a dict."""
    if isinstance(spec, Mapping):
        doc = dict(spec)
    elif spec in BUILTIN_SPECS:
        doc = copy.deepcopy(BUILTIN_SPECS[spec])
    elif os.path.exists(spec):
        with open(spec) as f:
            doc = json.load(f)
    else:
        raise ValueError(
            f"unknown SLO spec {spec!r} (a JSON file path or one of "
            f"{sorted(BUILTIN_SPECS)})"
        )
    rules = doc.get("slos")
    if not isinstance(rules, list) or not rules:
        raise ValueError("SLO spec must carry a non-empty 'slos' list")
    for rule in rules:
        if "metric" not in rule or ("max" not in rule and "min" not in rule):
            raise ValueError(
                f"each SLO needs 'metric' and 'max' (or 'min'): {rule!r}"
            )
    return doc


def lookup(values: Mapping[str, Any], dotted: str) -> Optional[float]:
    """Dotted-path numeric lookup (``serve.request_ms_p99``); None when
    any hop is missing or the leaf is not a number."""
    cur: Any = values
    for part in dotted.split("."):
        if not isinstance(cur, Mapping) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def _violates(rule: Mapping[str, Any], value: float) -> bool:
    if "max" in rule and value > float(rule["max"]):
        return True
    if "min" in rule and value < float(rule["min"]):
        return True
    return False


def _threshold(rule: Mapping[str, Any]) -> float:
    return float(rule["max"] if "max" in rule else rule["min"])


def evaluate_report(report: Mapping[str, Any],
                    spec: "str | Mapping[str, Any]") -> Dict[str, Any]:
    """Offline gate: the spec against one ``trace_report`` dict."""
    doc = load_spec(spec)
    breaches: List[Dict[str, Any]] = []
    skipped: List[str] = []
    checked = 0
    for rule in doc["slos"]:
        value = lookup(report, rule["metric"])
        if value is None:
            if rule.get("required"):
                breaches.append({"metric": rule["metric"], "value": None,
                                 "threshold": _threshold(rule),
                                 "reason": "required metric missing"})
            else:
                skipped.append(rule["metric"])
            continue
        checked += 1
        if _violates(rule, value):
            breaches.append({"metric": rule["metric"], "value": value,
                             "threshold": _threshold(rule)})
    return {"ok": not breaches, "checked": checked, "skipped": skipped,
            "breaches": breaches}


class SLOMonitor:
    """Burn-rate evaluation over a stream of metric snapshots.

    ``observe(values)`` records one snapshot and returns the rules that
    *newly* breached on it (each already emitted as an ``slo.breach``
    event). ``status()`` is the ``/healthz`` face: overall ok, currently
    burning metrics, and totals. Thread-safety: the serve pump is the
    single caller of ``observe``; ``status`` reads are tolerant of the
    races a snapshot view allows.
    """

    def __init__(self, spec: "str | Mapping[str, Any]",
                 clock=time.monotonic):
        self.spec = load_spec(spec)
        self._clock = clock
        # One deque[(t, violated)] and burn-state entry per *rule*, not
        # per metric: a spec may bound the same metric twice (max + min,
        # or two window/budget tiers) and their violation streams must
        # not mix.
        self._obs: List[Deque[Tuple[float, bool]]] = [
            collections.deque() for _ in self.spec["slos"]
        ]
        self._burning: Dict[int, Dict[str, Any]] = {}
        self.breaches_total = 0

    def observe(self, values: Mapping[str, Any]) -> List[Dict[str, Any]]:
        from deepdfa_tpu import telemetry

        now = self._clock()
        new_breaches: List[Dict[str, Any]] = []
        for i, rule in enumerate(self.spec["slos"]):
            metric = rule["metric"]
            value = lookup(values, metric)
            if value is None:
                continue
            window_s = float(rule.get("window_s", 60.0))
            budget = float(rule.get("budget", 0.0))
            # A nonzero budget is a *fraction*: it means nothing until at
            # least 1/budget observations exist — otherwise one flaky
            # sample reads as a 100% burn. Zero-budget rules (the
            # compiles-after-warmup class) stay single-observation.
            min_obs = int(rule.get("min_obs") or (
                1 if budget <= 0.0 else min(math.ceil(1.0 / budget), 100)))
            obs = self._obs[i]
            obs.append((now, _violates(rule, value)))
            while obs and obs[0][0] < now - window_s:
                obs.popleft()
            bad = sum(1 for _, v in obs if v)
            burn_rate = bad / len(obs)
            if burn_rate > budget and len(obs) >= min_obs:
                breach = {"metric": metric, "value": value,
                          "threshold": _threshold(rule),
                          "burn_rate": round(burn_rate, 4),
                          "budget": budget, "window_s": window_s}
                if i not in self._burning:
                    # Transition into breach: one event per episode, not
                    # one per polling tick.
                    self.breaches_total += 1
                    telemetry.event("slo.breach", **breach)
                    telemetry.REGISTRY.counter("slo_breach_total").inc()
                    new_breaches.append(breach)
                self._burning[i] = breach
            elif i in self._burning:
                del self._burning[i]
                telemetry.event("slo.recovered", metric=metric)
        telemetry.REGISTRY.gauge("slo_burning").set(len(self._burning))
        return new_breaches

    def status(self) -> Dict[str, Any]:
        burning = list(self._burning.values())
        return {"ok": not burning, "burning": burning,
                "breaches_total": self.breaches_total}

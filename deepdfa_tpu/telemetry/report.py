"""Offline run summary from ``events.jsonl`` alone.

``cli trace report <run>`` answers, from one file, the questions that used
to need five log formats: where did the run spend its time (step-time
p50/p99, host-dispatch vs device-execute split), did it recompile after
warmup (must be 0 for a warmed serving trace), and what faults / retries /
quarantines fired.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List

from deepdfa_tpu.core.metrics import latency_quantile as _quantile
from deepdfa_tpu.telemetry.export import read_events

# Span names whose durations are per-step work (host-dispatch side).
STEP_SPANS = ("train.step", "eval.step")
# Fenced rollup spans: device-inclusive wall time over a window of steps.
WINDOW_SPANS = ("train.window", "train.epoch")
WARMUP_MARKERS = ("serve.warmup_done", "train.warmup_done")


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The report body. Pure function of the event list — everything the
    acceptance gate asks for comes from here."""
    spans = [e for e in events if e.get("kind") == "span"]
    instants = [e for e in events if e.get("kind") == "event"]

    def named(kinds, names):
        return [e for e in kinds if e.get("name") in names]

    # --- compile/warmup boundary (also scopes the step quantiles) -------
    compiles = named(instants, ("jax.compile",))
    markers = named(instants, WARMUP_MARKERS)
    steps = named(spans, STEP_SPANS)
    if markers:
        boundary = max(float(m["ts"]) for m in markers)
    elif steps:
        boundary = min(float(s["ts"]) for s in steps)
    else:
        boundary = None

    # --- training steps: p50/p99 + host/device split --------------------
    # Quantiles cover POST-warmup steps when the run has them: the first
    # steps' durations are dominated by XLA compiles, and "step-time p99"
    # must not report a compile time. Short runs (nothing after the
    # marker) fall back to all steps.
    measured = ([s for s in steps if float(s["ts"]) > boundary]
                if boundary is not None else steps)
    if not measured:
        measured = steps
    step_ms = [float(s.get("dur_ms", 0.0)) for s in measured]
    windows = named(spans, WINDOW_SPANS)
    # A fenced window measures dispatch + device execution of its steps;
    # its own host_ms is the dispatch part. The split is computed over
    # fenced spans only — unfenced numbers cannot attribute device time.
    fenced = [s for s in windows if s.get("fenced")]
    wall_ms = sum(float(s.get("dur_ms", 0.0)) for s in fenced)
    host_ms = sum(float(s.get("host_ms", s.get("dur_ms", 0.0)))
                  for s in fenced)
    n_window_steps = sum(int((s.get("attrs") or {}).get("steps", 0))
                         for s in fenced)
    train: Dict[str, Any] = {
        "steps": len(steps),
        "steps_measured": len(measured),
        "step_dispatch_ms_p50": round(_quantile(step_ms, 0.50), 4),
        "step_dispatch_ms_p99": round(_quantile(step_ms, 0.99), 4),
        "fenced_windows": len(fenced),
        "wall_ms": round(wall_ms, 3),
        "host_ms": round(host_ms, 3),
        "host_frac": round(host_ms / wall_ms, 4) if wall_ms else None,
        "device_frac": (round(1.0 - host_ms / wall_ms, 4)
                        if wall_ms else None),
    }
    if n_window_steps:
        # Device-inclusive per-step time, amortized over fenced windows —
        # the honest "step time" (the dispatch p50/p99 above is the
        # host-side view).
        train["step_ms_fenced_mean"] = round(wall_ms / n_window_steps, 4)

    # --- compiles: total + after the warmup marker ----------------------
    after = ([c for c in compiles if float(c["ts"]) > boundary]
             if boundary is not None else [])
    compile_report = {
        "total": len(compiles),
        "after_warmup": len(after) if boundary is not None else None,
        "warmup_marker": bool(markers),
    }

    # --- resilience: retries / faults / quarantine ----------------------
    retries = named(instants, ("retry",))
    giveups = named(instants, ("retry.giveup",))
    faults = named(instants, ("fault.fired",))
    by_site: Dict[str, int] = {}
    for f in faults:
        site = (f.get("attrs") or {}).get("site", "?")
        by_site[site] = by_site.get(site, 0) + 1
    quarantined = named(instants, ("quarantine",))

    # --- serving --------------------------------------------------------
    reqs = named(spans, ("serve.request",))
    req_ms = [float(r.get("dur_ms", 0.0)) for r in reqs]
    flushes = named(spans, ("serve.flush",))
    serve = {
        "requests": len(reqs),
        "request_ms_p50": round(_quantile(req_ms, 0.50), 4),
        "request_ms_p99": round(_quantile(req_ms, 0.99), 4),
        "flushes": len(flushes),
    }

    # --- checkpointing: async overlap + supersede/drain accounting ------
    # ckpt.copy is the step-blocking portion (the submit-side host-copy
    # start); ckpt.write/ckpt.commit run on the writer thread. A write
    # span whose run-relative interval intersects a train.step span is
    # the overlap the async layer exists for — the acceptance evidence
    # that serialization rode alongside training instead of stalling it.
    copies = named(spans, ("ckpt.copy",))
    writes = named(spans, ("ckpt.write",))
    commits = named(spans, ("ckpt.commit",))

    def _interval(s):
        t0 = float(s.get("ts", 0.0))
        return t0, t0 + float(s.get("dur_ms", 0.0)) / 1e3

    step_ivs = [_interval(s) for s in steps]

    def _overlaps_steps(s):
        t0, t1 = _interval(s)
        return any(a < t1 and t0 < b for a, b in step_ivs)

    copy_ms = [float(s.get("dur_ms", 0.0)) for s in copies]
    write_ms = [float(s.get("dur_ms", 0.0)) for s in writes]
    drains = named(instants, ("ckpt.drain",))
    drain_ms = [float((e.get("attrs") or {}).get("wait_ms", 0.0))
                for e in drains]
    checkpoint = {
        "copies": len(copies),
        "copy_ms_p50": round(_quantile(copy_ms, 0.50), 4),
        "copy_ms_p99": round(_quantile(copy_ms, 0.99), 4),
        "writes": len(writes),
        "write_ms_total": round(sum(write_ms), 3),
        "writes_overlapping_steps": sum(1 for s in writes
                                        if _overlaps_steps(s)),
        "commits": len(commits),
        "superseded": len(named(instants, ("ckpt.superseded",))),
        "write_errors": len(named(instants, ("ckpt.write_error",))),
        "reshapes": len(named(instants, ("ckpt.reshape",))),
        "drains": len(drains),
        "drain_wait_ms_max": round(max(drain_ms, default=0.0), 3),
    }

    # --- bookkeeping ----------------------------------------------------
    flush_events = named(instants, ("telemetry.flush",))
    drops = max((int((e.get("attrs") or {}).get("drops", 0))
                 for e in flush_events), default=0)

    return {
        "events": len(events),
        "train": train,
        "compiles": compile_report,
        "checkpoint": checkpoint,
        "retries": len(retries),
        "retry_giveups": len(giveups),
        "faults": {"total": len(faults), "by_site": by_site},
        "quarantined": len(quarantined),
        "serve": serve,
        "telemetry_drops": drops,
    }


def events_path_of(run_dir: str) -> str:
    return os.path.join(run_dir, "telemetry", "events.jsonl")


def trace_report(run_dir: str) -> Dict[str, Any]:
    """``cli trace report <run>``: summarize one run directory."""
    path = events_path_of(run_dir)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no telemetry under {run_dir!r} (expected {path}); run the "
            "command with telemetry enabled (DEEPDFA_TELEMETRY unset/1)"
        )
    report = summarize(read_events(path))
    report["run"] = run_dir
    return report

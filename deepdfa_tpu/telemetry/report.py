"""Offline run summary from ``events.jsonl`` alone.

``cli trace report <run>`` answers, from one file, the questions that used
to need five log formats: where did the run spend its time (step-time
p50/p99, host-dispatch vs device-execute split), did it recompile after
warmup (must be 0 for a warmed serving trace), and what faults / retries /
quarantines fired.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from deepdfa_tpu.core.metrics import latency_quantile as _quantile
from deepdfa_tpu.telemetry import sketch as _sketch
from deepdfa_tpu.telemetry.export import read_run_dir

# Span names whose durations are per-step work (host-dispatch side).
STEP_SPANS = ("train.step", "eval.step")
# Fenced rollup spans: device-inclusive wall time over a window of steps.
WINDOW_SPANS = ("train.window", "train.epoch")
WARMUP_MARKERS = ("serve.warmup_done", "train.warmup_done")


def summarize(events: List[Dict[str, Any]],
              shards: Optional[List[Dict[str, Any]]] = None,
              ) -> Dict[str, Any]:
    """The report body. Pure function of the event list — everything the
    acceptance gate asks for comes from here. ``shards`` (per-shard
    stats from :func:`~deepdfa_tpu.telemetry.export.read_run_dir`) feeds
    the ``processes`` section's rotation/torn-row accounting when the
    caller read a whole run dir."""
    events = [e for e in events if e.get("kind") != "meta"]
    spans = [e for e in events if e.get("kind") == "span"]
    instants = [e for e in events if e.get("kind") == "event"]

    def named(kinds, names):
        return [e for e in kinds if e.get("name") in names]

    # --- compile/warmup boundary (also scopes the step quantiles) -------
    compiles = named(instants, ("jax.compile",))
    markers = named(instants, WARMUP_MARKERS)
    steps = named(spans, STEP_SPANS)
    if markers:
        boundary = max(float(m["ts"]) for m in markers)
    elif steps:
        boundary = min(float(s["ts"]) for s in steps)
    else:
        boundary = None

    # --- training steps: p50/p99 + host/device split --------------------
    # Quantiles cover POST-warmup steps when the run has them: the first
    # steps' durations are dominated by XLA compiles, and "step-time p99"
    # must not report a compile time. Short runs (nothing after the
    # marker) fall back to all steps.
    measured = ([s for s in steps if float(s["ts"]) > boundary]
                if boundary is not None else steps)
    if not measured:
        measured = steps
    step_ms = [float(s.get("dur_ms", 0.0)) for s in measured]
    windows = named(spans, WINDOW_SPANS)
    # A fenced window measures dispatch + device execution of its steps;
    # its own host_ms is the dispatch part. The split is computed over
    # fenced spans only — unfenced numbers cannot attribute device time.
    fenced = [s for s in windows if s.get("fenced")]
    wall_ms = sum(float(s.get("dur_ms", 0.0)) for s in fenced)
    host_ms = sum(float(s.get("host_ms", s.get("dur_ms", 0.0)))
                  for s in fenced)
    n_window_steps = sum(int((s.get("attrs") or {}).get("steps", 0))
                         for s in fenced)
    train: Dict[str, Any] = {
        "steps": len(steps),
        "steps_measured": len(measured),
        "step_dispatch_ms_p50": round(_quantile(step_ms, 0.50), 4),
        "step_dispatch_ms_p99": round(_quantile(step_ms, 0.99), 4),
        "fenced_windows": len(fenced),
        "wall_ms": round(wall_ms, 3),
        "host_ms": round(host_ms, 3),
        "host_frac": round(host_ms / wall_ms, 4) if wall_ms else None,
        "device_frac": (round(1.0 - host_ms / wall_ms, 4)
                        if wall_ms else None),
    }
    if n_window_steps:
        # Device-inclusive per-step time, amortized over fenced windows —
        # the honest "step time" (the dispatch p50/p99 above is the
        # host-side view).
        train["step_ms_fenced_mean"] = round(wall_ms / n_window_steps, 4)

    # --- compiles: total + after the warmup marker ----------------------
    after = ([c for c in compiles if float(c["ts"]) > boundary]
             if boundary is not None else [])
    compile_report = {
        "total": len(compiles),
        "after_warmup": len(after) if boundary is not None else None,
        "warmup_marker": bool(markers),
    }

    # --- resilience: retries / faults / quarantine ----------------------
    retries = named(instants, ("retry",))
    giveups = named(instants, ("retry.giveup",))
    faults = named(instants, ("fault.fired",))
    by_site: Dict[str, int] = {}
    for f in faults:
        site = (f.get("attrs") or {}).get("site", "?")
        by_site[site] = by_site.get(site, 0) + 1
    quarantined = named(instants, ("quarantine",))

    # --- serving --------------------------------------------------------
    reqs = named(spans, ("serve.request",))
    req_ms = [float(r.get("dur_ms", 0.0)) for r in reqs]
    flushes = named(spans, ("serve.flush",))
    serve = {
        "requests": len(reqs),
        "request_ms_p50": round(_quantile(req_ms, 0.50), 4),
        "request_ms_p99": round(_quantile(req_ms, 0.99), 4),
        "flushes": len(flushes),
    }

    def _req_stats(group: List[Dict[str, Any]]) -> Dict[str, Any]:
        ms = [float(r.get("dur_ms", 0.0)) for r in group]
        waits = [float((r.get("attrs") or {}).get("queue_ms", 0.0))
                 for r in group
                 if "queue_ms" in (r.get("attrs") or {})]
        out = {
            "requests": len(group),
            "request_ms_p50": round(_quantile(ms, 0.50), 4),
            "request_ms_p99": round(_quantile(ms, 0.99), 4),
            "queue_ms_p50": round(_quantile(waits, 0.50), 4),
            "queue_ms_p99": round(_quantile(waits, 0.99), 4),
        }
        return out

    # Per-replica and per-lane breakdowns (the fleet's fairness + skew
    # evidence): replica ids come from span attrs (engines tag their
    # spans with their REPLICA_IDS member), lanes are the batcher's
    # queues — per-lane queue_ms is THE fair-queueing number the
    # sustained-load gate bounds.
    by_replica: Dict[str, List[Dict[str, Any]]] = {}
    by_lane: Dict[str, List[Dict[str, Any]]] = {}
    for r in reqs:
        attrs = r.get("attrs") or {}
        if attrs.get("replica"):
            by_replica.setdefault(str(attrs["replica"]), []).append(r)
        if attrs.get("lane"):
            by_lane.setdefault(str(attrs["lane"]), []).append(r)
    if by_replica:
        serve["replicas"] = {rid: _req_stats(group)
                             for rid, group in sorted(by_replica.items())}
    if by_lane:
        serve["lanes"] = {lane: _req_stats(group)
                          for lane, group in sorted(by_lane.items())}

    # Padding waste from real flush shapes (ISSUE 17): every serve.flush
    # span carries its lane, real request count, and padded slot count —
    # the per-(lane, bucket) waste is the measured input the traffic-
    # shaped dynamic-batching work starts from, computed from the trace
    # alone so it holds across engine processes.
    if flushes:
        pad: Dict[str, Dict[str, float]] = {}
        total_used = total_slots = 0
        for f in flushes:
            attrs = f.get("attrs") or {}
            lane = attrs.get("lane")
            n = attrs.get("n")
            slots = attrs.get("slots")
            if lane is None or n is None or slots is None:
                continue
            cell = pad.setdefault(f"{lane}:b{int(slots)}",
                                  {"flushes": 0, "used": 0, "slots": 0})
            cell["flushes"] += 1
            cell["used"] += int(n)
            cell["slots"] += int(slots)
            total_used += int(n)
            total_slots += int(slots)
        for cell in pad.values():
            cell["waste_pct"] = round(
                100.0 * (1.0 - cell["used"] / cell["slots"]), 2
            ) if cell["slots"] else 0.0
        if pad:
            serve["padding_waste"] = dict(sorted(pad.items()))
            serve["padding_waste_pct"] = round(
                100.0 * (1.0 - total_used / total_slots), 2
            ) if total_slots else 0.0

    # Multi-process fleet audit (ISSUE 17): the engine-process
    # lifecycle (spawn/live/dead/reap/roll) and the router's forward/
    # re-route accounting, joined per statically-enumerated process id
    # — kill/shed/rejoin is readable from the merged trace alone.
    proc_spawns = named(instants, ("proc.spawn",))
    proc_forwards = named(spans, ("router.forward",))
    router_reqs = named(spans, ("router.request",))
    if proc_spawns or proc_forwards or router_reqs:
        by_proc: Dict[str, Dict[str, Any]] = {}

        def _proc_cell(rid: str) -> Dict[str, Any]:
            return by_proc.setdefault(rid, {"spawns": 0, "deaths": 0,
                                            "forwards": 0, "pids": []})

        for e in proc_spawns:
            attrs = e.get("attrs") or {}
            cell = _proc_cell(str(attrs.get("proc", "?")))
            cell["spawns"] += 1
            if attrs.get("pid") is not None:
                cell["pids"].append(attrs["pid"])
        for e in named(instants, ("proc.dead",)):
            attrs = e.get("attrs") or {}
            _proc_cell(str(attrs.get("proc", "?")))["deaths"] += 1
        for s in proc_forwards:
            attrs = s.get("attrs") or {}
            _proc_cell(str(attrs.get("proc", "?")))["forwards"] += 1
        serve["procfleet"] = {
            "spawns": len(proc_spawns),
            "live_transitions": len(named(instants, ("proc.live",))),
            "deaths": len(named(instants, ("proc.dead",))),
            "reaps": len(named(instants, ("proc.reap",))),
            "rolls": len(named(instants, ("proc.roll",))),
            "router_requests": len(router_reqs),
            "forwards": len(proc_forwards),
            "rerouted": sum(int((s.get("attrs") or {})
                                .get("rerouted", 0) or 0)
                            for s in router_reqs),
            "processes": dict(sorted(by_proc.items())),
        }

    # Adaptive flush-policy audit: every controller decision is an
    # event; the report replays the decision history (counts by action
    # and replica, and each replica's final thresholds) from the trace
    # alone.
    policy_events = named(instants, ("serve.flush_policy",))
    if policy_events:
        by_action: Dict[str, int] = {}
        last_by_replica: Dict[str, Dict[str, Any]] = {}
        moved: Dict[str, int] = {}
        for e in policy_events:
            attrs = e.get("attrs") or {}
            action = str(attrs.get("action", "?"))
            by_action[action] = by_action.get(action, 0) + 1
            rid = str(attrs.get("replica", "?"))
            last_by_replica[rid] = {
                "fraction": attrs.get("fraction"),
                "fill_slots": attrs.get("fill_slots"),
                "p99_ms": attrs.get("p99_ms"),
            }
            if action in ("raise", "lower"):
                moved[rid] = moved.get(rid, 0) + 1
        serve["flush_policy"] = {
            "decisions": len(policy_events),
            "by_action": by_action,
            "moves_by_replica": moved,
            "final_by_replica": last_by_replica,
        }

    # --- checkpointing: async overlap + supersede/drain accounting ------
    # ckpt.copy is the step-blocking portion (the submit-side host-copy
    # start); ckpt.write/ckpt.commit run on the writer thread. A write
    # span whose run-relative interval intersects a train.step span is
    # the overlap the async layer exists for — the acceptance evidence
    # that serialization rode alongside training instead of stalling it.
    copies = named(spans, ("ckpt.copy",))
    writes = named(spans, ("ckpt.write",))
    commits = named(spans, ("ckpt.commit",))

    def _interval(s):
        t0 = float(s.get("ts", 0.0))
        return t0, t0 + float(s.get("dur_ms", 0.0)) / 1e3

    step_ivs = [_interval(s) for s in steps]

    def _overlaps_steps(s):
        t0, t1 = _interval(s)
        return any(a < t1 and t0 < b for a, b in step_ivs)

    copy_ms = [float(s.get("dur_ms", 0.0)) for s in copies]
    write_ms = [float(s.get("dur_ms", 0.0)) for s in writes]
    drains = named(instants, ("ckpt.drain",))
    drain_ms = [float((e.get("attrs") or {}).get("wait_ms", 0.0))
                for e in drains]
    checkpoint = {
        "copies": len(copies),
        "copy_ms_p50": round(_quantile(copy_ms, 0.50), 4),
        "copy_ms_p99": round(_quantile(copy_ms, 0.99), 4),
        "writes": len(writes),
        "write_ms_total": round(sum(write_ms), 3),
        "writes_overlapping_steps": sum(1 for s in writes
                                        if _overlaps_steps(s)),
        "commits": len(commits),
        "superseded": len(named(instants, ("ckpt.superseded",))),
        "write_errors": len(named(instants, ("ckpt.write_error",))),
        "reshapes": len(named(instants, ("ckpt.reshape",))),
        "drains": len(drains),
        "drain_wait_ms_max": round(max(drain_ms, default=0.0), 3),
        # Cross-process-count redistributions (ISSUE 18). The instant
        # event shares its name with the surrounding span — the
        # "strategy" attr is what distinguishes it.
        "redistributions": [
            {"snapshot": a.get("snapshot", "?"),
             "strategy": a.get("strategy", "?"),
             "from_processes": int(a.get("from_processes", 0)),
             "to_processes": int(a.get("to_processes", 0)),
             "ms": round(float(a.get("ms", 0.0)), 3)}
            for a in ((e.get("attrs") or {})
                      for e in named(instants, ("ckpt.redistribute",)))
            if "strategy" in a
        ],
    }

    # --- traffic observatory: shapes + two-axis waste (ISSUE 20) --------
    traffic = _traffic(flushes, instants)

    # --- roofline: cost.model events joined to measured spans -----------
    roofline = _roofline(spans, instants, train, traffic)

    # --- memory: compiled HBM footprint + live device samples -----------
    memory = _memory(instants)

    # --- lifecycle: preemption notices and the drain audit --------------
    notices = named(instants, ("lifecycle.notice",))
    lc_drains = named(instants, ("lifecycle.drain",))
    hangs = named(instants, ("lifecycle.hang",))
    lifecycle = {
        "notices": len(notices),
        "reasons": sorted({(e.get("attrs") or {}).get("reason", "?")
                           for e in notices}),
        "lame_duck": len(named(instants, ("lifecycle.lame_duck",))),
        "preempt_snapshots": len(named(instants, ("lifecycle.preempted",))),
        "drains": [
            {"participant": (e.get("attrs") or {}).get("participant", "?"),
             "ok": bool((e.get("attrs") or {}).get("ok")),
             "drain_ms": round(float((e.get("attrs") or {})
                                     .get("drain_ms", 0.0)), 3)}
            for e in lc_drains
        ],
        "hangs": len(hangs),
        "forced_exits": len([e for e in named(instants, ("lifecycle.exit",))
                             if (e.get("attrs") or {}).get("forced")]),
        "fleet_barrier": _fleet_barrier(
            named(instants, ("lifecycle.drain_barrier",))),
    }

    # --- SLO breaches observed live during the run ----------------------
    slo_breaches = named(instants, ("slo.breach",))
    slo = {
        "breaches": len(slo_breaches),
        "breached_metrics": sorted({(e.get("attrs") or {}).get("metric", "?")
                                    for e in slo_breaches}),
    }

    # --- processes: the cross-process shard map (ISSUE 14) --------------
    processes = _processes(events, instants, shards)

    # --- propagation: client↔server request joins by trace id -----------
    propagation = _propagation(spans)

    # --- bookkeeping ----------------------------------------------------
    flush_events = named(instants, ("telemetry.flush",))
    drops = max((int((e.get("attrs") or {}).get("drops", 0))
                 for e in flush_events), default=0)

    return {
        "events": len(events),
        "train": train,
        "compiles": compile_report,
        "checkpoint": checkpoint,
        "retries": len(retries),
        "retry_giveups": len(giveups),
        "faults": {"total": len(faults), "by_site": by_site},
        "quarantined": len(quarantined),
        "serve": serve,
        "traffic": traffic,
        "roofline": roofline,
        "memory": memory,
        "lifecycle": lifecycle,
        "slo": slo,
        "processes": processes,
        "propagation": propagation,
        "telemetry_drops": drops,
    }


def _fleet_barrier(barrier: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The multi-process drain choreography (ISSUE 18), audited from the
    merged trace: which fleet members announced the coordinated stop,
    which observed a peer's announcement, and which reached the drain
    target — keyed by the per-host track (``_process``) when the events
    come from a merged fleet trace, falling back to the recorded
    ``process_index``."""
    by_phase: Dict[str, List[str]] = {}
    target = None
    for e in barrier:
        a = e.get("attrs") or {}
        who = str(e.get("_process")
                  or a.get("process_index", "?"))
        by_phase.setdefault(str(a.get("phase", "?")), []).append(who)
        if a.get("phase") == "announce":
            target = {"epoch": int(a.get("epoch", 0)),
                      "step": int(a.get("step", 0)),
                      "reason": a.get("reason", "?")}
    phases = {k: sorted(set(v)) for k, v in sorted(by_phase.items())}
    return {
        "events": len(barrier),
        "phases": phases,
        "target": target,
        # Complete choreography: someone announced, someone else
        # observed it, and every participant seen in any phase drained.
        "coordinated": bool(
            phases.get("announce") and phases.get("observe")
            and set(sum(phases.values(), []))
            <= set(phases.get("drain", []))),
    }


def _processes(events: List[Dict[str, Any]],
               instants: List[Dict[str, Any]],
               shards: Optional[List[Dict[str, Any]]],
               ) -> Dict[str, Dict[str, Any]]:
    """Per-process span/event stats plus drop/rotation accounting — the
    debugging surface for a run whose work crossed process boundaries
    (who emitted what, who dropped, who rotated). Emitter identity comes
    from the shard meta annotations (``_process``); legacy single-file
    runs collapse into ``"main"``."""
    by_proc: Dict[str, Dict[str, Any]] = {}

    def entry(name: str) -> Dict[str, Any]:
        return by_proc.setdefault(name, {
            "pid": None, "spans": 0, "events": 0, "span_ms": [],
            "drops": 0, "rotations": 0, "segments_dropped": 0,
            "torn_rows": 0, "segments": 0, "bytes": 0,
        })

    for e in events:
        d = entry(str(e.get("_process") or "main"))
        if d["pid"] is None and e.get("_pid") is not None:
            d["pid"] = int(e["_pid"])
        if e.get("kind") == "span":
            d["spans"] += 1
            d["span_ms"].append(float(e.get("dur_ms", 0.0)))
        else:
            d["events"] += 1
    # Each process's final flush summary carries its ring-drop and
    # rotation totals; fold them onto that process's entry.
    for e in instants:
        if e.get("name") not in ("telemetry.flush",):
            continue
        attrs = e.get("attrs") or {}
        d = entry(str(attrs.get("process")
                      or e.get("_process") or "main"))
        d["drops"] = max(d["drops"], int(attrs.get("drops", 0) or 0))
        d["rotations"] = max(d["rotations"],
                             int(attrs.get("rotations", 0) or 0))
        d["segments_dropped"] = max(
            d["segments_dropped"],
            int(attrs.get("segments_dropped", 0) or 0))
    for s in shards or ():
        d = entry(str(s.get("process") or "main"))
        if d["pid"] is None and s.get("pid") is not None:
            d["pid"] = int(s["pid"])
        d["torn_rows"] += int(s.get("torn_rows", 0))
        d["segments"] += int(s.get("segments", 0))
        d["bytes"] += int(s.get("bytes", 0))
    out: Dict[str, Dict[str, Any]] = {}
    for name, d in sorted(by_proc.items()):
        ms = d.pop("span_ms")
        d["span_ms_p50"] = round(_quantile(ms, 0.50), 4)
        d["span_ms_p99"] = round(_quantile(ms, 0.99), 4)
        out[name] = d
    return out


def _propagation(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The HTTP trace-context audit: how many server requests continued
    a client's trace (coverage), and per-trace client-observed vs
    server-observed latency for every joinable trace id — the
    network+framing overhead a server-side p99 alone cannot show."""
    client = [s for s in spans if s.get("name") == "client.request"
              and (s.get("attrs") or {}).get("trace_id")]
    server = [s for s in spans if s.get("name") == "serve.request"]
    continued = [s for s in server
                 if (s.get("attrs") or {}).get("trace_continued")]

    def _by_tid(group):
        out: Dict[str, float] = {}
        for s in group:
            tid = (s.get("attrs") or {}).get("trace_id")
            if tid:
                out[str(tid)] = max(out.get(str(tid), 0.0),
                                    float(s.get("dur_ms", 0.0)))
        return out

    client_ms = _by_tid(client)
    server_ms = _by_tid(continued)
    joined = sorted(set(client_ms) & set(server_ms))
    c = [client_ms[t] for t in joined]
    v = [server_ms[t] for t in joined]
    deltas = [a - b for a, b in zip(c, v)]
    return {
        "client_spans": len(client),
        "server_requests": len(server),
        "continued_requests": len(continued),
        "coverage": (round(len(continued) / len(server), 4)
                     if server else None),
        "joined_traces": len(joined),
        "client_ms_p50": round(_quantile(c, 0.50), 4),
        "client_ms_p99": round(_quantile(c, 0.99), 4),
        "server_ms_p50": round(_quantile(v, 0.50), 4),
        "server_ms_p99": round(_quantile(v, 0.99), 4),
        "client_minus_server_ms_p50": round(_quantile(deltas, 0.50), 4),
        "client_minus_server_ms_p99": round(_quantile(deltas, 0.99), 4),
    }


def _traffic_states(instants: List[Dict[str, Any]],
                    ) -> Dict[str, Dict[str, Any]]:
    """Reconstruct per-series shape-sketch states from the trace alone.

    Every process mirrors its sketches as *cumulative* ``traffic.shape``
    events (pow2 schedule + final flush), so the per-process total is
    the event with the highest count per (process, series); the cross-
    process total is then an exact bin-wise merge — order-independent,
    which is what makes the section stable across fleet shard layouts.
    """
    best: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for e in instants:
        if e.get("name") != "traffic.shape":
            continue
        a = e.get("attrs") or {}
        series = a.get("series")
        if not series:
            continue
        key = (str(e.get("_process") or "main"), str(series))
        cur = best.get(key)
        if cur is None or int(a.get("count", 0) or 0) >= int(
                cur.get("count", 0) or 0):
            best[key] = a
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for (_, series), state in sorted(best.items()):
        groups.setdefault(series, []).append(state)
    return {series: _sketch.merge_states(states)
            for series, states in sorted(groups.items())}


def _traffic(flushes: List[Dict[str, Any]],
             instants: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The traffic-observatory section (ISSUE 20), from the trace alone:
    per-series raw shape distributions, the two-axis waste decomposition
    per (lane, bucket) cell, flush-cause counts, and the training-side
    pad ledger.

    The cells reuse the SAME ``n``/``slots`` span attrs as
    ``serve.padding_waste``, and the three components are an exact
    integer partition of ``elems_budget - elems_used`` per flush —
    slot-axis underfill (empty slots), in-slot shape pad (real inputs
    below the per-slot cap), and flush overhead (tile/bucket headroom
    above ``slots * per_slot``) — so the decomposition *sums* to the
    waste the existing cells already report rather than re-estimating
    it."""
    states = _traffic_states(instants)
    shapes = {series: _sketch.summarize_state(state)
              for series, state in states.items()}

    cells: Dict[str, Dict[str, Any]] = {}
    causes: Dict[str, Dict[str, int]] = {}
    total_used = total_budget = 0
    for f in flushes:
        a = f.get("attrs") or {}
        lane, n, slots = a.get("lane"), a.get("n"), a.get("slots")
        if lane is None or n is None or slots is None:
            continue
        lane, n, slots = str(lane), int(n), int(slots)
        cause = a.get("cause")
        if cause:
            lane_causes = causes.setdefault(lane, {})
            lane_causes[str(cause)] = lane_causes.get(str(cause), 0) + 1
        e_used, e_slot, e_budget = (a.get("elems"), a.get("elems_slot"),
                                    a.get("elems_budget"))
        if e_used is None or e_slot is None or e_budget is None:
            continue
        e_used, e_slot, e_budget = int(e_used), int(e_slot), int(e_budget)
        cell = cells.setdefault(f"{lane}:b{slots}", {
            "flushes": 0, "used": 0, "slots": 0,
            "elems_used": 0, "elems_budget": 0, "elems_per_slot": 0,
            "elems_slot_underfill": 0, "elems_inslot_pad": 0,
            "elems_flush_overhead": 0,
        })
        cell["flushes"] += 1
        cell["used"] += n
        cell["slots"] += slots
        cell["elems_used"] += e_used
        cell["elems_budget"] += e_budget
        cell["elems_per_slot"] = max(cell["elems_per_slot"], e_slot)
        cell["elems_slot_underfill"] += (slots - n) * e_slot
        cell["elems_inslot_pad"] += n * e_slot - e_used
        cell["elems_flush_overhead"] += e_budget - slots * e_slot
        total_used += e_used
        total_budget += e_budget
    for cell in cells.values():
        b = cell["elems_budget"]
        cell["elem_waste_pct"] = round(
            100.0 * (1.0 - cell["elems_used"] / b), 2) if b else 0.0
        cell["slot_underfill_pct"] = round(
            100.0 * cell["elems_slot_underfill"] / b, 2) if b else 0.0
        cell["inslot_pad_pct"] = round(
            100.0 * cell["elems_inslot_pad"] / b, 2) if b else 0.0
        cell["flush_overhead_pct"] = round(
            100.0 * cell["elems_flush_overhead"] / b, 2) if b else 0.0

    # Train-side pad ledger: cumulative ``traffic.pad`` events, last
    # (max batches) per process, summed across processes.
    pad_best: Dict[str, Dict[str, Any]] = {}
    for e in instants:
        if e.get("name") != "traffic.pad":
            continue
        a = e.get("attrs") or {}
        proc = str(e.get("_process") or "main")
        cur = pad_best.get(proc)
        if cur is None or int(a.get("batches", 0) or 0) >= int(
                cur.get("batches", 0) or 0):
            pad_best[proc] = a
    train_pad: Optional[Dict[str, Any]] = None
    if pad_best:
        batches = sum(int(a.get("batches", 0) or 0)
                      for a in pad_best.values())
        p_used = sum(int(a.get("elems_used", 0) or 0)
                     for a in pad_best.values())
        p_budget = sum(int(a.get("elems_budget", 0) or 0)
                       for a in pad_best.values())
        train_pad = {
            "batches": batches,
            "elems_used": p_used,
            "elems_budget": p_budget,
            "elem_waste_pct": round(
                100.0 * (1.0 - p_used / p_budget), 2) if p_budget else 0.0,
        }

    out: Dict[str, Any] = {
        "shapes": shapes,
        "samples": sum(int(s.get("count", 0)) for s in states.values()),
    }
    if cells:
        out["waste"] = dict(sorted(cells.items()))
        out["elem_waste_pct"] = round(
            100.0 * (1.0 - total_used / total_budget), 4
        ) if total_budget else 0.0
    if causes:
        out["flush_causes"] = {lane: dict(sorted(c.items()))
                               for lane, c in sorted(causes.items())}
    if train_pad is not None:
        out["train_pad"] = train_pad
    return out


# cost.model event keys that are capture metadata, not span-join attrs.
# analytic_flops/analytic_bytes are the capture's hand-counted Pallas
# component (costmodel extra_flops/extra_bytes) — metadata feeding the
# roofline's `source` column, NOT a join key: treating them as one made
# every analytic capture silently unmatchable against its spans.
_CM_META = frozenset({
    "name", "span", "steps_per_call", "use_fenced_window", "flops",
    "bytes_accessed", "device_kind", "peak_flops",
    "peak_hbm_bytes_per_sec", "analytic_flops", "analytic_bytes",
})


def _roofline(spans: List[Dict[str, Any]], instants: List[Dict[str, Any]],
              train: Dict[str, Any],
              traffic: Optional[Dict[str, Any]] = None,
              ) -> List[Dict[str, Any]]:
    """Per-kernel roofline rows: XLA cost-model FLOPs/bytes (the
    ``cost.model`` events the costmodel captures emit at warmup) joined
    to the run's measured span durations — per-kernel MFU, operational
    intensity, and a compute-bound vs HBM-bound verdict.

    The time source is honest about attribution: fenced spans (or the
    fenced-window amortized step time for the train step) measure
    device-inclusive duration; dispatch-only span p50 is used — and
    labelled — only when nothing fenced matched. The ``source`` column
    is the same honesty for the FLOPs/bytes side: rows whose numbers
    include hand-counted Pallas work (analytic extra_flops/extra_bytes)
    say "analytic"/"xla+analytic" instead of passing as XLA-measured.

    The goodput column is the same honesty for padding: MFU counts every
    FLOP the padded program executed, but only ``effective_flops_frac``
    of the budget carried real elements (the ``traffic`` section's
    two-axis accounting), so ``effective_mfu = mfu * frac`` is the
    utilization spent on actual inputs — the number the bucket-ladder
    recommender tries to raise.
    """
    traffic = traffic or {}
    latest: Dict[str, Dict[str, Any]] = {}
    for e in instants:
        if e.get("name") == "cost.model":
            attrs = e.get("attrs") or {}
            if attrs.get("name"):
                latest[attrs["name"]] = attrs  # last capture per kernel wins
    rows: List[Dict[str, Any]] = []
    for name, cm in sorted(latest.items()):
        steps_per_call = max(int(cm.get("steps_per_call", 1)), 1)
        join_attrs = {k: v for k, v in cm.items()
                      if k not in _CM_META and not k.startswith("mem_")}
        matched = [
            s for s in spans
            if s.get("name") == cm.get("span")
            and all((s.get("attrs") or {}).get(k) == v
                    for k, v in join_attrs.items())
        ]
        ms_per_call = _quantile(
            [float(s.get("dur_ms", 0.0)) for s in matched], 0.50
        ) if matched else None
        time_source = "span_p50" if matched else None
        if any(s.get("fenced") for s in matched):
            fenced_ms = [float(s.get("dur_ms", 0.0)) for s in matched
                         if s.get("fenced")]
            ms_per_call = _quantile(fenced_ms, 0.50)
            time_source = "fenced_span"
        elif cm.get("use_fenced_window") and train.get("step_ms_fenced_mean"):
            # The train loops' per-step spans are dispatch-only; the
            # fenced epoch/window spans carry the device-inclusive time,
            # amortized per step by the train section.
            ms_per_call = train["step_ms_fenced_mean"] * steps_per_call
            time_source = "fenced_window"
        flops = float(cm.get("flops", 0.0)) / steps_per_call
        bytes_accessed = float(cm.get("bytes_accessed", 0.0)) / steps_per_call
        # Accounting provenance (the perf-evidence rule): "xla" = every
        # number below came from XLA's cost model of the compiled HLO;
        # "analytic" / "xla+analytic" = some or all FLOPs/bytes are
        # hand-counted Pallas-kernel work (capture extra_flops/
        # extra_bytes) that XLA counts as zero — those rows must never be
        # quoted as if measured.
        analytic_flops = float(cm.get("analytic_flops", 0.0))
        analytic_bytes = float(cm.get("analytic_bytes", 0.0))
        total_flops = float(cm.get("flops", 0.0))
        total_bytes = float(cm.get("bytes_accessed", 0.0))

        def _frac(part, total):
            return round(part / total, 3) if total else None

        if not (analytic_flops or analytic_bytes):
            source = "xla"
            analytic_flops_frac = analytic_bytes_frac = None
        else:
            # "analytic" only when BOTH sides are (essentially) entirely
            # hand-counted — a bytes-only analytic component must not
            # hide behind a 0.0 flops fraction.
            flops_all = (not total_flops
                         or analytic_flops >= total_flops * 0.999)
            bytes_all = (not total_bytes
                         or analytic_bytes >= total_bytes * 0.999)
            source = "analytic" if flops_all and bytes_all else (
                "xla+analytic")
            analytic_flops_frac = _frac(analytic_flops, total_flops)
            analytic_bytes_frac = _frac(analytic_bytes, total_bytes)
        peak_flops = cm.get("peak_flops")
        peak_bw = cm.get("peak_hbm_bytes_per_sec")
        oi = flops / bytes_accessed if bytes_accessed else None
        sec = ms_per_call / steps_per_call / 1e3 if ms_per_call else None
        achieved = flops / sec if sec else None
        row: Dict[str, Any] = {
            "name": name,
            "calls": len(matched),
            "source": source,
            "analytic_flops_frac": analytic_flops_frac,
            "analytic_bytes_frac": analytic_bytes_frac,
            "flops_per_step": flops,
            "bytes_per_step": bytes_accessed,
            "operational_intensity": round(oi, 3) if oi else None,
            "ms_per_step": (round(ms_per_call / steps_per_call, 4)
                            if ms_per_call else None),
            "time_source": time_source,
            "achieved_gflops_per_sec": (round(achieved / 1e9, 2)
                                        if achieved else None),
            "mfu": (round(achieved / peak_flops, 4)
                    if achieved and peak_flops else None),
            "hbm_frac": (round(bytes_accessed / sec / peak_bw, 4)
                         if sec and peak_bw and bytes_accessed else None),
            "device_kind": cm.get("device_kind"),
        }
        if oi and peak_flops and peak_bw:
            # The roofline verdict: above the ridge point the kernel can
            # saturate the MXU; below it HBM bandwidth is the ceiling —
            # the prerequisite fact for the megakernel arc.
            ridge = peak_flops / peak_bw
            row["ridge_intensity"] = round(ridge, 3)
            row["bound"] = ("compute-bound" if oi >= ridge else "hbm-bound")
        else:
            row["bound"] = None
        # Goodput: fraction of the padded element budget occupied by
        # real inputs. Serve kernels join their (lane, bucket) waste
        # cell (same lane/slots attrs as the cost.model join); train
        # kernels use the training-side pad ledger.
        frac = None
        cell_lane = join_attrs.get("lane")
        cell_slots = join_attrs.get("slots")
        if cell_lane is not None and cell_slots is not None:
            cell = (traffic.get("waste") or {}).get(
                f"{cell_lane}:b{int(cell_slots)}")
            if cell and cell.get("elems_budget"):
                frac = cell["elems_used"] / cell["elems_budget"]
        elif cm.get("use_fenced_window"):
            pad = traffic.get("train_pad")
            if pad and pad.get("elems_budget"):
                frac = pad["elems_used"] / pad["elems_budget"]
        row["effective_flops_frac"] = (round(frac, 4)
                                       if frac is not None else None)
        row["effective_mfu"] = (round(row["mfu"] * frac, 4)
                                if frac is not None and row["mfu"]
                                else None)
        if join_attrs:
            row["attrs"] = join_attrs
        rows.append(row)
    return rows


def _memory(instants: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Peak-HBM accounting from ``memory.analysis`` (compiled footprint,
    per kernel) + ``memory.sample`` (live allocator stats) events."""
    analyses = [e.get("attrs") or {} for e in instants
                if e.get("name") == "memory.analysis"]
    samples = [e.get("attrs") or {} for e in instants
               if e.get("name") == "memory.sample"]
    out: Dict[str, Any] = {"kernels": len(analyses),
                           "device_samples": len(samples)}
    for key in ("temp_bytes", "argument_bytes", "output_bytes",
                "total_bytes"):
        vals = [int(a[key]) for a in analyses if key in a]
        out[f"peak_{key}"] = max(vals) if vals else None
    ranked = sorted((a for a in analyses if a.get("total_bytes")),
                    key=lambda a: -int(a["total_bytes"]))
    out["top_kernels"] = [
        {"name": a.get("name", "?"), "total_bytes": int(a["total_bytes"]),
         "temp_bytes": int(a.get("temp_bytes", 0))}
        for a in ranked[:5]
    ]
    if samples:
        out["device_bytes_in_use_max"] = max(
            int(s.get("bytes_in_use", 0)) for s in samples)
        out["device_peak_bytes_in_use"] = max(
            int(s.get("peak_bytes_in_use", 0)) for s in samples)
    return out


def events_path_of(run_dir: str) -> str:
    return os.path.join(run_dir, "telemetry", "events.jsonl")


def trace_report(run_dir: str) -> Dict[str, Any]:
    """``cli trace report <run>``: summarize one run directory — every
    shard (child processes included) and sealed rotation segment, merged
    onto the one timeline."""
    events, shards = read_run_dir(run_dir)
    if not shards:
        path = events_path_of(run_dir)
        raise FileNotFoundError(
            f"no telemetry under {run_dir!r} (expected {path}); run the "
            "command with telemetry enabled (DEEPDFA_TELEMETRY unset/1)"
        )
    report = summarize(events, shards=shards)
    report["run"] = run_dir
    return report


def recommend_buckets(run_dir: str,
                      quantiles: Tuple[float, ...] = (
                          0.5, 0.75, 0.9, 0.95, 0.99, 1.0),
                      ) -> Dict[str, Any]:
    """``cli trace recommend-buckets <run>``: the offline bucket-ladder
    recommender. Report-only — it changes nothing; it replays the
    traffic observatory's shape distributions against percentile-fitted
    ladders and prints what a reshaped ladder would have cost.

    Per serve lane it proposes two ladders:

    * the **value axis** (nodes for graph lanes, source tokens for gen):
      rungs at the distribution's quantile bin edges, with predicted
      in-slot pad waste vs the ladder actually used in the trace (the
      observed per-slot caps) — both computed by the same
      :func:`~deepdfa_tpu.telemetry.sketch.predicted_waste_pct` replay,
      next to the *measured* in-slot waste over occupied slots;
    * the **slot axis**: rungs fitted to the per-flush request-count
      distribution vs the pow2 slot buckets the trace used, next to the
      measured slot-underfill waste.

    Every extra rung is an extra warmed program, so each proposal also
    carries its compile-count price (value rungs x slot rungs)."""
    events, shards = read_run_dir(run_dir)
    if not shards:
        raise FileNotFoundError(
            f"no telemetry under {run_dir!r} "
            f"(expected {events_path_of(run_dir)})")
    events = [e for e in events if e.get("kind") != "meta"]
    spans = [e for e in events if e.get("kind") == "span"]
    instants = [e for e in events if e.get("kind") == "event"]
    flushes = [s for s in spans if s.get("name") == "serve.flush"]
    states = _traffic_states(instants)
    traffic = _traffic(flushes, instants)
    cells = traffic.get("waste") or {}

    # Per-lane flush evidence: the per-slot caps the ladder actually
    # used, the slot buckets hit, and the per-flush fill counts.
    lane_caps: Dict[str, set] = {}
    lane_slot_buckets: Dict[str, set] = {}
    lane_fills: Dict[str, List[int]] = {}
    for f in flushes:
        a = f.get("attrs") or {}
        lane, n, slots = a.get("lane"), a.get("n"), a.get("slots")
        if lane is None or n is None or slots is None:
            continue
        lane = str(lane)
        lane_slot_buckets.setdefault(lane, set()).add(int(slots))
        lane_fills.setdefault(lane, []).append(int(n))
        if a.get("elems_slot") is not None:
            lane_caps.setdefault(lane, set()).add(int(a["elems_slot"]))

    def _lane_cells(lane: str) -> List[Dict[str, Any]]:
        return [c for key, c in cells.items()
                if key.startswith(f"{lane}:b")]

    recs: List[Dict[str, Any]] = []
    for lane in sorted(lane_slot_buckets):
        series = ("traffic_shape_serve_gen_src_tokens" if lane == "gen"
                  else f"traffic_shape_serve_{lane}_nodes")
        axis = "src_tokens" if lane == "gen" else "nodes"
        n_slot_buckets = max(len(lane_slot_buckets[lane]), 1)
        lane_cells = _lane_cells(lane)

        # --- value axis --------------------------------------------------
        state = states.get(series)
        if state and state.get("count"):
            current = sorted(lane_caps.get(lane, ()))
            fitted = _sketch.fit_ladder(state, quantiles)
            # Measured in-slot waste over occupied slots: pad within
            # slots that held a real input — the waste a value-axis
            # ladder can actually recover (empty slots belong to the
            # slot axis below).
            inslot = sum(c.get("elems_inslot_pad", 0) for c in lane_cells)
            occupied = sum(c.get("elems_used", 0) for c in lane_cells)
            occupied += inslot
            rec: Dict[str, Any] = {
                "lane": lane,
                "axis": axis,
                "series": series,
                "samples": int(state.get("count", 0)),
                "current_rungs": current,
                "fitted_rungs": fitted,
                "predicted_fitted_waste_pct": _sketch.predicted_waste_pct(
                    state, fitted),
                "compiles_current": len(current) * n_slot_buckets,
                "compiles_fitted": len(fitted) * n_slot_buckets,
            }
            if current:
                rec["predicted_current_waste_pct"] = (
                    _sketch.predicted_waste_pct(state, current))
            if occupied:
                rec["measured_waste_pct"] = round(
                    100.0 * inslot / occupied, 2)
                rec["improves"] = bool(
                    rec["predicted_fitted_waste_pct"]
                    < rec["measured_waste_pct"])
            recs.append(rec)

        # --- slot axis ---------------------------------------------------
        fills = lane_fills.get(lane) or []
        if fills:
            slot_state = _sketch.state_from_values(fills)
            current_slots = sorted(lane_slot_buckets[lane])
            fitted_slots = _sketch.fit_ladder(slot_state, quantiles)
            used = sum(c.get("used", 0) for c in lane_cells)
            slots_total = sum(c.get("slots", 0) for c in lane_cells)
            rec = {
                "lane": lane,
                "axis": "slots",
                "samples": len(fills),
                "current_rungs": current_slots,
                "fitted_rungs": fitted_slots,
                "predicted_fitted_waste_pct": _sketch.predicted_waste_pct(
                    slot_state, fitted_slots),
                "predicted_current_waste_pct": _sketch.predicted_waste_pct(
                    slot_state, current_slots),
                "compiles_current": len(current_slots),
                "compiles_fitted": len(fitted_slots),
            }
            if slots_total:
                rec["measured_waste_pct"] = round(
                    100.0 * (1.0 - used / slots_total), 2)
                rec["improves"] = bool(
                    rec["predicted_fitted_waste_pct"]
                    < rec["measured_waste_pct"])
            recs.append(rec)

    return {
        "run": run_dir,
        "quantiles": [float(q) for q in quantiles],
        "flushes": len(flushes),
        "elem_waste_pct": traffic.get("elem_waste_pct"),
        "recommendations": recs,
    }

"""Bounded deterministic request-shape sketches (the traffic observatory).

Serving and training both pad every input up a pow2 ladder
(``graphs.batch.select_bucket``), so the cost of a ladder is decided by
the *raw pre-bucket* shape distribution — which, until this module, the
trace never carried. A :class:`ShapeSketch` records those raw sizes
(node/edge counts per graph, gen source-token lengths, scan function
byte sizes) into a **fixed** log-spaced bin ladder:

  * values 1..8 get exact unit bins;
  * each octave ``[2^o, 2^(o+1))`` above splits into 8 linear
    sub-buckets (HdrHistogram-style: 3 significant bits, <= 12.5%
    relative error), clamped at ``2^24``;

so the whole sketch is at most ~180 integer counters — bounded memory
regardless of traffic volume, unlike an unbounded sample list (that
anti-pattern is graftlint rule GL027). Binning is pure integer
arithmetic (``bit_length`` + shifts): deterministic across platforms,
processes and seeds, which makes merges **exact** — merging two sketches
is bin-wise counter addition, so merge is associative and commutative
and a fleet's shards reduce to the same answer in any order.

Quantiles are nearest-rank over the bins and return the bin's *inclusive
upper edge* — the conservative "pad-to" value a bucket ladder would need
to cover everything in that bin, which is exactly the unit the offline
ladder recommender (``cli trace recommend-buckets``) optimizes.

Capture is wired at every admission edge (serve submit, ``batch_graphs``
training batches, scan validation) under statically-enumerated series
names (:data:`SHAPE_SERIES` — the GL014 discipline), registered in the
telemetry :class:`~deepdfa_tpu.telemetry.registry.Registry` and mirrored
as cumulative ``traffic.shape`` trace events on a power-of-two count
schedule (bounded event volume) plus a final flush, so the offline
report reconstructs every distribution from ``events.jsonl`` alone.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from deepdfa_tpu.telemetry.registry import REGISTRY

# --------------------------------------------------------------------------
# Fixed bin ladder: exact unit bins 1..8, then 8 linear sub-buckets per
# pow2 octave. Integer-only arithmetic keeps indexing deterministic.

_SUB = 8                 # sub-buckets per octave (3 significant bits)
MAX_VALUE = 1 << 24      # clamp: far above every ladder top in the repo


def bucket_index(value: int) -> int:
    """Bin index for ``value`` (clamped to [1, MAX_VALUE])."""
    v = int(value)
    if v < 1:
        v = 1
    elif v > MAX_VALUE:
        v = MAX_VALUE
    if v <= _SUB:
        return v - 1
    o = v.bit_length() - 1           # octave, >= 3
    sub = (v - (1 << o)) >> (o - 3)  # 0..7 within the octave
    return _SUB * (o - 3) + sub + _SUB


def bucket_value(index: int) -> int:
    """Inclusive upper edge of bin ``index`` — the conservative
    "pad-to" representative every consumer (quantiles, predicted-waste
    math) uses for values landing in that bin."""
    i = int(index)
    if i < _SUB:
        return i + 1
    j = i - _SUB
    o = j // _SUB + 3
    sub = j % _SUB
    return min((1 << o) + ((sub + 1) << (o - 3)) - 1, MAX_VALUE)


def merge_states(states: Iterable[Mapping]) -> Dict:
    """Exact merge of sketch states (bin-wise sum; associative and
    commutative by construction). States are ``ShapeSketch.state()``
    dicts or the attrs of ``traffic.shape`` events."""
    out: Dict = {"count": 0, "total": 0, "min": None, "max": None,
                 "bins": {}}
    for st in states:
        if not st or not st.get("count"):
            continue
        out["count"] += int(st["count"])
        out["total"] += int(st.get("total", 0))
        for key in ("min", "max"):
            v = st.get(key)
            if v is None:
                continue
            cur = out[key]
            better = (min if key == "min" else max)
            out[key] = int(v) if cur is None else better(cur, int(v))
        for idx, cnt in (st.get("bins") or {}).items():
            k = str(idx)
            out["bins"][k] = out["bins"].get(k, 0) + int(cnt)
    return out


def state_from_values(values: Iterable[int]) -> Dict:
    """Build a sketch state offline from exact values (the report uses
    this for distributions it already holds per-event, like per-flush
    request counts, so one quantile/ladder code path serves both)."""
    st: Dict = {"count": 0, "total": 0, "min": None, "max": None,
                "bins": {}}
    for v in values:
        v = int(v)
        st["count"] += 1
        st["total"] += v
        st["min"] = v if st["min"] is None else min(st["min"], v)
        st["max"] = v if st["max"] is None else max(st["max"], v)
        k = str(bucket_index(v))
        st["bins"][k] = st["bins"].get(k, 0) + 1
    return st


def quantile_from_bins(bins: Mapping, q: float) -> Optional[int]:
    """Nearest-rank quantile over a ``{index: count}`` bin map,
    returned as the owning bin's inclusive upper edge."""
    items = sorted((int(i), int(c)) for i, c in bins.items() if int(c) > 0)
    total = sum(c for _, c in items)
    if not total:
        return None
    # Nearest rank = ceil(q * total), computed in exact integer millionths
    # so 0.9 * 10 can never float-drift past rank 9.
    q_millionths = int(round(float(q) * 1_000_000))
    rank = max(1, min(total, -(-(q_millionths * total) // 1_000_000)))
    seen = 0
    for idx, cnt in items:
        seen += cnt
        if seen >= rank:
            return bucket_value(idx)
    return bucket_value(items[-1][0])


def summarize_state(state: Mapping,
                    quantiles: Sequence[float] = (0.5, 0.9, 0.99)) -> Dict:
    """Report-facing summary of a (merged) sketch state."""
    count = int(state.get("count") or 0)
    out: Dict = {"count": count}
    if not count:
        return out
    total = int(state.get("total") or 0)
    out["mean"] = round(total / count, 2)
    out["min"] = state.get("min")
    out["max"] = state.get("max")
    bins = state.get("bins") or {}
    for q in quantiles:
        out[f"p{int(q * 100)}"] = quantile_from_bins(bins, q)
    return out


# --------------------------------------------------------------------------
# Ladder fitting + predicted-waste math (the recommend-buckets core).
# Everything operates in bin-edge space: values and rungs are both bin
# upper edges, so the arithmetic below is exact over the sketch.

def fit_ladder(state: Mapping,
               quantiles: Sequence[float] = (0.5, 0.75, 0.9, 0.95,
                                             0.99, 1.0)) -> List[int]:
    """Percentile-fitted bucket ladder: the deduped, sorted bin edges at
    ``quantiles`` (1.0 always included so the ladder covers the max)."""
    bins = state.get("bins") or {}
    qs = sorted(set(float(q) for q in quantiles) | {1.0})
    rungs = sorted({v for v in (quantile_from_bins(bins, q) for q in qs)
                    if v is not None})
    return rungs


def predicted_waste_pct(state: Mapping, rungs: Sequence[int]) -> Optional[float]:
    """Predicted pad waste of a ladder over the sketched distribution:
    ``100 * (1 - sum(c*v) / sum(c*rung(v)))`` with v = bin upper edge and
    rung(v) = smallest rung >= v (values above the top rung clamp to it —
    callers ensure the ladder covers the observed max)."""
    ladder = sorted(int(r) for r in rungs)
    if not ladder:
        return None
    top = ladder[-1]
    used = 0
    padded = 0
    for idx, cnt in (state.get("bins") or {}).items():
        c = int(cnt)
        if c <= 0:
            continue
        v = min(bucket_value(int(idx)), top)
        rung = next((r for r in ladder if r >= v), top)
        used += c * v
        padded += c * rung
    if not padded:
        return None
    return round(100.0 * (1.0 - used / padded), 2)


# --------------------------------------------------------------------------
# The registry metric: a named sketch with bounded bins. ``Registry``
# constructs these via ``REGISTRY.sketch(name)`` (create-or-get, like
# counters/gauges/histograms).

class ShapeSketch:
    """Thread-safe bounded quantile sketch (a telemetry metric kind)."""

    kind = "sketch"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._bins: Dict[int, int] = {}
        self._count = 0
        self._total = 0
        self._min: Optional[int] = None
        self._max: Optional[int] = None
        # Count at the last traffic.shape event emission: the flush hook
        # re-emits only when new samples landed since.
        self._emitted_count = 0

    def observe(self, value: int) -> int:
        v = int(value)
        idx = bucket_index(v)
        with self._lock:
            self._bins[idx] = self._bins.get(idx, 0) + 1
            self._count += 1
            self._total += v
            self._min = v if self._min is None else min(self._min, v)
            self._max = v if self._max is None else max(self._max, v)
            return self._count

    def state(self) -> Dict:
        """Cumulative mergeable state (the ``traffic.shape`` payload)."""
        with self._lock:
            return {
                "count": self._count,
                "total": self._total,
                "min": self._min,
                "max": self._max,
                "bins": {str(i): c for i, c in sorted(self._bins.items())},
            }

    def quantile(self, q: float) -> Optional[int]:
        with self._lock:
            bins = dict(self._bins)
        return quantile_from_bins(bins, q)

    def mark_emitted(self, count: int) -> None:
        with self._lock:
            self._emitted_count = max(self._emitted_count, int(count))

    def dirty(self) -> bool:
        with self._lock:
            return self._count > self._emitted_count

    def reset(self) -> None:
        with self._lock:
            self._bins.clear()
            self._count = 0
            self._total = 0
            self._min = None
            self._max = None
            self._emitted_count = 0

    @property
    def value(self) -> Dict:
        """Registry snapshot value (quantiles in pad-to bin edges)."""
        with self._lock:
            bins = dict(self._bins)
            count, total = self._count, self._total
            vmin, vmax = self._min, self._max
        out: Dict = {"count": count, "sum": total}
        if count:
            out.update({
                "min": vmin,
                "max": vmax,
                "p50": quantile_from_bins(bins, 0.5),
                "p90": quantile_from_bins(bins, 0.9),
                "p99": quantile_from_bins(bins, 0.99),
            })
        return out


# --------------------------------------------------------------------------
# Capture plane: statically-named series (GL014 — every sketch name in
# the process is a member of THIS tuple, never formatted from runtime
# data), observed only while telemetry is enabled so the A/B overhead
# benches measure a true no-op on the disabled side.

SHAPE_SERIES = (
    "traffic_shape_serve_gnn_nodes",
    "traffic_shape_serve_gnn_edges",
    "traffic_shape_serve_combined_nodes",
    "traffic_shape_serve_combined_edges",
    "traffic_shape_serve_gen_src_tokens",
    "traffic_shape_train_nodes",
    "traffic_shape_train_edges",
    "traffic_shape_scan_source_bytes",
)

# Kill switch for the capture plane alone (telemetry stays on): the
# traffic_capture_overhead_pct bench A/Bs this, isolating sketch cost
# from the rest of the trace plane. None = on.
_capture_on: Optional[bool] = None
_capture_lock = threading.Lock()

# Train-side pad accumulator (the goodput numerator/denominator for
# fenced-window train rows in the roofline): cumulative element counts,
# mirrored as traffic.pad events on the same pow2 + flush schedule.
_train_pad = {"batches": 0, "elems_used": 0, "elems_budget": 0}
_train_pad_emitted = 0


def set_capture(on: Optional[bool]) -> None:
    """Force the shape-capture plane on/off (None restores default)."""
    global _capture_on
    _capture_on = on


def capture_enabled() -> bool:
    from deepdfa_tpu.telemetry import spans
    if not spans.enabled():
        return False
    return _capture_on is None or bool(_capture_on)


def _emit_shape(sk: ShapeSketch) -> None:
    from deepdfa_tpu.telemetry import spans
    st = sk.state()
    spans.event("traffic.shape", series=sk.name, **st)
    sk.mark_emitted(st["count"])


def observe_shape(series: str, value: int) -> None:
    """Record one raw pre-bucket size into ``series``.

    Emits a cumulative ``traffic.shape`` trace event whenever the
    series' count reaches a power of two — O(log n) events per series —
    and the flush hook (:func:`flush_traffic`) emits the final state, so
    ``events.jsonl`` always ends with the complete distribution.
    """
    if series not in SHAPE_SERIES:
        raise ValueError(f"unknown traffic series {series!r} "
                         "(add it to telemetry.sketch.SHAPE_SERIES)")
    if not capture_enabled():
        return
    sk = REGISTRY.sketch(series)
    count = sk.observe(value)
    if count & (count - 1) == 0:
        _emit_shape(sk)


def observe_train_pad(elems_used: int, elems_budget: int) -> None:
    """Record one training batch's node-element fill vs its padded
    budget (the train half of the goodput ledger)."""
    if not capture_enabled():
        return
    global _train_pad_emitted
    with _capture_lock:
        _train_pad["batches"] += 1
        _train_pad["elems_used"] += int(elems_used)
        _train_pad["elems_budget"] += int(elems_budget)
        batches = _train_pad["batches"]
        snap = dict(_train_pad)
    REGISTRY.counter("traffic_train_elems_used_total").inc(int(elems_used))
    REGISTRY.counter("traffic_train_elems_budget_total").inc(
        int(elems_budget))
    if batches & (batches - 1) == 0:
        from deepdfa_tpu.telemetry import spans
        spans.event("traffic.pad", scope="train", **snap)
        with _capture_lock:
            _train_pad_emitted = max(_train_pad_emitted, batches)


def flush_traffic() -> None:
    """Emit the final cumulative state of every dirty series — called
    from the telemetry flush/end-run path so the trace's last
    ``traffic.shape``/``traffic.pad`` events always hold the complete
    picture (the report keys on the LAST event per process+series)."""
    global _train_pad_emitted
    if not capture_enabled():
        return
    for sk in REGISTRY.sketches():
        if sk.dirty():
            _emit_shape(sk)
    with _capture_lock:
        batches = _train_pad["batches"]
        snap = dict(_train_pad)
        dirty_pad = batches > _train_pad_emitted
        if dirty_pad:
            _train_pad_emitted = batches
    if dirty_pad:
        from deepdfa_tpu.telemetry import spans
        spans.event("traffic.pad", scope="train", **snap)


def reset_traffic() -> None:
    """Zero the capture plane (new telemetry run / tests): sketches are
    per-run so a process serving several runs never leaks one run's
    distribution into the next run's trace."""
    global _train_pad_emitted
    for sk in REGISTRY.sketches():
        sk.reset()
    with _capture_lock:
        _train_pad.update(batches=0, elems_used=0, elems_budget=0)
        _train_pad_emitted = 0

"""Run-scoped tracing spans over per-thread ring buffers.

The observability contract (ISSUE 5):

* **Nestable spans.** ``with span("train.step", step=n) as sp: ...``
  records one timed event into the *current thread's* ring buffer —
  appends take that thread's own uncontended lock (contended only while
  the exporter drains), so instrumentation stays in production code
  paths. Nesting is tracked per thread; every span record carries its
  ``parent`` and ``depth`` for offline attribution.
* **Honest device attribution.** Wall-clock deltas around a jitted call
  measure *dispatch*, not execution (XLA runs async). ``sp.fence(x)``
  marks the dispatch boundary and ``jax.block_until_ready(x)`` at span
  exit, so fenced spans split into ``host_ms`` (dispatch) and total
  duration (device-inclusive) — graftlint GL011 exists because timings
  without this fence are lies. The fence runs whether or not a run is
  active: it is measurement semantics at the call site, and blocking
  changes no values (the bit-identical-history guarantee).
* **Compile events.** A ``jax.monitoring`` listener forwards every
  backend compile into the active run as a ``jax.compile`` event —
  silent recompiles in train/serve become first-class, countable
  events (the post-warmup-compiles-must-be-0 gate).
* **Run scoping.** ``start_run(run_dir)`` / ``end_run()`` (or the
  ``run_scope`` context manager) bind the process to one
  ``<run_dir>/telemetry/`` sink. With no run active — or with
  ``DEEPDFA_TELEMETRY=0`` — every hook is a cheap no-op and nothing is
  written anywhere.

Full drops are counted, never silent: a ring at capacity drops the new
event and bumps the ring's drop counter, surfaced in ``/healthz`` and
the flush summary event.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

ENV_VAR = "DEEPDFA_TELEMETRY"
RING_ENV_VAR = "DEEPDFA_TELEMETRY_RING"
DEFAULT_RING_CAPACITY = 65536
# Trace retention (ISSUE 14): the active shard seals into a segment at
# the rotate threshold, and sealed segments are dropped oldest-first
# past the retention budget — a long-lived serve appends bounded bytes,
# with every rotation/drop counted in the shared registry.
ROTATE_ENV_VAR = "DEEPDFA_TRACE_ROTATE_BYTES"
RETAIN_ENV_VAR = "DEEPDFA_TRACE_RETAIN_BYTES"
DEFAULT_ROTATE_BYTES = 64 * 1024 * 1024
DEFAULT_RETAIN_BYTES = 512 * 1024 * 1024

_ENABLED: Optional[bool] = None  # tri-state: None = read the env lazily
# Guards writes to _ENABLED only (GL022): every thread closure reaches
# enabled() through event()/span(), so the lazy env read raced set_enabled.
# The hot path still reads lock-free — only the None->value transition and
# the explicit override serialize.
_ENABLED_LOCK = threading.Lock()


def enabled() -> bool:
    """Master switch: ``DEEPDFA_TELEMETRY=0`` disables spans, events,
    runs, and exports entirely (fences at call sites still run — they
    are timing semantics, not telemetry)."""
    global _ENABLED
    if _ENABLED is None:
        with _ENABLED_LOCK:
            if _ENABLED is None:
                _ENABLED = os.environ.get(ENV_VAR, "1") != "0"
    return _ENABLED


def set_enabled(value: Optional[bool]) -> None:
    """Override the env switch (``None`` re-reads the env) — the
    bench A/B and test hook."""
    global _ENABLED
    with _ENABLED_LOCK:
        _ENABLED = value


# ---------------------------------------------------------------------------
# Per-thread ring buffers
# ---------------------------------------------------------------------------


class _Ring:
    """Bounded event buffer owned by one thread.

    ``append`` takes this ring's own lock — uncontended except while the
    exporter swaps the buffer out (the "lock-cheap" design: no global
    lock anywhere near the hot path)."""

    def __init__(self, tid: int, capacity: int):
        self.tid = tid
        self.capacity = capacity
        self.lock = threading.Lock()
        self.events: List[Dict[str, Any]] = []
        self.drops = 0

    def append(self, record: Dict[str, Any]) -> None:
        with self.lock:
            if len(self.events) >= self.capacity:
                self.drops += 1
                return
            self.events.append(record)

    def drain(self) -> List[Dict[str, Any]]:
        with self.lock:
            out, self.events = self.events, []
            return out


class _ThreadState(threading.local):
    def __init__(self):
        self.ring: Optional[_Ring] = None
        self.stack: List[str] = []  # open span names, outermost first


_TLS = _ThreadState()
_RINGS: List[_Ring] = []
_RINGS_LOCK = threading.Lock()
_REAPED_DROPS = 0  # drop counts carried over from reaped dead-thread rings


def _ring() -> _Ring:
    ring = _TLS.ring
    if ring is None:
        capacity = int(os.environ.get(RING_ENV_VAR, DEFAULT_RING_CAPACITY))
        ring = _Ring(threading.get_ident(), capacity)
        _TLS.ring = ring
        with _RINGS_LOCK:
            _RINGS.append(ring)
    return ring


def _reap_dead_rings() -> None:
    """Drop rings whose owner thread is gone (one HTTP handler thread per
    connection would otherwise leak a ring per request, and every flush/
    drop_count walk would grow with total requests served). Callers drain
    first; only the drop counter survives, folded into the global."""
    global _REAPED_DROPS
    live = {t.ident for t in threading.enumerate()}
    with _RINGS_LOCK:
        kept = []
        for ring in _RINGS:
            if ring.tid in live:
                kept.append(ring)
            else:
                _REAPED_DROPS += ring.drops
        _RINGS[:] = kept


def drop_count() -> int:
    """Events dropped by full rings, process-wide (the /healthz field)."""
    with _RINGS_LOCK:
        rings = list(_RINGS)
        reaped = _REAPED_DROPS
    return reaped + sum(r.drops for r in rings)


# ---------------------------------------------------------------------------
# The active run
# ---------------------------------------------------------------------------


class TelemetryRun:
    """One process's sink into a run: ``<run_dir>/telemetry/``.

    The PRIMARY process (the one that opened the run) writes
    ``events.jsonl`` and owns the merged ``trace.json`` view; a process
    with an *inherited* context (``DEEPDFA_TRACE_CONTEXT`` from its
    parent, or a post-``fork`` rebind) writes its own
    ``events-<process>-<pid>.jsonl`` shard into the SAME run dir, on the
    SAME clock (``t0`` is inherited; ``perf_counter`` is system-wide
    CLOCK_MONOTONIC on Linux, so timestamps merge into one timeline).

    Every shard file opens with a ``kind: "meta"`` record carrying the
    emitter's pid/process name — the Chrome view stamps the *emitter's*
    pid on every event, never the reader's. ``flush()`` drains every
    thread's ring and appends (a single writer per shard under one
    lock); at the rotate threshold the active file seals into a
    ``.seg-NNNNNN.jsonl`` segment and sealed segments beyond the
    retention budget are dropped oldest-first, all counted. ``close()``
    flushes, emits the final summary event, and (primary only)
    regenerates the merged Chrome-trace view from every shard present.
    """

    def __init__(self, run_dir: str, process: str = "main", inherit=None):
        self.run_dir = run_dir
        self.process = process
        self.pid = os.getpid()
        self.inherited = inherit is not None
        self.dir = os.path.join(run_dir, "telemetry")
        os.makedirs(self.dir, exist_ok=True)
        self.trace_path = os.path.join(self.dir, "trace.json")
        if inherit is None:
            self.run_id = (f"{os.path.basename(os.path.abspath(run_dir)) or 'run'}"
                           f"-{os.urandom(4).hex()}")
            self.t0 = time.perf_counter()
            self.wall_start = time.time()
            shard = "events.jsonl"
        else:
            # One timeline: the child stamps ts relative to the PARENT's
            # t0 (shared monotonic clock), under the parent's run id.
            self.run_id = inherit.run_id
            self.t0 = float(inherit.t0)
            self.wall_start = float(inherit.wall_start)
            from deepdfa_tpu.telemetry.context import sanitize_process

            shard = f"events-{sanitize_process(process)}-{self.pid}.jsonl"
        self.events_path = os.path.join(self.dir, shard)
        self.rotate_bytes = int(os.environ.get(ROTATE_ENV_VAR,
                                               DEFAULT_ROTATE_BYTES))
        self.retain_bytes = int(os.environ.get(RETAIN_ENV_VAR,
                                               DEFAULT_RETAIN_BYTES))
        self.rotations = 0
        self.segments_dropped = 0
        self.segment_bytes_dropped = 0
        self._seg_seq = 0
        self.drops0 = drop_count()  # ring drops are process-lifetime;
        # the run reports its own delta
        self.n_written = 0
        self._write_lock = threading.Lock()
        if inherit is None:
            # Fresh files per run: a resumed run dir must not interleave
            # two runs' clocks, a previous run's shards/segments must not
            # pose as this run's processes, and a stale trace.json must
            # not pose as a view of the new run (regenerated at close()).
            for name in os.listdir(self.dir):
                if name.startswith("events") and name.endswith(".jsonl"):
                    try:
                        os.remove(os.path.join(self.dir, name))
                    except OSError:
                        pass
            if os.path.exists(self.trace_path):
                os.remove(self.trace_path)
            with open(self.events_path, "w") as f:
                self._write_meta(f)
        else:
            # A shard never truncates: the parent's files are live, and a
            # pid-reusing sibling's history is worth more than a clean
            # slate. Each (re)open appends a fresh meta record.
            with open(self.events_path, "a") as f:
                self._write_meta(f)

    def now(self) -> float:
        return time.perf_counter() - self.t0

    def _write_meta(self, f) -> None:
        """The shard header: who is writing this file. Readers annotate
        every subsequent record with this pid/process, so the Chrome
        view carries real emitter identity (ISSUE 14 satellite: the old
        exporter stamped the *converting* process's pid on everything)."""
        f.write(json.dumps({
            "kind": "meta", "name": "telemetry.shard", "ts": self.now(),
            "pid": self.pid, "process": self.process,
            "run_id": self.run_id, "wall_start": self.wall_start,
        }) + "\n")

    def flush(self) -> int:
        """Drain all rings into this process's shard; returns events
        written. Rotation happens here, under the same write lock."""
        with _RINGS_LOCK:
            rings = list(_RINGS)
        batch: List[Dict[str, Any]] = []
        for ring in rings:
            batch.extend(ring.drain())
        _reap_dead_rings()
        if not batch:
            return 0
        batch.sort(key=lambda r: r.get("ts", 0.0))
        with self._write_lock:
            with open(self.events_path, "a") as f:
                for rec in batch:
                    f.write(json.dumps(rec) + "\n")
                size = f.tell()
            self.n_written += len(batch)
            if self.rotate_bytes > 0 and size >= self.rotate_bytes:
                self._rotate_locked(size)
        return len(batch)

    def _rotate_locked(self, size: int) -> None:
        """Seal the active file into a segment and enforce the retention
        budget over this shard's sealed segments (oldest-first drops,
        all accounted — a long-run trace loses its oldest history, never
        its accounting)."""
        from deepdfa_tpu.telemetry.registry import REGISTRY

        self._seg_seq += 1
        stem = self.events_path[:-len(".jsonl")]
        seg = f"{stem}.seg-{self._seg_seq:06d}.jsonl"
        os.replace(self.events_path, seg)
        self.rotations += 1
        REGISTRY.counter("telemetry_rotations_total").inc()
        with open(self.events_path, "w") as f:
            self._write_meta(f)
        prefix = os.path.basename(stem) + ".seg-"
        segments = sorted(
            name for name in os.listdir(self.dir)
            if name.startswith(prefix) and name.endswith(".jsonl")
        )
        sizes = {}
        for name in segments:
            try:
                sizes[name] = os.path.getsize(os.path.join(self.dir, name))
            except OSError:
                sizes[name] = 0
        total = sum(sizes.values())
        dropped = 0
        while segments and total > self.retain_bytes and len(segments) > 1:
            victim = segments.pop(0)
            try:
                os.remove(os.path.join(self.dir, victim))
            except OSError:
                break
            total -= sizes[victim]
            dropped += 1
            self.segments_dropped += 1
            self.segment_bytes_dropped += sizes[victim]
            REGISTRY.counter(
                "telemetry_retention_dropped_segments_total").inc()
            REGISTRY.counter(
                "telemetry_retention_dropped_bytes_total").inc(
                    sizes[victim])
        # Queued into the ring: lands in the fresh active file on the
        # next flush — the rotation is auditable from the trace itself.
        event("telemetry.rotate", segment=os.path.basename(seg),
              bytes=size, process=self.process,
              dropped_segments=dropped)

    def close(self) -> None:
        # Final traffic.shape/traffic.pad emission BEFORE the summary
        # event: the run's last events must carry each series' complete
        # distribution (the offline report keys on last-per-process).
        from deepdfa_tpu.telemetry import sketch as _sketch

        _sketch.flush_traffic()
        event("telemetry.flush", drops=drop_count() - self.drops0,
              events=self.n_written, process=self.process,
              rotations=self.rotations,
              segments_dropped=self.segments_dropped)
        self.flush()
        if not self.inherited:
            # The merged Perfetto view over every shard present at close
            # (children that already exited included). A shard-writing
            # child never writes trace.json — the primary owns the view.
            from deepdfa_tpu.telemetry.export import write_merged_trace

            write_merged_trace(self.run_dir, wall_start=self.wall_start)


_RUN: Optional[TelemetryRun] = None
_JAX_LISTENER_INSTALLED = False
_JAX_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _install_jax_listener() -> None:
    """Forward backend compiles into the active run (idempotent; the
    listener itself is process-lifetime — jax has no unregister)."""
    global _JAX_LISTENER_INSTALLED
    if _JAX_LISTENER_INSTALLED:
        return
    _JAX_LISTENER_INSTALLED = True
    try:
        from jax import monitoring

        def _on_duration(name: str, duration: float, **kw: Any) -> None:
            if name == _JAX_COMPILE_EVENT and _RUN is not None:
                event("jax.compile", dur_ms=duration * 1e3)

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # pragma: no cover - monitoring API drift
        import logging

        logging.getLogger(__name__).warning(
            "jax.monitoring unavailable; compile events will not be "
            "captured", exc_info=True)


def current_run() -> Optional[TelemetryRun]:
    return _RUN


def start_run(run_dir: str) -> Optional[TelemetryRun]:
    """Bind the process to one run sink. No-op (returns None) when
    telemetry is disabled; nested runs are an error — end the previous
    one first (``run_scope`` does).

    When the process was spawned with a ``DEEPDFA_TRACE_CONTEXT`` env
    payload (ISSUE 14), the inherited context WINS over ``run_dir``: the
    child binds to the parent's run directory and writes its own
    ``events-<process>-<pid>.jsonl`` shard on the parent's clock, so one
    merged timeline covers both processes. Without the env var, behavior
    is unchanged — the caller's run_dir, the primary ``events.jsonl``.
    """
    global _RUN
    if not enabled():
        return None
    if _RUN is not None:
        raise RuntimeError(
            f"telemetry run already active ({_RUN.run_dir}); end it first"
        )
    from deepdfa_tpu.telemetry import context as _context

    _install_jax_listener()
    ctx = _context.inherited()
    if ctx is not None:
        _RUN = TelemetryRun(ctx.run_dir, process=ctx.process, inherit=ctx)
    else:
        _RUN = TelemetryRun(run_dir)
    # Traffic sketches are per-run: a process serving several runs must
    # not leak one run's shape distribution into the next run's trace.
    from deepdfa_tpu.telemetry import sketch as _sketch

    _sketch.reset_traffic()
    event("telemetry.start", run_dir=_RUN.run_dir,
          process=_RUN.process,
          **({"requested_run_dir": run_dir} if ctx is not None else {}))
    return _RUN


def rebind_forked(process: str) -> Optional[TelemetryRun]:
    """Post-``fork`` shard rebind: the forked child inherited the
    parent's run object and ring *copies* by memory; writing either from
    the child would duplicate the parent's events or tear its file. This
    discards the copied rings and binds the child to its own shard of
    the same run (same run id, same clock). No-op when no run is active,
    telemetry is disabled, or the caller is not actually a fork (same
    pid)."""
    global _RUN, _REAPED_DROPS
    run = _RUN
    if run is None or not enabled():
        return None
    if run.pid == os.getpid():
        return run
    with _RINGS_LOCK:
        _RINGS.clear()
    _REAPED_DROPS = 0
    _TLS.ring = None
    _RUN = TelemetryRun(run.run_dir, process=process, inherit=run)
    # The fork copied the parent's sketch states by memory; re-emitting
    # them from this child's shard would double-count the parent's
    # samples in the merged report. Start the child's traffic ledger
    # from zero.
    from deepdfa_tpu.telemetry import sketch as _sketch

    _sketch.reset_traffic()
    event("telemetry.start", run_dir=run.run_dir, process=process,
          forked=True)
    return _RUN


def in_child_shard() -> bool:
    """True when this process writes a shard of an inherited run — the
    hook per-item flush policies key on (a forked ETL worker must make
    its events durable before it can be killed)."""
    run = _RUN
    return run is not None and run.inherited


def end_run() -> None:
    global _RUN
    run = _RUN
    if run is None:
        return
    try:
        # close() emits the final summary event, so the run must still be
        # current while it runs.
        run.close()
    finally:
        _RUN = None


@contextlib.contextmanager
def run_scope(run_dir: str):
    """``with run_scope(run_dir): ...`` — the command-level entry."""
    run = start_run(run_dir)
    try:
        yield run
    finally:
        if run is not None:
            end_run()


def flush() -> int:
    """Drain rings into the active run's events.jsonl (0 when none).

    Emits any dirty traffic sketches first, so an explicit flush always
    leaves the shape distributions on disk current."""
    run = _RUN
    if run is None:
        return 0
    from deepdfa_tpu.telemetry import sketch as _sketch

    _sketch.flush_traffic()
    return run.flush()


# ---------------------------------------------------------------------------
# Spans and events
# ---------------------------------------------------------------------------


class Span:
    """One timed region. Always measures (two perf_counter reads — the
    call-site contract that ``dur_s`` is usable even when no run is
    active); emits only into an active run."""

    __slots__ = ("name", "attrs", "_t0", "_fence", "dur_s", "host_s")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0
        self._fence: Any = None
        self.dur_s = 0.0   # total duration (device-inclusive when fenced)
        self.host_s: Optional[float] = None  # dispatch-only, fenced spans

    def fence(self, value: Any) -> None:
        """Block on ``value`` at span exit: the span then measures
        dispatch AND device execution, split into host_ms / dur_ms."""
        self._fence = value

    def set(self, **attrs: Any) -> None:
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        _TLS.stack.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter()
        if self._fence is not None:
            import jax

            jax.block_until_ready(self._fence)
            t2 = time.perf_counter()
            self.host_s = t1 - self._t0
            self.dur_s = t2 - self._t0
        else:
            self.dur_s = t1 - self._t0
        stack = _TLS.stack
        if stack and stack[-1] == self.name:
            stack.pop()
        run = _RUN
        if run is not None:
            rec: Dict[str, Any] = {
                "kind": "span",
                "name": self.name,
                "ts": self._t0 - run.t0,
                "dur_ms": self.dur_s * 1e3,
                "tid": threading.get_ident(),
                "depth": len(stack),
            }
            if stack:
                rec["parent"] = stack[-1]
            if self.host_s is not None:
                rec["host_ms"] = self.host_s * 1e3
                rec["fenced"] = True
            if exc_type is not None:
                rec["error"] = exc_type.__name__
            if self.attrs:
                rec["attrs"] = self.attrs
            _ring().append(rec)
        return False


class _NullSpan:
    """The disabled-path span: every method a no-op, ``dur_s`` stays 0
    (disabled means *disabled* — not even the clock is read)."""

    __slots__ = ()
    dur_s = 0.0
    host_s: Optional[float] = None

    def fence(self, value: Any) -> None:
        pass

    def set(self, **attrs: Any) -> None:
        pass

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NULL_SPAN = _NullSpan()


def span(name: str, /, **attrs: Any):
    """``with span("train.step", step=n) as sp:`` — nestable timed
    region. Cheap no-op object when telemetry is disabled."""
    if not enabled():
        return _NULL_SPAN
    return Span(name, attrs)


def record_span(name: str, start_s: float, end_s: Optional[float] = None,
                **attrs: Any) -> None:
    """Retroactive span from explicit perf_counter timestamps — for
    regions whose start and end live on different threads (a serving
    request's submit -> finish)."""
    run = _RUN
    if run is None or not enabled():
        return
    end_s = time.perf_counter() if end_s is None else end_s
    rec: Dict[str, Any] = {
        "kind": "span",
        "name": name,
        "ts": start_s - run.t0,
        "dur_ms": (end_s - start_s) * 1e3,
        "tid": threading.get_ident(),
        "depth": 0,
    }
    if attrs:
        rec["attrs"] = attrs
    _ring().append(rec)


def event(name: str, /, **attrs: Any) -> None:
    """Instant event into the active run (no-op without one). The event
    name is positional-only so an attr may itself be called ``name``
    (the cost-model and memory events carry the kernel's)."""
    run = _RUN
    if run is None or not enabled():
        return
    rec: Dict[str, Any] = {
        "kind": "event",
        "name": name,
        "ts": run.now(),
        "tid": threading.get_ident(),
    }
    if attrs:
        rec["attrs"] = attrs
    _ring().append(rec)


def now() -> float:
    """THE telemetry clock (perf_counter seconds) — call sites that
    stamp retroactive spans must use this, not their own clock."""
    return time.perf_counter()

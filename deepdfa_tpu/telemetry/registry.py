"""One process-wide metrics registry: counters, gauges, histograms,
shape sketches.

The stack grew four private counter families (``core.metrics.ServingStats``,
``core.metrics.IngestStats``, ``contracts.STATS``, the resilience retry
loop) with four snapshot formats. This registry is the single sink they
all publish into — the existing snapshot APIs stay as views over their own
state, but every bump is mirrored here, so one Prometheus text exposition
(``prometheus_text``) and one offline report can see the whole process.

Thread-safety: metric creation serializes on the registry lock; each
metric carries its own lock for mutation (serve admission bumps from many
transport threads at once — the test gate hammers exactly that).

Names follow Prometheus conventions: ``[a-zA-Z_:][a-zA-Z0-9_:]*``;
:func:`sanitize` coerces free-form boundary/reason strings (``reason:v1``)
into legal metric names.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Mapping, Optional, Union

# Colons are legal in Prometheus names but reserved for recording rules;
# exposition names here stay [a-zA-Z_][a-zA-Z0-9_]*.
_NAME_OK = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_]")


def sanitize(name: str) -> str:
    """Free-form string -> legal Prometheus metric-name fragment."""
    out = _BAD_CHARS.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


class Counter:
    """Monotonic counter (``*_total`` by convention)."""

    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, by: Union[int, float] = 1) -> None:
        if by < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += by

    @property
    def value(self) -> Union[int, float]:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue depth, ring occupancy)."""

    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, by: float = 1.0) -> None:
        with self._lock:
            self._value += by

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """count/sum plus a bounded sample ring for offline quantiles.

    Not a bucketed Prometheus histogram: the exposition publishes
    ``_count``/``_sum`` (enough for rate/mean panels) and the snapshot
    adds p50/p99 over the most recent ``window`` observations — the same
    rolling-quantile convention ``ServingStats`` uses.
    """

    kind = "histogram"

    def __init__(self, name: str, window: int = 4096):
        self.name = name
        self._lock = threading.Lock()
        self._window = window
        self._ring: List[float] = [0.0] * window
        self._count = 0
        self._sum = 0.0

    def observe(self, value: float) -> None:
        with self._lock:
            self._ring[self._count % self._window] = float(value)
            self._count += 1
            self._sum += float(value)

    def _samples(self) -> List[float]:
        with self._lock:
            n = min(self._count, self._window)
            return list(self._ring[:n])

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile over the sample window (the
        ``core.metrics.latency_quantile`` convention: a value some
        observation actually took)."""
        xs = sorted(self._samples())
        if not xs:
            return 0.0
        rank = min(int(-(-q * len(xs) // 1)) - 1, len(xs) - 1)
        return xs[max(rank, 0)]

    @property
    def value(self) -> Dict[str, float]:
        with self._lock:
            count, total = self._count, self._sum
        return {
            "count": count,
            "sum": total,
            "p50": self.quantile(0.50),
            "p99": self.quantile(0.99),
        }


_Metric = Union[Counter, Gauge, Histogram]


class Registry:
    """Create-or-get metric store; kind conflicts are programming errors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, name: str, cls, **kw) -> _Metric:
        if not _NAME_OK.match(name):
            raise ValueError(f"illegal metric name {name!r} "
                             "(use registry.sanitize)")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, window: int = 4096) -> Histogram:
        return self._get(name, Histogram, window=window)

    def sketch(self, name: str):
        """Create-or-get a bounded shape sketch
        (:class:`deepdfa_tpu.telemetry.sketch.ShapeSketch`) — the
        traffic-observatory metric kind: fixed log-spaced bins, exact
        merge, no unbounded sample list."""
        from deepdfa_tpu.telemetry.sketch import ShapeSketch
        return self._get(name, ShapeSketch)

    def sketches(self) -> List:
        """Every registered sketch (the traffic flush hook iterates
        these to emit final ``traffic.shape`` events)."""
        with self._lock:
            return [m for m in self._metrics.values()
                    if getattr(m, "kind", "") == "sketch"]

    def reset(self) -> None:
        """Drop every metric — test isolation only."""
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> Dict[str, object]:
        """JSON-able {name: value} (histograms expand to count/sum/p50/p99)."""
        with self._lock:
            metrics = dict(self._metrics)
        return {name: m.value for name, m in sorted(metrics.items())}

    def prometheus_text(
        self, extra: Optional[Mapping[str, float]] = None,
        prefix: str = "deepdfa_",
    ) -> str:
        """Prometheus text exposition (version 0.0.4) of every metric,
        plus ``extra`` numeric values exposed as gauges (the serve
        endpoint passes its ``ServingStats`` snapshot through here)."""
        with self._lock:
            metrics = dict(self._metrics)
        lines: List[str] = []
        for name, m in sorted(metrics.items()):
            full = prefix + name
            lines.append(f"# TYPE {full} {m.kind}")
            if getattr(m, "kind", "") in ("histogram", "sketch"):
                v = m.value
                lines.append(f"{full}_count {_fmt(v['count'])}")
                lines.append(f"{full}_sum {_fmt(v['sum'])}")
            else:
                lines.append(f"{full} {_fmt(m.value)}")
        for name, value in sorted((extra or {}).items()):
            if not isinstance(value, (int, float)) or isinstance(value, bool):
                continue
            full = prefix + sanitize(name)
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {_fmt(value)}")
        return "\n".join(lines) + "\n"


def _fmt(v: Union[int, float]) -> str:
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


# THE process registry every subsystem publishes into.
REGISTRY = Registry()

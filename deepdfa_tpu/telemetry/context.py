"""Cross-process trace context: one run, many processes, one timeline.

The telemetry plane (ISSUE 5/7) was strictly single-process: a child
process's spans died with its rings, and every event in the Chrome view
wore the *reader's* pid. This module is the propagation layer that makes
the run a distributed object (ISSUE 14):

* :class:`TraceContext` — the identity a parent hands a child: the run
  directory and run id, the child's **process name**, the parent's clock
  origin (``t0``/``wall_start``, so both processes stamp events on ONE
  run-relative timeline — ``time.perf_counter`` is CLOCK_MONOTONIC on
  Linux, shared across processes), and optionally a trace id / parent
  span id for request-scoped joins.
* **Env propagation** — :func:`child_env` serializes the active run's
  context into the ``DEEPDFA_TRACE_CONTEXT`` env var for an exec'd child
  (``cli fit`` under chaos, module workers); ``spans.start_run`` in the
  child sees :func:`inherited` and binds to the parent's run dir,
  writing its own ``events-<process>-<pid>.jsonl`` shard. graftlint
  GL020 polices that deepdfa entrypoint spawns go through this helper.
* **Fork propagation** — :func:`init_forked_worker` is the
  ``ProcessPoolExecutor`` initializer (and the isolated-requeue entry
  hook) that rebinds a fork-inherited run to the worker's own shard, so
  ETL pool workers' events stop dying in copied rings.
* **HTTP propagation** — a W3C-``traceparent``-style header
  (``00-<trace32>-<span16>-01``): :func:`make_traceparent` on the client,
  :func:`parse_traceparent` on the server (malformed values are ignored
  with a ``trace_ctx_malformed_total`` bump, never a 500), and the
  ``serve.request`` span continues the client's trace id so the offline
  report joins client-observed and server-observed latency.

A malformed env payload is counted and ignored — a broken parent must
never crash a child at import time.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
from typing import Dict, Mapping, Optional, Tuple

from deepdfa_tpu.telemetry.registry import REGISTRY

logger = logging.getLogger(__name__)

ENV_VAR = "DEEPDFA_TRACE_CONTEXT"
# Lowercase per RFC 9110 header-name case-insensitivity; the stdlib
# server's self.headers.get() is case-insensitive anyway.
TRACEPARENT_HEADER = "traceparent"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")
_PROCESS_SAFE = re.compile(r"[^A-Za-z0-9_-]")


def sanitize_process(name: str) -> str:
    """Process name -> shard-filename-safe fragment (no dots: segment
    suffixes are dot-delimited)."""
    out = _PROCESS_SAFE.sub("_", str(name) or "proc")
    return out or "proc"


def new_trace_id() -> str:
    """128-bit hex trace id (the traceparent trace-id field)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """64-bit hex span id (the traceparent parent-id field)."""
    return os.urandom(8).hex()


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """What a child inherits: where the run lives, who the child is, and
    the parent's clock origin."""

    run_dir: str
    run_id: str
    process: str
    t0: float           # parent's perf_counter at run start (shared clock)
    wall_start: float
    parent_process: str = "main"
    trace_id: Optional[str] = None
    parent_span: Optional[str] = None

    def encode(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def decode(cls, payload: str) -> "TraceContext":
        """Parse an env payload; raises ValueError on anything malformed
        (callers count-and-ignore — see :func:`inherited`)."""
        try:
            doc = json.loads(payload)
        except Exception as e:
            raise ValueError(f"unparseable trace context: {e}") from e
        if not isinstance(doc, dict):
            raise ValueError("trace context payload must be an object")
        try:
            return cls(
                run_dir=str(doc["run_dir"]),
                run_id=str(doc["run_id"]),
                process=sanitize_process(str(doc["process"])),
                t0=float(doc["t0"]),
                wall_start=float(doc["wall_start"]),
                parent_process=str(doc.get("parent_process", "main")),
                trace_id=(str(doc["trace_id"])
                          if doc.get("trace_id") else None),
                parent_span=(str(doc["parent_span"])
                             if doc.get("parent_span") else None),
            )
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"incomplete trace context: {e}") from e


# Cached once per process: the payload is set by the spawning parent and
# never changes underneath a running child.
_INHERITED_READ = False
_INHERITED: Optional[TraceContext] = None


def inherited() -> Optional[TraceContext]:
    """The context this process was spawned with (``DEEPDFA_TRACE_CONTEXT``),
    or None. A malformed payload is ignored with a counter bump — a
    broken parent must never crash the child."""
    global _INHERITED_READ, _INHERITED
    if not _INHERITED_READ:
        _INHERITED_READ = True
        payload = os.environ.get(ENV_VAR)
        if payload:
            try:
                _INHERITED = TraceContext.decode(payload)
            except ValueError:
                REGISTRY.counter("trace_ctx_malformed_total").inc()
                logger.warning("ignoring malformed %s", ENV_VAR,
                               exc_info=True)
    return _INHERITED


def reset_inherited() -> None:
    """Re-read the env on next :func:`inherited` — test isolation only."""
    global _INHERITED_READ, _INHERITED
    _INHERITED_READ = False
    _INHERITED = None


def child_env(process: str,
              base: Optional[Mapping[str, str]] = None,
              **extra: str) -> Dict[str, str]:
    """A subprocess env that joins the active run's trace plane.

    Returns a full env mapping (a copy of ``base``, default
    ``os.environ``) with ``DEEPDFA_TRACE_CONTEXT`` carrying the active
    run's context under the child's ``process`` name — the propagation
    helper GL020 expects at every deepdfa entrypoint spawn. With no
    active run (or telemetry disabled) the var is *removed*: a stale
    payload from this process's own parent must not leak a wrong process
    name into the grandchild.
    """
    from deepdfa_tpu.telemetry import spans

    env = dict(os.environ if base is None else base)
    env.update(extra)
    run = spans.current_run()
    if run is not None and spans.enabled():
        ctx = TraceContext(
            run_dir=os.path.abspath(run.run_dir),
            run_id=run.run_id,
            process=sanitize_process(process),
            t0=run.t0,
            wall_start=run.wall_start,
            parent_process=run.process,
        )
        env[ENV_VAR] = ctx.encode()
    else:
        env.pop(ENV_VAR, None)
    return env


def init_forked_worker(process: str = "forked") -> None:
    """``ProcessPoolExecutor(initializer=...)`` hook: rebind a
    fork-inherited telemetry run to THIS process's own shard, discarding
    the parent's copied ring contents (the parent is their durable
    writer). A no-op without an active run."""
    from deepdfa_tpu.telemetry import spans

    spans.rebind_forked(sanitize_process(process))


def make_traceparent(trace_id: Optional[str] = None,
                     span_id: Optional[str] = None) -> str:
    """The propagation header value for one outbound request."""
    return f"00-{trace_id or new_trace_id()}-{span_id or new_span_id()}-01"


def parse_traceparent(value: Optional[str]
                      ) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a traceparent header, or None
    when absent/malformed (all-zero ids are malformed per the W3C spec)."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id

"""Unified telemetry: run-scoped spans, one metrics registry, exporters.

The observability layer shared by ETL, training, and serving (ISSUE 5):

* :mod:`~deepdfa_tpu.telemetry.spans` — nestable ``span()`` context
  managers over lock-cheap per-thread ring buffers, with explicit
  ``block_until_ready`` fencing for honest host/device attribution and
  ``jax.monitoring``-based compile-event capture.
* :mod:`~deepdfa_tpu.telemetry.registry` — the one counter/gauge/
  histogram registry every subsystem publishes into (``ServingStats``,
  ``IngestStats``, ``contracts.STATS``, the retry loop), with a
  Prometheus text exposition.
* :mod:`~deepdfa_tpu.telemetry.export` — per-run
  ``runs/<run>/telemetry/{events.jsonl,trace.json}`` (Chrome
  trace-event format, loadable in Perfetto).
* :mod:`~deepdfa_tpu.telemetry.report` — the offline summary behind
  ``cli trace report <run>``.

The performance observatory (ISSUE 7) extends the layer with:

* :mod:`~deepdfa_tpu.telemetry.costmodel` — XLA cost-model capture of
  compiled callables (``cost_analysis`` FLOPs + ``memory_analysis``
  bytes at AOT/warmup time), joined to fenced spans by the report's
  roofline section: per-kernel MFU, operational intensity, and a
  compute-bound vs HBM-bound verdict.
* :mod:`~deepdfa_tpu.telemetry.memory` — peak-HBM gauges from compiled
  footprints plus a live ``device.memory_stats`` sampler where the
  backend supports it.
* :mod:`~deepdfa_tpu.telemetry.slo` — declarative SLO specs evaluated
  as burn rates over registry snapshots (live, degrading ``/healthz``)
  or against a trace report (``cli trace report --slo``).

The distributed trace plane (ISSUE 14) extends the layer with:

* :mod:`~deepdfa_tpu.telemetry.context` — cross-process trace context:
  ``DEEPDFA_TRACE_CONTEXT`` env propagation to subprocesses (a child
  writes its own ``events-<process>-<pid>.jsonl`` shard of the SAME run,
  on the same clock), a fork-worker rebind hook, and traceparent-style
  HTTP header helpers so a client span joins its server
  ``serve.request`` span offline by trace id.
* Shard rotation/retention: the active events file seals into segments
  at ``DEEPDFA_TRACE_ROTATE_BYTES``, sealed segments are dropped
  oldest-first past ``DEEPDFA_TRACE_RETAIN_BYTES`` — all counted in the
  registry; the report and the merged Chrome view read segments
  transparently.

The traffic observatory (ISSUE 20) extends the layer with:

* :mod:`~deepdfa_tpu.telemetry.sketch` — bounded deterministic
  quantile sketches over raw pre-bucket request shapes (nodes/edges,
  gen source tokens, scan sizes) at every admission edge, mirrored as
  mergeable ``traffic.shape`` events; plus the ladder-fitting math
  behind ``cli trace recommend-buckets``. The report's ``traffic``
  section reconstructs shape quantiles and the two-axis padding-waste
  decomposition (slot underfill vs in-slot pad vs flush overhead) from
  ``events.jsonl`` alone, and the roofline gains a goodput column
  (``effective_flops_frac`` / ``effective_mfu``).

``DEEPDFA_TELEMETRY=0`` disables everything; with no run active every
hook is a cheap no-op, so instrumentation lives in production code paths.
"""

from deepdfa_tpu.telemetry import context, sketch
from deepdfa_tpu.telemetry.registry import REGISTRY, Registry, sanitize
from deepdfa_tpu.telemetry.sketch import (
    SHAPE_SERIES,
    ShapeSketch,
    observe_shape,
    observe_train_pad,
)
from deepdfa_tpu.telemetry.spans import (
    ENV_VAR,
    Span,
    TelemetryRun,
    current_run,
    drop_count,
    enabled,
    end_run,
    event,
    flush,
    in_child_shard,
    now,
    rebind_forked,
    record_span,
    run_scope,
    set_enabled,
    span,
    start_run,
)

__all__ = [
    "ENV_VAR",
    "REGISTRY",
    "Registry",
    "SHAPE_SERIES",
    "ShapeSketch",
    "Span",
    "TelemetryRun",
    "context",
    "current_run",
    "drop_count",
    "enabled",
    "end_run",
    "event",
    "flush",
    "in_child_shard",
    "now",
    "observe_shape",
    "observe_train_pad",
    "rebind_forked",
    "record_span",
    "run_scope",
    "sanitize",
    "set_enabled",
    "sketch",
    "span",
    "start_run",
]

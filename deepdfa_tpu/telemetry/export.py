"""Exporters: Chrome trace-event JSON (Perfetto-loadable) and JSONL.

The shard set under ``<run>/telemetry/`` is the source of truth (the
report and every acceptance gate read it alone); ``trace.json`` is a
*view* generated from it in the Chrome trace-event format, so
``chrome://tracing`` / https://ui.perfetto.dev can render the same run
the report summarizes — they cannot disagree.

Cross-process layout (ISSUE 14): the primary process writes
``events.jsonl``; every child with an inherited trace context writes
``events-<process>-<pid>.jsonl`` into the same directory, and rotation
seals either into ``<stem>.seg-NNNNNN.jsonl`` segments. Every file opens
with a ``kind: "meta"`` record naming its emitter (pid + process), so
the merged Chrome view stamps the *emitter's* pid on every event —
never the converting process's — and emits ``M``-phase ``process_name``
metadata so Perfetto renders each process as a named track group.

Reads are skip-and-count: a torn trailing row (a child killed mid-append,
a segment sealed mid-write) costs that row, never the report.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

META_KIND = "meta"

# Shard filenames: "events.jsonl" (primary) / "events-<proc>-<pid>.jsonl"
# (children); sealed segments insert ".seg-NNNNNN" before the extension.
# Process-name fragments are sanitized to [A-Za-z0-9_-], so the dot
# reliably separates the stem from the segment suffix.
_ACTIVE_RE = re.compile(r"^(events(?:-[A-Za-z0-9_-]+)?)\.jsonl$")
_SEGMENT_RE = re.compile(
    r"^(events(?:-[A-Za-z0-9_-]+)?)\.seg-(\d+)\.jsonl$")


def append_jsonl(path: str, record: Dict[str, Any]) -> None:
    """THE JSONL writer every telemetry-adjacent file goes through
    (``events.jsonl`` flushes batch their own writes; per-step profile
    records come one at a time)."""
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


def read_events(events_path: str,
                stats: Optional[Dict[str, Any]] = None,
                ) -> List[Dict[str, Any]]:
    """One shard file's records, annotated and torn-row tolerant.

    Unparseable / non-object lines are skipped and counted (into
    ``stats["torn_rows"]`` when a stats dict is passed) — a child killed
    mid-append must cost its last row, never the report. Records after a
    ``meta`` header are annotated with the emitter's ``_pid`` /
    ``_process`` so downstream views carry real process identity; the
    meta records themselves stay in the list (``kind: "meta"`` — the
    report and the Chrome exporter both filter on kind).
    """
    out: List[Dict[str, Any]] = []
    torn = 0
    pid: Optional[int] = None
    process: Optional[str] = None
    with open(events_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                torn += 1
                continue
            if not isinstance(rec, dict):
                torn += 1
                continue
            if rec.get("kind") == META_KIND:
                if rec.get("pid") is not None:
                    pid = int(rec["pid"])
                    process = rec.get("process")
            elif pid is not None and "_pid" not in rec:
                rec["_pid"] = pid
                rec["_process"] = process
            out.append(rec)
    if stats is not None:
        stats["torn_rows"] = stats.get("torn_rows", 0) + torn
        if pid is not None:
            stats.setdefault("pid", pid)
            stats.setdefault("process", process)
    return out


def shard_files(telemetry_dir: str) -> Dict[str, List[str]]:
    """``{shard stem: [file paths, sealed segments first in sequence
    order, active file last]}`` for every shard under a run's telemetry
    directory."""
    groups: Dict[str, Dict[str, Any]] = {}
    try:
        names = sorted(os.listdir(telemetry_dir))
    except OSError:
        return {}
    for name in names:
        seg = _SEGMENT_RE.match(name)
        if seg is not None:
            g = groups.setdefault(seg.group(1), {"segs": [], "active": None})
            g["segs"].append((int(seg.group(2)), name))
            continue
        active = _ACTIVE_RE.match(name)
        if active is not None:
            g = groups.setdefault(active.group(1), {"segs": [],
                                                    "active": None})
            g["active"] = name
    out: Dict[str, List[str]] = {}
    for stem, g in sorted(groups.items()):
        ordered = [name for _, name in sorted(g["segs"])]
        if g["active"] is not None:
            ordered.append(g["active"])
        out[stem] = [os.path.join(telemetry_dir, n) for n in ordered]
    return out


def read_run_dir(run_dir: str
                 ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """Merged, annotated events from EVERY shard (and sealed segment) of
    a run, sorted onto the one shared timeline, plus per-shard stats
    (process, pid, segment/torn-row/byte accounting) for the report's
    ``processes`` section."""
    tdir = os.path.join(run_dir, "telemetry")
    events: List[Dict[str, Any]] = []
    shards: List[Dict[str, Any]] = []
    for stem, files in shard_files(tdir).items():
        stats: Dict[str, Any] = {
            "shard": stem,
            "files": len(files),
            "segments": sum(1 for p in files if ".seg-" in
                            os.path.basename(p)),
            "torn_rows": 0,
            "bytes": 0,
            "events": 0,
        }
        for path in files:
            try:
                stats["bytes"] += os.path.getsize(path)
            except OSError:
                pass
            recs = read_events(path, stats=stats)
            stats["events"] += sum(1 for r in recs
                                   if r.get("kind") != META_KIND)
            events.extend(recs)
        shards.append(stats)

    def _ts(rec: Dict[str, Any]) -> float:
        try:
            return float(rec.get("ts", 0.0))
        except (TypeError, ValueError):
            return 0.0

    events.sort(key=_ts)
    return events, shards


def events_to_chrome_trace(events: Iterable[Dict[str, Any]],
                           wall_start: Optional[float] = None,
                           default_pid: Optional[int] = None,
                           ) -> Dict[str, Any]:
    """Telemetry records -> Chrome trace-event document.

    Spans become complete (``ph: "X"``) events, instants become
    ``ph: "i"`` — both with microsecond timestamps, which is what the
    format specifies and Perfetto expects. Every event carries its
    EMITTER's pid (the ``_pid`` annotation from the shard's meta header
    — the ISSUE 14 fix for the exporter stamping the reader's
    ``os.getpid()`` on cross-process traces), and each distinct emitter
    gets an ``M``-phase ``process_name`` metadata event so the merged
    view renders named per-process track groups. ``default_pid`` covers
    legacy un-annotated records only.
    """
    if default_pid is None:
        default_pid = os.getpid()
    trace_events: List[Dict[str, Any]] = []
    procs: Dict[int, Optional[str]] = {}
    for rec in events:
        if rec.get("kind") == META_KIND:
            if rec.get("pid") is not None:
                procs.setdefault(int(rec["pid"]), rec.get("process"))
            continue
        pid = int(rec.get("_pid", default_pid))
        if rec.get("_process") is not None:
            procs.setdefault(pid, rec["_process"])
        else:
            procs.setdefault(pid, None)
        base: Dict[str, Any] = {
            "name": rec.get("name", "?"),
            "pid": pid,
            "tid": rec.get("tid", 0),
            "ts": float(rec.get("ts", 0.0)) * 1e6,
            "cat": rec.get("kind", "event"),
        }
        args = dict(rec.get("attrs") or {})
        for k in ("host_ms", "fenced", "error", "parent", "depth"):
            if k in rec:
                args[k] = rec[k]
        if args:
            base["args"] = args
        if rec.get("kind") == "span":
            base["ph"] = "X"
            base["dur"] = float(rec.get("dur_ms", 0.0)) * 1e3
        else:
            base["ph"] = "i"
            base["s"] = "t"  # thread-scoped instant
        trace_events.append(base)
    metadata = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0, "ts": 0,
         "args": {"name": name if name is not None else f"pid {pid}"}}
        for pid, name in sorted(procs.items())
    ]
    doc: Dict[str, Any] = {
        "traceEvents": metadata + trace_events,
        "displayTimeUnit": "ms",
    }
    if wall_start is not None:
        doc["otherData"] = {"wall_start_unix_s": wall_start}
    return doc


def _write_trace_doc(doc: Dict[str, Any], trace_path: str) -> int:
    # Pid-unique scratch: in an elastic fleet every member of a shared
    # run dir rewrites the merged trace at its own end_run, and two
    # writers racing one ".tmp" name lose it under the other's replace.
    tmp = f"{trace_path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, trace_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return len(doc["traceEvents"])


def write_chrome_trace(events_path: str, trace_path: str,
                       wall_start: Optional[float] = None) -> int:
    """ONE shard file -> trace.json; returns the trace-event count.
    (Runs with children should use :func:`write_merged_trace`.)"""
    events = read_events(events_path) if os.path.exists(events_path) else []
    doc = events_to_chrome_trace(events, wall_start=wall_start)
    return _write_trace_doc(doc, trace_path)


def write_merged_trace(run_dir: str, trace_path: Optional[str] = None,
                       wall_start: Optional[float] = None) -> int:
    """Every shard and sealed segment of a run -> ONE ``trace.json``
    with per-emitter pids and named processes; returns the trace-event
    count. Idempotent and callable while children's shards sit on disk
    after they exited — the acceptance path for auditing a cross-process
    drain from one merged timeline."""
    events, _ = read_run_dir(run_dir)
    if wall_start is None:
        metas = [e for e in events if e.get("kind") == META_KIND
                 and e.get("wall_start") is not None]
        if metas:
            wall_start = float(min(m["wall_start"] for m in metas))
    doc = events_to_chrome_trace(events, wall_start=wall_start)
    if trace_path is None:
        trace_path = os.path.join(run_dir, "telemetry", "trace.json")
    return _write_trace_doc(doc, trace_path)

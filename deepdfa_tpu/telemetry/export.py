"""Exporters: Chrome trace-event JSON (Perfetto-loadable) and JSONL.

``events.jsonl`` is the source of truth (the report and every acceptance
gate read it alone); ``trace.json`` is a *view* generated from it in the
Chrome trace-event format, so ``chrome://tracing`` / https://ui.perfetto.dev
can render the same run the report summarizes — they cannot disagree.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional


def append_jsonl(path: str, record: Dict[str, Any]) -> None:
    """THE JSONL writer every telemetry-adjacent file goes through
    (``events.jsonl`` flushes batch their own writes; per-step profile
    records come one at a time)."""
    with open(path, "a") as f:
        f.write(json.dumps(record) + "\n")


def read_events(events_path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    with open(events_path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def events_to_chrome_trace(events: Iterable[Dict[str, Any]],
                           wall_start: Optional[float] = None,
                           ) -> Dict[str, Any]:
    """Telemetry records -> Chrome trace-event document.

    Spans become complete (``ph: "X"``) events, instants become
    ``ph: "i"`` — both with microsecond timestamps, which is what the
    format specifies and Perfetto expects.
    """
    trace_events: List[Dict[str, Any]] = []
    pid = os.getpid()
    for rec in events:
        base: Dict[str, Any] = {
            "name": rec.get("name", "?"),
            "pid": pid,
            "tid": rec.get("tid", 0),
            "ts": float(rec.get("ts", 0.0)) * 1e6,
            "cat": rec.get("kind", "event"),
        }
        args = dict(rec.get("attrs") or {})
        for k in ("host_ms", "fenced", "error", "parent", "depth"):
            if k in rec:
                args[k] = rec[k]
        if args:
            base["args"] = args
        if rec.get("kind") == "span":
            base["ph"] = "X"
            base["dur"] = float(rec.get("dur_ms", 0.0)) * 1e3
        else:
            base["ph"] = "i"
            base["s"] = "t"  # thread-scoped instant
        trace_events.append(base)
    doc: Dict[str, Any] = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
    }
    if wall_start is not None:
        doc["otherData"] = {"wall_start_unix_s": wall_start}
    return doc


def write_chrome_trace(events_path: str, trace_path: str,
                       wall_start: Optional[float] = None) -> int:
    """events.jsonl -> trace.json; returns the trace-event count."""
    events = read_events(events_path) if os.path.exists(events_path) else []
    doc = events_to_chrome_trace(events, wall_start=wall_start)
    tmp = trace_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, trace_path)
    return len(doc["traceEvents"])

"""Memory telemetry: compiled-program HBM accounting + live device stats.

Two instruments, both exported through the shared metrics registry (so
the serve ``GET /metrics`` Prometheus exposition and ``/healthz`` carry
them for free):

* **Compiled footprint** — ``compiled.memory_analysis()`` splits one
  executable's device memory into temp (XLA scratch), argument, and
  output bytes. :func:`record_compiled` folds each capture into the
  run-peak gauges (``hbm_peak_*_bytes``) and emits a
  ``memory.analysis`` event per capture, so the offline report can name
  the kernel that owns the watermark.
* **Live device stats** — :class:`DeviceMemorySampler` polls
  ``device.memory_stats()`` (bytes_in_use / peak_bytes_in_use) where the
  backend supports it. CPU returns None; the sampler records itself
  unsupported and every later call is a cheap no-op — availability is a
  property of the backend, not an error.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)

# memory_analysis() attribute -> the short key the events/report use.
_MEM_FIELDS = {
    "temp_size_in_bytes": "temp_bytes",
    "argument_size_in_bytes": "argument_bytes",
    "output_size_in_bytes": "output_bytes",
    "generated_code_size_in_bytes": "code_bytes",
    "alias_size_in_bytes": "alias_bytes",
}
# The gauges a capture can raise — statically enumerated names (GL014:
# per-kernel detail rides the events, never per-kernel metric names).
_PEAK_GAUGE_NAMES = {
    "temp_bytes": "hbm_peak_temp_bytes",
    "argument_bytes": "hbm_peak_argument_bytes",
    "output_bytes": "hbm_peak_output_bytes",
    "total_bytes": "hbm_peak_total_bytes",
}

_LOCK = threading.Lock()


def compiled_memory(compiled) -> Optional[Dict[str, int]]:
    """temp/argument/output/... byte split of one compiled executable,
    plus ``total_bytes``; None when the backend has no memory analysis."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out: Dict[str, int] = {}
    for attr, key in _MEM_FIELDS.items():
        v = getattr(ma, attr, None)
        if isinstance(v, (int, float)):
            out[key] = int(v)
    if not out:
        return None
    out["total_bytes"] = (out.get("temp_bytes", 0)
                          + out.get("argument_bytes", 0)
                          + out.get("output_bytes", 0))
    return out


def record_compiled(name: str, mem: Dict[str, int]) -> None:
    """Fold one capture into the run-peak gauges + emit its event."""
    from deepdfa_tpu import telemetry

    with _LOCK:
        for key, gauge_name in _PEAK_GAUGE_NAMES.items():
            if key in mem:
                gauge = telemetry.REGISTRY.gauge(gauge_name)
                if mem[key] > gauge.value:
                    gauge.set(mem[key])
    telemetry.event("memory.analysis", name=name, **mem)


class DeviceMemorySampler:
    """Rate-limited ``device.memory_stats()`` poller.

    ``sample()`` reads the first addressable device's allocator stats,
    sets the ``device_bytes_in_use`` / ``device_peak_bytes_in_use``
    gauges, and emits a ``memory.sample`` event — at most once per
    ``min_interval_s``. Returns the stats dict, or None when the backend
    does not expose them (CPU) or the interval has not elapsed.
    """

    def __init__(self, min_interval_s: float = 1.0):
        self.min_interval_s = min_interval_s
        self.supported: Optional[bool] = None  # unknown until first poll
        self._last = 0.0
        self._lock = threading.Lock()

    def sample(self, force: bool = False) -> Optional[Dict[str, Any]]:
        from deepdfa_tpu import telemetry

        if self.supported is False or not telemetry.enabled():
            return None
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last < self.min_interval_s:
                return None
            self._last = now
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
        except Exception:
            logger.debug("device memory_stats read failed", exc_info=True)
            stats = None
        if not stats:
            if self.supported is None:
                self.supported = False
                logger.info("device memory_stats unsupported on this "
                            "backend; live HBM sampling disabled")
            return None
        self.supported = True
        out = {k: v for k, v in stats.items()
               if isinstance(v, (int, float))}
        if "bytes_in_use" in out:
            telemetry.REGISTRY.gauge("device_bytes_in_use").set(
                out["bytes_in_use"])
        if "peak_bytes_in_use" in out:
            telemetry.REGISTRY.gauge("device_peak_bytes_in_use").set(
                out["peak_bytes_in_use"])
        telemetry.event("memory.sample", **out)
        return out


#: The process sampler (serve pump + train epoch cadence share it, so the
#: rate limit is global — one poll per interval no matter how many sites).
SAMPLER = DeviceMemorySampler()

"""Cost-model capture: roofline/MFU attribution for compiled callables.

The paper's evaluation hinges on exactly this accounting — DeepSpeed
FlopsProfiler MACs joined to measured latency (Table 5) — and the
ROADMAP's megakernel arc needs its prerequisite: knowing whether each hot
path is compute-bound or HBM-bound *before* fusing anything. The
instrument here is XLA's own cost model: for an AOT-compiled executable,
``compiled.cost_analysis()`` counts post-fusion FLOPs and bytes for the
exact HLO that runs, and ``compiled.memory_analysis()`` reports the
temp/argument/output HBM footprint.

:func:`capture_compiled` records one compiled callable into the
process-wide :data:`CAPTURED` registry, mirrors its HBM footprint into
the shared metrics registry (``telemetry/memory.py``), and — when a
telemetry run is active — emits a ``cost.model`` event so the offline
report can join FLOPs to the run's measured spans and compute per-kernel
MFU, operational intensity, and a compute-vs-HBM-bound verdict from
``events.jsonl`` alone.

Availability is gated, never assumed: backends without
``cost_analysis``/``memory_analysis`` (or the single-device CPU tier-1
environment mid-API-drift) degrade to partial records, and a failed
capture logs instead of failing the training run it instruments.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

# Peak dense bf16 matmul throughput and HBM bandwidth per device kind —
# the roofline's two ceilings. The tunneled device reports kind
# "TPU v5 lite" (v5e): 197 TFLOP/s bf16, 819 GB/s HBM. Unknown kinds
# (the CPU tier-1 environment) report None and the roofline degrades to
# FLOPs/bytes/intensity without an MFU or a verdict.
PEAK_FLOPS: Dict[str, float] = {"TPU v5 lite": 197e12, "TPU v5e": 197e12}
PEAK_HBM_BYTES_PER_SEC: Dict[str, float] = {
    "TPU v5 lite": 819e9, "TPU v5e": 819e9,
}


def device_kind() -> Optional[str]:
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:  # pragma: no cover - no backend at all
        logger.debug("no device available for cost-model peaks",
                     exc_info=True)
        return None


def device_peaks(kind: Optional[str] = None,
                 ) -> Tuple[Optional[float], Optional[float]]:
    """(peak_flops, peak_hbm_bytes_per_sec) for ``kind`` (default: the
    current backend's first device); (None, None) when unknown."""
    if kind is None:
        kind = device_kind()
    if kind is None:
        return None, None
    return PEAK_FLOPS.get(kind), PEAK_HBM_BYTES_PER_SEC.get(kind)


def costs_of_compiled(compiled) -> Dict[str, float]:
    """XLA cost model of one AOT-compiled executable.

    Returns at least ``{"flops": ..., "macs": ...}`` (macs = flops/2, the
    DeepSpeed-comparison convention the profiling layer has always used);
    backend-provided numeric keys (``bytes accessed``, utilization) pass
    through. THE one flops accounting — ``eval/profiling.py``, bench.py
    diagnostics, and the roofline report all read this function, so their
    numbers cannot disagree.
    """
    raw = compiled.cost_analysis()
    if isinstance(raw, (list, tuple)):  # older jax returns [dict]
        raw = raw[0] if raw else {}
    out: Dict[str, float] = {}
    for k, v in (raw or {}).items():
        if isinstance(v, (int, float)):
            out[k] = float(v)
    flops = out.get("flops", 0.0)
    out["flops"] = flops
    out["macs"] = flops / 2.0
    return out


# ---------------------------------------------------------------------------
# The process-wide registry of captured callables
# ---------------------------------------------------------------------------

#: name -> the latest capture record for that callable (CLI/debug surface;
#: the offline report reads the ``cost.model`` events, not this dict).
CAPTURED: Dict[str, Dict[str, Any]] = {}
_LOCK = threading.Lock()


def reset() -> None:
    """Drop captured records — test isolation only."""
    with _LOCK:
        CAPTURED.clear()


def capture_compiled(name: str, compiled, steps_per_call: int = 1,
                     span: Optional[str] = None,
                     use_fenced_window: bool = False,
                     extra_flops: float = 0.0,
                     extra_bytes: float = 0.0,
                     **attrs: Any) -> Optional[Dict[str, Any]]:
    """Record one compiled executable's cost model under ``name``.

    ``steps_per_call``: logical steps one dispatch of this executable
    runs (bench's K-unrolled GNN program); the report divides by it.
    ``span``: the span name whose measured durations this kernel joins
    to in the roofline report (default: ``name``); extra ``attrs`` must
    be a subset of the joined spans' attrs (the serve lanes match on
    ``lane``/``slots``). ``use_fenced_window``: tell the report to prefer
    the fenced-window amortized step time over the dispatch-only span
    p50 when computing MFU (the train loops' honest device-inclusive
    per-step time).

    ``extra_flops``/``extra_bytes``: analytic work XLA's cost model
    cannot see — Pallas kernels are opaque custom calls it counts as
    zero, so callables built on them (the fused GNN megakernel, the
    flash attention kernels) register their hand-counted FLOPs/bytes
    here, summed over the whole dispatch (all ``steps_per_call`` steps).
    Added on top of the XLA-counted remainder of the program; recorded
    separately in the event so the roofline can attribute the split.

    Returns the record, or None when telemetry is fully disabled or the
    backend supports neither analysis. Never raises: a cost-model gap
    must not take down the run it observes.
    """
    from deepdfa_tpu import telemetry

    if not telemetry.enabled():
        return None
    costs: Dict[str, float] = {}
    try:
        costs = costs_of_compiled(compiled)
    except Exception:
        logger.warning("cost_analysis unavailable for %s", name,
                       exc_info=True)
    from deepdfa_tpu.telemetry import memory as telemetry_memory

    mem = telemetry_memory.compiled_memory(compiled)
    if not costs and mem is None:
        return None
    kind = device_kind()
    peak_flops, peak_bw = device_peaks(kind)
    record: Dict[str, Any] = {
        "name": name,
        "span": span or name,
        "steps_per_call": int(steps_per_call),
        "use_fenced_window": bool(use_fenced_window),
        "flops": costs.get("flops", 0.0) + float(extra_flops),
        "bytes_accessed": (costs.get("bytes accessed", 0.0)
                           + float(extra_bytes)),
        "device_kind": kind,
        "peak_flops": peak_flops,
        "peak_hbm_bytes_per_sec": peak_bw,
    }
    if extra_flops or extra_bytes:
        record["analytic_flops"] = float(extra_flops)
        record["analytic_bytes"] = float(extra_bytes)
    if mem is not None:
        record["memory"] = mem
        telemetry_memory.record_compiled(name, mem)
    if attrs:
        record["attrs"] = dict(attrs)
    with _LOCK:
        CAPTURED[name] = record
    # Flat event attrs: the report rebuilds the record from events.jsonl
    # alone (the round-trip contract), so everything rides the event.
    ev: Dict[str, Any] = {k: v for k, v in record.items() if k != "memory"}
    if mem is not None:
        ev.update({f"mem_{k}": v for k, v in mem.items()})
    if attrs:
        ev.pop("attrs", None)
        ev.update(attrs)
    telemetry.event("cost.model", **ev)
    return record


def capture_jitted(name: str, jitted, *args: Any,
                   steps_per_call: int = 1, span: Optional[str] = None,
                   use_fenced_window: bool = False,
                   **attrs: Any) -> Optional[Dict[str, Any]]:
    """``lower(*args).compile()`` + :func:`capture_compiled` for a jitted
    callable that was never AOT-compiled (the train loops jit in place).

    Costs one extra compile of an already-warm program, so call sites gate
    on an active telemetry run and fire once, at warmup time — before the
    ``warmup_done`` marker, so the compile never trips the
    post-warmup-compiles-must-be-0 gate. Never raises.
    """
    from deepdfa_tpu import telemetry

    if not telemetry.enabled():
        return None
    try:
        compiled = jitted.lower(*args).compile()
    except Exception:
        logger.warning("cost-model lower/compile failed for %s", name,
                       exc_info=True)
        return None
    return capture_compiled(name, compiled, steps_per_call=steps_per_call,
                            span=span, use_fenced_window=use_fenced_window,
                            **attrs)

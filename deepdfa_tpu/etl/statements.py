"""Statement-level vulnerability labels.

The reference labels a statement (line) vulnerable when it is removed by the
fix or data/control-dependent on lines the fix added
(DDFA/sastvd/helpers/evaluate.py:194-255 ``get_dep_add_lines``). Dependence
comes from the PDG: REACHING_DEF edges are data dependence, CDG edges are
control dependence, aggregated to line granularity.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set

from deepdfa_tpu.etl.cpg import CPG


def line_dependencies(cpg: CPG) -> Dict[int, Set[int]]:
    """line -> set of lines it depends on (data or control).

    A PDG edge src->dst means dst depends on src; both endpoints are mapped
    to their line numbers (unlined nodes are skipped)."""
    deps: Dict[int, Set[int]] = {}
    for s, d, t in cpg.edges:
        if t not in ("REACHING_DEF", "CDG"):
            continue
        src_line = cpg.nodes[s].line_number
        dst_line = cpg.nodes[d].line_number
        if src_line < 0 or dst_line < 0 or src_line == dst_line:
            continue
        deps.setdefault(dst_line, set()).add(src_line)
    return deps


def dependent_added_lines(
    before_cpg: CPG, after_cpg: CPG, added_lines: Iterable[int]
) -> List[int]:
    """Lines of the BEFORE graph that the fix's added lines depend on
    (evaluate.py:206-218: deps of added lines in the after graph, filtered
    to lines present in the before graph)."""
    added = set(added_lines)
    deps = line_dependencies(after_cpg)
    dep_lines: Set[int] = set()
    for line in added:
        dep_lines |= deps.get(line, set())
    before_lines = {n.line_number for n in before_cpg.nodes.values() if n.line_number >= 0}
    return sorted(dep_lines & before_lines)


def statement_labels(
    before_cpg: CPG,
    removed_lines: Iterable[int],
    dep_add_lines: Iterable[int],
) -> Dict[int, int]:
    """Per-line binary labels over the before graph: 1 if removed by the
    fix or dependent on added lines (the `_VULN` node attribute's line-level
    source, dbize.py:30-107)."""
    vuln = set(removed_lines) | set(dep_add_lines)
    return {
        line: int(line in vuln)
        for line in sorted(
            {n.line_number for n in before_cpg.nodes.values() if n.line_number >= 0}
        )
    }

"""Loader for the reference pipeline's cache artifacts.

The reference's dbize stage writes ``nodes[_sample].csv`` /
``edges[_sample].csv`` (DDFA/sastvd/scripts/dbize.py:75-76: per-node rows
with ``graph_id``/``dgl_id``/``node_id``/``vuln``; per-edge rows with
``graph_id``/``innode``/``outnode``) plus per-feature
``nodes_feat_<feat>_<split>[_sample].csv`` files holding the abstract-
dataflow vocab index per (graph_id, node_id) (dbize_absdf.py:21-45), and
bakes the graphs into DGL's ``graphs.bin``. This module reads the CSVs —
the complete information; ``graphs.bin`` is just the edge list re-serialized
(dbize_graphs.py:15-27, self-loops re-added at our batch time) — and
produces the example dicts ``graphs/batch.py`` consumes, so datasets
preprocessed by the reference pipeline feed this framework without rerunning
Joern.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Sequence

import numpy as np

from deepdfa_tpu.core.config import ALL_SUBKEYS, FeatureSpec


def _feat_path(processed_dir: Path, feature: FeatureSpec, subkey: str,
               split: str, sample: bool) -> Path:
    name = (
        f"_ABS_DATAFLOW_{subkey}_all"
        f"_limitall_{feature.limit_all}_limitsubkeys_{feature.limit_subkeys}"
    )
    sample_text = "_sample" if sample else ""
    return processed_dir / f"nodes_feat_{name}_{split}{sample_text}.csv"


def load_reference_cache(
    processed_dir: str,
    feature: Optional[FeatureSpec] = None,
    split: str = "fixed",
    sample: bool = False,
    labels_by_id: Optional[Dict[int, int]] = None,
) -> List[Dict]:
    """Read nodes/edges/nodes_feat CSVs into example dicts.

    Node order within a graph is ``dgl_id`` (the dense ids ``graphs.bin``
    used); graph label defaults to max node vuln (base_module.py:87-88)
    unless ``labels_by_id`` provides it.
    """
    import pandas as pd

    feature = feature or FeatureSpec()
    root = Path(processed_dir)
    sample_text = "_sample" if sample else ""
    nodes = pd.read_csv(root / f"nodes{sample_text}.csv", index_col=0)
    edges = pd.read_csv(root / f"edges{sample_text}.csv", index_col=0)

    subkeys = ALL_SUBKEYS if feature.concat_all else (feature.subkey,)
    feats_frames = {}
    for subkey in subkeys:
        path = _feat_path(root, feature, subkey, split, sample)
        fdf = pd.read_csv(path, index_col=0)
        feat_col = [c for c in fdf.columns if c.startswith("_ABS_DATAFLOW")]
        if len(feat_col) != 1:
            raise ValueError(f"{path} has no unique feature column: {list(fdf.columns)}")
        # Plain dict keyed by (graph_id, node_id): one vectorized pass here
        # beats millions of per-node pandas MultiIndex lookups below.
        feats_frames[subkey] = dict(
            zip(
                zip(fdf["graph_id"].to_numpy(), fdf["node_id"].to_numpy()),
                fdf[feat_col[0]].to_numpy(),
            )
        )

    out: List[Dict] = []
    edge_groups = dict(tuple(edges.groupby("graph_id")))
    for graph_id, n in nodes.groupby("graph_id"):
        n = n.sort_values("dgl_id")
        num_nodes = int(n["dgl_id"].max()) + 1
        vuln = np.zeros(num_nodes, np.int32)
        vuln[n["dgl_id"].to_numpy()] = n["vuln"].to_numpy()

        e = edge_groups.get(graph_id)
        senders = (
            e["innode"].to_numpy(np.int32) if e is not None else np.zeros(0, np.int32)
        )
        receivers = (
            e["outnode"].to_numpy(np.int32) if e is not None else np.zeros(0, np.int32)
        )

        feats = {}
        node_ids = n["node_id"].to_numpy()
        dgl_ids = n["dgl_id"].to_numpy()
        for subkey in subkeys:
            table = feats_frames[subkey]
            vals = np.zeros(num_nodes, np.int64)
            for nid, did in zip(node_ids, dgl_ids):
                vals[did] = int(table.get((graph_id, nid), 0))
            feats[subkey] = vals

        gid = int(graph_id)
        out.append(
            {
                "id": gid,
                "num_nodes": num_nodes,
                "senders": senders,
                "receivers": receivers,
                "vuln": vuln,
                "feats": feats,
                "label": (
                    labels_by_id[gid]
                    if labels_by_id is not None and gid in labels_by_id
                    else int(vuln.max(initial=0))
                ),
            }
        )
    return out

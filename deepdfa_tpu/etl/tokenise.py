"""IVDetect-style subtoken tokenization
(reference: DDFA/sastvd/helpers/tokenise.py:4-35)."""

from __future__ import annotations

import re
from typing import List

_SPEC_CHAR = re.compile(r"[^a-zA-Z0-9\s]")
_CAMEL = re.compile(r".+?(?:(?<=[a-z])(?=[A-Z])|(?<=[A-Z])(?=[A-Z][a-z])|$)")


def tokenise(s: str) -> str:
    """Split on special chars, then camelCase; drop single-char tokens."""
    spec_split = re.split(_SPEC_CHAR, s)
    space_split = " ".join(spec_split).split()
    camel_split = [m.group(0) for tok in space_split for m in re.finditer(_CAMEL, tok)]
    return " ".join(t for t in camel_split if len(t) > 1)


def tokenise_lines(s: str) -> List[str]:
    """Per-line tokenization, dropping lines that tokenize to nothing."""
    out = []
    for line in s.splitlines():
        tok = tokenise(line)
        if tok:
            out.append(tok)
    return out

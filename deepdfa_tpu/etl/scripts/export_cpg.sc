// Export a single-function CPG as nodes/edges JSON.
//
// TPU-framework equivalent of the reference's Joern export script
// (DDFA/storage/external/get_func_graph.sc:26-81): import the C file, run
// the ossdataflow overlay, and write `<file>.nodes.json` + `<file>.edges.json`
// next to it. Written fresh for Joern v1.1.x (same version the reference
// pins, scripts/install_joern.sh:6-8).
//
// Invoked through the REPL protocol as
//   export_cpg.exec(filename="/abs/path/x.c")

import io.shiftleft.semanticcpg.language._
import io.joern.dataflowengineoss.language._

@main def exec(filename: String) = {
  importCode(inputPath = filename, projectName = filename)
  run.ossdataflow

  val nodes = cpg.all.map { node =>
    val props = node.propertiesMap.asScala.map { case (k, v) =>
      s""""${k}": ${ujson.write(v.toString)}"""
    }.mkString(", ")
    s"""{"id": ${node.id}, "_label": "${node.label}", ${props}}"""
  }.l

  val edges = cpg.graph.edges.map { e =>
    s"""{"innode": ${e.inNode.id}, "outnode": ${e.outNode.id}, "etype": "${e.label}"}"""
  }.l

  os.write.over(os.Path(filename + ".nodes.json"), "[" + nodes.mkString(",\n") + "]")
  os.write.over(os.Path(filename + ".edges.json"), "[" + edges.mkString(",\n") + "]")
  delete  // drop the project so the workspace does not grow per file
}

"""Bridge from the ETL's CPG world to the training graph substrate.

Replaces the reference's dbize stage (DDFA/sastvd/scripts/dbize.py:30-107 +
dbize_graphs.py:20-33): instead of writing nodes.csv/edges.csv and a DGL
``graphs.bin``, a :class:`~deepdfa_tpu.etl.cpg.CPG` plus its abstract-
dataflow vocab indices exports directly to the dict schema consumed by
``deepdfa_tpu.graphs.batch.batch_graphs`` (and by the native graph cache in
``native/``).
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence, Tuple

import numpy as np

from deepdfa_tpu.etl.absdf import AbstractDataflowVocab, node_feature_indices
from deepdfa_tpu.etl.cpg import CPG, reduce_graph


def cpg_to_example(
    cpg: CPG,
    vocabs: Mapping[str, AbstractDataflowVocab],
    features: Mapping[int, Sequence[Tuple[str, str]]],
    graph_id: int,
    gtype: str = "cfg",
    line_labels: Optional[Mapping[int, int]] = None,
    label: Optional[int] = None,
    project: int = 0,
    dataflow: Optional[Tuple[Mapping[int, int], Mapping[int, int]]] = None,
) -> Dict:
    """Export one function graph.

    - Node order: sorted Joern id (dense re-indexing).
    - Edges: the ``gtype`` reduction (training uses "cfg",
      configs/config_bigvul.yaml); self-loops are added at batch time.
    - ``vuln``: per-node bit from line-level labels (dbize.py maps line
      labels onto nodes by lineNumber).
    - ``label``: graph bit; defaults to max node bit (base_module.py:87-88).
    """
    node_ids = sorted(cpg.nodes)
    dense = {nid: i for i, nid in enumerate(node_ids)}
    edges = reduce_graph(cpg, gtype).edges
    senders = np.asarray([dense[s] for s, _, _ in edges], np.int32)
    receivers = np.asarray([dense[d] for _, d, _ in edges], np.int32)

    vuln = np.zeros(len(node_ids), np.int32)
    if line_labels:
        for i, nid in enumerate(node_ids):
            vuln[i] = int(line_labels.get(cpg.nodes[nid].line_number, 0))

    feats = {
        subkey: np.asarray(idxs, np.int64)
        for subkey, idxs in node_feature_indices(cpg, features, vocabs).items()
    }
    # Index 0 means "not a definition" — a per-NODE property, so every
    # subkey must agree on the zero set (the cut_nodef mask and the
    # input_dim=limit_all+2 layout both rest on this; dbize_absdf.py:35-43).
    zero_sets = [f == 0 for f in feats.values()]
    if not all(np.array_equal(zero_sets[0], z) for z in zero_sets[1:]):
        # ValueError, not assert: this must fail loudly under python -O too.
        raise ValueError(
            f"subkeys disagree on the non-definition node set (graph {graph_id})"
        )
    extra: Dict = {}
    if dataflow is not None:
        # Per-node reaching-definitions solution bits (label styles
        # dataflow_solution_in/out, base_module.py:83-95), keyed by Joern id.
        df_in_map, df_out_map = dataflow
        extra["df_in"] = np.asarray(
            [int(df_in_map.get(n, 0)) for n in node_ids], np.int32
        )
        extra["df_out"] = np.asarray(
            [int(df_out_map.get(n, 0)) for n in node_ids], np.int32
        )
    return {
        **extra,
        "id": graph_id,
        "num_nodes": len(node_ids),
        "senders": senders,
        "receivers": receivers,
        "vuln": vuln,
        "feats": feats,
        "label": int(label) if label is not None else int(vuln.max(initial=0)),
        "project": project,
        # Joern id + line per dense node, for line-level reporting.
        "node_ids": np.asarray(node_ids, np.int64),
        "node_lines": np.asarray(
            [cpg.nodes[n].line_number for n in node_ids], np.int32
        ),
    }


VOCABS_FILENAME = "vocabs.json"
_VOCABS_VERSION = 1


def save_vocabs(vocabs: Mapping[str, AbstractDataflowVocab],
                path: str) -> str:
    """Persist the train-split abstract-dataflow vocabularies next to the
    export (``<workdir>/vocabs.json``).

    This is the checkpoint-faithful-scan gap the ROADMAP recorded: a model
    trained on these vocab indices must be *scanned* with the same
    index_for mapping, but the export stage never wrote the vocabs, so the
    scan path degraded to a deterministic hashing vocabulary. Index maps
    serialize as ordered ``[key, index]`` pairs because the reserved
    not-a-definition/UNKNOWN entry is keyed by ``None`` — not a legal JSON
    object key — and the frequency-rank order is the contract."""
    import json
    import os

    doc = {
        "version": _VOCABS_VERSION,
        "vocabs": {
            subkey: {
                "subkey": v.subkey,
                "limit_all": v.limit_all,
                "limit_subkeys": v.limit_subkeys,
                "subkey_index": [[k, i] for k, i in v.subkey_index.items()],
                "all_index": [[k, i] for k, i in v.all_index.items()],
            }
            for subkey, v in vocabs.items()
        },
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def load_vocabs(path: str) -> Dict[str, AbstractDataflowVocab]:
    """Load :func:`save_vocabs` output back into the vocab objects the
    featurizers consume (``index_for`` contract unchanged). Raises
    ``ValueError`` on a wrong version or shape — a scan must fail loudly
    rather than silently score with half a vocabulary."""
    import json

    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("version") != _VOCABS_VERSION:
        raise ValueError(
            f"{path}: not a vocabs.json (version "
            f"{doc.get('version') if isinstance(doc, dict) else '?'}, "
            f"expected {_VOCABS_VERSION})")
    if not isinstance(doc.get("vocabs"), dict):
        raise ValueError(f"{path}: vocabs.json has no 'vocabs' mapping")
    out: Dict[str, AbstractDataflowVocab] = {}
    for subkey, v in doc["vocabs"].items():
        try:
            out[subkey] = AbstractDataflowVocab(
                subkey=v["subkey"],
                limit_all=int(v["limit_all"]),
                limit_subkeys=int(v["limit_subkeys"]),
                subkey_index={k: int(i) for k, i in v["subkey_index"]},
                all_index={k: int(i) for k, i in v["all_index"]},
            )
        except (KeyError, TypeError, ValueError) as e:
            raise ValueError(f"{path}: malformed vocab {subkey!r}: {e}")
        if None not in out[subkey].all_index:
            raise ValueError(
                f"{path}: vocab {subkey!r} lacks the reserved UNKNOWN "
                "entry (None key)")
    return out


def export_codet5_defect_jsonl(
    rows: Sequence[Mapping],
    path: str,
    graphs_by_id: Optional[Mapping[int, Mapping]] = None,
) -> int:
    """Dump examples to the CodeT5 defect JSONL schema ``{idx, code,
    target}`` (get_examples_list_codet5, unixcoder/linevul_main.py:1400-1423)
    so a LineVul-prepared dataset feeds the CodeT5 trainers directly. With
    ``graphs_by_id`` rows lacking a parsed graph are dropped (the
    ``keep_idx`` filter). Returns the number of rows written."""
    import json
    import os

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    n = 0
    with open(path, "w") as f:
        for row in rows:
            idx = int(row["idx"])
            if graphs_by_id is not None and idx not in graphs_by_id:
                continue
            f.write(json.dumps({
                "idx": idx,
                "code": row["code"],
                "target": int(row["target"]),
            }) + "\n")
            n += 1
    return n

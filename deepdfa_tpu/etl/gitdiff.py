"""Vulnerability labeling from before/after function diffs.

The reference shells out to ``git diff --no-index`` with a context size
larger than both files so the patch is a single hunk, then records the
1-based positions of +/- lines *within the hunk body*
(DDFA/sastvd/helpers/git.py:12-79 ``gitdiff``/``md_lines``). Those positions
index the "combined" function text (old lines + added lines interleaved),
which is what the statement-level labels refer to.

Here the same hunk body comes from :mod:`difflib` (no subprocess, no temp
files): with full context, git's unified hunk body and difflib's agree —
every line of both files appears once, prefixed ' ', '-' or '+'.
"""

from __future__ import annotations

import difflib
from typing import Dict, List, Sequence


def unified_hunk_body(old: str, new: str) -> List[str]:
    """The single full-context hunk body: ' ' context, '-' removed,
    '+' added lines."""
    old_lines = old.splitlines()
    new_lines = new.splitlines()
    body: List[str] = []
    matcher = difflib.SequenceMatcher(a=old_lines, b=new_lines, autojunk=False)
    for tag, i1, i2, j1, j2 in matcher.get_opcodes():
        if tag == "equal":
            body.extend(" " + line for line in old_lines[i1:i2])
        else:
            body.extend("-" + line for line in old_lines[i1:i2])
            body.extend("+" + line for line in new_lines[j1:j2])
    return body


def code2diff(old: str, new: str) -> Dict[str, object]:
    """{"added": [hunk-body line idx...], "removed": [...], "diff": body}
    (git.py:38-79 ``md_lines`` semantics: indices are 1-based positions in
    the hunk body)."""
    if old == new:
        return {"added": [], "removed": [], "diff": ""}
    body = unified_hunk_body(old, new)
    added, removed = [], []
    for idx, line in enumerate(body, start=1):
        if line.startswith("+"):
            added.append(idx)
        elif line.startswith("-"):
            removed.append(idx)
    return {"added": added, "removed": removed, "diff": "\n".join(body)}


def combined_function(old: str, new: str, which: str = "before") -> str:
    """The reference's "combined function" (git.py:128-165 ``allfunc``):
    the hunk body with markers stripped, line numbers aligned with the
    diff indices of :func:`code2diff`.

    - ``which="before"``: ADDED lines are commented out (the pre-fix code,
      with the fix visible as comments) — this is the text fed to Joern and
      indexed by the removed-line labels.
    - ``which="after"``: REMOVED lines are commented out (post-fix code).

    Deviation: the reference keeps the leading ' ' on context lines
    (allfunc strips only +/- markers); we strip uniformly — whitespace-only,
    invisible to the parser.
    """
    if which not in ("before", "after"):
        raise ValueError(f"which={which!r} (want 'before' or 'after')")
    comment_marker = "+" if which == "before" else "-"
    body = unified_hunk_body(old, new)
    out = []
    for line in body:
        text = line[1:]
        if line.startswith(comment_marker):
            out.append("// " + text)
        else:
            out.append(text)
    return "\n".join(out)

"""ETL stage driver: the preprocess.sh of this framework.

The reference preprocesses in five SLURM-able stages
(DDFA/scripts/preprocess.sh:1-9 — prepare, getgraphs, dbize(+graphs),
abstract_dataflow, absdf). Here the same flow is three stages over one
``workdir``:

  prepare  — load a dataset (bigvul csv / devign json), write one ``.c``
             file per function plus ``meta.jsonl``;
  graphs   — run Joern over every function lacking exports (process-
             parallel via etl/parallel.pmap; failures land in
             ``failed_joern.txt`` and the row is skipped, getgraphs.py:57-59);
  export   — parse the Joern JSON, build the train-split abstract-dataflow
             vocabs, compute line-level labels (removed + dependent-added
             lines), and write ``examples.jsonl`` (the format
             ``cli.load_dataset`` and the graph batcher consume) plus
             ``splits.json``.

CLI: ``python -m deepdfa_tpu.etl.pipeline prepare|graphs|export|all ...``.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
from pathlib import Path
from typing import Dict, List, Optional

import numpy as np

from deepdfa_tpu.core.config import FeatureSpec

logger = logging.getLogger(__name__)


def prepare(rows: List[Dict], workdir: str) -> int:
    """Write functions/<id>.c (+ functions_after/<id>.c for fixed rows) and
    meta.jsonl; returns row count.

    The after-function files mirror the reference's ``processed/bigvul/
    after/`` tree (datasets.py:333-335 itempath): the graphs stage extracts
    a CPG from them too, which the export stage needs to compute
    dependent-added-line labels (evaluate.py:194-218).
    """
    root = Path(workdir)
    (root / "functions").mkdir(parents=True, exist_ok=True)
    (root / "functions_after").mkdir(parents=True, exist_ok=True)
    with open(root / "meta.jsonl", "w") as f:
        for row in rows:
            (root / "functions" / f"{row['id']}.c").write_text(row["before"])
            if row.get("vul") and row.get("after", "").strip():
                (root / "functions_after" / f"{row['id']}.c").write_text(
                    row["after"]
                )
            f.write(json.dumps({
                "id": int(row["id"]),
                "vul": int(row["vul"]),
                "project": row.get("project", ""),
                "added": list(row.get("added", [])),
                "removed": list(row.get("removed", [])),
                "after": row.get("after", ""),
            }) + "\n")
    return len(rows)


def _meta(workdir: Path) -> List[Dict]:
    with open(workdir / "meta.jsonl") as f:
        return [json.loads(line) for line in f]


def run_graphs(workdir: str, workers: int = 6) -> List[Path]:
    """Joern extraction for every function without exports."""
    from deepdfa_tpu.etl.joern_session import extract_cpg_batch, joern_available

    root = Path(workdir)
    pending = [
        p
        for d in ("functions", "functions_after")
        for p in sorted((root / d).glob("*.c"))
        if not p.with_suffix(".c.nodes.json").exists()
    ]
    if not pending:
        return []
    if not joern_available():
        raise RuntimeError(
            "joern binary not found on PATH; install it or provide "
            "pre-extracted <id>.c.nodes.json/<id>.c.edges.json files"
        )
    # Shard across worker sessions (run_getgraphs.sh job-array semantics);
    # each worker gets its own Joern workspace keyed by shard index.
    from deepdfa_tpu.etl.parallel import pmap

    shards = [
        (i, pending[i::workers]) for i in range(workers) if pending[i::workers]
    ]
    done_lists = pmap(
        lambda job: extract_cpg_batch(
            job[1], root, worker_id=job[0],
            failed_log=root / "failed_joern.txt",
        ),
        shards,
        workers=workers,
        desc="joern",
        failed_log=str(root / "failed_joern.txt"),
    )
    return [p for lst in done_lists if lst for p in lst]


def _dataflow_bits(stem: Path, cpg):
    """Per-node dataflow-solution bits for one function.

    Prefers Joern's own solver output (``<id>.c.dataflow.json``, written by
    get_dataflow_output.sc) when the graphs stage produced it; otherwise
    computes the identical fixpoint with the native reaching-definitions
    solver over the CFG (etl/reaching.py + native/src/reachdef.cpp) — the
    Joern-free path.
    """
    from deepdfa_tpu.etl.reaching import ReachingDefinitions, parse_dataflow_output

    df_path = stem.with_suffix(".c.dataflow.json")
    if df_path.exists():
        in_map, out_map = parse_dataflow_output(df_path)
        # The training bit is "any definition reaches this node", so the
        # values must be the exporter's list-of-definition-ids
        # (get_dataflow_output.sc:37-55). Pin the format: a scalar or dict
        # would binarize by truthiness and silently corrupt the labels.
        for m in (in_map, out_map):
            for v in m.values():
                if not isinstance(v, list):
                    # ValueError, not assert: must fail under python -O too.
                    raise ValueError(
                        f"dataflow.json value is {type(v).__name__}, "
                        "expected the exporter's list of definition ids"
                    )
        return (
            {n: int(bool(v)) for n, v in in_map.items()},
            {n: int(bool(v)) for n, v in out_map.items()},
        )
    return ReachingDefinitions(cpg).solution_node_bits()


def export(
    workdir: str,
    feature: Optional[FeatureSpec] = None,
    gtype: str = "cfg",
    split_seed: int = 0,
    split_mode: str = "random",
) -> Dict[str, int]:
    """Joern JSON -> vocabs -> labeled examples.jsonl + splits.json."""
    from deepdfa_tpu.data.splits import make_splits
    from deepdfa_tpu.etl.absdf import build_all_vocabs, extract_decl_features
    from deepdfa_tpu.etl.cpg import load_joern_export
    from deepdfa_tpu.etl.export import cpg_to_example
    from deepdfa_tpu.etl.statements import dependent_added_lines, statement_labels

    feature = feature or FeatureSpec()
    root = Path(workdir)
    meta = {m["id"]: m for m in _meta(root)}

    def fail(gid, exc):
        logger.warning("export: graph %d failed: %s", gid, exc)
        with open(root / "failed_export.txt", "a") as f:
            f.write(f"{gid}\t{exc}\n")

    # Pass 1: decl features only — CPGs are re-parsed in pass 2 so graph
    # residency stays O(1) at Big-Vul scale (~188k functions).
    from deepdfa_tpu.etl.cache import ValidityCache

    validity = ValidityCache(root / "valid_cache.csv")
    features_by_graph: Dict[int, Dict] = {}
    stems: Dict[int, Path] = {}
    for stem in sorted((root / "functions").glob("*.c")):
        if not stem.with_suffix(".c.nodes.json").exists():
            continue
        gid = int(stem.stem)
        # Per-id validity memo (datasets.py:295-330,386-399): known-bad
        # exports skip on re-runs without re-parsing.
        if not validity.is_valid(gid, stem):
            fail(gid, "invalid joern export (valid_cache)")
            continue
        try:
            features_by_graph[gid] = extract_decl_features(load_joern_export(stem))
            stems[gid] = stem
        except Exception as exc:  # per-item fault tolerance
            fail(gid, exc)

    # The vocab's defining split IS the split shipped with the data
    # (splits.json, consumed by cli.load_dataset) — a re-split downstream
    # would leak vocab-defining train examples into test.
    ordered = [{"id": gid, "project": meta.get(gid, {}).get("project", "")}
               for gid in sorted(stems)]
    # split_mode must match the evaluation protocol (cross-project exports
    # need cross-project vocab splits, or the vocab leaks into test).
    splits = make_splits(ordered, mode=split_mode, seed=split_seed)
    train_ids = [ordered[i]["id"] for i in splits["train"]]
    vocabs = build_all_vocabs(features_by_graph, train_ids, feature)
    # Persist the vocabs WITH the export (checkpoint-faithful scanning):
    # the scan service loads them so a live sweep indexes features with
    # the exact mapping the model trained on, instead of the hashing
    # fallback (etl/export.save_vocabs / scan `--scan-vocabs`).
    from deepdfa_tpu.etl.export import VOCABS_FILENAME, save_vocabs

    save_vocabs(vocabs, str(root / VOCABS_FILENAME))

    n_written = 0
    with open(root / "examples.jsonl", "w") as f:
        for gid in sorted(stems):
            m = meta.get(gid, {})
            try:
                cpg = load_joern_export(stems[gid])
            except Exception as exc:
                fail(gid, exc)
                continue
            line_labels = None
            if m.get("vul"):
                # Vulnerable lines: removed by the fix + lines of the before
                # function that the fix's added lines depend on
                # (evaluate.py:194-255). The dependency half needs the
                # after-function CPG (graphs stage over functions_after/);
                # when it's missing, labels degrade to removed-only, the
                # reference's own failure path (evaluate.py:234-236
                # except -> dep_add_lines = []).
                dep_added: List[int] = []
                after_stem = root / "functions_after" / f"{gid}.c"
                if after_stem.with_suffix(".c.nodes.json").exists():
                    try:
                        after_cpg = load_joern_export(after_stem)
                        dep_added = dependent_added_lines(
                            cpg, after_cpg, m.get("added", [])
                        )
                    except Exception as exc:
                        logger.warning(
                            "export: dep-added labels for %d failed: %s", gid, exc
                        )
                line_labels = statement_labels(cpg, m.get("removed", []), dep_added)
            try:
                dataflow = _dataflow_bits(stems[gid], cpg)
            except Exception as exc:
                # Same per-item posture as every other export step: a
                # malformed .dataflow.json or solver failure must not abort
                # a multi-hour export — degrade to all-zero solution bits.
                logger.warning("export: dataflow bits for %d failed: %s", gid, exc)
                dataflow = ({}, {})
            ex = cpg_to_example(
                cpg, vocabs, features_by_graph[gid], gid, gtype=gtype,
                line_labels=line_labels,
                label=int(m.get("vul", 0)) if m else None,
                dataflow=dataflow,
            )
            f.write(json.dumps({
                "id": ex["id"],
                "num_nodes": ex["num_nodes"],
                "senders": np.asarray(ex["senders"]).tolist(),
                "receivers": np.asarray(ex["receivers"]).tolist(),
                "vuln": np.asarray(ex["vuln"]).tolist(),
                "feats": {k: np.asarray(v).tolist() for k, v in ex["feats"].items()},
                "label": ex["label"],
                "project": m.get("project", ""),
                "df_in": np.asarray(ex["df_in"]).tolist(),
                "df_out": np.asarray(ex["df_out"]).tolist(),
            }) + "\n")
            n_written += 1
    partition = {}
    for part, idxs in splits.items():
        for i in idxs:
            partition[str(ordered[i]["id"])] = part
    with open(root / "splits.json", "w") as f:
        json.dump(partition, f)
    return {"graphs": len(stems), "examples": n_written}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="deepdfa_tpu.etl.pipeline")
    sub = parser.add_subparsers(dest="stage", required=True)

    p = sub.add_parser("prepare")
    p.add_argument("--dataset", choices=["bigvul", "devign"], required=True)
    p.add_argument("--path", required=True)
    p.add_argument("--workdir", required=True)
    p.add_argument("--sample", type=int, default=None)

    g = sub.add_parser("graphs")
    g.add_argument("--workdir", required=True)
    g.add_argument("--workers", type=int, default=6)

    e = sub.add_parser("export")
    e.add_argument("--workdir", required=True)
    e.add_argument("--feature", default=None, help="legacy feature name")
    e.add_argument("--gtype", default="cfg")
    e.add_argument("--split-mode", default="random",
                   choices=["random", "cross-project"])

    args = parser.parse_args(argv)
    if args.stage == "prepare":
        from deepdfa_tpu.etl.datasets import load_bigvul, load_devign

        rows = (
            load_bigvul(args.path, sample=args.sample)
            if args.dataset == "bigvul"
            else load_devign(args.path, sample=args.sample)
        )
        print(json.dumps({"prepared": prepare(rows, args.workdir)}))
    elif args.stage == "graphs":
        done = run_graphs(args.workdir, args.workers)
        print(json.dumps({"extracted": len(done)}))
    elif args.stage == "export":
        feat = FeatureSpec.parse_legacy(args.feature) if args.feature else None
        print(json.dumps(export(args.workdir, feat, gtype=args.gtype,
                                split_mode=args.split_mode)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Tokenizer training: byte-level BPE and word-level vocabularies.

Parity with the reference's tokenizer assets (SURVEY §2 #28):
  - CodeT5's BPE training script (CodeT5/tokenizer/train_tokenizer.py:1-22:
    ByteLevelBPETokenizer over code+doc corpora, vocab 32000, min_frequency
    3, the five special tokens);
  - LineVul's bpe_tokenizer / word_level_tokenizer JSON assets
    (LineVul/linevul/{bpe_tokenizer,word_level_tokenizer}/).

Uses the ``tokenizers`` Rust library bundled with transformers; gated so
environments without it fail with a clear error, not an import crash.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence

SPECIAL_TOKENS = ["<pad>", "<s>", "</s>", "<unk>", "<mask>"]


def _require_tokenizers():
    try:
        import tokenizers  # noqa: F401
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "tokenizer training needs the `tokenizers` package"
        ) from e


def train_bpe(
    files: Sequence[str],
    out_dir: str,
    prefix: str = "codet5",
    vocab_size: int = 32000,
    min_frequency: int = 3,
    special_tokens: Optional[List[str]] = None,
) -> List[str]:
    """Train a byte-level BPE tokenizer; writes ``<prefix>-vocab.json`` and
    ``<prefix>-merges.txt`` (the salesforce/codet5 asset layout)."""
    _require_tokenizers()
    from tokenizers import ByteLevelBPETokenizer

    tok = ByteLevelBPETokenizer()
    tok.train(
        files=list(files),
        vocab_size=vocab_size,
        min_frequency=min_frequency,
        special_tokens=special_tokens or SPECIAL_TOKENS,
    )
    Path(out_dir).mkdir(parents=True, exist_ok=True)
    return tok.save_model(out_dir, prefix)


def train_word_level(
    files: Sequence[str],
    out_path: str,
    vocab_size: int = 50000,
    special_tokens: Optional[List[str]] = None,
) -> str:
    """Train a whitespace word-level tokenizer to one JSON file (the
    LineVul word_level_tokenizer asset shape)."""
    _require_tokenizers()
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace
    from tokenizers.trainers import WordLevelTrainer

    tok = Tokenizer(WordLevel(unk_token="<unk>"))
    tok.pre_tokenizer = Whitespace()
    trainer = WordLevelTrainer(
        vocab_size=vocab_size,
        special_tokens=special_tokens or SPECIAL_TOKENS,
    )
    tok.train(list(files), trainer)
    Path(out_path).parent.mkdir(parents=True, exist_ok=True)
    tok.save(out_path)
    return out_path


def load_tokenizer(path: str):
    """Load a saved tokenizer JSON (word-level) or a BPE vocab/merges pair
    (pass the vocab.json path; merges.txt expected alongside)."""
    _require_tokenizers()
    if path.endswith("vocab.json"):
        from tokenizers import ByteLevelBPETokenizer

        merges = path.replace("vocab.json", "merges.txt")
        return ByteLevelBPETokenizer(path, merges)
    from tokenizers import Tokenizer

    return Tokenizer.from_file(path)

"""Driver for a long-lived Joern REPL (CPG extraction, L0 of the pipeline).

The reference keeps one ``joern`` process per ETL worker and speaks its REPL
protocol through pexpect (DDFA/sastvd/helpers/joern_session.py:33-141),
invoking Scala scripts like ``get_func_graph.sc`` that export
``<id>.c.nodes.json`` / ``.edges.json`` / ``.dataflow.json``.

Joern is an external JVM tool and is not bundled in this image; this driver
degrades to a clear error when the binary is missing
(:func:`joern_available` gates callers and tests). The interactive protocol
is implemented over a pty via the stdlib (pexpect is not a baked-in dep):
write a line, read until the ``joern>`` prompt, strip ANSI escapes.
"""

from __future__ import annotations

import logging
import os
import re
import shutil
import subprocess
import time
from pathlib import Path
from typing import Callable, List, Mapping, Optional, Sequence

from deepdfa_tpu import telemetry
from deepdfa_tpu.resilience import inject

logger = logging.getLogger(__name__)

_ANSI_RE = re.compile(r"\x1b\[[0-9;?]*[A-Za-z]|\x1b\][^\x07]*\x07|[\r\x00\x08]")
PROMPT = "joern>"


class JoernDiedError(RuntimeError):
    """The Joern child exited (EOF on the pty) — distinct from a hang
    (:class:`TimeoutError`), but both recover the same way: restart the
    session and re-run the item."""


def joern_available() -> bool:
    return shutil.which("joern") is not None


def resolve_command(binary) -> List[str]:
    """Normalize a session ``binary`` — a PATH name, an executable path, or
    a full argv list (the scan layer's hermetic fake transport runs as
    ``[sys.executable, fake_joern.py]``) — to the Popen argv. Raises the
    historic "not found" RuntimeError when the executable is missing, so
    callers keep one failure mode."""
    argv = [str(binary)] if isinstance(binary, (str, Path)) else \
        [str(part) for part in binary]
    if not argv:
        raise RuntimeError("empty joern command")
    exe = argv[0]
    if shutil.which(exe) is None and not os.path.exists(exe):
        raise RuntimeError(
            f"joern binary not found on PATH ({exe!r}); install Joern "
            "v1.1.107 (reference scripts/install_joern.sh) to run CPG "
            "extraction, or pass a transport command (e.g. the hermetic "
            "fake-Joern: deepdfa_tpu.scan.fake_joern.fake_joern_command())"
        )
    return argv


def shesc(value: str) -> str:
    """Escape a string for interpolation into a Scala string literal
    (joern_session.py:11-30)."""
    return value.replace("\\", "\\\\").replace('"', '\\"')


class JoernSession:
    """One REPL per worker, with a private workspace directory."""

    def __init__(
        self,
        worker_id: int = 0,
        workspace_root: str | Path = "joern_workspaces",
        timeout_s: float = 600.0,
        binary: "str | Sequence[str]" = "joern",
    ):
        argv = resolve_command(binary)
        self.timeout_s = timeout_s
        self.worker_id = worker_id
        self.workspace = Path(workspace_root) / f"worker_{worker_id}"
        self.workspace.mkdir(parents=True, exist_ok=True)
        import pty

        self._master, slave = pty.openpty()
        try:
            # Trace-context propagation (ISSUE 14): the child env carries
            # DEEPDFA_TRACE_CONTEXT for this worker, so a deepdfa-python
            # transport (the hermetic fake Joern is one) could shard into
            # the active run; a real JVM simply ignores it. A stale
            # inherited payload is scrubbed either way.
            from deepdfa_tpu.telemetry import context as trace_context

            self._proc = subprocess.Popen(
                argv,
                stdin=slave,
                stdout=slave,
                stderr=slave,
                cwd=self.workspace,
                env=trace_context.child_env(f"joern-{worker_id}",
                                            TERM="dumb"),
                close_fds=True,
            )
        except BaseException:
            os.close(self._master)
            os.close(slave)
            raise
        os.close(slave)
        try:
            self._read_until_prompt()
        except BaseException:
            # Startup failed: don't leak the JVM or the pty master.
            self._proc.kill()
            self._proc.wait()
            os.close(self._master)
            raise

    def _read_until_prompt(self) -> str:
        import select

        buf = b""
        deadline = time.time() + self.timeout_s
        while time.time() < deadline:
            ready, _, _ = select.select([self._master], [], [], min(deadline - time.time(), 1.0))
            if not ready:
                continue
            try:
                chunk = os.read(self._master, 65536)
            except OSError as e:
                raise JoernDiedError(
                    f"joern pty read failed ({e}); the JVM likely died"
                ) from e
            if not chunk:
                # EOF: the child exited. Failing immediately (instead of
                # spinning until the read deadline) is what keeps a crashed
                # JVM from stalling a whole ETL worker for timeout_s.
                raise JoernDiedError(
                    "joern exited mid-command (EOF on the REPL pty)"
                )
            buf += chunk
            text = _ANSI_RE.sub("", buf.decode(errors="replace"))
            if text.rstrip().endswith(PROMPT):
                return text
        raise TimeoutError(f"joern prompt not seen within {self.timeout_s}s")

    def send(self, line: str) -> str:
        # Fault hooks: `kill` murders the child JVM (the next read sees
        # EOF -> JoernDiedError), `hang` raises the read deadline's
        # TimeoutError directly — both drive the restart-and-retry path in
        # extract_cpg_batch without a real Joern install.
        for spec in inject.fire("joern.send"):
            if spec.kind == "kill":
                self._proc.kill()
                self._proc.wait()
        with telemetry.span("joern.send", worker=self.worker_id):
            os.write(self._master, (line + "\n").encode())
            out = self._read_until_prompt()
        # Strip the echoed command and the trailing prompt.
        body = out.split("\n", 1)[-1]
        return body.rsplit(PROMPT, 1)[0].strip()

    def run_script(self, script: str | Path, params: Mapping[str, str]) -> str:
        """``script.exec(k="v", ...)`` protocol (joern_session.py:96-114):
        the script is imported once, then its @main def is invoked with
        named string parameters."""
        stem = Path(script).stem
        self.send(f"import $file.`{shesc(str(Path(script).with_suffix('')))}`")
        args = ", ".join(f'{k}="{shesc(str(v))}"' for k, v in params.items())
        return self.send(f"{stem}.exec({args})")

    def import_code(self, path: str | Path) -> str:
        return self.send(f'importCode("{shesc(str(path))}")')

    def alive(self) -> bool:
        """Non-invasive liveness: has the child exited? (The scan pool's
        cheap health check — a protocol-level probe would race the worker
        thread that owns this REPL.)"""
        return self._proc.poll() is None

    def close(self, timeout_s: float = 10.0) -> None:
        """Graceful shutdown via the session protocol: ``exit`` on the
        REPL, bounded wait, then kill — the close→wait→kill escalation.
        Idempotent (a pool drain and an owner's close may race)."""
        if self._master < 0:
            return
        try:
            os.write(self._master, b"exit\n")
        except OSError:
            pass
        try:
            self._proc.wait(timeout=max(timeout_s, 0.1))
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()
        self._close_master()

    def kill(self) -> None:
        """Force-kill the child (the escalation terminus): SIGKILL + reap.
        A worker thread blocked in the REPL read then sees EOF and fails
        typed instead of wedging. Leaves the pty master open when a
        reader may still be draining it; :meth:`close` reaps it."""
        try:
            self._proc.kill()
            self._proc.wait(timeout=5)
        except (OSError, subprocess.TimeoutExpired):
            pass

    def _close_master(self) -> None:
        if self._master >= 0:
            try:
                os.close(self._master)
            except OSError:
                pass
            self._master = -1


def extract_cpg_batch(
    c_files: List[Path],
    out_dir: Path,
    worker_id: int = 0,
    failed_log: Optional[Path] = None,
    session_factory: Optional[Callable[..., "JoernSession"]] = None,
    attempts: int = 3,
) -> List[Path]:
    """Run Joern over a batch of single-function C files, exporting
    ``<name>.nodes.json``/``.edges.json`` next to each via
    ``scripts/export_cpg.sc`` (getgraphs.py:71-156 semantics: per-item fault
    tolerance, failures logged and skipped). ``worker_id`` keys the Joern
    workspace — concurrent sessions must not share one (the REPL writes
    project metadata into its workspace directory).

    Session-death recovery: a read timeout (hung REPL) or a dead JVM
    (:class:`JoernDiedError`) restarts the session and re-runs the item,
    up to ``attempts`` tries per item under jittered backoff
    (core/retry.py) — one wedged JVM must cost one restart, not the batch.
    ``session_factory`` (tests) substitutes the real REPL.
    """
    from deepdfa_tpu.core.retry import GiveUp, RetryPolicy, retry_call

    factory = session_factory or JoernSession
    if session_factory is None and not joern_available():
        raise RuntimeError("joern binary not found on PATH")
    script = Path(__file__).parent / "scripts" / "export_cpg.sc"
    done: List[Path] = []
    holder = [factory(worker_id, out_dir / "ws")]
    _SESSION_FATAL = (TimeoutError, JoernDiedError, OSError)

    def new_session() -> None:
        try:
            holder[0].close()
        except Exception:
            logger.warning("joern worker %d: close of the dead session "
                           "failed", worker_id, exc_info=True)
        holder[0] = factory(worker_id, out_dir / "ws")

    def restart(attempt: int, exc: BaseException, delay: float) -> None:
        logger.warning(
            "joern worker %d: %s: %s — restarting the session (attempt %d, "
            "retrying in %.2fs)", worker_id, type(exc).__name__, exc,
            attempt, delay,
        )
        telemetry.event("joern.restart", worker=worker_id, attempt=attempt,
                        error=type(exc).__name__)
        new_session()

    def run_item(path: Path) -> None:
        holder[0].run_script(script, {"filename": str(Path(path).resolve())})
        if not path.with_suffix(path.suffix + ".nodes.json").exists():
            raise RuntimeError("export produced no nodes.json")

    policy = RetryPolicy(
        max_attempts=max(attempts, 1),
        base_delay_s=0.1,
        retry_on=(TimeoutError, JoernDiedError, OSError),
    )
    try:
        for path in c_files:
            try:
                with telemetry.span("joern.item", worker=worker_id,
                                    item=str(path)):
                    retry_call(run_item, (path,), policy=policy,
                               on_retry=restart)
                done.append(path)
            except Exception as exc:  # per-item fault tolerance (incl. GiveUp)
                logger.warning("joern worker %d: giving up on %s (%s)",
                               worker_id, path, exc)
                if failed_log:
                    with open(failed_log, "a") as f:
                        f.write(f"{path}\t{exc}\n")
                # A give-up on a dead/hung session (retry_call only
                # restarts BETWEEN attempts, so the final failure leaves
                # the corpse in the holder — and attempts=1 never restarts
                # at all) must not poison the next item's budget.
                if isinstance(exc, _SESSION_FATAL) or (
                        isinstance(exc, GiveUp)
                        and isinstance(exc.last, _SESSION_FATAL)):
                    logger.warning("joern worker %d: restarting the session "
                                   "after a terminal failure", worker_id)
                    new_session()
    finally:
        holder[0].close()
    return done

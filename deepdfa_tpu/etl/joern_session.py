"""Driver for a long-lived Joern REPL (CPG extraction, L0 of the pipeline).

The reference keeps one ``joern`` process per ETL worker and speaks its REPL
protocol through pexpect (DDFA/sastvd/helpers/joern_session.py:33-141),
invoking Scala scripts like ``get_func_graph.sc`` that export
``<id>.c.nodes.json`` / ``.edges.json`` / ``.dataflow.json``.

Joern is an external JVM tool and is not bundled in this image; this driver
degrades to a clear error when the binary is missing
(:func:`joern_available` gates callers and tests). The interactive protocol
is implemented over a pty via the stdlib (pexpect is not a baked-in dep):
write a line, read until the ``joern>`` prompt, strip ANSI escapes.
"""

from __future__ import annotations

import os
import re
import shutil
import subprocess
import time
from pathlib import Path
from typing import List, Mapping, Optional

_ANSI_RE = re.compile(r"\x1b\[[0-9;?]*[A-Za-z]|\x1b\][^\x07]*\x07|[\r\x00\x08]")
PROMPT = "joern>"


def joern_available() -> bool:
    return shutil.which("joern") is not None


def shesc(value: str) -> str:
    """Escape a string for interpolation into a Scala string literal
    (joern_session.py:11-30)."""
    return value.replace("\\", "\\\\").replace('"', '\\"')


class JoernSession:
    """One REPL per worker, with a private workspace directory."""

    def __init__(
        self,
        worker_id: int = 0,
        workspace_root: str | Path = "joern_workspaces",
        timeout_s: float = 600.0,
        binary: str = "joern",
    ):
        if not joern_available():
            raise RuntimeError(
                "joern binary not found on PATH; install Joern v1.1.107 "
                "(reference scripts/install_joern.sh) to run CPG extraction"
            )
        self.timeout_s = timeout_s
        self.workspace = Path(workspace_root) / f"worker_{worker_id}"
        self.workspace.mkdir(parents=True, exist_ok=True)
        import pty

        self._master, slave = pty.openpty()
        try:
            self._proc = subprocess.Popen(
                [binary],
                stdin=slave,
                stdout=slave,
                stderr=slave,
                cwd=self.workspace,
                env={**os.environ, "TERM": "dumb"},
                close_fds=True,
            )
        except BaseException:
            os.close(self._master)
            os.close(slave)
            raise
        os.close(slave)
        try:
            self._read_until_prompt()
        except BaseException:
            # Startup failed: don't leak the JVM or the pty master.
            self._proc.kill()
            self._proc.wait()
            os.close(self._master)
            raise

    def _read_until_prompt(self) -> str:
        import select

        buf = b""
        deadline = time.time() + self.timeout_s
        while time.time() < deadline:
            ready, _, _ = select.select([self._master], [], [], min(deadline - time.time(), 1.0))
            if not ready:
                continue
            try:
                chunk = os.read(self._master, 65536)
            except OSError:
                break
            buf += chunk
            text = _ANSI_RE.sub("", buf.decode(errors="replace"))
            if text.rstrip().endswith(PROMPT):
                return text
        raise TimeoutError(f"joern prompt not seen within {self.timeout_s}s")

    def send(self, line: str) -> str:
        os.write(self._master, (line + "\n").encode())
        out = self._read_until_prompt()
        # Strip the echoed command and the trailing prompt.
        body = out.split("\n", 1)[-1]
        return body.rsplit(PROMPT, 1)[0].strip()

    def run_script(self, script: str | Path, params: Mapping[str, str]) -> str:
        """``script.exec(k="v", ...)`` protocol (joern_session.py:96-114):
        the script is imported once, then its @main def is invoked with
        named string parameters."""
        stem = Path(script).stem
        self.send(f"import $file.`{shesc(str(Path(script).with_suffix('')))}`")
        args = ", ".join(f'{k}="{shesc(str(v))}"' for k, v in params.items())
        return self.send(f"{stem}.exec({args})")

    def import_code(self, path: str | Path) -> str:
        return self.send(f'importCode("{shesc(str(path))}")')

    def close(self) -> None:
        try:
            os.write(self._master, b"exit\n")
        except OSError:
            pass
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()
        os.close(self._master)


def extract_cpg_batch(
    c_files: List[Path],
    out_dir: Path,
    worker_id: int = 0,
    failed_log: Optional[Path] = None,
) -> List[Path]:
    """Run Joern over a batch of single-function C files, exporting
    ``<name>.nodes.json``/``.edges.json`` next to each via
    ``scripts/export_cpg.sc`` (getgraphs.py:71-156 semantics: per-item fault
    tolerance, failures logged and skipped). ``worker_id`` keys the Joern
    workspace — concurrent sessions must not share one (the REPL writes
    project metadata into its workspace directory)."""
    if not joern_available():
        raise RuntimeError("joern binary not found on PATH")
    script = Path(__file__).parent / "scripts" / "export_cpg.sc"
    done: List[Path] = []
    session = JoernSession(worker_id, out_dir / "ws")
    try:
        for path in c_files:
            try:
                session.run_script(script, {"filename": str(Path(path).resolve())})
                if not path.with_suffix(path.suffix + ".nodes.json").exists():
                    raise RuntimeError("export produced no nodes.json")
                done.append(path)
            except Exception as exc:  # per-item fault tolerance
                if failed_log:
                    with open(failed_log, "a") as f:
                        f.write(f"{path}\t{exc}\n")
    finally:
        session.close()
    return done

"""Process-parallel ETL map with per-item fault tolerance.

Parity with the reference's ``dfmp`` (DDFA/sastvd/__init__.py:198-244:
multiprocessing Pool map over dataframe rows, 6 workers default, tqdm
progress, ordered results) and its ETL failure posture (SURVEY §5: every
per-function step catches, logs, and continues — failures land in
``failed_joern.txt``-style sidecar files rather than aborting a multi-hour
preprocessing run).
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

_SENTINEL_ERROR = "__pmap_error__"

# The mapped function travels to fork()ed workers by memory inheritance,
# not pickling — so closures and lambdas work (the reference's dfmp
# requires module-level functions; this lifts that restriction). The slot is
# process-global state, so concurrent pmap calls (threads, or a nested pmap
# reached from a mapped fn on the serial path) serialize on _ACTIVE_LOCK
# rather than clobbering each other's function.
_ACTIVE_FN: Optional[Callable] = None
_ACTIVE_LOCK = threading.RLock()


def _call(item):
    try:
        return _ACTIVE_FN(item)
    except Exception as e:  # per-item fault tolerance: record, don't abort
        return (_SENTINEL_ERROR, repr(item)[:200], f"{type(e).__name__}: {e}")


def pmap(
    fn: Callable[[Any], Any],
    items: Sequence[Any],
    workers: int = 6,
    desc: str = "",
    failed_log: Optional[str] = None,
    chunksize: int = 1,
) -> List[Any]:
    """Map ``fn`` over ``items`` with a process pool; ordered results.

    Items whose ``fn`` raises yield ``None`` in the result list; the failure
    is logged (and appended to ``failed_log`` when given) and processing
    continues — the reference's getgraphs.py:57-59 semantics.
    Degenerates to a serial loop for ``workers <= 1``, tiny inputs, or
    platforms without fork (avoids fork overhead and keeps tracebacks
    direct under debuggers).
    """
    global _ACTIVE_FN
    with _ACTIVE_LOCK:  # RLock: threads serialize, same-thread nesting enters
        prev = _ACTIVE_FN  # save/restore so a nested serial pmap doesn't
        _ACTIVE_FN = fn    # null the outer call's function
        try:
            if workers <= 1 or len(items) < 2 or os.name != "posix":
                results = [_call(item) for item in items]
            else:
                with mp.get_context("fork").Pool(workers) as pool:
                    results = pool.map(_call, items, chunksize=chunksize)
        finally:
            _ACTIVE_FN = prev

    out: List[Any] = []
    failures = []
    for r in results:
        if isinstance(r, tuple) and len(r) == 3 and r[0] == _SENTINEL_ERROR:
            failures.append((r[1], r[2]))
            out.append(None)
        else:
            out.append(r)
    if failures:
        logger.warning("%s: %d/%d items failed", desc or "pmap",
                       len(failures), len(items))
        if failed_log:
            with open(failed_log, "a") as f:
                for item_repr, err in failures:
                    f.write(f"{item_repr}\t{err}\n")
    return out

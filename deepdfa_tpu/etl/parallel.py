"""Process-parallel ETL map with per-item fault tolerance and requeue.

Parity with the reference's ``dfmp`` (DDFA/sastvd/__init__.py:198-244:
multiprocessing Pool map over dataframe rows, 6 workers default, tqdm
progress, ordered results) and its ETL failure posture (SURVEY §5: every
per-function step catches, logs, and continues — failures land in
``failed_joern.txt``-style sidecar files rather than aborting a multi-hour
preprocessing run).

On top of that, the resilience contract (ISSUE 3):

* **Per-item attempt cap.** An item whose ``fn`` raises is requeued and
  retried up to ``attempts`` total tries before its slot becomes ``None``
  — transient faults (a flaky external tool, an injected chaos fault)
  self-heal instead of punching holes in the dataset.
* **Crashed-worker requeue.** If the pool itself dies (a worker segfaults
  or is OOM-killed, which tears down ``Pool.map`` entirely), the
  unfinished items are requeued into *isolated* single-item subprocesses
  with a timeout, so one poison item can neither kill the parent nor
  take the rest of the batch down with it.
* **Fault hook.** ``inject`` site ``etl.item`` (index = item position)
  lets fault plans fail or kill specific work items deterministically.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import os
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from deepdfa_tpu import telemetry
from deepdfa_tpu.resilience import inject
from deepdfa_tpu.telemetry import context as trace_context

logger = logging.getLogger(__name__)

_SENTINEL_ERROR = "__pmap_error__"

# Timeout for the isolated requeue path only (the pool path keeps the
# reference's no-timeout semantics): a poison item that hangs its isolated
# subprocess is killed and recorded as failed.
ISOLATED_TIMEOUT_S = 300.0

# The mapped function travels to fork()ed workers by memory inheritance,
# not pickling — so closures and lambdas work (the reference's dfmp
# requires module-level functions; this lifts that restriction). The slot is
# process-global state, so concurrent pmap calls (threads, or a nested pmap
# reached from a mapped fn on the serial path) serialize on _ACTIVE_LOCK
# rather than clobbering each other's function.
_ACTIVE_FN: Optional[Callable] = None
_ACTIVE_LOCK = threading.RLock()


def _call(indexed: Tuple[int, Any]):
    idx, item = indexed
    try:
        inject.fire("etl.item", index=idx)
        result = _ACTIVE_FN(item)
    except Exception as e:  # per-item fault tolerance: record, don't abort
        result = (_SENTINEL_ERROR, repr(item)[:200],
                  f"{type(e).__name__}: {e}")
    if telemetry.in_child_shard():
        # A child process writing a shard of the parent's run (ISSUE 14)
        # makes each item's events durable before the next — a killed
        # worker costs at most its in-flight item's tail, and the merged
        # report still sees every completed item. Never fatal: a shard
        # write failure (disk full, run dir gone) costs the trace, not
        # the sweep — the per-item fault-tolerance contract holds on the
        # serial path too, where this runs outside the try above.
        try:
            telemetry.flush()
        except Exception:
            logger.warning("per-item telemetry flush failed",
                           exc_info=True)
    return result


def _isolated_entry(indexed: Tuple[int, Any], queue) -> None:
    # The isolated child is a fork: rebind the inherited run to this
    # process's own shard so its events merge instead of dying with it.
    trace_context.init_forked_worker("etl-iso")
    queue.put(_call(indexed))


def _run_isolated(indexed: Tuple[int, Any],
                  timeout_s: float = ISOLATED_TIMEOUT_S):
    """One item in its own fork()ed process: survives segfaults and hangs.
    Returns the item result or an error sentinel."""
    ctx = mp.get_context("fork")
    queue = ctx.SimpleQueue()
    proc = ctx.Process(target=_isolated_entry, args=(indexed, queue))
    proc.start()
    # Drain the queue BEFORE joining: a result bigger than the pipe buffer
    # (~64KB — CPG-sized payloads easily are) blocks the child's put until
    # the parent reads, so a blind join would deadlock and misreport a
    # healthy item as a timeout.
    deadline = time.monotonic() + timeout_s
    while queue.empty() and proc.is_alive() and time.monotonic() < deadline:
        time.sleep(0.01)
    if not queue.empty():
        result = queue.get()
        proc.join(10.0)
        if proc.is_alive():
            proc.kill()
            proc.join()
        return result
    if proc.is_alive():
        proc.kill()
        proc.join()
        return (_SENTINEL_ERROR, repr(indexed[1])[:200],
                f"TimeoutError: isolated item exceeded {timeout_s}s")
    proc.join()
    return (_SENTINEL_ERROR, repr(indexed[1])[:200],
            f"WorkerCrash: isolated worker exit code {proc.exitcode}")


def _is_failure(r: Any) -> bool:
    return isinstance(r, tuple) and len(r) == 3 and r[0] == _SENTINEL_ERROR


def pmap(
    fn: Callable[[Any], Any],
    items: Sequence[Any],
    workers: int = 6,
    desc: str = "",
    failed_log: Optional[str] = None,
    chunksize: int = 1,
    attempts: int = 2,
) -> List[Any]:
    """Map ``fn`` over ``items`` with a process pool; ordered results.

    Items whose ``fn`` raises are retried up to ``attempts`` total tries
    (requeued into isolated subprocesses on the pool path, re-called
    inline on the serial path); items still failing yield ``None`` in the
    result list, with the failure logged (and appended to ``failed_log``
    when given) — the reference's getgraphs.py:57-59 semantics, plus the
    attempt cap. A crashed *pool* (worker segfault) requeues the whole
    batch through the isolated path instead of aborting.
    Degenerates to a serial loop for ``workers <= 1``, tiny inputs, or
    platforms without fork (avoids fork overhead and keeps tracebacks
    direct under debuggers). ``chunksize`` is accepted for dfmp-call-site
    parity; scheduling is per-item (ETL payloads are seconds each, so
    chunking never paid for itself).
    """
    attempts = max(attempts, 1)
    indexed = list(enumerate(items))
    # Telemetry: the map itself is one span; per-item bookkeeping events
    # are emitted from the PARENT as results land. Worker-side events
    # (anything `fn` itself emits) land in each forked worker's own shard
    # of the active run (trace_context.init_forked_worker) and merge into
    # the same timeline offline — they no longer die in copied rings.
    with telemetry.span("etl.pmap", n_items=len(items), workers=workers,
                        desc=desc or "pmap") as pmap_span:
        return _pmap_locked(fn, indexed, items, workers, desc, failed_log,
                            attempts, pmap_span)


def _pmap_locked(fn, indexed, items, workers, desc, failed_log, attempts,
                 pmap_span):
    global _ACTIVE_FN
    with _ACTIVE_LOCK:  # RLock: threads serialize, same-thread nesting enters
        prev = _ACTIVE_FN  # save/restore so a nested serial pmap doesn't
        _ACTIVE_FN = fn    # null the outer call's function
        try:
            serial = workers <= 1 or len(items) < 2 or os.name != "posix"
            if serial:
                results = [_call(x) for x in indexed]
            else:
                # ProcessPoolExecutor over mp.Pool: a hard-crashed worker
                # (segfault, OOM-kill) breaks the pool with an exception on
                # the affected futures instead of hanging map() forever —
                # detection is what makes requeue possible at all. fn
                # exceptions never reach the futures (_call returns error
                # sentinels), so a future failure IS a pool-level crash;
                # those items fall into the requeue loop below.
                from concurrent.futures import ProcessPoolExecutor

                results = []
                # initializer: each forked worker rebinds the inherited
                # telemetry run to its own events-<process>-<pid>.jsonl
                # shard (GL020's blessed shape for module workers) —
                # worker-side spans/events used to die in copied rings.
                with ProcessPoolExecutor(
                    max_workers=workers, mp_context=mp.get_context("fork"),
                    initializer=trace_context.init_forked_worker,
                    initargs=("etl-pool",),
                ) as pool:
                    futures = [pool.submit(_call, x) for x in indexed]
                    for x, fut in zip(indexed, futures):
                        try:
                            results.append(fut.result())
                        except Exception as e:
                            logger.warning(
                                "%s: worker crashed under item %d (%s); "
                                "requeueing it isolated", desc or "pmap",
                                x[0], type(e).__name__,
                            )
                            results.append((
                                _SENTINEL_ERROR, repr(x[1])[:200],
                                f"WorkerCrash: {type(e).__name__}: {e}",
                            ))
            # Per-item attempt cap: requeue failures until the budget is
            # spent. Serial path retries inline (same-process semantics);
            # pool path retries isolated, so a repeatedly-crashing item
            # stays contained.
            for retry in range(attempts - 1):
                failed_idx = [i for i, r in enumerate(results)
                              if _is_failure(r)]
                if not failed_idx:
                    break
                logger.warning("%s: retrying %d failed item(s) (attempt "
                               "%d/%d)", desc or "pmap", len(failed_idx),
                               retry + 2, attempts)
                telemetry.event("etl.requeue", n=len(failed_idx),
                                attempt=retry + 2, desc=desc or "pmap")
                for i in failed_idx:
                    results[i] = (_call(indexed[i]) if serial
                                  else _run_isolated(indexed[i]))
        finally:
            _ACTIVE_FN = prev

    out: List[Any] = []
    failures = []
    for i, r in enumerate(results):
        if _is_failure(r):
            failures.append((r[1], r[2]))
            out.append(None)
            telemetry.event("etl.item", index=i, ok=False, error=r[2][:200],
                            desc=desc or "pmap")
        else:
            out.append(r)
            telemetry.event("etl.item", index=i, ok=True,
                            desc=desc or "pmap")
        if (i + 1) % 4096 == 0:
            # Corpus-scale maps emit more per-item events than one ring
            # holds (65536); flush on a cadence so the tail survives.
            telemetry.flush()
    pmap_span.set(n_failed=len(failures))
    if failures:
        logger.warning("%s: %d/%d items failed", desc or "pmap",
                       len(failures), len(items))
        if failed_log:
            with open(failed_log, "a") as f:
                for item_repr, err in failures:
                    f.write(f"{item_repr}\t{err}\n")
    return out

"""Dataset caching and per-id validity checks.

Parity with the reference's two caching layers:

- **Minimal parquet cache** (DDFA/sastvd/helpers/datasets.py:219-268): the
  expensive Big-Vul prepare (comment stripping, per-row git diff, quality
  filters) persists its minimal-column result so later runs load in seconds.
  Here :func:`minimal_cache` wraps any row loader with a parquet file keyed
  by source path/mtime/size + sample cap (gzip parquet like the reference;
  gzip JSONL fallback when no parquet engine is available).

- **Per-id validity cache** (datasets.py:295-330 ``check_validity`` +
  ``:386-399`` cached filter): whether a function's Joern exports parse,
  carry line numbers, and contain dataflow edges — checked once per id and
  remembered in a CSV so re-runs of the export stage skip known-bad graphs
  without re-parsing them.

Data-contract posture (deepdfa_tpu/contracts): JSONL cache rows are written
with a per-row ``__sha1__`` content digest, and the reader skip-and-counts
corrupt/truncated/checksum-mismatched lines into the cache's ``quarantine/``
sibling instead of raising mid-corpus — one torn row costs that row, not
the whole (expensive) cached prepare.
"""

from __future__ import annotations

import csv
import json
import logging
import os
from pathlib import Path
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Minimal row cache
# ---------------------------------------------------------------------------


def _source_key(src: Path) -> str:
    st = src.stat()
    return f"{st.st_mtime_ns}:{st.st_size}"


def minimal_cache(
    src_path: str | Path,
    loader: Callable[[], List[Dict]],
    cache_dir: Optional[str | Path] = None,
    tag: str = "minimal",
    sample: Optional[int] = None,
) -> List[Dict]:
    """Load rows through a persistent cache.

    ``loader`` runs only when no fresh cache exists; the cache is invalid
    whenever the source file's mtime/size changed (the reference caches by
    bare filename and can serve stale data — keying on mtime+size here).
    """
    src = Path(src_path)
    root = Path(cache_dir) if cache_dir else src.parent / ".deepdfa_cache"
    root.mkdir(parents=True, exist_ok=True)
    sample_text = f"_sample{sample}" if sample is not None else ""
    # Suffixes append by string concat: with_suffix() would truncate dotted
    # stems ("data.v2_bigvul_sample100" -> "data.key") and collapse distinct
    # cache entries into one file.
    base = root / f"{src.stem}_{tag}{sample_text}"
    meta_path = _sib(base, ".key")
    key = _source_key(src)

    if meta_path.exists() and meta_path.read_text() == key:
        rows = _read_cache(base)
        if rows is not None:
            logger.info("cache hit: %s (%d rows)", base, len(rows))
            return rows

    rows = loader()
    _write_cache(base, rows)
    meta_path.write_text(key)
    return rows


def _sib(base: Path, suffix: str) -> Path:
    return base.parent / (base.name + suffix)


def _write_cache(base: Path, rows: List[Dict]) -> None:
    # Whichever format we write, drop the other: a stale sibling from an
    # earlier run must not be served under the refreshed key (_read_cache
    # prefers parquet).
    try:
        import pandas as pd

        pd.DataFrame(_encode(rows)).to_parquet(
            _sib(base, ".parquet"), index=False, compression="gzip"
        )
        _sib(base, ".jsonl.gz").unlink(missing_ok=True)
    except Exception as exc:  # no parquet engine -> gzip jsonl
        logger.info("parquet cache unavailable (%s); using jsonl.gz", exc)
        import gzip

        from deepdfa_tpu.contracts.schema import CHECKSUM_KEY, row_checksum

        with gzip.open(_sib(base, ".jsonl.gz"), "wt") as f:
            for row in rows:
                # Per-row content digest: bitrot in a cached row must read
                # as checksum_mismatch at load, not as silent bad data.
                f.write(json.dumps(
                    dict(row, **{CHECKSUM_KEY: row_checksum(row)})) + "\n")
        _sib(base, ".parquet").unlink(missing_ok=True)


def _read_cache(base: Path) -> Optional[List[Dict]]:
    pq = _sib(base, ".parquet")
    jl = _sib(base, ".jsonl.gz")
    try:
        if pq.exists():
            import pandas as pd

            return _decode(pd.read_parquet(pq).to_dict("records"))
        if jl.exists():
            return _decode(_read_jsonl_cache(jl))
    except Exception as exc:
        logger.warning("cache read failed (%s); rebuilding", exc)
    return None


def _read_jsonl_cache(jl: Path) -> List[Dict]:
    """Read a gzip-JSONL cache, skip-and-counting bad rows.

    Corrupt/truncated lines (including a gzip stream cut mid-record) and
    checksum-mismatched rows are quarantined into the cache directory's
    ``quarantine/`` sibling and skipped — the surviving rows are served
    instead of raising mid-corpus and forcing a full re-prepare.
    """
    import gzip

    from deepdfa_tpu.contracts import ContractError, Quarantine
    from deepdfa_tpu.contracts.quarantine import quarantine_dir
    from deepdfa_tpu.contracts.schema import validate_cache_row

    rows: List[Dict] = []
    sink: Optional[Quarantine] = None

    def quarantine(err: ContractError, raw) -> None:
        nonlocal sink
        if sink is None:
            sink = Quarantine(quarantine_dir(jl))
        sink.put(err, raw=raw)

    with gzip.open(jl, "rt") as f:
        i = 0
        while True:
            try:
                line = f.readline()
            except (EOFError, OSError) as e:
                # The gzip stream itself was cut: everything already read
                # is intact; the tail is one truncated record.
                quarantine(ContractError(
                    "truncated_json", f"gzip stream truncated: {e}",
                    boundary="cache", item_id=i), raw="")
                break
            if not line:
                break
            if line.strip():
                try:
                    doc = json.loads(line)
                    rows.append(validate_cache_row(
                        doc, boundary="cache",
                        item_id=doc.get("id", i)
                        if isinstance(doc, dict) else i))
                except json.JSONDecodeError as e:
                    quarantine(ContractError(
                        "truncated_json", f"row {i}: {e}",
                        boundary="cache", item_id=i), raw=line)
                except ContractError as e:
                    quarantine(e, raw=line)
            i += 1
    if sink is not None and sink.total:
        if not rows:
            # Every row was corrupt: serving [] would read as a valid
            # "0-row cache hit" upstream. The source of truth still
            # exists — fail the read so minimal_cache rebuilds.
            raise ValueError(
                f"all {sink.total} cache rows corrupt (quarantined "
                f"-> {sink.root})")
        logger.warning("cache %s: %d corrupt row(s) quarantined -> %s",
                       jl, sink.total, sink.root)
    return rows


# List-valued fields (added/removed line numbers) ride JSON-encoded inside
# the parquet columns — the reference uses fastparquet object_encoding=json
# for the same reason (datasets.py:263-266).
_LIST_FIELDS = ("added", "removed")


def _encode(rows: List[Dict]) -> List[Dict]:
    out = []
    for row in rows:
        row = dict(row)
        for k in _LIST_FIELDS:
            if k in row:
                row[k] = json.dumps(list(row[k]))
        out.append(row)
    return out


def _decode(rows: List[Dict]) -> List[Dict]:
    for row in rows:
        for k in _LIST_FIELDS:
            if k in row and isinstance(row[k], str):
                row[k] = json.loads(row[k])
    return rows


# ---------------------------------------------------------------------------
# Per-id validity
# ---------------------------------------------------------------------------


def check_validity(
    stem: str | Path,
    require_line_number: bool = False,
    require_dataflow: bool = False,
) -> bool:
    """check_validity parity (datasets.py:295-330): exports parse, at least
    one node carries a lineNumber (warn / fail per flag), and the edge set
    contains dataflow (REACHING_DEF or CDG) edges (warn / fail per flag)."""
    from deepdfa_tpu.contracts.schema import (
        validate_joern_edges,
        validate_joern_nodes,
    )

    stem = Path(stem)
    try:
        with open(stem.with_suffix(".c.nodes.json")) as f:
            nodes = validate_joern_nodes(json.load(f), item_id=str(stem))
        if not any("lineNumber" in n for n in nodes):
            logger.warning("valid (%s): no line number", stem)
            if require_line_number:
                return False
        with open(stem.with_suffix(".c.edges.json")) as f:
            edges = validate_joern_edges(json.load(f), item_id=str(stem))
        etypes = {e[2] for e in edges if len(e) > 2}
        if "REACHING_DEF" not in etypes and "CDG" not in etypes:
            logger.warning("valid (%s): no dataflow", stem)
            if require_dataflow:
                return False
    except Exception as exc:
        logger.warning("valid (%s): %s", stem, exc)
        return False
    return True


class ValidityCache:
    """CSV-backed per-id validity memo (the reference caches the check
    results per dataset and filters with them, datasets.py:386-399).

    Each verdict is keyed on the export's mtime/size: regenerating a
    once-corrupt export invalidates the memo instead of excluding the graph
    forever (the reference's bare-id cache has exactly that staleness bug).
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self._known: Dict[int, tuple] = {}  # gid -> (export_key, valid)
        if self.path.exists():
            with open(self.path, newline="") as f:
                for rec in csv.DictReader(f):
                    self._known[int(rec["id"])] = (
                        rec.get("key", ""), rec["valid"] == "1"
                    )

    @staticmethod
    def _export_key(stem: Path) -> str:
        # Key on BOTH export files — check_validity reads both, and a
        # regenerated edges.json alone must invalidate a cached verdict.
        parts = []
        for suffix in (".c.nodes.json", ".c.edges.json"):
            try:
                parts.append(_source_key(stem.with_suffix(suffix)))
            except OSError:
                parts.append("missing")
        return "|".join(parts)

    def is_valid(self, gid: int, stem: str | Path, **flags) -> bool:
        key = self._export_key(Path(stem))
        cached = self._known.get(gid)
        if cached is None or cached[0] != key:
            valid = check_validity(stem, **flags)
            self._known[gid] = (key, valid)
            self._append(gid, key, valid)
        return self._known[gid][1]

    def _append(self, gid: int, key: str, valid: bool) -> None:
        new = not self.path.exists()
        with open(self.path, "a", newline="") as f:
            w = csv.writer(f)
            if new:
                w.writerow(["id", "key", "valid"])
            w.writerow([gid, key, int(valid)])

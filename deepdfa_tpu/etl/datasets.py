"""Dataset loaders: Big-Vul (MSR) CSV and Devign JSON.

Mirrors the reference loaders (DDFA/sastvd/helpers/datasets.py:139-292
``bigvul``, :36-102 ``devign``) without pandas: rows become plain dicts with
the minimal columns the rest of the pipeline uses
(id/before/after/added/removed/diff/vul/project). Comment stripping and the
vulnerable-row quality filters reproduce the reference's post-processing.

Real archives are not bundled; loaders take explicit paths and raise
``FileNotFoundError`` naturally when absent — the test path is the
synthetic sample generator (``deepdfa_tpu.data.synthetic``).
"""

from __future__ import annotations

import csv
import json
import logging
import re
import sys
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from deepdfa_tpu.etl.gitdiff import code2diff, combined_function

logger = logging.getLogger(__name__)

_COMMENT_RE = re.compile(
    r'//.*?$|/\*.*?\*/|\'(?:\\.|[^\\\'])*\'|"(?:\\.|[^\\"])*"',
    re.DOTALL | re.MULTILINE,
)


def remove_comments(text: str) -> str:
    """Strip // and /* */ comments, leaving strings/chars intact
    (datasets.py:19-33; comments become a space to preserve tokenization)."""

    def replacer(match: re.Match) -> str:
        s = match.group(0)
        return " " if s.startswith("/") else s

    return _COMMENT_RE.sub(replacer, text)


def _diff_fields(before: str, after: str) -> Dict:
    d = code2diff(before, after)
    if not d["diff"]:  # unchanged function: combined == raw (allfunc :142-144)
        return {"added": [], "removed": [], "diff": "", "before": before, "after": before}
    return {
        "added": d["added"],
        "removed": d["removed"],
        "diff": d["diff"],
        # Combined texts (git.py allfunc): "before" comments out added
        # lines, "after" comments out removed lines; both align 1:1 with
        # the diff body so added/removed indices address them directly.
        "before": combined_function(before, after, "before"),
        "after": combined_function(before, after, "after"),
    }


def _keep_vulnerable(row: Dict) -> bool:
    """The reference's vulnerable-row quality filters (datasets.py:224-248)."""
    if not row["added"] and not row["removed"]:
        return False
    fb = row["func_before"].strip()
    if fb and fb[-1] != "}" and fb[-1] != ";":
        return False
    fa = row["func_after"].strip()
    if fa and fa[-1] != "}" and not row["after"].strip()[-1:] == ";":
        return False
    if row["before"][-2:] == ");":
        return False
    n_diff = len(row["diff"].splitlines())
    if n_diff and (len(row["added"]) + len(row["removed"])) / n_diff >= 0.7:
        return False
    if len(row["before"].splitlines()) <= 5:
        return False
    return True


def load_bigvul(
    csv_path: str | Path,
    sample: Optional[int] = None,
    id_column: str = "",
    cache: bool = True,
    cache_dir: Optional[str | Path] = None,
) -> List[Dict]:
    """Load the MSR_data_cleaned.csv Big-Vul dump into minimal rows.

    ``sample``: cap row count (the reference's 100+100 subset is built
    separately, sample_MSR_data.py; here a simple head-count cap).
    ``cache``: persist the minimal rows next to the source (parquet minimal
    cache, reference datasets.py:219-268) so re-runs skip the comment
    stripping + per-row diffing.
    """
    if cache:
        from deepdfa_tpu.etl.cache import minimal_cache

        return minimal_cache(
            csv_path,
            lambda: load_bigvul(csv_path, sample, id_column, cache=False),
            cache_dir=cache_dir,
            # id_column changes the rows' ids; it must key the cache entry.
            tag=f"bigvul_{id_column}" if id_column else "bigvul",
            sample=sample,
        )
    csv.field_size_limit(sys.maxsize)
    out: List[Dict] = []
    with open(csv_path, newline="") as f:
        reader = csv.DictReader(f)
        for i, rec in enumerate(reader):
            if sample is not None and len(out) >= sample:
                break
            func_before = remove_comments(rec.get("func_before", ""))
            func_after = remove_comments(rec.get("func_after", ""))
            row = {
                "id": int(rec.get(id_column or "", "") or i),
                "vul": int(rec.get("vul", 0) or 0),
                "project": rec.get("project", ""),
                "func_before": func_before,
                "func_after": func_after,
            }
            row.update(_diff_fields(func_before, func_after))
            if row["vul"] and not _keep_vulnerable(row):
                continue
            out.append(row)
    logger.info("bigvul: %d rows from %s", len(out), csv_path)
    return out


def load_devign(
    json_path: str | Path,
    sample: Optional[int] = None,
    cache: bool = True,
    cache_dir: Optional[str | Path] = None,
) -> List[Dict]:
    """Devign function.json: [{project, commit_id, target, func}, ...]
    (datasets.py:36-102; no before/after pair, so no diff labels)."""
    if cache:
        from deepdfa_tpu.etl.cache import minimal_cache

        return minimal_cache(
            json_path,
            lambda: load_devign(json_path, sample, cache=False),
            cache_dir=cache_dir,
            tag="devign",
            sample=sample,
        )
    with open(json_path) as f:
        records = json.load(f)
    out: List[Dict] = []
    for i, rec in enumerate(records):
        if sample is not None and len(out) >= sample:
            break
        code = remove_comments(rec["func"])
        # Reference post-processing (datasets.py:62-73): collapse blank
        # lines, drop abnormal endings.
        code = code.replace("\n\n", "\n")
        stripped = code.strip()
        if not stripped or (stripped[-1] != "}" and stripped[-1] != ";"):
            continue
        if stripped[-2:] == ");":
            continue
        out.append(
            {
                "id": i,
                "vul": int(rec.get("target", 0)),
                "project": rec.get("project", ""),
                "func_before": code,
                "func_after": code,
                "before": code,
                "after": code,
                "added": [],
                "removed": [],
                "diff": "",
            }
        )
    logger.info("devign: %d rows from %s", len(out), json_path)
    return out


def load_mutated(
    rows: List[Dict], jsonl_path: str | Path, subdataset: str
) -> List[Dict]:
    """Join Big-Vul rows with a mutated-code JSONL (reference
    datasets.py:105-125 ``mutated``): each JSONL line carries
    ``{idx, source, target}``; ``*_flip`` subdatasets take ``source`` as the
    function body, others take ``target``. Inner join — only rows with a
    mutated counterpart survive; diff-derived fields are dropped (mutants
    have no before/after pair)."""
    use_source = "flip" in subdataset
    mutated_by_id: Dict[int, str] = {}
    with open(jsonl_path) as f:
        for line in f:
            rec = json.loads(line)
            code = rec["source"] if use_source else rec["target"]
            mutated_by_id[int(rec["idx"])] = code
    out: List[Dict] = []
    for row in rows:
        code = mutated_by_id.get(int(row["id"]))
        if code is None:
            continue
        new = {k: v for k, v in row.items()
               if k not in ("after", "added", "removed", "diff")}
        new["before"] = code
        new["func_before"] = code
        new["dataset"] = f"mutated_{subdataset}"
        out.append(new)
    logger.info("mutated_%s: %d rows joined from %s",
                subdataset, len(out), jsonl_path)
    return out

"""Abstract dataflow embedding: definition-node feature mining + vocab.

The DeepDFA node feature. For every *definition* node (a Joern CALL whose
operator is an assignment/inc/dec, reference
DDFA/sastvd/scripts/abstract_dataflow_full.py:44-51 ``is_decl``), mine four
subkey feature sets by AST/ARGUMENT traversal (``get_dataflow_features``,
abstract_dataflow_full.py:54-201):

- ``datatype``: the declared/assigned variable's type, resolved by
  recursing through known operator argument positions;
- ``literal``: codes of LITERAL descendants;
- ``operator``: ``<operator>.X`` call names among descendants (minus
  ``indirection``);
- ``api``: non-operator CALL names among descendants.

Each node's features hash to a canonical JSON string (``to_hash``,
abstract_dataflow_full.py:285-295). The vocabulary is built from the TRAIN
split only (``abs_dataflow``, datasets.py:587-692): per-subkey values are
frequency-capped at ``limit_subkeys`` (rarer values become UNKNOWN), then
whole-node hashes are frequency-capped at ``limit_all``. Final node index
(dbize_absdf.py:35-43): 0 = not a definition, 1 = UNKNOWN hash, else
frequency rank + 1 — hence ``input_dim == limit_all + 2``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import re
from collections import Counter
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from deepdfa_tpu.core.config import ALL_SUBKEYS, FeatureSpec
from deepdfa_tpu.etl.cpg import CPG

logger = logging.getLogger(__name__)

# all_assignment_types (abstract_dataflow_full.py:24-42): assignments plus
# inc/dec — "local variable declarations are not considered definitions".
DECL_OPS = frozenset(
    "<operator>." + op
    for op in (
        "assignment", "assignmentPlus", "assignmentMinus", "assignmentDivision",
        "assignmentExponentiation", "assignmentModulo", "assignmentMultiplication",
        "assignmentOr", "assignmentAnd", "assignmentXor",
        "assignmentArithmeticShiftRight", "assignmentLogicalShiftRight",
        "assignmentShiftLeft",
        "preIncrement", "preDecrement", "postIncrement", "postDecrement",
    )
)

# Which ARGUMENT position holds the variable when recursing through an
# operator for datatype resolution (abstract_dataflow_full.py:72-84).
_NAME_IDX = {
    "<operator>.indirectIndexAccess": 1,
    "<operator>.indirectFieldAccess": 1,
    "<operator>.indirection": 1,
    "<operator>.fieldAccess": 1,
    "<operator>.postIncrement": 1,
    "<operator>.postDecrement": 1,
    "<operator>.preIncrement": 1,
    "<operator>.preDecrement": 1,
    "<operator>.addressOf": 1,
    "<operator>.cast": 2,
    "<operator>.addition": 1,
}

# Subkeys whose per-node feature is a single value rather than a set
# (datasets.py:551-556 ``single``).
SINGLE_SUBKEYS = frozenset({"datatype"})

UNKNOWN = "UNKNOWN"


def is_decl(node) -> bool:
    return node.label == "CALL" and node.name in DECL_OPS


def clean_datatype(dt: str) -> str:
    """Normalize a datatype string (abstract_dataflow_full.py:240-251):
    strip leading ``const``, collapse array extents to ``[]``, squeeze
    whitespace."""
    return re.sub(r"\s+", " ", re.sub(r"^const ", "", re.sub(r"\s*\[.*\]", "[]", dt))).strip()


def _args_by_order(cpg: CPG, arg_adj, nid: int) -> Dict[int, int]:
    return {cpg.nodes[s].order: s for s in arg_adj.get(nid, [])}


def _recurse_datatype(cpg: CPG, arg_adj, v: int) -> Tuple[int, str]:
    attr = cpg.nodes[v]
    if attr.label == "IDENTIFIER":
        return v, attr.type_full_name
    if attr.label == "CALL" and attr.name in _NAME_IDX:
        args = _args_by_order(cpg, arg_adj, v)
        arg = args[_NAME_IDX[attr.name]]
        arg_attr = cpg.nodes[arg]
        if arg_attr.label == "IDENTIFIER":
            return arg, arg_attr.type_full_name
        if arg_attr.label == "CALL":
            return _recurse_datatype(cpg, arg_adj, arg)
        raise NotImplementedError(f"datatype recursion hit {arg_attr.label} at {arg}")
    raise NotImplementedError(f"datatype recursion hit {attr.label}/{attr.name} at {v}")


def _raw_datatype(cpg: CPG, arg_adj, decl: int) -> Tuple[int, str]:
    attr = cpg.nodes[decl]
    if attr.label == "LOCAL":
        return decl, attr.type_full_name
    if attr.label == "CALL" and attr.name in (DECL_OPS | {"<operator>.cast"}):
        args = _args_by_order(cpg, arg_adj, decl)
        return _recurse_datatype(cpg, arg_adj, args[1])
    raise NotImplementedError(f"datatype of {attr.label}/{attr.name} at {decl}")


def extract_decl_features(
    cpg: CPG, raise_errors: bool = False
) -> Dict[int, List[Tuple[str, str]]]:
    """Per definition node: [(subkey, text), ...].

    Per-node failures are caught and logged, matching the reference's
    per-item fault tolerance (abstract_dataflow_full.py:160-166).
    """
    out: Dict[int, List[Tuple[str, str]]] = {}
    # Adjacency built once per CPG, not per definition node.
    arg_adj = cpg.out_adjacency(("ARGUMENT",))
    ast_adj = cpg.out_adjacency(("AST",))
    for nid, node in cpg.nodes.items():
        if not is_decl(node):
            continue
        fields: List[Tuple[str, str]] = []
        try:
            _, datatype = _raw_datatype(cpg, arg_adj, nid)
            fields.append(("datatype", clean_datatype(datatype)))
            # Descend the AST minus METHOD subtrees
            # (abstract_dataflow_full.py:137-146).
            for n in cpg.ast_descendants(nid, exclude_labels=("METHOD",), adj=ast_adj):
                attr = cpg.nodes[n]
                if attr.label == "LITERAL":
                    fields.append(("literal", attr.code))
                elif attr.label == "CALL":
                    m = re.match(r"<operator>\.(.*)", attr.name)
                    if m:
                        if m.group(1) != "indirection":
                            fields.append(("operator", m.group(1)))
                    else:
                        fields.append(("api", attr.name))
        except Exception:
            if raise_errors:
                raise
            logger.warning("decl feature extraction failed for node %d", nid, exc_info=True)
        out[nid] = fields
    return out


def node_subkey_values(
    fields: Sequence[Tuple[str, str]], subkey: str
) -> List[str]:
    """The node's raw value list for one subkey, sorted with duplicates kept
    — the stored-hash form (``to_hash``, abstract_dataflow_full.py:285-295).
    Consumers that mirror ``abs_dataflow``'s vocab/index stages dedupe this
    list themselves (datasets.py:624-625,670-672 apply ``sorted(set(...))``
    before counting and before the final all-hash)."""
    return sorted(text for key, text in fields if key == subkey)


@dataclasses.dataclass
class AbstractDataflowVocab:
    """Train-split frequency vocabulary for ONE subkey's feature
    (the concat_all model uses four of these, one per subkey)."""

    subkey: str
    limit_all: int
    limit_subkeys: int
    subkey_index: Dict[Optional[str], int]
    all_index: Dict[Optional[str], int]

    @classmethod
    def build(
        cls,
        features_by_graph: Mapping[int, Mapping[int, Sequence[Tuple[str, str]]]],
        train_graph_ids: Iterable[int],
        spec: FeatureSpec,
        subkey: Optional[str] = None,
    ) -> "AbstractDataflowVocab":
        subkey = subkey or spec.subkey
        train = [gid for gid in train_graph_ids if gid in features_by_graph]

        # Stage 1: per-subkey value vocabulary, frequency-capped.
        counts: Counter = Counter()
        for gid in train:
            for fields in features_by_graph[gid].values():
                values = node_subkey_values(fields, subkey)
                if subkey in SINGLE_SUBKEYS:
                    if values:
                        counts[values[0]] += 1
                else:
                    counts.update(sorted(set(values)))
        kept = [h for h, _ in counts.most_common(spec.limit_subkeys)]
        subkey_index: Dict[Optional[str], int] = {None: 0}
        for h in kept:
            subkey_index[h] = len(subkey_index)

        # Stage 2: whole-node hash vocabulary over UNKNOWN-substituted values.
        all_counts: Counter = Counter()
        for gid in train:
            for fields in features_by_graph[gid].values():
                if not fields:  # dropped by the reference's explode+dropna
                    continue
                all_counts[cls._all_hash(fields, subkey, subkey_index)] += 1
        kept_all = [h for h, _ in all_counts.most_common(spec.limit_all)]
        all_index: Dict[Optional[str], int] = {None: 0}
        for h in kept_all:
            all_index[h] = len(all_index)
        return cls(subkey, spec.limit_all, spec.limit_subkeys, subkey_index, all_index)

    @staticmethod
    def _all_hash(
        fields: Sequence[Tuple[str, str]],
        subkey: str,
        subkey_index: Mapping[Optional[str], int],
    ) -> str:
        values = node_subkey_values(fields, subkey)
        if subkey in SINGLE_SUBKEYS:
            values = values[:1] if values else []
        subst = [v if v in subkey_index else UNKNOWN for v in values]
        # sorted(set(...)) matches get_all_hash (datasets.py:670-672): the
        # final hash is over the deduplicated UNKNOWN-substituted values.
        return json.dumps({subkey: sorted(set(subst))})

    def index_for(self, fields: Optional[Sequence[Tuple[str, str]]]) -> int:
        """0 = not a definition; 1 = UNKNOWN hash; else rank+1
        (dbize_absdf.py:35-43). A definition whose extraction yielded no
        fields at all is indistinguishable from a non-definition (the
        reference's explode+dropna drops such nodes from the hash table)."""
        if not fields:
            return 0
        h = self._all_hash(fields, self.subkey, self.subkey_index)
        return self.all_index.get(h, self.all_index[None]) + 1


def node_feature_indices(
    cpg: CPG,
    features: Mapping[int, Sequence[Tuple[str, str]]],
    vocabs: Mapping[str, AbstractDataflowVocab],
) -> Dict[str, List[int]]:
    """Per-subkey index per node, ordered by sorted node id — the
    ``_ABS_DATAFLOW_*`` columns the model embeds (graphmogrifier.py:74-88)."""
    node_ids = sorted(cpg.nodes)
    return {
        subkey: [vocabs[subkey].index_for(features.get(n)) for n in node_ids]
        for subkey in vocabs
    }


def build_all_vocabs(
    features_by_graph: Mapping[int, Mapping[int, Sequence[Tuple[str, str]]]],
    train_graph_ids: Iterable[int],
    spec: FeatureSpec,
) -> Dict[str, AbstractDataflowVocab]:
    """One vocab per subkey (concat_all model: 4 embedding tables)."""
    from deepdfa_tpu.core.config import subkeys_for

    subkeys = subkeys_for(spec)
    return {
        sk: AbstractDataflowVocab.build(features_by_graph, train_graph_ids, spec, sk)
        for sk in subkeys
    }

"""Reaching-definitions dataflow analysis over the Joern CFG.

Pure-Python worklist solver with the same gen/kill semantics as the
reference's verification oracle (DDFA/code_gnn/analysis/dataflow.py:103-181
``ReachingDefinitions``): a node *generates* a definition when its Joern
operator is an assignment or increment/decrement (the ``mod_ops`` table,
dataflow.py:60-84), the defined variable is the code of the first ARGUMENT
child by order, and a definition of ``v`` *kills* all other definitions of
``v``. The in-sets of the fixpoint are the "dataflow solution" used for the
``dataflow_solution_in/out`` label styles (base_module.py:83-95).

The C++ solver in ``native/`` must produce bit-identical in/out sets; this
module is its correctness oracle.
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from deepdfa_tpu.etl.cpg import CPG

_ASSIGNMENT_SUFFIXES = (
    "assignment",
    "assignmentAnd",
    "assignmentArithmeticShiftRight",
    "assignmentDivision",
    "assignmentExponentiation",
    "assignmentLogicalShiftRight",
    "assignmentMinus",
    "assignmentModulo",
    "assignmentMultiplication",
    "assignmentOr",
    "assignmentPlus",
    "assignmentShiftLeft",
    "assignmentXor",
)
_INC_DEC_SUFFIXES = (
    "incBy",
    "postDecrement",
    "postIncrement",
    "preDecrement",
    "preIncrement",
)

# Joern emits both "<operator>.x" and (in some versions) "<operators>.x"
# (dataflow.py:81-84 handles both spellings).
ASSIGNMENT_OPS = frozenset(
    f"<operator{s}>.{op}" for s in ("", "s") for op in _ASSIGNMENT_SUFFIXES
)
MOD_OPS = ASSIGNMENT_OPS | frozenset(
    f"<operator{s}>.{op}" for s in ("", "s") for op in _INC_DEC_SUFFIXES
)


@dataclasses.dataclass(frozen=True)
class Definition:
    """A (variable, defining node) pair; identity is the node id, matching
    the reference's ``VariableDefinition.__hash__`` (dataflow.py:92-100)."""

    variable: str
    node: int


class ReachingDefinitions:
    """Worklist fixpoint over the CFG subgraph."""

    def __init__(self, cpg: CPG):
        self.cpg = cpg
        self._arg_adj = cpg.out_adjacency(("ARGUMENT",))
        self._cfg_succ = cpg.out_adjacency(("CFG",))
        self._cfg_pred = cpg.in_adjacency(("CFG",))
        self.gen: Dict[int, FrozenSet[Definition]] = {}
        self._assigned: Dict[int, Optional[str]] = {}
        for nid, node in cpg.nodes.items():
            var = self._compute_assigned_variable(nid)
            self._assigned[nid] = var
            self.gen[nid] = (
                frozenset({Definition(var, nid)}) if var is not None else frozenset()
            )

    def _compute_assigned_variable(self, nid: int) -> Optional[str]:
        """Code of the first ARGUMENT child by order (dataflow.py:124-134)."""
        if self.cpg.nodes[nid].name not in MOD_OPS:
            return None
        children = sorted(self._arg_adj.get(nid, []), key=lambda c: self.cpg.nodes[c].order)
        if not children:
            return None
        return self.cpg.nodes[children[0]].code

    def assigned_variable(self, nid: int) -> Optional[str]:
        """Cached per-node assigned variable (fixed once the CPG is built;
        the worklist revisits nodes many times)."""
        return self._assigned[nid]

    @property
    def domain(self) -> Set[Definition]:
        out: Set[Definition] = set()
        for g in self.gen.values():
            out |= g
        return out

    def _cfg_node_list(self) -> List[int]:
        # Only nodes incident to a CFG edge, matching the reference's
        # edge-subgraph worklist (dataflow.py:156 iterates self.cfg.nodes()
        # of an nx.edge_subgraph).
        return sorted(
            {n for n, succs in self._cfg_succ.items() if succs}
            | {n for n, preds in self._cfg_pred.items() if preds}
        )

    def solve(
        self, backend: str = "auto"
    ) -> Tuple[Dict[int, FrozenSet[Definition]], Dict[int, FrozenSet[Definition]]]:
        """Return (in_sets, out_sets) at the fixpoint.

        ``backend``: "native" (C++ bitset worklist, deepdfa_tpu/native),
        "python" (this module — the oracle), or "auto" (native when it
        builds, else python). Both produce identical sets: the fixpoint of
        this monotone system is unique.
        """
        if backend not in ("auto", "native", "python"):
            raise ValueError(f"unknown backend {backend!r}")
        if backend in ("auto", "native"):
            try:
                return self._solve_native()
            except RuntimeError:
                if backend == "native":
                    raise
        return self._solve_python()

    def _solve_native(self):
        import numpy as np

        from deepdfa_tpu import native

        cfg_nodes = self._cfg_node_list()
        idx = {n: i for i, n in enumerate(cfg_nodes)}
        var_ids: Dict[str, int] = {}
        gen_var = np.full(len(cfg_nodes), -1, np.int32)
        for n in cfg_nodes:
            var = self._assigned[n]
            if var is not None:
                gen_var[idx[n]] = var_ids.setdefault(var, len(var_ids))

        def csr(adj):
            indptr = np.zeros(len(cfg_nodes) + 1, np.int32)
            indices = []
            for i, n in enumerate(cfg_nodes):
                nbrs = [idx[m] for m in adj.get(n, []) if m in idx]
                indices.extend(nbrs)
                indptr[i + 1] = len(indices)
            return indptr, np.asarray(indices, np.int32)

        s_ptr, s_idx = csr(self._cfg_succ)
        p_ptr, p_idx = csr(self._cfg_pred)
        in_defs, out_defs = native.solve_reaching(
            len(cfg_nodes), s_ptr, s_idx, p_ptr, p_idx, gen_var
        )

        def to_sets(per_node):
            out: Dict[int, FrozenSet[Definition]] = {}
            for i, n in enumerate(cfg_nodes):
                out[n] = frozenset(
                    Definition(self._assigned[cfg_nodes[d]], cfg_nodes[d])
                    for d in per_node[i]
                )
            return out

        return to_sets(in_defs), to_sets(out_defs)

    def _solve_python(self):
        cfg_nodes = self._cfg_node_list()
        out_sets: Dict[int, FrozenSet[Definition]] = {n: frozenset() for n in cfg_nodes}
        in_sets: Dict[int, FrozenSet[Definition]] = {n: frozenset() for n in cfg_nodes}
        work = deque(cfg_nodes)
        queued = set(cfg_nodes)
        while work:
            n = work.popleft()
            queued.discard(n)
            in_n = frozenset().union(*(out_sets[p] for p in self._cfg_pred.get(n, [])))
            in_sets[n] = in_n
            var = self.assigned_variable(n)
            if var is None:
                out_n = self.gen[n] | in_n
            else:
                out_n = self.gen[n] | frozenset(
                    d for d in in_n if not (d.variable == var and d.node != n)
                )
            if out_n != out_sets[n]:
                out_sets[n] = out_n
                for s in self._cfg_succ.get(n, []):
                    if s not in queued:
                        work.append(s)
                        queued.add(s)
        return in_sets, out_sets

    def solution_bits(self) -> Tuple[Dict[int, List[int]], List[Definition]]:
        """Per-node membership vectors over the sorted definition domain —
        the ground-truth targets for dataflow-solution training
        (get_dataflow_output.sc analogue, computed natively)."""
        in_sets, _ = self.solve()
        domain = sorted(self.domain, key=lambda d: d.node)
        index = {d: i for i, d in enumerate(domain)}
        bits = {
            n: sorted(index[d] for d in s) for n, s in in_sets.items()
        }
        return bits, domain

    def solution_node_bits(self) -> Tuple[Dict[int, int], Dict[int, int]]:
        """(df_in, df_out): the scalar per-node training bits for the
        ``dataflow_solution_in/out`` label styles.

        The reference asserts the attached ``_DF_IN`` solution is one 0/1
        bit per node (main_cli.py:250-254) but ships no attach script; the
        bit here is defined as "some definition reaches this node's IN/OUT"
        — non-empty fixpoint set — which is per-node, 0/1, and requires the
        GNN to simulate gen/kill propagation through the CFG to predict.
        Non-CFG nodes get 0 (they are outside the flow graph).
        """
        in_sets, out_sets = self.solve()
        df_in = {n: int(bool(s)) for n, s in in_sets.items()}
        df_out = {n: int(bool(s)) for n, s in out_sets.items()}
        return df_in, df_out


def parse_dataflow_output(path) -> Tuple[Dict[int, list], Dict[int, list]]:
    """Parse Joern's ``<id>.c.dataflow.json`` into (in_map, out_map).

    Schema from the exporter (DDFA/storage/external/get_dataflow_output.sc:
    37-55): one entry per method, each with ``solution.in``/``solution.out``
    mapping node-id strings to lists of reaching-definition node ids.
    Consumed like the reference's ``get_dataflow_output``
    (DDFA/sastvd/helpers/datasets.py:780-796): per-method maps merge with a
    node-disjointness assert, keys to int.
    """
    import json

    with open(path) as f:
        doc = json.load(f)
    in_map: Dict[int, list] = {}
    out_map: Dict[int, list] = {}
    for _, data in doc.items():
        for src, dst in (("solution.in", in_map), ("solution.out", out_map)):
            part = data[src]
            overlap = set(dst) & {int(k) for k in part}
            assert not overlap, f"solution node sets overlap: {sorted(overlap)[:5]}"
            dst.update({int(k): v for k, v in part.items()})
    return in_map, out_map

"""CPU-side ETL: Joern CPG parsing, dataflow analysis, feature extraction.

This subsystem mirrors the reference's preprocessing pipeline
(DDFA/sastvd/ + DDFA/code_gnn/analysis/) but with typed containers instead
of ad-hoc pandas frames, and no accelerator involvement — everything here
runs on host CPUs and feeds the padded-batch graph substrate in
``deepdfa_tpu.graphs``.
"""

from deepdfa_tpu.etl.cpg import CPG, CPGNode, from_joern_json, reduce_graph
from deepdfa_tpu.etl.reaching import ReachingDefinitions
from deepdfa_tpu.etl.absdf import (
    AbstractDataflowVocab,
    extract_decl_features,
    node_feature_indices,
)

__all__ = [
    "CPG",
    "CPGNode",
    "from_joern_json",
    "reduce_graph",
    "ReachingDefinitions",
    "AbstractDataflowVocab",
    "extract_decl_features",
    "node_feature_indices",
]

"""Code property graph container and Joern-output parser.

The reference parses Joern's ``<id>.c.nodes.json`` / ``<id>.c.edges.json``
into pandas frames with a chain of in-place filters
(DDFA/sastvd/helpers/joern.py:182-319 ``get_node_edges``). Here the same
observable semantics land on a typed container:

- drop COMMENT and FILE nodes (joern.py:251-253);
- drop CONTAINS / SOURCE_FILE / DOMINATE / POST_DOMINATE edges
  (joern.py:255-259);
- keep only edges where at least one endpoint has a line number
  (joern.py:261-272);
- drop nodes with no remaining edges (joern.py:485-493 ``drop_lone_nodes``);
- de-duplicate (src, dst, etype) triples (joern.py:306).

Graph-type reduction (:func:`reduce_graph`) mirrors ``rdg``
(joern.py:419-441): e.g. "cfg" keeps CFG edges, "pdg" keeps
REACHING_DEF+CDG, "all" the DeepDFA training set union.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, Iterable, List, Mapping, Optional, Sequence, Tuple

from deepdfa_tpu.contracts.schema import (
    ContractError,
    validate_joern_edges,
    validate_joern_nodes,
)

DROPPED_NODE_LABELS = frozenset({"COMMENT", "FILE"})
DROPPED_EDGE_TYPES = frozenset(
    {"CONTAINS", "SOURCE_FILE", "DOMINATE", "POST_DOMINATE"}
)

# rdg() gtype -> kept edge types (joern.py:419-441).
GRAPH_REDUCTIONS: Dict[str, frozenset] = {
    "reftype": frozenset({"EVAL_TYPE", "REF"}),
    "ast": frozenset({"AST"}),
    "pdg": frozenset({"REACHING_DEF", "CDG"}),
    "cfgcdg": frozenset({"CFG", "CDG"}),
    "cfg": frozenset({"CFG"}),
    "all": frozenset({"REACHING_DEF", "CDG", "AST", "EVAL_TYPE", "REF"}),
    "dataflow": frozenset({"CFG", "AST"}),
}


@dataclasses.dataclass
class CPGNode:
    id: int
    label: str = ""  # Joern _label: METHOD, CALL, IDENTIFIER, LOCAL, ...
    name: str = ""
    code: str = ""
    line_number: int = -1
    order: int = 0
    type_full_name: str = ""
    control_structure_type: str = ""


@dataclasses.dataclass
class CPG:
    """Nodes + typed directed edges (src, dst, etype), with adjacency
    helpers. Node ids are Joern ids (not dense)."""

    nodes: Dict[int, CPGNode]
    edges: List[Tuple[int, int, str]]

    def out_adjacency(self, etypes: Iterable[str]) -> Dict[int, List[int]]:
        keep = frozenset(etypes)
        adj: Dict[int, List[int]] = {n: [] for n in self.nodes}
        for s, d, t in self.edges:
            if t in keep and s in adj and d in self.nodes:
                adj[s].append(d)
        return adj

    def in_adjacency(self, etypes: Iterable[str]) -> Dict[int, List[int]]:
        keep = frozenset(etypes)
        adj: Dict[int, List[int]] = {n: [] for n in self.nodes}
        for s, d, t in self.edges:
            if t in keep and d in adj and s in self.nodes:
                adj[d].append(s)
        return adj

    def subgraph_edges(self, gtype: str) -> List[Tuple[int, int, str]]:
        keep = GRAPH_REDUCTIONS[gtype]
        return [(s, d, t) for s, d, t in self.edges if t in keep]

    def ast_descendants(
        self,
        root: int,
        exclude_labels: Sequence[str] = (),
        adj: Optional[Dict[int, List[int]]] = None,
    ) -> List[int]:
        """DFS over AST edges from ``root`` (excluding it), skipping subtrees
        rooted at excluded labels (the reference removes METHOD nodes from
        its AST copy before descending, abstract_dataflow_full.py:137-146).
        Pass a prebuilt ``out_adjacency(("AST",))`` when calling per-node in
        a loop."""
        if adj is None:
            adj = self.out_adjacency(("AST",))
        excluded = frozenset(exclude_labels)
        seen, order, stack = set(), [], [root]
        while stack:
            cur = stack.pop()
            for child in adj.get(cur, []):
                if child in seen or self.nodes[child].label in excluded:
                    continue
                seen.add(child)
                order.append(child)
                stack.append(child)
        return order


def _to_int(value, default: int = -1) -> int:
    try:
        return int(value)
    except (TypeError, ValueError):
        return default


def from_joern_json(
    nodes_json: Sequence[Mapping],
    edges_json: Sequence[Sequence],
    validate: bool = True,
) -> CPG:
    """Build a filtered :class:`CPG` from Joern export payloads.

    ``nodes_json``: list of node property dicts; ``edges_json``: list of
    ``[inNode, outNode, etype, dataflow]`` rows — Joern/TinkerPop naming,
    where the edge runs **outNode -> inNode** (get_func_graph.sc:53 exports
    ``List(node.inNode.id, node.outNode.id, node.label, ...)``; the
    reference builds its analysis graph as (outnode, innode) pairs,
    dataflow.py:242-244). Edges here are stored in semantic
    source->target direction: ``src = row[1]``, ``dst = row[0]``.

    Both payloads pass the Joern ingestion contract first
    (``contracts.validate_joern_nodes/edges``): mis-typed records and
    duplicated node ids raise :class:`~deepdfa_tpu.contracts.ContractError`
    here, at the boundary, instead of surfacing as a KeyError three stages
    later (or not at all). ``validate=False`` skips the pass for callers
    that already ran it with a better item id (:func:`load_joern_export`)
    — one validation per export, not two.
    """
    if validate:
        nodes_json = validate_joern_nodes(nodes_json)
        edges_json = validate_joern_edges(edges_json)
    nodes: Dict[int, CPGNode] = {}
    for rec in nodes_json:
        label = str(rec.get("_label", ""))
        if label in DROPPED_NODE_LABELS:
            continue
        nid = int(rec["id"])
        nodes[nid] = CPGNode(
            id=nid,
            label=label,
            name=str(rec.get("name", "") or ""),
            code="" if rec.get("code") in (None, "<empty>") else str(rec["code"]),
            line_number=_to_int(rec.get("lineNumber")),
            order=_to_int(rec.get("order"), 0),
            type_full_name=str(rec.get("typeFullName", "") or ""),
            control_structure_type=str(rec.get("controlStructureType", "") or ""),
        )
    # Code falls back to the node name when empty (joern.py:242-244).
    for n in nodes.values():
        if not n.code:
            n.code = n.name

    if not any(n.label == "METHOD" for n in nodes.values()):
        # ContractError subclasses ValueError: pre-contract callers that
        # caught ValueError here keep working, new callers get the reason.
        raise ContractError("no_method_node", "empty graph: no METHOD node",
                            boundary="joern")

    edges: List[Tuple[int, int, str]] = []
    seen = set()
    for row in edges_json:
        src, dst, etype = int(row[1]), int(row[0]), str(row[2])
        if etype in DROPPED_EDGE_TYPES:
            continue
        if src not in nodes or dst not in nodes:
            continue
        # Keep only edges touching at least one line-numbered node
        # (joern.py:261-272).
        if nodes[src].line_number < 0 and nodes[dst].line_number < 0:
            continue
        key = (src, dst, etype)
        if key in seen:
            continue
        seen.add(key)
        edges.append(key)

    connected = {s for s, _, _ in edges} | {d for _, d, _ in edges}
    nodes = {i: n for i, n in nodes.items() if i in connected}
    return CPG(nodes=nodes, edges=edges)


def load_joern_export(stem: str | Path) -> CPG:
    """Read ``<stem>.nodes.json`` + ``<stem>.edges.json`` from disk,
    through the Joern ingestion contract (a truncated or mis-typed export
    raises :class:`~deepdfa_tpu.contracts.ContractError`/JSONDecodeError at
    this boundary — the export driver's per-item fault handling quarantines
    it instead of aborting the corpus)."""
    stem = str(stem)
    with open(stem + ".nodes.json") as f:
        nodes_json = validate_joern_nodes(json.load(f), item_id=stem)
    with open(stem + ".edges.json") as f:
        edges_json = validate_joern_edges(json.load(f), item_id=stem)
    return from_joern_json(nodes_json, edges_json, validate=False)


def reduce_graph(cpg: CPG, gtype: str) -> CPG:
    """rdg() semantics: same nodes, edges restricted by graph type."""
    if gtype not in GRAPH_REDUCTIONS:
        raise ValueError(f"unknown graph type {gtype!r}; want one of {sorted(GRAPH_REDUCTIONS)}")
    return CPG(nodes=dict(cpg.nodes), edges=cpg.subgraph_edges(gtype))

"""Block-sparse tile SpMM: the Pallas TPU kernel for GNN message aggregation.

The hot op of the reference's training step is DGL ``GatedGraphConv``'s
CUDA SpMM (reference: DDFA/code_gnn/models/flow_gnn/ggnn.py:57-60,95 — the
per-step scatter-add of transformed sender states into receivers). A CUDA
scatter translates badly to TPU: the MXU wants dense tiles, not per-row
atomics. But the batch layout gives us structure for free — every graph's
nodes are contiguous (graphs/batch.py), and CFG edges never cross graphs, so
the batched adjacency is block-sparse with nonzero tiles hugging the
diagonal.

This module therefore represents aggregation as ``agg = A @ msg`` where A is
stored as a sorted list of dense ``tile × tile`` blocks, and computes it with
one MXU matmul per nonzero tile:

- grid = one step per nonzero tile, sequential on a TPU core;
- scalar-prefetched (row, col) tile coordinates drive the BlockSpec index
  maps, DMA-ing the right ``msg`` row-tile in and the right ``out`` row-tile
  out;
- tiles are sorted by row, so the output block stays resident in VMEM across
  a row's tiles and is zeroed exactly when the row changes (the classic
  k-loop accumulation pattern).

Dense-tile FLOPs exceed the "true" edge-gather work, but they run on the MXU
at full tilt instead of serializing through irregular memory traffic; for
CFG-sized graphs (~40-200 nodes) the tile occupancy is high.

The backward pass is the same kernel over host-pretransposed tiles
(d msg = Aᵀ @ d out), wired in with ``jax.custom_vjp``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# MXU-native tile edge; batching callers align node budgets with
# align_to_tile() so the single source of truth lives here.
DEFAULT_TILE = 128


def align_to_tile(n: int, tile: int = DEFAULT_TILE) -> int:
    return -(-n // tile) * tile


@struct.dataclass
class TileAdjacency:
    """Sorted block-sparse adjacency plus its transpose (for the VJP).

    vals    : f32[n_nz, tile, tile] — dense tile values, sorted by ``rows``;
              ``vals[k][i, j]`` = multiplicity of edge (sender s, receiver r)
              with r = rows[k]*tile + i, s = cols[k]*tile + j.
    rows    : i32[n_nz] non-decreasing receiver tile indices; every row tile
              in [0, n_row_tiles) appears at least once (filler zero tiles
              keep uncovered output rows defined).
    cols    : i32[n_nz] sender tile indices.
    t_vals/t_rows/t_cols : the transposed adjacency in the same layout.
    """

    vals: jnp.ndarray
    rows: jnp.ndarray
    cols: jnp.ndarray
    t_vals: jnp.ndarray
    t_rows: jnp.ndarray
    t_cols: jnp.ndarray
    tile: int = struct.field(pytree_node=False, default=DEFAULT_TILE)
    n_row_tiles: int = struct.field(pytree_node=False, default=0)


def _dense_tiles(rows, cols, data, tile, n_tiles, pad_nz):
    """Group COO entries into sorted dense tiles with full row coverage."""
    tr, tc = rows // tile, cols // tile
    order = np.lexsort((tc, tr))
    tr, tc = tr[order], tc[order]
    rows, cols, data = rows[order], cols[order], data[order]

    # Unique (row_tile, col_tile) pairs and the span of edges in each.
    key = tr.astype(np.int64) * n_tiles + tc
    uniq, start = np.unique(key, return_index=True)
    end = np.append(start[1:], len(key))

    out_rows, out_cols, out_vals = [], [], []
    covered = np.zeros(n_tiles, bool)
    for u, s, e in zip(uniq, start, end):
        r, c = int(u // n_tiles), int(u % n_tiles)
        block = np.zeros((tile, tile), np.float32)
        np.add.at(block, (rows[s:e] - r * tile, cols[s:e] - c * tile), data[s:e])
        out_rows.append(r)
        out_cols.append(c)
        out_vals.append(block)
        covered[r] = True

    # Filler zero tiles so every output row tile is visited (and zeroed).
    for r in np.nonzero(~covered)[0]:
        out_rows.append(int(r))
        out_cols.append(int(r))
        out_vals.append(np.zeros((tile, tile), np.float32))

    order = np.argsort(np.asarray(out_rows), kind="stable")
    out_rows = np.asarray(out_rows, np.int32)[order]
    out_cols = np.asarray(out_cols, np.int32)[order]
    out_vals = np.stack([out_vals[i] for i in order])

    # Pad the tile list to a fixed budget with zero tiles on the last row
    # (keeps `rows` sorted; adding zeros is inert).
    n_nz = len(out_rows)
    if pad_nz < n_nz:
        raise ValueError(f"tile budget {pad_nz} < {n_nz} nonzero tiles")
    pad = pad_nz - n_nz
    if pad:
        out_rows = np.concatenate([out_rows, np.full(pad, n_tiles - 1, np.int32)])
        out_cols = np.concatenate([out_cols, np.full(pad, n_tiles - 1, np.int32)])
        out_vals = np.concatenate(
            [out_vals, np.zeros((pad, tile, tile), np.float32)]
        )
    return out_vals, out_rows, out_cols


def _round_up_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def tile_nz_budget(
    senders: np.ndarray,
    receivers: np.ndarray,
    max_nodes: int,
    tile: int = DEFAULT_TILE,
) -> int:
    """The pow2 tile budget :func:`build_tile_adjacency` picks for these
    (real) edges — without materializing any dense tiles.

    Multi-controller input pipelines use this to agree on remote shards'
    stacked shapes from their edge lists alone: each host builds dense
    tiles only for its own shards but must pad them to the global maximum
    budget.
    """
    n_tiles = max_nodes // tile
    s = np.asarray(senders, np.int64)
    r = np.asarray(receivers, np.int64)
    nz = len(np.unique((r // tile) * n_tiles + (s // tile)))
    nz = max(nz, n_tiles)
    return _round_up_pow2(nz + n_tiles)


def tile_vals_dtype(senders: np.ndarray, receivers: np.ndarray) -> jnp.dtype:
    """The dtype :func:`build_tile_adjacency` picks for these (real) edges,
    from the edge lists alone.

    Tile values are edge multiplicities; they stay bf16-resident when every
    multiplicity is exactly representable (≤ 256 — the same rule as the
    builder's own ``tile_dtype`` check over the dense tiles, which see those
    multiplicities as their maxima). A and Aᵀ share multiplicities, so one
    check covers both. Multi-controller hosts use this to agree on remote
    shards' leaf dtypes without materializing them.
    """
    s = np.asarray(senders, np.int64)
    r = np.asarray(receivers, np.int64)
    if len(s) == 0:
        return jnp.bfloat16
    key = r * (int(s.max()) + 1) + s
    _, counts = np.unique(key, return_counts=True)
    return jnp.bfloat16 if counts.max() <= 256 else jnp.float32


def combine_tile_stats(stats) -> "tuple[int, jnp.dtype]":
    """Fold per-shard ``(pad_nz, vals_dtype)`` stats into the globally-agreed
    stack budget and dtype: max budget, f32 if ANY shard needs it (upcasts
    only — never a lossy bf16 force). The one reduction both multi-controller
    input pipelines (train/loop.py, train/text_loop.py) apply."""
    nz = max(n for n, _ in stats)
    dt = (
        jnp.float32
        if any(d == jnp.float32 for _, d in stats)
        else jnp.bfloat16
    )
    return nz, dt


def build_tile_adjacency(
    senders: np.ndarray,
    receivers: np.ndarray,
    edge_mask: np.ndarray,
    max_nodes: int,
    tile: int = DEFAULT_TILE,
    pad_nz: Optional[int] = None,
) -> TileAdjacency:
    """Host-side: build the sorted dense-tile adjacency for one GraphBatch.

    ``agg[r] = Σ_{(s,r)∈E} msg[s]`` becomes A[r, s] += 1 per edge. ``pad_nz``
    fixes the tile-count so batches of similar sparsity share one compiled
    kernel; default rounds to the next power of two.
    """
    if max_nodes % tile:
        raise ValueError(f"max_nodes {max_nodes} not a multiple of tile {tile}")
    n_tiles = max_nodes // tile
    s = np.asarray(senders)[np.asarray(edge_mask)].astype(np.int64)
    r = np.asarray(receivers)[np.asarray(edge_mask)].astype(np.int64)
    data = np.ones(len(s), np.float32)

    # Worst-case nonzero tile count (before filler/padding) to size budgets.
    if pad_nz is None:
        pad_nz = tile_nz_budget(s, r, max_nodes, tile)

    vals, rows, cols = _dense_tiles(r, s, data, tile, n_tiles, pad_nz)
    # Aᵀ[s, r] = A[r, s]: swapping the (row, col) roles of each edge when
    # building tiles yields the transposed adjacency directly.
    t_vals, t_rows, t_cols = _dense_tiles(s, r, data, tile, n_tiles, pad_nz)
    # Tiles stay bf16-resident when exact (halves the adjacency's HBM
    # traffic, ~4-5% kernel speedup in both model dtypes); the rule lives in
    # tile_vals_dtype so multi-controller hosts predicting remote shards'
    # dtypes share the builder's source of truth.
    dt = tile_vals_dtype(s, r)

    return TileAdjacency(
        vals=jnp.asarray(vals, dt),
        rows=jnp.asarray(rows),
        cols=jnp.asarray(cols),
        t_vals=jnp.asarray(t_vals, dt),
        t_rows=jnp.asarray(t_rows),
        t_cols=jnp.asarray(t_cols),
        tile=tile,
        n_row_tiles=n_tiles,
    )


def cast_tiles(adj: TileAdjacency, dtype: jnp.dtype) -> TileAdjacency:
    return adj.replace(
        vals=adj.vals.astype(dtype), t_vals=adj.t_vals.astype(dtype)
    )


def pad_tiles(adj: TileAdjacency, pad_nz: int) -> TileAdjacency:
    """Pad the tile lists to a larger budget with inert zero tiles.

    Zero tiles appended on the last row keep ``rows`` sorted and add nothing
    to the product — the same trick ``_dense_tiles`` uses for its own pad.
    """
    n_nz = int(adj.vals.shape[0])
    if pad_nz == n_nz:
        return adj
    if pad_nz < n_nz:
        raise ValueError(f"pad budget {pad_nz} < {n_nz} existing tiles")
    pad = pad_nz - n_nz
    last = adj.n_row_tiles - 1

    def pv(v):
        return jnp.concatenate([v, jnp.zeros((pad,) + v.shape[1:], v.dtype)])

    def pi(ix):
        return jnp.concatenate([ix, jnp.full((pad,), last, ix.dtype)])

    return TileAdjacency(
        vals=pv(adj.vals), rows=pi(adj.rows), cols=pi(adj.cols),
        t_vals=pv(adj.t_vals), t_rows=pi(adj.t_rows), t_cols=pi(adj.t_cols),
        tile=adj.tile, n_row_tiles=adj.n_row_tiles,
    )


def stack_tile_adjacencies(
    adjs: "list[TileAdjacency]",
    pad_nz: Optional[int] = None,
    force_dtype: Optional[jnp.dtype] = None,
) -> TileAdjacency:
    """Stack per-shard adjacencies along a leading device axis.

    The result's array leaves are ``[D, n_nz, ...]`` with every shard padded
    to a common power-of-two tile budget, ready to shard over the mesh's
    data axis and consume with :func:`tile_spmm_sharded`. Valid because the
    batch alignment contract (parallel/mesh.py) guarantees no edge crosses a
    shard boundary: the global adjacency is block-diagonal over shards.

    ``pad_nz``: explicit common budget. Multi-controller callers pass the
    global maximum over ALL shards of the batch (every host packs the full
    shard-group deterministically, so the maximum is locally computable)
    — hosts stacking only their local slice must still agree on the padded
    shape or ``assemble_global_batch`` hands XLA conflicting leaves.

    ``force_dtype``: cast vals/t_vals before stacking. Multi-controller
    callers pass the globally-agreed dtype (f32 if ANY shard needs it,
    per :func:`tile_vals_dtype`) — per-shard bf16/f32 choices otherwise
    diverge across hosts the same way shapes would. Upcasts only; a bf16
    force on an f32 shard would lose exactness and is refused.
    """
    a0 = adjs[0]
    for a in adjs:
        if a.tile != a0.tile or a.n_row_tiles != a0.n_row_tiles:
            raise ValueError("shards must share tile size and row-tile count")
    nz_max = max(int(a.vals.shape[0]) for a in adjs)
    nz = _round_up_pow2(nz_max) if pad_nz is None else pad_nz
    if nz < nz_max:
        raise ValueError(f"pad_nz {nz} < largest shard tile count {nz_max}")
    adjs = [pad_tiles(a, nz) for a in adjs]
    if force_dtype is not None:
        if any(
            a.vals.dtype == jnp.float32 and force_dtype == jnp.bfloat16
            for a in adjs
        ):
            raise ValueError("refusing lossy f32 -> bf16 tile downcast")
        adjs = [cast_tiles(a, force_dtype) for a in adjs]

    def stack(field):
        return jnp.stack([getattr(a, field) for a in adjs])

    return TileAdjacency(
        vals=stack("vals"), rows=stack("rows"), cols=stack("cols"),
        t_vals=stack("t_vals"), t_rows=stack("t_rows"), t_cols=stack("t_cols"),
        tile=a0.tile, n_row_tiles=a0.n_row_tiles,
    )


def tile_spmm_sharded(
    adj: TileAdjacency, msg: jnp.ndarray, mesh, impl: str = "auto"
) -> jnp.ndarray:
    """``agg = blockdiag(A_d) @ msg`` on a data-sharded mesh.

    ``adj`` is a stacked adjacency (leaves ``[D, ...]``); ``msg`` is the
    node-flat message array whose leading axis is sharded over ``data``.
    Each device runs the tile kernel on its own shard's tile list — shard
    boundaries coincide with graph boundaries, so the product needs no
    cross-device collectives, and gradients flow through the per-shard
    custom VJP.
    """
    from jax.sharding import PartitionSpec as P

    from deepdfa_tpu.parallel.mesh import DATA_AXIS

    adj_spec = TileAdjacency(
        vals=P(DATA_AXIS), rows=P(DATA_AXIS), cols=P(DATA_AXIS),
        t_vals=P(DATA_AXIS), t_rows=P(DATA_AXIS), t_cols=P(DATA_AXIS),
        tile=adj.tile, n_row_tiles=adj.n_row_tiles,
    )

    def local(a: TileAdjacency, m: jnp.ndarray) -> jnp.ndarray:
        squeezed = jax.tree_util.tree_map(lambda x: x[0], a)
        return tile_spmm(squeezed, m, impl)

    from deepdfa_tpu.parallel.mesh import shard_map_compat

    return shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(adj_spec, P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )(adj, msg)


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------


def _spmm_kernel(rows_ref, cols_ref, vals_ref, msg_ref, out_ref):
    i = pl.program_id(0)

    first = jnp.where(i == 0, True, rows_ref[i] != rows_ref[jnp.maximum(i - 1, 0)])

    @pl.when(first)
    def _zero():
        out_ref[:] = jnp.zeros_like(out_ref)

    # The accumulator (out_ref) is always f32 — the MXU requires 32-bit
    # accumulation, and for f32 inputs HIGHEST precision is free here (the
    # kernel is HBM-bound; measured ~1.54ms vs ~1.46ms on v5e for the
    # 256-graph training shape) and keeps parity with the segment-sum path
    # bit-tight. bf16 inputs ride the MXU's native mixed-precision path
    # (bf16 × bf16 → f32).
    msg = msg_ref[:]
    vals = vals_ref[0]
    if vals.dtype == jnp.float32 and msg.dtype != jnp.float32:
        # Upcast-only rule (as in band_spmm): f32 vals carry an edge
        # multiplicity that is not bf16-exact — upcast msg, never downcast
        # vals.
        msg = msg.astype(jnp.float32)
    else:
        vals = vals.astype(msg.dtype)
    precision = (
        jax.lax.Precision.HIGHEST
        if msg.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )
    out_ref[:] += jnp.dot(
        vals,
        msg,
        preferred_element_type=jnp.float32,
        precision=precision,
    )


def _spmm_pallas(vals, rows, cols, msg, tile, n_row_tiles, interpret):
    n_nz = vals.shape[0]
    h = msg.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(n_nz,),
        in_specs=[
            pl.BlockSpec((1, tile, tile), lambda i, rows, cols: (i, 0, 0)),
            pl.BlockSpec((tile, h), lambda i, rows, cols: (cols[i], 0)),
        ],
        out_specs=pl.BlockSpec((tile, h), lambda i, rows, cols: (rows[i], 0)),
    )
    out = pl.pallas_call(
        _spmm_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_row_tiles * tile, h), jnp.float32),
        interpret=interpret,
    )(rows, cols, vals, msg)
    return out.astype(msg.dtype)


def _spmm_xla(vals, rows, cols, msg, tile, n_row_tiles):
    """Pure-XLA oracle/fallback: gather msg row-tiles, batched matmul,
    segment-sum by row tile."""
    msg_tiles = msg.reshape(n_row_tiles, tile, -1)[cols]
    # f32 accumulation regardless of input dtype, matching the Pallas
    # kernel's MXU accumulator so both impls agree bit-for-bit in bf16 too.
    # Upcast-only dtype rule, same as the kernel.
    if vals.dtype == jnp.float32 and msg.dtype != jnp.float32:
        msg_tiles = msg_tiles.astype(jnp.float32)
    else:
        vals = vals.astype(msg.dtype)
    prod = jnp.einsum(
        "krc,kch->krh", vals, msg_tiles,
        preferred_element_type=jnp.float32,
        precision=(
            jax.lax.Precision.HIGHEST
            if msg_tiles.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT
        ),
    )
    out = jax.ops.segment_sum(prod, rows, num_segments=n_row_tiles)
    return out.reshape(n_row_tiles * tile, -1).astype(msg.dtype)


def _use_pallas() -> bool:
    return jax.default_backend() == "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def tile_spmm(adj: TileAdjacency, msg: jnp.ndarray, impl: str = "auto") -> jnp.ndarray:
    """agg = A @ msg over the block-sparse tiles.

    impl: "pallas" | "xla" | "interpret" | "auto" (pallas on TPU, xla
    elsewhere). Differentiable in ``msg`` (adjacency is structural).
    """
    return _spmm_fwd(adj, msg, impl)[0]


def _dispatch(vals, rows, cols, msg, tile, n_row_tiles, impl):
    if impl == "auto":
        impl = "pallas" if _use_pallas() else "xla"
    if impl == "pallas":
        return _spmm_pallas(vals, rows, cols, msg, tile, n_row_tiles, False)
    if impl == "interpret":
        return _spmm_pallas(vals, rows, cols, msg, tile, n_row_tiles, True)
    if impl == "xla":
        return _spmm_xla(vals, rows, cols, msg, tile, n_row_tiles)
    raise ValueError(f"unknown impl {impl!r}")


def _spmm_fwd(adj, msg, impl):
    out = _dispatch(
        adj.vals, adj.rows, adj.cols, msg, adj.tile, adj.n_row_tiles, impl
    )
    return out, adj


def _spmm_bwd(impl, adj, g):
    # d msg = Aᵀ @ g, computed with the same kernel over the transposed tiles.
    dmsg = _dispatch(
        adj.t_vals, adj.t_rows, adj.t_cols, g, adj.tile, adj.n_row_tiles, impl
    )
    return jax.tree_util.tree_map(jnp.zeros_like, adj), dmsg


tile_spmm.defvjp(_spmm_fwd, _spmm_bwd)

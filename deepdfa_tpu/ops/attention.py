"""Memory-efficient attention primitives for long context.

The reference truncates every transformer input to 512 tokens
(LineVul/linevul/linevul_main.py:126-131, CodeT5/utils.py max_source_length)
because dense O(T^2) attention is all it has. Here long context is
first-class: a blockwise streaming-softmax attention (pure JAX ``lax.scan``,
O(T) memory in sequence length, differentiable) and Pallas TPU flash
kernels for BOTH passes — the standard forward with a saved logsumexp plus
dq and dk/dv backward kernels that rebuild probabilities from it (Dao et
al.'s algorithm), so training keeps no O(T^2) residuals either. All compute
exact softmax attention — not an approximation — via the online
max/denominator recurrence, so they are drop-in replacements for the dense
path at any length.

These per-device primitives are also the building block of ring attention
(deepdfa_tpu/parallel/ring.py): the streaming state ``(o, m, l)`` merges
associatively across KV chunks, so chunks may arrive from a ``lax.scan``
block loop or from ICI neighbors — the math is the same.

Layouts: q ``[B, Tq, H, D]``, k/v ``[B, Tk, H, D]``, kv_mask ``[B, Tk]``
(True = real token). Causal masking uses *global* positions ``q_offset +
i`` / ``kv_offset + j`` so sharded callers can pass their shard's offset.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


class AttnState(NamedTuple):
    """Streaming softmax accumulator; merges associatively across KV chunks.

    o: [B, Tq, H, D] un-normalized output accumulator (float32)
    m: [B, H, Tq]    running row max of scores (float32)
    l: [B, H, Tq]    running softmax denominator (float32)
    """

    o: jnp.ndarray
    m: jnp.ndarray
    l: jnp.ndarray


def init_state(batch: int, tq: int, heads: int, dim: int) -> AttnState:
    return AttnState(
        o=jnp.zeros((batch, tq, heads, dim), jnp.float32),
        m=jnp.full((batch, heads, tq), NEG_INF, jnp.float32),
        l=jnp.zeros((batch, heads, tq), jnp.float32),
    )


def update_state(
    state: AttnState,
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_mask: Optional[jnp.ndarray],
    causal: bool,
    q_offset,
    kv_offset,
) -> AttnState:
    """Fold one KV chunk into the streaming state. ``q`` must be pre-scaled
    by 1/sqrt(D). Offsets may be traced values (ring shards)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    tq, tk = q.shape[1], k.shape[1]
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    if causal:
        qpos = q_offset + jnp.arange(tq)
        kpos = kv_offset + jnp.arange(tk)
        s = jnp.where(qpos[None, None, :, None] >= kpos[None, None, None, :], s, NEG_INF)

    m_new = jnp.maximum(state.m, s.max(axis=-1))
    # Fully-masked rows keep m == NEG_INF; pin the shift to 0 there so the
    # exp stays finite (their l stays ~0 and the caller masks them anyway).
    shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - shift[..., None])
    if kv_mask is not None:
        p = jnp.where(kv_mask[:, None, None, :], p, 0.0)
    corr = jnp.exp(jnp.where(state.m <= NEG_INF / 2, NEG_INF, state.m) - shift)
    l = state.l * corr + p.sum(axis=-1)
    o = state.o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    )
    return AttnState(o=o, m=m_new, l=l)


def finalize_state(state: AttnState, dtype=None) -> jnp.ndarray:
    l = state.l.transpose(0, 2, 1)[..., None]
    out = state.o / jnp.maximum(l, 1e-30)
    return out.astype(dtype) if dtype is not None else out


def blockwise_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    kv_mask: Optional[jnp.ndarray] = None,
    causal: bool = False,
    q_offset=0,
    kv_offset=0,
    block_size: int = 512,
    state: Optional[AttnState] = None,
    return_state: bool = False,
):
    """Exact attention over KV chunks of ``block_size`` via ``lax.scan``:
    O(Tq·block) live memory instead of O(Tq·Tk). Pass ``state``/
    ``return_state`` to continue accumulation across calls (ring steps)."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    qs = q.astype(jnp.float32) / np.sqrt(d)
    if state is None:
        state = init_state(b, tq, h, d)

    block = min(block_size, tk)
    if tk % block:  # pad KV to a block multiple; padding is masked out
        pad = block - tk % block
        k = jnp.pad(k, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad), (0, 0), (0, 0)))
        base = kv_mask if kv_mask is not None else jnp.ones((b, tk), bool)
        kv_mask = jnp.pad(base, ((0, 0), (0, pad)))
        tk += pad
    nb = tk // block

    def chunk(arr, i):
        return jax.lax.dynamic_slice_in_dim(arr, i * block, block, axis=1)

    def body(st, i):
        mask_i = None if kv_mask is None else chunk(kv_mask, i)
        st = update_state(
            st, qs, chunk(k, i), chunk(v, i), mask_i, causal,
            q_offset, kv_offset + i * block,
        )
        return st, None

    state, _ = jax.lax.scan(body, state, jnp.arange(nb))
    if return_state:
        return state
    return finalize_state(state, dtype=q.dtype)


def dense_attention(
    q, k, v, kv_mask=None, causal=False, q_offset=0, kv_offset=0,
    return_weights: bool = False,
):
    """Reference O(T^2) attention (the semantics the reference's HF encoders
    use); also the correctness oracle for the blockwise/flash/ring paths."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
    s = s / np.sqrt(d)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    if causal:
        qpos = q_offset + jnp.arange(q.shape[1])
        kpos = kv_offset + jnp.arange(k.shape[1])
        s = jnp.where(qpos[None, None, :, None] >= kpos[None, None, None, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", w, v.astype(jnp.float32)).astype(q.dtype)
    return (out, w) if return_weights else out


# ---------------------------------------------------------------------------
# Pallas TPU flash-attention forward kernel.
# ---------------------------------------------------------------------------

def _bmm(a, b, contract):
    """Batched-over-heads matmul with f32 MXU accumulation — the one dot
    shape every flash kernel uses ([H, rows, cols] operands, batch dim 0)."""
    return jax.lax.dot_general(
        a, b, (((contract[0],), (contract[1],)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )


def _tile_scores(mask_ref, q_ref, k_ref, qi, ki, *, causal, block_q, block_k,
                 scale):
    """The score tile every flash kernel rebuilds: pre-scaled q, raw k,
    s = q·kᵀ with the padding and (optionally) causal masks at NEG_INF —
    batched over the block's heads ([H, Bq, D] x [H, Bk, D] -> [H, Bq, Bk]
    as ONE dot_general; at D=64 a head only half-fills the MXU lanes, so
    per-program work must be deep, and head-batching is what amortizes the
    ~4 us/program overhead). One implementation so forward and backward can
    never desynchronize."""
    q = q_ref[...].astype(jnp.float32) * scale           # [H, Bq, D]
    k = k_ref[...].astype(jnp.float32)                   # [H, Bk, D]
    s = _bmm(q, k, (2, 2))                               # [H, Bq, Bk]
    mask = mask_ref[0, 0] != 0                           # [Bk] padding mask
    s = jnp.where(mask[None, None, :], s, NEG_INF)
    if causal:
        qpos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        kpos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    return q, k, s, mask


def _tile_grads(s, q, v_ref, do_ref, lse_ref, delta_ref, dk_acc, dv_acc):
    """The shared dK/dV tile-gradient step (used by both the two-pass dk/dv
    kernel and the fused backward, so they can never desynchronize):
    rebuild P from the saved logsumexp, accumulate dV += Pᵀ·dO and
    dK += dSᵀ·(scale·Q), and hand back dS for the caller's dQ use. ``q``
    arrives pre-scaled, which IS the scale factor dK needs."""
    v = v_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[:, 0, :]
    delta = delta_ref[:, 0, :]
    p = jnp.exp(s - lse[..., None])                      # [H, Bq, Bk]
    dv_acc[...] = dv_acc[...] + _bmm(p, do, (1, 1))      # Pᵀ·dO [H, Bk, D]
    dp = _bmm(do, v, (2, 2))
    ds = p * (dp - delta[..., None])
    dk_acc[...] = dk_acc[...] + _bmm(ds, q, (1, 1))      # dSᵀ·Q [H, Bk, D]
    return ds


def _flash_kernel(mask_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                  acc, m_s, l_s, *, causal, block_q, block_k, scale):
    """Grid (B*H, nq, nk); TPU executes the grid sequentially with the last
    axis innermost, so (acc, m, l) scratch carries the streaming-softmax
    state across the nk steps of one (bh, qi) tile. Also emits the row
    logsumexp (the flash-attention residual the backward kernels rebuild
    normalized probabilities from)."""
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)

    _, _, s, mask = _tile_scores(
        mask_ref, q_ref, k_ref, pl.program_id(1), ki, causal=causal,
        block_q=block_q, block_k=block_k, scale=scale,
    )
    v = v_ref[...].astype(jnp.float32)                   # [H, Bk, D]

    m_prev = m_s[..., 0]                                 # [H, Bq]
    m_new = jnp.maximum(m_prev, s.max(axis=2))
    shift = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - shift[..., None])
    p = jnp.where(mask[None, None, :], p, 0.0)
    corr = jnp.exp(jnp.where(m_prev <= NEG_INF / 2, NEG_INF, m_prev) - shift)
    l_s[..., 0] = l_s[..., 0] * corr + p.sum(axis=2)
    m_s[..., 0] = m_new
    acc[...] = acc[...] * corr[..., None] + _bmm(p, v, (2, 1))

    @pl.when(ki == nk - 1)
    def _finalize():
        l = jnp.maximum(l_s[..., 0], 1e-30)
        o_ref[...] = (acc[...] / l[..., None]).astype(o_ref.dtype)
        # lse = shift + log(l): exp(s - lse) is the NORMALIZED probability.
        # Fully-masked rows land near log(1e-30) ≈ -69, so exp(NEG_INF -
        # lse) underflows to exactly 0 in the backward — no NaNs.
        lse_ref[:, 0, :] = shift + jnp.log(l)


def _flash_bwd_dq_kernel(mask_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref,
                         do_ref, dq_ref, dq_acc, *, causal, block_q, block_k,
                         scale):
    """dQ pass, grid (B*H, nq, nk): for one q tile, stream k tiles and
    accumulate dq = scale * Σ_j dS·K with dS = P∘(dP − Δ), P rebuilt from
    the saved logsumexp (standard flash backward; Dao et al. alg. 4)."""
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    _, k, s, _ = _tile_scores(
        mask_ref, q_ref, k_ref, pl.program_id(1), ki, causal=causal,
        block_q=block_q, block_k=block_k, scale=scale,
    )
    v = v_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)                 # [H, Bq, D]
    lse = lse_ref[:, 0, :]                               # [H, Bq]
    delta = delta_ref[:, 0, :]                           # [H, Bq]
    p = jnp.exp(s - lse[..., None])
    dp = _bmm(do, v, (2, 2))                             # [H, Bq, Bk]
    ds = p * (dp - delta[..., None])
    dq_acc[...] = dq_acc[...] + _bmm(ds, k, (2, 1))

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[...] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(mask_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref,
                          do_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, causal,
                          block_q, block_k, scale):
    """dK/dV pass, grid (B*H, nk, nq): for one k tile, stream q tiles and
    accumulate dV = Σ_i Pᵀ·dO and dK = Σ_i dSᵀ·(scale·Q) — q is loaded
    pre-scaled, which IS the scale factor dK needs (S = (scale·Q)·Kᵀ)."""
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q, _, s, _ = _tile_scores(
        mask_ref, q_ref, k_ref, qi, pl.program_id(1), causal=causal,
        block_q=block_q, block_k=block_k, scale=scale,
    )
    ds = _tile_grads(s, q, v_ref, do_ref, lse_ref, delta_ref, dk_acc, dv_acc)

    @pl.when(qi == nq - 1)
    def _finalize():
        # No extra scale: dk_acc already used the pre-scaled q.
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_fused_kernel(mask_ref, lse_ref, delta_ref, q_ref, k_ref, v_ref,
                            do_ref, dq_ref, dk_ref, dv_ref,
                            dq_acc, dk_acc, dv_acc, *, causal, block_q,
                            block_k, scale):
    """Single-pass backward, grid (B*H, nk, qi(inner)): the dK/dV streaming
    pattern, with dQ accumulated across the WHOLE (ki, qi) sweep in a
    full-sequence-length VMEM scratch and written once per (batch, head).
    Every (p, dp, ds) tile is computed ONCE instead of twice (the separate
    dq pass reloads q/k/v/do and rebuilds the same scores), which halves
    the backward's loads and per-program overhead — used whenever the
    [Tq, D] f32 accumulator fits VMEM (dispatch guard in _flash_backward);
    longer sequences take the two-pass kernels."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nk = pl.num_programs(1)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init_kv():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when((ki == 0) & (qi == 0))
    def _init_q():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q, k, s, _ = _tile_scores(
        mask_ref, q_ref, k_ref, qi, ki, causal=causal,
        block_q=block_q, block_k=block_k, scale=scale,
    )
    ds = _tile_grads(s, q, v_ref, do_ref, lse_ref, delta_ref, dk_acc, dv_acc)
    rows = pl.ds(qi * block_q, block_q)
    dq_acc[:, rows] = dq_acc[:, rows] + _bmm(ds, k, (2, 1))

    @pl.when(qi == nq - 1)
    def _finalize_kv():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)

    @pl.when((ki == nk - 1) & (qi == nq - 1))
    def _finalize_q():
        dq_ref[...] = (dq_acc[...] * scale).astype(dq_ref.dtype)


try:  # Pallas import is deferred-safe: CPU-only environments still work.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _HAVE_PALLAS = False


# Ceiling for the fused backward's [Tq, D] f32 dq accumulator (VMEM scratch);
# longer sequences take the two-pass dq + dk/dv kernels. Module-level so
# tests can force the two-pass path at small shapes.
_FUSED_BWD_MAX_BYTES = 4 * 1024 * 1024


def _flash_blocks(q, k, block_q, block_k):
    tq, tk = q.shape[1], k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if tq % block_q or tk % block_k:
        raise ValueError(
            f"flash attention needs Tq%block_q==0 and Tk%block_k==0 "
            f"(got {tq}%{block_q}, {tk}%{block_k}); pad or use blockwise"
        )
    return block_q, block_k


def _bh(x):
    """[B, T, H, D] -> [B*H, T, D] so one grid row is one (batch, head)."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unbh(x, b, h):
    bh_, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _mask_3d(kv_mask, b, tk):
    # [B, 1, Tk]: TPU block shapes must tile the last two dims, and a
    # singleton second-to-last dim satisfies the "equal to the array dim"
    # escape hatch that a [B, Tk] layout (block (1, Bk) over B>1) does not.
    if kv_mask is None:
        kv_mask = jnp.ones((b, tk), jnp.int32)
    return kv_mask.astype(jnp.int32)[:, None, :]


def _pick_block_h(h: int, block_q: int, block_k: int, tq: int, d: int,
                  with_dq_scratch: bool = False) -> int:
    """Heads per program: the largest divisor of ``h`` keeping the VMEM
    working set within a conservative ~10 MB of the 16 MB scoped limit.
    The dominant live buffers are the [H, Bq, Bk] f32 score/probability
    intermediates (several alive at once in the backward — measured 26.5 M
    at block_h 6, bq=bk=512, which the compiler rejects), not the [*, D]
    tiles. The kernels batch heads with 3-D dot_generals (see
    _tile_scores). At the flagship shapes the winning config is the
    LARGEST q tile with block_h 1 (whole-step A/B: bq512/bh1 225.5 ex/s vs
    bq256/bh2 215.4) — big score tiles already amortize the per-program
    overhead, so the head axis stays a knob for shapes whose score tiles
    must be small."""
    per_head = (
        4 * block_q * block_k * 4            # score-sized f32 intermediates
        + (2 * block_q + 2 * block_k) * d * 8  # tiles + accumulators
    )
    if with_dq_scratch:
        per_head += tq * d * 4               # fused-backward dq accumulator
    budget = 10 * 1024 * 1024
    best = 1
    for cand in range(1, h + 1):
        if h % cand == 0 and cand * per_head <= budget:
            best = cand
    return best


# (config key) -> bool: did Mosaic accept a block_h > 1 program for this
# shape? _pick_block_h's VMEM model is a hand-fit heuristic; rather than
# hard-failing the training step when it undercounts for an untested shape,
# a one-time batch-1 probe compile confirms each multi-head config and
# degrades to the next smaller head divisor (block_h 1 always compiles).
_BLOCK_H_OK: Dict[tuple, bool] = {}


def _confirmed_block_h(cand: int, h: int, key: tuple, probe) -> int:
    """Largest head divisor <= ``cand`` whose probe compile succeeds.
    Probing only happens on real TPU backends — interpret-mode/CPU runs
    have no Mosaic VMEM limit to trip."""
    from deepdfa_tpu.core.backend import tpu_backend

    if cand <= 1 or not tpu_backend():
        return max(cand, 1)
    while cand > 1:
        if h % cand == 0:
            ok = _BLOCK_H_OK.get(key + (cand,))
            if ok is None:
                try:
                    probe(cand)
                    ok = True
                except Exception:
                    ok = False
                _BLOCK_H_OK[key + (cand,)] = ok
            if ok:
                return cand
        cand -= 1
    return 1


def _flash_forward(q, k, v, kv_mask, causal, block_q, block_k, interpret,
                   block_h=None):
    b, tq, h, d = q.shape
    tk = k.shape[1]
    block_q, block_k = _flash_blocks(q, k, block_q, block_k)
    if block_h is None:
        cand = _pick_block_h(h, block_q, block_k, tq, d)
        block_h = _confirmed_block_h(
            cand, h,
            ("fwd", h, block_q, block_k, tq, tk, d, str(q.dtype), causal),
            lambda bh: jax.jit(
                lambda q1, k1, v1: _flash_forward(
                    q1, k1, v1, None, causal, block_q, block_k, interpret,
                    block_h=bh,
                )
            ).lower(
                jax.ShapeDtypeStruct((1, tq, h, d), q.dtype),
                jax.ShapeDtypeStruct((1, tk, h, d), k.dtype),
                jax.ShapeDtypeStruct((1, tk, h, d), v.dtype),
            ).compile(),
        )
    hb = h // block_h  # head-blocks per batch; block_h | h by construction
    mask3 = _mask_3d(kv_mask, b, tk)

    grid = (b * hb, tq // block_q, tk // block_k)
    kernel = functools.partial(
        _flash_kernel, causal=causal, block_q=block_q, block_k=block_k,
        scale=1.0 / np.sqrt(d),
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_k), lambda g, qi, ki: (g // hb, 0, ki)),
            pl.BlockSpec((block_h, block_q, d), lambda g, qi, ki: (g, qi, 0)),
            pl.BlockSpec((block_h, block_k, d), lambda g, qi, ki: (g, ki, 0)),
            pl.BlockSpec((block_h, block_k, d), lambda g, qi, ki: (g, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_h, block_q, d), lambda g, qi, ki: (g, qi, 0)),
            pl.BlockSpec((block_h, 1, block_q), lambda g, qi, ki: (g, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_h, block_q, d), jnp.float32),
            pltpu.VMEM((block_h, block_q, 1), jnp.float32),
            pltpu.VMEM((block_h, block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(mask3, _bh(q), _bh(k), _bh(v))
    return _unbh(out, b, h), lse


def _flash_backward(q, k, v, kv_mask, out, lse, g, causal, block_q, block_k,
                    interpret, block_h=None):
    """Pallas dq + dk/dv passes (the standard flash backward): rebuild the
    normalized probabilities from the saved logsumexp, Δ = rowsum(dO∘O),
    dS = P∘(dP − Δ). O(T) memory like the forward — no quadratic residuals,
    which is what lets 4096-token training fit and batch 64 at 512."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    block_q, block_k = _flash_blocks(q, k, block_q, block_k)
    mask3 = _mask_3d(kv_mask, b, tk)
    scale = 1.0 / np.sqrt(d)

    if block_h is None:
        block_h = _pick_block_h(h, block_q, block_k, tq, d,
                                with_dq_scratch=True)
        # Prefer fusing over a wider head batch: a smaller block_h whose
        # [block_h, Tq, D] dq accumulator passes the fused guard beats a
        # wider two-pass grid (the fused kernel halves the backward's
        # loads).
        fusable = [
            c for c in range(1, block_h + 1)
            if h % c == 0 and c * tq * d * 4 <= _FUSED_BWD_MAX_BYTES
        ]
        if fusable:
            block_h = max(fusable)
        block_h = _confirmed_block_h(
            block_h, h,
            ("bwd", h, block_q, block_k, tq, tk, d, str(q.dtype), causal),
            lambda bh: jax.jit(
                lambda q1, k1, v1, o1, l1, g1: _flash_backward(
                    q1, k1, v1, None, o1, l1, g1, causal, block_q, block_k,
                    interpret, block_h=bh,
                )
            ).lower(
                jax.ShapeDtypeStruct((1, tq, h, d), q.dtype),
                jax.ShapeDtypeStruct((1, tk, h, d), k.dtype),
                jax.ShapeDtypeStruct((1, tk, h, d), v.dtype),
                jax.ShapeDtypeStruct((1, tq, h, d), out.dtype),
                jax.ShapeDtypeStruct((h, 1, tq), jnp.float32),
                jax.ShapeDtypeStruct((1, tq, h, d), g.dtype),
            ).compile(),
        )
    hb = h // block_h

    qb, kb, vb = _bh(q), _bh(k), _bh(v)
    dob = _bh(g)
    # Δ_i = Σ_d dO_id · O_id, [B*H, 1, Tq] like the lse layout.
    delta = jnp.einsum(
        "xtd,xtd->xt", dob.astype(jnp.float32), _bh(out).astype(jnp.float32)
    )[:, None, :]

    # Single-pass backward whenever the (possibly shrunk) head batch's
    # full-length dq accumulator fits VMEM: every score tile is computed
    # once instead of twice.
    if block_h * tq * d * 4 <= _FUSED_BWD_MAX_BYTES:
        mask_f = pl.BlockSpec((1, 1, block_k), lambda g, ki, qi: (g // hb, 0, ki))
        row_qf = pl.BlockSpec((block_h, 1, block_q), lambda g, ki, qi: (g, 0, qi))
        qtf = pl.BlockSpec((block_h, block_q, d), lambda g, ki, qi: (g, qi, 0))
        ktf = pl.BlockSpec((block_h, block_k, d), lambda g, ki, qi: (g, ki, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_fused_kernel, causal=causal,
                              block_q=block_q, block_k=block_k, scale=scale),
            grid=(b * hb, tk // block_k, tq // block_q),
            in_specs=[mask_f, row_qf, row_qf, qtf, ktf, ktf, qtf],
            out_specs=[
                pl.BlockSpec((block_h, tq, d), lambda g, ki, qi: (g, 0, 0)),
                ktf,
                ktf,
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
                jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
                jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_h, tq, d), jnp.float32),
                pltpu.VMEM((block_h, block_k, d), jnp.float32),
                pltpu.VMEM((block_h, block_k, d), jnp.float32),
            ],
            interpret=interpret,
        )(mask3, lse, delta, qb, kb, vb, dob)
        return _unbh(dq, b, h), _unbh(dk, b, h), _unbh(dv, b, h)

    mask_spec = pl.BlockSpec((1, 1, block_k), lambda g, qi, ki: (g // hb, 0, ki))
    row_q = pl.BlockSpec((block_h, 1, block_q), lambda g, qi, ki: (g, 0, qi))
    qtile = pl.BlockSpec((block_h, block_q, d), lambda g, qi, ki: (g, qi, 0))
    ktile = pl.BlockSpec((block_h, block_k, d), lambda g, qi, ki: (g, ki, 0))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, scale=scale),
        grid=(b * hb, tq // block_q, tk // block_k),
        in_specs=[mask_spec, row_q, row_q, qtile, ktile, ktile, qtile],
        out_specs=qtile,
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_h, block_q, d), jnp.float32)],
        interpret=interpret,
    )(mask3, lse, delta, qb, kb, vb, dob)

    # dK/dV grid puts the k tile on the middle axis: (g, ki, qi(inner)).
    mask_k = pl.BlockSpec((1, 1, block_k), lambda g, ki, qi: (g // hb, 0, ki))
    row_q2 = pl.BlockSpec((block_h, 1, block_q), lambda g, ki, qi: (g, 0, qi))
    qtile2 = pl.BlockSpec((block_h, block_q, d), lambda g, ki, qi: (g, qi, 0))
    ktile2 = pl.BlockSpec((block_h, block_k, d), lambda g, ki, qi: (g, ki, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, scale=scale),
        grid=(b * hb, tk // block_k, tq // block_q),
        in_specs=[mask_k, row_q2, row_q2, qtile2, ktile2, ktile2, qtile2],
        out_specs=[ktile2, ktile2],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_h, block_k, d), jnp.float32),
            pltpu.VMEM((block_h, block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(mask3, lse, delta, qb, kb, vb, dob)
    return _unbh(dq, b, h), _unbh(dk, b, h), _unbh(dv, b, h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(q, k, v, kv_mask, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    out, _ = _flash_forward(q, k, v, kv_mask, causal, block_q, block_k,
                            interpret)
    return out


def _flash_fwd(q, k, v, kv_mask, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, kv_mask, causal, block_q, block_k,
                              interpret)
    return out, (q, k, v, kv_mask, out, lse)


def _flash_bwd(causal, block_q, block_k, res, g):
    q, k, v, kv_mask, out, lse = res
    interpret = jax.default_backend() != "tpu"
    dq, dk, dv = _flash_backward(q, k, v, kv_mask, out, lse, g, causal,
                                 block_q, block_k, interpret)
    dmask = (
        None if kv_mask is None
        else np.zeros(kv_mask.shape, jax.dtypes.float0)
    )
    return dq, dk, dv, dmask


_flash.defvjp(_flash_fwd, _flash_bwd)


def _pick_block(t: int, target: int) -> Optional[int]:
    """Largest lane-aligned (128-multiple) divisor of ``t`` up to
    ``target``; ``t`` itself for short sequences; None when no bounded tile
    exists (odd lengths — the caller falls back to blockwise rather than
    compiling an unbounded single-tile kernel).

    Grid sizing is the difference between winning and losing the 512-token
    A/B: small tiles make thousands of ~4-MFLOP programs and per-program
    overhead dominates. Whole-train-step A/Bs on v5e (the only measurement
    this backend supports — bench.py): combined bs16 at (bq, bk) =
    (128, 512) 188.3 ex/s, (256, 512) 206.5, (512, 512) 214.1 — one
    program per (head, whole sequence) at the parity shape. VMEM stays
    comfortable (tiles are [block, 64])."""
    if t <= max(target, 128):
        return t
    best = None
    for b in range(128, min(target, t) + 1, 128):
        if t % b == 0:
            best = b
    return best


def flash_attention(q, k, v, kv_mask=None, causal=False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None):
    """Pallas TPU flash attention (exact), fwd + bwd kernels. Interprets on
    non-TPU backends so tests cover the kernel math on the CPU mesh.

    Block sizes default to the measured sweet spot (q and kv tiles up to
    512, divisor-aligned) — see ``_pick_block``. Sequences with no bounded
    tiling (e.g. long odd lengths) take the blockwise path."""
    if not _HAVE_PALLAS:  # pragma: no cover
        return blockwise_attention(q, k, v, kv_mask=kv_mask, causal=causal)
    if block_q is None:
        block_q = _pick_block(q.shape[1], 512)
    if block_k is None:
        block_k = _pick_block(k.shape[1], 512)
    if block_q is None or block_k is None:
        return blockwise_attention(q, k, v, kv_mask=kv_mask, causal=causal)
    return _flash(q, k, v, kv_mask, causal, block_q, block_k)


def attention(q, k, v, kv_mask=None, causal=False, impl: str = "auto", **kw):
    """Dispatch: 'dense' | 'blockwise' | 'flash' | 'auto' (flash on TPU —
    it handles untileable shapes by falling back internally — else
    blockwise)."""
    from deepdfa_tpu.core.backend import resolve_auto

    impl = resolve_auto(impl, tpu="flash", other="blockwise")
    if impl == "dense":
        return dense_attention(q, k, v, kv_mask=kv_mask, causal=causal, **kw)
    if impl == "blockwise":
        return blockwise_attention(q, k, v, kv_mask=kv_mask, causal=causal, **kw)
    if impl == "flash":
        return flash_attention(q, k, v, kv_mask=kv_mask, causal=causal, **kw)
    raise ValueError(f"unknown attention impl {impl!r}")

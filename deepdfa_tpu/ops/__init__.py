"""Pallas TPU kernels for the hot ops.

The default compute path is the XLA segment-op formulation in
``deepdfa_tpu.graphs.segment``; kernels here specialize the hot ops when
profiling shows XLA's generated code leaving HBM bandwidth on the table.

- ``tile_spmm``: block-sparse dense-tile SpMM for GNN message aggregation
  (MXU matmuls over scalar-prefetched tile coordinates), with a custom VJP.
  Select with ``FlowGNNConfig(message_impl="tile")`` on batches built with
  ``batch_graphs(build_tile_adj=True)``.
"""

from deepdfa_tpu.ops.tile_spmm import (  # noqa: F401
    TileAdjacency,
    build_tile_adjacency,
    tile_spmm,
)

"""TPU kernels and dense reformulations of the hot ops.

The default compute path is the XLA segment-op formulation in
``deepdfa_tpu.graphs.segment``; the modules here specialize the hot ops
when profiling shows XLA's generated code leaving the MXU idle.

- ``band_spmm``: block-banded dense adjacency — GNN message aggregation as
  2B+1 parallel batched MXU matmuls (pure XLA, autodiff backward). The
  measured flagship on TPU (bench.py). Select with
  ``FlowGNNConfig(message_impl="band")`` on batches built with
  ``batch_graphs(build_band_adj=True)``.
- ``tile_spmm``: block-sparse dense-tile SpMM (Pallas; MXU matmuls over
  scalar-prefetched tile coordinates, sequential grid), with a custom VJP.
  Select with ``FlowGNNConfig(message_impl="tile")`` on batches built with
  ``batch_graphs(build_tile_adj=True)``.
- ``fused_gnn``: the GatedGraphStep megakernels (Pallas; custom VJP with
  in-kernel remat). ``fused_gate_step`` fuses one whole step (edge
  message + band SpMM + GRU gate) into one ``pallas_call`` per
  direction; ``persistent_unroll`` fuses the entire K-step unroll — h
  VMEM-resident across steps, h_0 in / h_K out the only per-unroll h
  HBM traffic. Select with ``FlowGNNConfig(message_impl="fused")`` /
  ``message_impl="persistent"`` on band-adjacency batches (dense-slot
  packed); both degrade to the bitwise band composition off-TPU.
- ``attention``: blockwise streaming-softmax attention + Pallas flash
  kernels (forward and dq/dk/dv backward) — the long-context path.
"""

from deepdfa_tpu.ops.band_spmm import (  # noqa: F401
    BandAdjacency,
    band_spmm,
    build_band_adjacency,
)
from deepdfa_tpu.ops.tile_spmm import (  # noqa: F401
    TileAdjacency,
    build_tile_adjacency,
    tile_spmm,
)

"""Pallas TPU kernels for the hot ops.

The default compute path is the XLA segment-op formulation in
``deepdfa_tpu.graphs.segment``; kernels here specialize the fused
gather→transform→scatter-add message-passing step when profiling shows XLA's
generated code leaving HBM bandwidth on the table. Import the XLA fallbacks
from ``deepdfa_tpu.graphs`` unless a kernel is explicitly requested.
"""

"""Block-banded dense adjacency: GNN message aggregation as batched MXU
matmuls.

The tile SpMM (ops/tile_spmm.py) already turned the reference's CUDA
scatter-add (DDFA/code_gnn/models/flow_gnn/ggnn.py:57-60,95 — DGL
``GatedGraphConv``'s SpMM) into dense MXU tiles, but it walks its tile list
with a *sequential* Pallas grid: one 128x128 matmul per step, each waiting on
its own DMA. This module exploits one more structural fact for a fully
parallel layout: batched graphs are CONTIGUOUS node ranges and CFG edges
never cross graphs, so every nonzero tile of the batched adjacency sits
within ``bandwidth`` tiles of the diagonal, where bandwidth is set by the
largest graph's node span (small: Big-Vul CFGs are ~40-200 nodes, 1-2
tiles).

Store the adjacency as its 2B+1 block diagonals — ``vals[i, t]`` is the
tile-row-t block whose senders live in tile t+(i-B) — and aggregation is

    agg = sum_i  bmm(vals[i], msg_tiles shifted by i-B)

a handful of [T, tile, tile] x [T, tile, H] batched matmuls: no sequential
grid, no scalar prefetch, no per-tile DMA latency — XLA tiles the whole band
onto the MXU at once. Pure XLA also means the backward (d msg = A^T g) falls
out of autodiff (the pad/slice/einsum transpose), the same program runs on
CPU test meshes, and GSPMD handles it under pjit via the stacked per-shard
form (:func:`band_spmm_sharded`, mirroring the tile path's shard contract).

Off-band blocks are zero by construction, so band FLOPs exceed the "true"
edge work by the zero-fill ratio — but they run as one parallel MXU op
instead of a latency chain, which wins by a wide margin at CFG sparsity
(measured on v5e: see bench.py).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from deepdfa_tpu.ops.tile_spmm import (
    DEFAULT_TILE,
    _round_up_pow2,
    tile_vals_dtype,
)


@struct.dataclass
class BandAdjacency:
    """The 2B+1 block diagonals of a batched-graph adjacency.

    vals : [2B+1, n_tiles, tile, tile]; ``vals[i, t, r, c]`` = multiplicity
           of edge (sender s, receiver r') with r' = t*tile + r and
           s = (t + i - B)*tile + c. Blocks whose sender tile falls outside
           [0, n_tiles) are zero (edges cannot reach them).
    """

    vals: jnp.ndarray
    tile: int = struct.field(pytree_node=False, default=DEFAULT_TILE)
    n_tiles: int = struct.field(pytree_node=False, default=0)
    bandwidth: int = struct.field(pytree_node=False, default=1)


def _bucket_bandwidth(b: int) -> int:
    """Pow2 ladder (min 1) so near-miss batches share a compiled program —
    the same bucketing rule as the tile path's budgets (shared helper, so
    the multi-controller shape agreement can never drift between them)."""
    return _round_up_pow2(max(b, 1))


def band_width_for(
    senders: np.ndarray, receivers: np.ndarray, tile: int = DEFAULT_TILE
) -> int:
    """The (bucketed) bandwidth :func:`build_band_adjacency` picks for these
    (real) edges — from the edge lists alone, so multi-controller hosts can
    agree on remote shards' leaf shapes without materializing them."""
    s = np.asarray(senders, np.int64)
    r = np.asarray(receivers, np.int64)
    if len(s) == 0:
        return 1
    return _bucket_bandwidth(int(np.abs(s // tile - r // tile).max()))


def combine_band_stats(stats: Sequence) -> "tuple[int, jnp.dtype]":
    """Fold per-shard ``(bandwidth, vals_dtype)`` into the globally-agreed
    values: max bandwidth, f32 if ANY shard needs it (upcasts only) — the
    same reduction rule as tile_spmm.combine_tile_stats."""
    bw = max(b for b, _ in stats)
    dt = (
        jnp.float32
        if any(d == jnp.float32 for _, d in stats)
        else jnp.bfloat16
    )
    return bw, dt


def build_band_adjacency(
    senders: np.ndarray,
    receivers: np.ndarray,
    edge_mask: np.ndarray,
    max_nodes: int,
    tile: int = DEFAULT_TILE,
    bandwidth: Optional[int] = None,
) -> BandAdjacency:
    """Host-side: scatter edge multiplicities into the block diagonals.

    ``bandwidth``: explicit common width (multi-controller callers pass the
    global maximum over all shards); default = this edge list's own bucketed
    width. Values keep the tile path's dtype rule: bf16-resident when every
    multiplicity is exactly representable (tile_spmm.tile_vals_dtype).
    """
    if max_nodes % tile:
        raise ValueError(f"max_nodes {max_nodes} not a multiple of tile {tile}")
    n_tiles = max_nodes // tile
    mask = np.asarray(edge_mask, bool)
    s = np.asarray(senders)[mask].astype(np.int64)
    r = np.asarray(receivers)[mask].astype(np.int64)

    need = band_width_for(s, r, tile)
    bw = need if bandwidth is None else int(bandwidth)
    if bw < need:
        raise ValueError(f"bandwidth {bw} < required {need} for these edges")

    vals = np.zeros((2 * bw + 1, n_tiles, tile, tile), np.float32)
    if len(s):
        diag = (s // tile) - (r // tile) + bw
        np.add.at(vals, (diag, r // tile, r % tile, s % tile), 1.0)
    return BandAdjacency(
        vals=jnp.asarray(vals, tile_vals_dtype(s, r)),
        tile=tile,
        n_tiles=n_tiles,
        bandwidth=bw,
    )


def band_spmm(adj: BandAdjacency, msg: jnp.ndarray) -> jnp.ndarray:
    """``agg = A @ msg`` over the block diagonals.

    One einsum per diagonal (2B+1 total), each a [T, tile, tile] x
    [T, tile, H] batched matmul; shifted sender tiles come from a zero-padded
    static slice, so out-of-range senders contribute nothing. f32
    accumulation on the MXU matches the tile/segment paths bit-for-bit
    (HIGHEST precision for f32 inputs, native mixed bf16 x bf16 -> f32
    otherwise). Adjacency values are structural (stop_gradient), so autodiff
    produces only the d msg = A^T g transpose — dense ops, no custom VJP.
    """
    t, bw = adj.tile, adj.bandwidth
    n_tiles = adj.n_tiles
    h = msg.shape[1]
    vals = jax.lax.stop_gradient(adj.vals)
    if vals.dtype == jnp.float32 and msg.dtype != jnp.float32:
        # Upcast-only rule (the stack_band_adjacencies guard, applied at
        # compute time too): tile_vals_dtype chose f32 because some edge
        # multiplicity is not bf16-exact, so the einsum runs in f32 with
        # upcast messages rather than downcasting vals.
        msg_in = msg.astype(jnp.float32)
    else:
        vals = vals.astype(msg.dtype)
        msg_in = msg
    precision = (
        jax.lax.Precision.HIGHEST
        if msg_in.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )
    m = msg_in.reshape(n_tiles, t, h)
    mp = jnp.pad(m, ((bw, bw), (0, 0), (0, 0)))
    out = jnp.zeros((n_tiles, t, h), jnp.float32)
    for i in range(2 * bw + 1):
        out = out + jnp.einsum(
            "tij,tjh->tih",
            vals[i],
            jax.lax.slice_in_dim(mp, i, i + n_tiles, axis=0),
            preferred_element_type=jnp.float32,
            precision=precision,
        )
    return out.reshape(n_tiles * t, h).astype(msg.dtype)


def pad_band(adj: BandAdjacency, bandwidth: int) -> BandAdjacency:
    """Widen to a larger common bandwidth with zero diagonals (inert)."""
    bw = adj.bandwidth
    if bandwidth == bw:
        return adj
    if bandwidth < bw:
        raise ValueError(f"pad bandwidth {bandwidth} < existing {bw}")
    extra = bandwidth - bw
    z = jnp.zeros((extra,) + adj.vals.shape[1:], adj.vals.dtype)
    return BandAdjacency(
        vals=jnp.concatenate([z, adj.vals, z]),
        tile=adj.tile,
        n_tiles=adj.n_tiles,
        bandwidth=bandwidth,
    )


def cast_band(adj: BandAdjacency, dtype: jnp.dtype) -> BandAdjacency:
    return adj.replace(vals=adj.vals.astype(dtype))


def stack_band_adjacencies(
    adjs: "list[BandAdjacency]",
    bandwidth: Optional[int] = None,
    force_dtype: Optional[jnp.dtype] = None,
) -> BandAdjacency:
    """Stack per-shard band adjacencies along a leading device axis.

    Shard boundaries coincide with graph boundaries (parallel/mesh.py batch
    alignment contract), so the global adjacency is block-diagonal over
    shards and each device aggregates its own band under shard_map. All
    shards pad to a common bandwidth (multi-controller callers pass the
    global maximum) and, when ``force_dtype`` is given, cast to the
    globally-agreed dtype — upcasts only, a lossy bf16 force is refused.
    """
    a0 = adjs[0]
    for a in adjs:
        if a.tile != a0.tile or a.n_tiles != a0.n_tiles:
            raise ValueError("shards must share tile size and tile count")
    bw_max = max(a.bandwidth for a in adjs)
    bw = bw_max if bandwidth is None else bandwidth
    if bw < bw_max:
        raise ValueError(f"bandwidth {bw} < largest shard bandwidth {bw_max}")
    adjs = [pad_band(a, bw) for a in adjs]
    if force_dtype is not None:
        if any(
            a.vals.dtype == jnp.float32 and force_dtype == jnp.bfloat16
            for a in adjs
        ):
            raise ValueError("refusing lossy f32 -> bf16 band downcast")
        adjs = [cast_band(a, force_dtype) for a in adjs]
    return BandAdjacency(
        vals=jnp.stack([a.vals for a in adjs]),
        tile=a0.tile,
        n_tiles=a0.n_tiles,
        bandwidth=bw,
    )


def band_spmm_sharded(
    adj: BandAdjacency, msg: jnp.ndarray, mesh
) -> jnp.ndarray:
    """``agg = blockdiag(A_d) @ msg`` on a data-sharded mesh.

    ``adj`` is a stacked adjacency (vals ``[D, 2B+1, T, tile, tile]``);
    ``msg``'s leading axis is sharded over ``data``. No cross-device
    collectives: shard boundaries are graph boundaries.
    """
    from jax.sharding import PartitionSpec as P

    from deepdfa_tpu.parallel.mesh import DATA_AXIS

    adj_spec = BandAdjacency(
        vals=P(DATA_AXIS),
        tile=adj.tile, n_tiles=adj.n_tiles, bandwidth=adj.bandwidth,
    )

    def local(a: BandAdjacency, m: jnp.ndarray) -> jnp.ndarray:
        return band_spmm(jax.tree_util.tree_map(lambda x: x[0], a), m)

    from deepdfa_tpu.parallel.mesh import shard_map_compat

    return shard_map_compat(
        local,
        mesh=mesh,
        in_specs=(adj_spec, P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_vma=False,
    )(adj, msg)

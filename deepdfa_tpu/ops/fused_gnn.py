"""Fused FlowGNN megakernel: gather + band SpMM + GRU gate in ONE Pallas pass.

The unfused GatedGraphStep is a chain of separate dispatches — edge-message
dense (``h @ W_e``), the band/tile SpMM aggregate, then six GRU gate matmuls
— and every link round-trips its [max_nodes, H] intermediate through HBM.
At the published shape the step is HBM-bound (roofline verdict, PR 7
observatory): the MXU waits on loads, and the f32/tile paths sit at ~55% of
the bf16 band flagship. This module fuses the whole per-graph-step compute
into one ``pallas_call`` per direction:

- **Forward** (:func:`_fwd_kernel`): a sequential grid over node row tiles.
  Step ``i`` computes the edge-message tile ``msg[i] = h[i] @ W_e + b_e``
  into a rolling VMEM window of ``2B+1`` tiles (B = band bandwidth), then —
  once the window covers row ``r = i - B`` — aggregates
  ``agg[r] = Σ_d A[d, r] @ msg[r+d-B]`` and applies the full GRU gate update
  in-register, writing ``h'[r]`` straight out. HBM sees each ``h`` tile
  twice (message + carry reads) and each ``h'`` tile once; ``msg``/``agg``
  and every gate pre-activation never leave VMEM. The grid runs ``T + B``
  steps so the window warm-up costs B extra tiles, not a prologue branch;
  Pallas's block pipeline double-buffers the next tile's HBM→VMEM DMAs
  under the current tile's MXU work.
- **Backward** (:func:`_bwd_kernel`): the same rolling-window structure with
  two extra phase offsets — step ``i`` recomputes ``msg[i]``, runs the gate
  backward at row ``r = i - B`` (holding ``d agg`` and the local carry
  cotangent in windows), and completes ``d msg[c] = Σ Aᵀ[c] d agg`` plus
  ``d h[c]`` at ``c = i - 2B``. Weight gradients accumulate in f32 output
  blocks that stay VMEM-resident across the whole grid (constant index
  maps) and flush once. Gradients therefore need no [nodes, H]
  intermediates in HBM either — the unfused backward materializes five.

**Dense-slot packing** (``graphs/batch.py slot_pack=True``) feeds the
kernel: binning each CPG into a fixed node slot from the ``select_bucket``
ladder keeps every graph inside (at most) adjacent row tiles, collapsing
the band bandwidth — and with it the window size, the warm-up, and the
zero-padded off-diagonal FLOPs — before the kernel ever sees the batch.

**Fallback contract**: ``impl="xla"`` (the CPU/tier-1 path, and what
``auto`` resolves to off-TPU) is :func:`fused_reference` — math-for-math
the flax ``Dense`` + ``band_spmm`` + ``GRUCell`` composition, so the fused
flag degrades to the *bitwise* band path where Pallas is unavailable;
``models/flowgnn.py`` routes its fallback through the very same flax
modules, which is what the gradient-parity acceptance test pins.
``impl="interpret"`` runs the real kernels on the Pallas interpreter (the
tier-1 numerics tests). Never pin ``interpret=True`` on an importable
path — graftlint GL016 exists because that ships a silent ~100× slowdown.

XLA's ``cost_analysis`` cannot see inside a Pallas custom call, so
:func:`fused_step_cost` provides the analytic FLOPs/bytes accounting that
``telemetry/costmodel.capture_compiled(extra_flops=..., extra_bytes=...)``
folds into the roofline report.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, Mapping, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepdfa_tpu.ops.band_spmm import BandAdjacency, band_spmm


def resolve_impl(impl: str = "auto") -> str:
    """Resolve the fused dispatch: "pallas" | "interpret" | "xla".

    ``auto`` honours ``DEEPDFA_FUSED_IMPL`` (the test/debug override),
    then picks the compiled kernel on TPU and the XLA reference
    elsewhere — the same backend gate as pool_impl/embed_impl.
    """
    if impl == "auto":
        impl = os.environ.get("DEEPDFA_FUSED_IMPL", "auto")
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl not in ("pallas", "interpret", "xla"):
        raise ValueError(f"unknown fused impl {impl!r}")
    return impl


# ---------------------------------------------------------------------------
# Parameter declaration (the flax tree the unfused modules own)
# ---------------------------------------------------------------------------
#
# The fused kernel consumes raw weight arrays, but the param TREE must stay
# byte-identical to nn.Dense(name="edge_linear") + nn.GRUCell(name="gru") —
# checkpoints restore across message_impl flips, and the serving layer
# restores params target-free. Flax derives each param's init RNG from its
# scope path, so declaring the same names/shapes/inits at the same paths
# yields the identical tree (pinned by tests/test_fused_gnn.py).


class _DenseParams(nn.Module):
    """Declares ``{kernel[, bias]}`` exactly as ``nn.Dense`` would."""

    features: int
    in_features: int
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self) -> Dict[str, jnp.ndarray]:
        out = {"kernel": self.param(
            "kernel", self.kernel_init, (self.in_features, self.features))}
        if self.use_bias:
            out["bias"] = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,))
        return out


class _GRUParams(nn.Module):
    """Declares the six gate kernels exactly as ``nn.GRUCell`` would
    (input gates: lecun_normal + bias; recurrent gates: orthogonal,
    bias only on ``hn``)."""

    hidden: int

    @nn.compact
    def __call__(self) -> Dict[str, Dict[str, jnp.ndarray]]:
        lecun = nn.initializers.lecun_normal()
        orth = nn.initializers.orthogonal()
        spec = (
            ("ir", True, lecun), ("iz", True, lecun), ("in", True, lecun),
            ("hr", False, orth), ("hz", False, orth), ("hn", True, orth),
        )
        return {
            name: _DenseParams(self.hidden, self.hidden, use_bias=bias,
                               kernel_init=init, name=name)()
            for name, bias, init in spec
        }


def declare_step_params(hidden: int, in_features: int
                        ) -> Dict[str, Any]:
    """Instantiate inside a compact module: declares (and returns) the
    GatedGraphStep param tree under the canonical ``edge_linear``/``gru``
    child scopes."""
    return {
        "edge_linear": _DenseParams(hidden, in_features,
                                    name="edge_linear")(),
        "gru": _GRUParams(hidden, name="gru")(),
    }


# ---------------------------------------------------------------------------
# XLA reference (the CPU fallback and numerics oracle)
# ---------------------------------------------------------------------------


def _dense_apply(p: Mapping[str, jnp.ndarray], x: jnp.ndarray,
                 dt) -> jnp.ndarray:
    """``nn.Dense.__call__`` math, op for op (promote to ``dt``, dot,
    reshape-broadcast bias add)."""
    y = jax.lax.dot_general(
        x, p["kernel"].astype(dt), (((x.ndim - 1,), (0,)), ((), ())))
    if "bias" in p:
        y = y + jnp.reshape(p["bias"].astype(dt),
                            (1,) * (y.ndim - 1) + (-1,))
    return y


def fused_reference(params: Mapping, h: jnp.ndarray,
                    adj: BandAdjacency) -> jnp.ndarray:
    """The unfused composition with the fused op's signature: flax-Dense
    edge message → ``band_spmm`` aggregate → flax-GRUCell gate, in the
    model's compute dtype. This IS the ``impl="xla"`` path, and the
    program the interpret/pallas kernels are tested against."""
    dt = h.dtype
    msg = _dense_apply(params["edge_linear"], h.astype(dt), dt)
    agg = band_spmm(adj, msg)
    g = params["gru"]
    x, hc = agg.astype(dt), h.astype(dt)
    r = nn.sigmoid(_dense_apply(g["ir"], x, dt) + _dense_apply(g["hr"], hc, dt))
    z = nn.sigmoid(_dense_apply(g["iz"], x, dt) + _dense_apply(g["hz"], hc, dt))
    n = nn.tanh(_dense_apply(g["in"], x, dt)
                + r * _dense_apply(g["hn"], hc, dt))
    return (1.0 - z) * n + z * hc


# ---------------------------------------------------------------------------
# Packed weights (one [H, 3H] matmul per gate family inside the kernel)
# ---------------------------------------------------------------------------


def _precision(dt) -> jax.lax.Precision:
    # The band_spmm/tile_spmm rule: f32 keeps HIGHEST so the kernel stays
    # comparable with the unfused oracle; bf16 rides the native MXU path.
    return (jax.lax.Precision.HIGHEST if jnp.dtype(dt) == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _packed_weights(params: Mapping, dt):
    """(ek, eb, wi, bi, wh, bh): edge weights plus the r|z|n gate kernels
    concatenated on the output axis — three dots become one MXU pass; the
    recurrent bias vector packs ``[0, 0, b_hn]`` so the hn-only bias rides
    the same add."""
    g = params["gru"]
    h = g["ir"]["kernel"].shape[0]
    ek = params["edge_linear"]["kernel"].astype(dt)
    eb = params["edge_linear"]["bias"].astype(dt).reshape(1, -1)
    wi = jnp.concatenate(
        [g["ir"]["kernel"], g["iz"]["kernel"], g["in"]["kernel"]],
        axis=1).astype(dt)
    bi = jnp.concatenate(
        [g["ir"]["bias"], g["iz"]["bias"], g["in"]["bias"]]
    ).astype(dt).reshape(1, -1)
    wh = jnp.concatenate(
        [g["hr"]["kernel"], g["hz"]["kernel"], g["hn"]["kernel"]],
        axis=1).astype(dt)
    bh = jnp.concatenate(
        [jnp.zeros((2 * h,), dt), g["hn"]["bias"].astype(dt)]
    ).reshape(1, -1)
    return ek, eb, wi, bi, wh, bh


def _vals_compute(adj: BandAdjacency, dt):
    """(vals, message dtype) under the upcast-only rule: f32 adjacency
    values (a multiplicity not bf16-exact) force f32 messages; otherwise
    the adjacency rides the model dtype."""
    vals = adj.vals
    if vals.dtype == jnp.float32 and jnp.dtype(dt) != jnp.float32:
        return vals, jnp.float32
    return vals.astype(dt), jnp.dtype(dt)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(vals_ref, hmsg_ref, hc_ref, ek_ref, eb_ref, wi_ref, bi_ref,
                wh_ref, bh_ref, out_ref, msg_win, *, n_tiles, bandwidth,
                hidden, dt, mdt):
    i = pl.program_id(0)
    b, w = bandwidth, 2 * bandwidth + 1
    prec = _precision(mdt)

    # Phase 1: edge-message tile i into the rolling window. The dense-slot
    # packed batch keeps b tiny, so the window (and its warm-up) is small.
    @pl.when(i < n_tiles)
    def _msg():
        m = jnp.dot(hmsg_ref[:].astype(mdt), ek_ref[:].astype(mdt),
                    preferred_element_type=jnp.float32, precision=prec)
        msg_win[i % w] = (m.astype(mdt)
                          + eb_ref[:].astype(mdt))

    # Phase 2: aggregate + GRU gate for row r = i - b, entirely in VMEM.
    @pl.when(i >= b)
    def _gate():
        r = i - b
        agg = jnp.zeros((hmsg_ref.shape[0], hidden), jnp.float32)
        for d in range(w):
            j = r + d - b
            contrib = jnp.dot(
                vals_ref[d, 0].astype(mdt), msg_win[j % w],
                preferred_element_type=jnp.float32, precision=prec)
            # Off-range sender tiles hold zero adjacency blocks, but the
            # window slot may hold uninitialized VMEM (NaN × 0 = NaN) —
            # the mask, not the zero blocks, is what makes padding inert.
            agg = agg + jnp.where((j >= 0) & (j < n_tiles), contrib, 0.0)
        x = agg.astype(dt)
        hc = hc_ref[:]
        gi = jnp.dot(x, wi_ref[:], preferred_element_type=jnp.float32,
                     precision=_precision(dt)).astype(dt) + bi_ref[:]
        gh = jnp.dot(hc, wh_ref[:], preferred_element_type=jnp.float32,
                     precision=_precision(dt)).astype(dt) + bh_ref[:]
        rg = jax.nn.sigmoid(gi[:, :hidden] + gh[:, :hidden])
        zg = jax.nn.sigmoid(gi[:, hidden:2 * hidden]
                            + gh[:, hidden:2 * hidden])
        ng = jnp.tanh(gi[:, 2 * hidden:] + rg * gh[:, 2 * hidden:])
        out_ref[:] = ((1.0 - zg) * ng + zg * hc).astype(out_ref.dtype)


def _run_fwd(params, h, adj: BandAdjacency, interpret: bool) -> jnp.ndarray:
    dt = h.dtype
    t, nt, b = adj.tile, adj.n_tiles, adj.bandwidth
    w = 2 * b + 1
    hidden = h.shape[1]
    vals, mdt = _vals_compute(adj, dt)
    ek, eb, wi, bi, wh, bh = _packed_weights(params, dt)

    kernel = functools.partial(
        _fwd_kernel, n_tiles=nt, bandwidth=b, hidden=hidden, dt=dt, mdt=mdt)
    const = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    out = pl.pallas_call(
        kernel,
        grid=(nt + b,),
        in_specs=[
            pl.BlockSpec((w, 1, t, t),
                         lambda i: (0, jnp.maximum(i - b, 0), 0, 0)),
            pl.BlockSpec((t, hidden), lambda i: (jnp.minimum(i, nt - 1), 0)),
            pl.BlockSpec((t, hidden), lambda i: (jnp.maximum(i - b, 0), 0)),
            const((hidden, hidden)), const((1, hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
        ],
        out_specs=pl.BlockSpec((t, hidden),
                               lambda i: (jnp.maximum(i - b, 0), 0)),
        out_shape=jax.ShapeDtypeStruct((nt * t, hidden), dt),
        scratch_shapes=[pltpu.VMEM((w, t, hidden), mdt)],
        interpret=interpret,
    )(vals, h, h, ek, eb, wi, bi, wh, bh)
    return out


# ---------------------------------------------------------------------------
# Backward kernel
# ---------------------------------------------------------------------------


def band_transpose_vals(vals: jnp.ndarray, bandwidth: int,
                        n_tiles: int) -> jnp.ndarray:
    """The block-band form of Aᵀ from the block-band form of A:
    ``t_vals[e, c] = vals[2B-e, c+e-B]ᵀ`` (zero where the source row tile
    falls outside the batch) — the same pad/slice idiom as band_spmm's
    shifted message tiles, plus a per-block transpose."""
    w = 2 * bandwidth + 1
    outs = []
    for e in range(w):
        src = vals[w - 1 - e]
        shift = e - bandwidth
        padded = jnp.pad(src, ((bandwidth, bandwidth), (0, 0), (0, 0)))
        sl = jax.lax.slice_in_dim(
            padded, shift + bandwidth, shift + bandwidth + n_tiles, axis=0)
        outs.append(jnp.swapaxes(sl, 1, 2))
    return jnp.stack(outs)


def _bwd_kernel(vals_ref, tvals_ref, hmsg_ref, hc_ref, hdwe_ref, g_ref,
                ek_ref, eb_ref, wi_ref, bi_ref, wh_ref, bh_ref,
                dh_ref, dek_ref, deb_ref, dwi_ref, dbi_ref, dwh_ref, dbh_ref,
                msg_win, dx_win, dhl_win, *, n_tiles, bandwidth, hidden,
                dt, mdt):
    i = pl.program_id(0)
    b, w = bandwidth, 2 * bandwidth + 1
    prec = _precision(mdt)
    pdt = _precision(dt)

    # Weight-grad accumulators live in the (VMEM-resident, constant-index)
    # output blocks; zero them exactly once, before any accumulation.
    @pl.when(i == 0)
    def _zero():
        for ref in (dek_ref, deb_ref, dwi_ref, dbi_ref, dwh_ref, dbh_ref):
            ref[:] = jnp.zeros_like(ref)

    # Phase 1: recompute edge-message tile i (the remat of the fused op —
    # nothing but h is saved as residual).
    @pl.when(i < n_tiles)
    def _msg():
        m = jnp.dot(hmsg_ref[:].astype(mdt), ek_ref[:].astype(mdt),
                    preferred_element_type=jnp.float32, precision=prec)
        msg_win[i % w] = m.astype(mdt) + eb_ref[:].astype(mdt)

    # Phase 2: gate backward at row r = i - b — recompute the forward
    # gates, then push the output cotangent through them. d agg and the
    # local carry cotangent land in rolling windows for phase 3.
    @pl.when((i >= b) & (i < n_tiles + b))
    def _gate_bwd():
        r = i - b
        agg = jnp.zeros((hmsg_ref.shape[0], hidden), jnp.float32)
        for d in range(w):
            j = r + d - b
            contrib = jnp.dot(
                vals_ref[d, 0].astype(mdt), msg_win[j % w],
                preferred_element_type=jnp.float32, precision=prec)
            agg = agg + jnp.where((j >= 0) & (j < n_tiles), contrib, 0.0)
        x = agg.astype(dt)
        hc = hc_ref[:]
        gi = jnp.dot(x, wi_ref[:], preferred_element_type=jnp.float32,
                     precision=pdt).astype(dt) + bi_ref[:]
        gh = jnp.dot(hc, wh_ref[:], preferred_element_type=jnp.float32,
                     precision=pdt).astype(dt) + bh_ref[:]
        rg = jax.nn.sigmoid(gi[:, :hidden] + gh[:, :hidden])
        zg = jax.nn.sigmoid(gi[:, hidden:2 * hidden]
                            + gh[:, hidden:2 * hidden])
        pre_hn = gh[:, 2 * hidden:]
        ng = jnp.tanh(gi[:, 2 * hidden:] + rg * pre_hn)

        g32 = g_ref[:].astype(jnp.float32)
        hc32 = hc.astype(jnp.float32)
        rg32, zg32, ng32 = (rg.astype(jnp.float32), zg.astype(jnp.float32),
                            ng.astype(jnp.float32))
        dz = g32 * (hc32 - ng32)
        dn = g32 * (1.0 - zg32)
        dhc = g32 * zg32
        dpre_n = dn * (1.0 - ng32 * ng32)
        drg = dpre_n * pre_hn.astype(jnp.float32)
        dpre_hn = dpre_n * rg32
        dpre_r = drg * rg32 * (1.0 - rg32)
        dpre_z = dz * zg32 * (1.0 - zg32)
        dpre_i = jnp.concatenate([dpre_r, dpre_z, dpre_n], axis=1)
        dpre_h = jnp.concatenate([dpre_r, dpre_z, dpre_hn], axis=1)

        dpre_i_c = dpre_i.astype(dt)
        dpre_h_c = dpre_h.astype(dt)
        # d agg = dpre_i @ Wiᵀ — contract the gate axis against Wi's.
        dx = jax.lax.dot_general(
            dpre_i_c, wi_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dx_win[r % w] = dx.astype(mdt)
        dhl = dhc + jax.lax.dot_general(
            dpre_h_c, wh_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dhl_win[r % w] = dhl

        # Gate weight grads: contract the node-tile axis, accumulate f32.
        dwi_ref[:] += jax.lax.dot_general(
            x, dpre_i_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dbi_ref[:] += jnp.sum(dpre_i, axis=0, keepdims=True)
        dwh_ref[:] += jax.lax.dot_general(
            hc, dpre_h_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dbh_ref[:] += jnp.sum(dpre_h, axis=0, keepdims=True)

    # Phase 3: d msg[c] = Σ Aᵀ[c] · d agg, then the edge weights' grads and
    # the total d h[c] — the dx window now covers c ± b.
    @pl.when(i >= 2 * b)
    def _dmsg():
        c = i - 2 * b
        dmsg = jnp.zeros((hmsg_ref.shape[0], hidden), jnp.float32)
        for e in range(w):
            j = c + e - b
            contrib = jnp.dot(
                tvals_ref[e, 0].astype(mdt), dx_win[j % w],
                preferred_element_type=jnp.float32, precision=prec)
            dmsg = dmsg + jnp.where((j >= 0) & (j < n_tiles), contrib, 0.0)
        dmsg_c = dmsg.astype(mdt)
        dek_ref[:] += jax.lax.dot_general(
            hdwe_ref[:].astype(mdt), dmsg_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        deb_ref[:] += jnp.sum(dmsg, axis=0, keepdims=True)
        dh_from_msg = jax.lax.dot_general(
            dmsg_c, ek_ref[:].astype(mdt), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dh_ref[:] = (dhl_win[c % w] + dh_from_msg).astype(dh_ref.dtype)


def _run_bwd(params, h, adj: BandAdjacency, g: jnp.ndarray,
             interpret: bool):
    dt = h.dtype
    t, nt, b = adj.tile, adj.n_tiles, adj.bandwidth
    w = 2 * b + 1
    hidden = h.shape[1]
    vals, mdt = _vals_compute(adj, dt)
    tvals = band_transpose_vals(vals, b, nt)
    ek, eb, wi, bi, wh, bh = _packed_weights(params, dt)

    kernel = functools.partial(
        _bwd_kernel, n_tiles=nt, bandwidth=b, hidden=hidden, dt=dt, mdt=mdt)
    const = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    f32 = jnp.float32
    band_blk = pl.BlockSpec((w, 1, t, t),
                            lambda i: (0, jnp.maximum(i - b, 0), 0, 0))
    tband_blk = pl.BlockSpec((w, 1, t, t),
                             lambda i: (0, jnp.maximum(i - 2 * b, 0), 0, 0))
    row = lambda off: pl.BlockSpec(
        (t, hidden),
        lambda i, off=off: (jnp.clip(i - off, 0, nt - 1), 0))
    dh, dek, deb, dwi, dbi, dwh, dbh = pl.pallas_call(
        kernel,
        grid=(nt + 2 * b,),
        in_specs=[
            band_blk, tband_blk,
            row(0),        # h for the message recompute
            row(b),        # h as the GRU carry
            row(2 * b),    # h against d msg for dW_e
            row(b),        # output cotangent at the gate row
            const((hidden, hidden)), const((1, hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
        ],
        out_specs=(
            pl.BlockSpec((t, hidden),
                         lambda i: (jnp.maximum(i - 2 * b, 0), 0)),
            const((hidden, hidden)), const((1, hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((nt * t, hidden), dt),
            jax.ShapeDtypeStruct((hidden, hidden), f32),
            jax.ShapeDtypeStruct((1, hidden), f32),
            jax.ShapeDtypeStruct((hidden, 3 * hidden), f32),
            jax.ShapeDtypeStruct((1, 3 * hidden), f32),
            jax.ShapeDtypeStruct((hidden, 3 * hidden), f32),
            jax.ShapeDtypeStruct((1, 3 * hidden), f32),
        ),
        scratch_shapes=[
            pltpu.VMEM((w, t, hidden), mdt),   # msg window
            pltpu.VMEM((w, t, hidden), mdt),   # d agg window
            pltpu.VMEM((w, t, hidden), f32),   # local d h window
        ],
        interpret=interpret,
    )(vals, tvals, h, h, h, g, ek, eb, wi, bi, wh, bh)
    return dh, dek, deb, dwi, dbi, dwh, dbh


def _unpack_grads(params, dek, deb, dwi, dbi, dwh, dbh):
    """Packed kernel-space gradients back to the flax param tree, in the
    params' own (f32 storage) dtypes."""
    h = params["gru"]["ir"]["kernel"].shape[0]

    def like(ref, val):
        return val.astype(ref.dtype)

    g = params["gru"]
    sl = lambda a, k: a[:, k * h:(k + 1) * h]
    out = {
        "edge_linear": {
            "kernel": like(params["edge_linear"]["kernel"], dek),
            "bias": like(params["edge_linear"]["bias"], deb[0]),
        },
        "gru": {
            "ir": {"kernel": like(g["ir"]["kernel"], sl(dwi, 0)),
                   "bias": like(g["ir"]["bias"], sl(dbi, 0)[0])},
            "iz": {"kernel": like(g["iz"]["kernel"], sl(dwi, 1)),
                   "bias": like(g["iz"]["bias"], sl(dbi, 1)[0])},
            "in": {"kernel": like(g["in"]["kernel"], sl(dwi, 2)),
                   "bias": like(g["in"]["bias"], sl(dbi, 2)[0])},
            "hr": {"kernel": like(g["hr"]["kernel"], sl(dwh, 0))},
            "hz": {"kernel": like(g["hz"]["kernel"], sl(dwh, 1))},
            "hn": {"kernel": like(g["hn"]["kernel"], sl(dwh, 2)),
                   "bias": like(g["hn"]["bias"], sl(dbh, 2)[0])},
        },
    }
    return out


# ---------------------------------------------------------------------------
# The differentiable fused op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_pallas(params, h, adj: BandAdjacency,
                  interpret: bool) -> jnp.ndarray:
    return _run_fwd(params, h, adj, interpret)


def _fused_fwd(params, h, adj, interpret):
    # Residuals: params + h + the structural adjacency — no activations.
    # The backward kernel recomputes messages/gates tile by tile (the
    # in-kernel remat), so the fused step saves nothing [nodes, H]-sized.
    return _run_fwd(params, h, adj, interpret), (params, h, adj)


def _fused_bwd(interpret, res, g):
    params, h, adj = res
    dh, dek, deb, dwi, dbi, dwh, dbh = _run_bwd(params, h, adj, g, interpret)
    dparams = _unpack_grads(params, dek, deb, dwi, dbi, dwh, dbh)
    dadj = jax.tree_util.tree_map(jnp.zeros_like, adj)  # structural
    return dparams, dh, dadj


_fused_pallas.defvjp(_fused_fwd, _fused_bwd)


def fused_gate_step(params: Mapping, h: jnp.ndarray, adj: BandAdjacency,
                    impl: str = "auto") -> jnp.ndarray:
    """One fused gated graph step: ``h' = GRU(A @ (h W_e + b_e), h)``.

    ``params``: the flax GatedGraphStep subtree (``edge_linear`` +
    ``gru/{ir,iz,in,hr,hz,hn}``). ``impl``: "pallas" (the TPU megakernel)
    | "interpret" (same kernels on the Pallas interpreter — tests) |
    "xla" (the unfused reference composition — the CPU/tier-1 fallback)
    | "auto". Differentiable in ``params`` and ``h``; the adjacency is
    structural.
    """
    impl = resolve_impl(impl)
    if impl == "xla":
        return fused_reference(params, h, adj)
    if adj.vals.ndim != 4:
        raise ValueError(
            "fused kernel takes one shard's band adjacency (vals "
            f"[2B+1, T, t, t]); got ndim={adj.vals.ndim} — sharded batches "
            "dispatch through the band fallback (models/flowgnn.py)")
    return _fused_pallas(params, h, adj, impl == "interpret")


# ---------------------------------------------------------------------------
# Analytic cost accounting (Pallas is invisible to XLA's cost model)
# ---------------------------------------------------------------------------


def fused_step_cost(adj: BandAdjacency, hidden: int,
                    dtype="float32") -> Dict[str, float]:
    """FLOPs / HBM bytes of ONE fused forward step, counted the way the
    roofline counts the unfused ops: dense matmul FLOPs (2mnk) over the
    message dense, the 2B+1 band block-matmuls, and the packed gate
    matmuls; bytes = the HBM the kernel actually touches (h in twice +
    carry, h' out, adjacency once, weights once). The backward is ~2× the
    matmul work plus the Aᵀ pass — callers scale by steps as needed."""
    t, nt, b = adj.tile, adj.n_tiles, adj.bandwidth
    w = 2 * b + 1
    n = nt * t
    itemsize = jnp.dtype(dtype).itemsize
    flops = (
        2.0 * n * hidden * hidden            # msg = h @ We
        + 2.0 * w * nt * t * t * hidden      # agg = A @ msg (band bmms)
        + 2.0 * n * hidden * 3 * hidden      # x @ Wi
        + 2.0 * n * hidden * 3 * hidden      # h @ Wh
        + 10.0 * n * hidden                  # gate elementwise
    )
    bytes_accessed = (
        3.0 * n * hidden * itemsize          # h: msg read + carry read, h' out
        + float(adj.vals.size) * adj.vals.dtype.itemsize
        + (8.0 * hidden * hidden + 7.0 * hidden) * itemsize
    )
    # Backward: the in-kernel remat replays every forward matmul, then the
    # gate/edge cotangent matmuls (dx, dh_local, dWi, dWh each one packed
    # [n,3H] pass), the Aᵀ band pass, and dW_e / dh-from-msg.
    bwd_flops = (
        flops                                   # forward recompute
        + 4.0 * 2.0 * n * hidden * 3 * hidden   # dx, dh_local, dWi, dWh
        + 2.0 * w * nt * t * t * hidden         # d msg = Aᵀ @ d agg
        + 2.0 * 2.0 * n * hidden * hidden       # dW_e, dh from d msg
        + 30.0 * n * hidden                     # gate backward elementwise
    )
    # Backward HBM: h fetched through three row pipelines (message
    # recompute, carry, dW_e), the cotangent in, dh out, both band forms
    # (A and the host-built Aᵀ), weights in + packed f32 grads out.
    bwd_bytes_accessed = (
        5.0 * n * hidden * itemsize              # h ×3, g in, dh out
        + 2.0 * float(adj.vals.size) * adj.vals.dtype.itemsize
        + (8.0 * hidden * hidden + 7.0 * hidden) * itemsize
        + (8.0 * hidden * hidden + 7.0 * hidden) * 4.0   # f32 grads out
    )
    return {"flops": flops, "bwd_flops": bwd_flops,
            "bytes_accessed": bytes_accessed,
            "bwd_bytes_accessed": bwd_bytes_accessed,
            "flops_unfused_hbm_bytes": (
                # What the unfused chain moves: msg, agg and the six gate
                # pre-activations all round-trip [n, hidden] through HBM.
                bytes_accessed + 9.0 * n * hidden * itemsize)}

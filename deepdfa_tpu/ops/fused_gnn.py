"""Fused FlowGNN megakernel: gather + band SpMM + GRU gate in ONE Pallas pass.

The unfused GatedGraphStep is a chain of separate dispatches — edge-message
dense (``h @ W_e``), the band/tile SpMM aggregate, then six GRU gate matmuls
— and every link round-trips its [max_nodes, H] intermediate through HBM.
At the published shape the step is HBM-bound (roofline verdict, PR 7
observatory): the MXU waits on loads, and the f32/tile paths sit at ~55% of
the bf16 band flagship. This module fuses the whole per-graph-step compute
into one ``pallas_call`` per direction:

- **Forward** (:func:`_fwd_kernel`): a sequential grid over node row tiles.
  Step ``i`` computes the edge-message tile ``msg[i] = h[i] @ W_e + b_e``
  into a rolling VMEM window of ``2B+1`` tiles (B = band bandwidth), then —
  once the window covers row ``r = i - B`` — aggregates
  ``agg[r] = Σ_d A[d, r] @ msg[r+d-B]`` and applies the full GRU gate update
  in-register, writing ``h'[r]`` straight out. HBM sees each ``h`` tile
  twice (message + carry reads) and each ``h'`` tile once; ``msg``/``agg``
  and every gate pre-activation never leave VMEM. The grid runs ``T + B``
  steps so the window warm-up costs B extra tiles, not a prologue branch;
  Pallas's block pipeline double-buffers the next tile's HBM→VMEM DMAs
  under the current tile's MXU work.
- **Backward** (:func:`_bwd_kernel`): the same rolling-window structure with
  two extra phase offsets — step ``i`` recomputes ``msg[i]``, runs the gate
  backward at row ``r = i - B`` (holding ``d agg`` and the local carry
  cotangent in windows), and completes ``d msg[c] = Σ Aᵀ[c] d agg`` plus
  ``d h[c]`` at ``c = i - 2B``. Weight gradients accumulate in f32 output
  blocks that stay VMEM-resident across the whole grid (constant index
  maps) and flush once. Gradients therefore need no [nodes, H]
  intermediates in HBM either — the unfused backward materializes five.

**Dense-slot packing** (``graphs/batch.py slot_pack=True``) feeds the
kernel: binning each CPG into a fixed node slot from the ``select_bucket``
ladder keeps every graph inside (at most) adjacent row tiles, collapsing
the band bandwidth — and with it the window size, the warm-up, and the
zero-padded off-diagonal FLOPs — before the kernel ever sees the batch.

**Fallback contract**: ``impl="xla"`` (the CPU/tier-1 path, and what
``auto`` resolves to off-TPU) is :func:`fused_reference` — math-for-math
the flax ``Dense`` + ``band_spmm`` + ``GRUCell`` composition, so the fused
flag degrades to the *bitwise* band path where Pallas is unavailable;
``models/flowgnn.py`` routes its fallback through the very same flax
modules, which is what the gradient-parity acceptance test pins.
``impl="interpret"`` runs the real kernels on the Pallas interpreter (the
tier-1 numerics tests). Never pin ``interpret=True`` on an importable
path — graftlint GL016 exists because that ships a silent ~100× slowdown.

XLA's ``cost_analysis`` cannot see inside a Pallas custom call, so
:func:`fused_step_cost` provides the analytic FLOPs/bytes accounting that
``telemetry/costmodel.capture_compiled(extra_flops=..., extra_bytes=...)``
folds into the roofline report.

**Persistent K-step unroll** (ISSUE 15): the paper's FlowGNN applies the
gated step K times with shared weights (``models/flowgnn.py``'s scan), so
even with the fused step the hidden state ``h`` still round-trips HBM
2×K times per direction — the dominant term in the step's byte budget
once the per-step intermediates are fused away. :func:`persistent_unroll`
collapses the whole unroll into ONE ``pallas_call`` per direction:

- **Forward** (:func:`_persist_fwd_kernel`): grid ``(K, T+B)``. ``h``
  lives in the constant-index-map output block — VMEM-resident across
  the entire grid, updated in place (row ``r`` is read for the last time
  as the GRU carry at inner step ``r+B``, exactly when it is overwritten;
  the next outer step's message read of row ``r`` happens strictly
  later). The rolling (2B+1)-tile message window restarts per outer step.
  HBM sees ``h_0`` once in (streamed during outer step 0 and copied
  through into the resident block) and ``h_K`` once out (the constant
  block's single end-of-grid flush) instead of 2×K tile round-trips.
- **Backward**: residuals stay ``(params, h_0, adj)``. A hist-recompute
  sweep (the same forward kernel with ``emit_hist``) re-runs the step
  chain and streams ``h_1..h_{K-1}`` out, then ONE reverse-sweep kernel
  (:func:`_persist_bwd_kernel`, grid ``(K, T+2B)``) walks steps
  ``s = K-1..0`` with the same two extra phase offsets as the single-step
  backward. The inter-step cotangent lives in the VMEM-resident ``dh``
  output block (written at phase 3 of step ``s``, read as the incoming
  cotangent at phase 2 of step ``s-1`` — never both for the same row in
  the same inner step). Weight grads accumulate per step into f32 VMEM
  scratch (zeroed at each row start) and fold into constant-index f32
  output blocks at row end, flushed once across all K steps — the same
  left-fold-over-descending-steps association as ``lax.scan``'s VJP
  carry, which is what makes the grads BITWISE equal to the
  scan-of-fused-step oracle.

``K == 1`` degenerates to the single-step kernels (:func:`_fused_pallas`)
— same program, no persistent machinery. :func:`persistent_unroll_cost`
extends the analytic accounting to the K-step program with per-step vs
amortized byte columns.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, Mapping, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepdfa_tpu.ops.band_spmm import BandAdjacency, band_spmm


def resolve_impl(impl: str = "auto") -> str:
    """Resolve the fused dispatch: "pallas" | "interpret" | "xla".

    ``auto`` honours ``DEEPDFA_FUSED_IMPL`` (the test/debug override),
    then picks the compiled kernel on TPU and the XLA reference
    elsewhere — the same backend gate as pool_impl/embed_impl.
    """
    if impl == "auto":
        impl = os.environ.get("DEEPDFA_FUSED_IMPL", "auto")
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl not in ("pallas", "interpret", "xla"):
        raise ValueError(f"unknown fused impl {impl!r}")
    return impl


# ---------------------------------------------------------------------------
# Parameter declaration (the flax tree the unfused modules own)
# ---------------------------------------------------------------------------
#
# The fused kernel consumes raw weight arrays, but the param TREE must stay
# byte-identical to nn.Dense(name="edge_linear") + nn.GRUCell(name="gru") —
# checkpoints restore across message_impl flips, and the serving layer
# restores params target-free. Flax derives each param's init RNG from its
# scope path, so declaring the same names/shapes/inits at the same paths
# yields the identical tree (pinned by tests/test_fused_gnn.py).


class _DenseParams(nn.Module):
    """Declares ``{kernel[, bias]}`` exactly as ``nn.Dense`` would."""

    features: int
    in_features: int
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self) -> Dict[str, jnp.ndarray]:
        out = {"kernel": self.param(
            "kernel", self.kernel_init, (self.in_features, self.features))}
        if self.use_bias:
            out["bias"] = self.param(
                "bias", nn.initializers.zeros_init(), (self.features,))
        return out


class _GRUParams(nn.Module):
    """Declares the six gate kernels exactly as ``nn.GRUCell`` would
    (input gates: lecun_normal + bias; recurrent gates: orthogonal,
    bias only on ``hn``)."""

    hidden: int

    @nn.compact
    def __call__(self) -> Dict[str, Dict[str, jnp.ndarray]]:
        lecun = nn.initializers.lecun_normal()
        orth = nn.initializers.orthogonal()
        spec = (
            ("ir", True, lecun), ("iz", True, lecun), ("in", True, lecun),
            ("hr", False, orth), ("hz", False, orth), ("hn", True, orth),
        )
        return {
            name: _DenseParams(self.hidden, self.hidden, use_bias=bias,
                               kernel_init=init, name=name)()
            for name, bias, init in spec
        }


def declare_step_params(hidden: int, in_features: int
                        ) -> Dict[str, Any]:
    """Instantiate inside a compact module: declares (and returns) the
    GatedGraphStep param tree under the canonical ``edge_linear``/``gru``
    child scopes."""
    return {
        "edge_linear": _DenseParams(hidden, in_features,
                                    name="edge_linear")(),
        "gru": _GRUParams(hidden, name="gru")(),
    }


# ---------------------------------------------------------------------------
# XLA reference (the CPU fallback and numerics oracle)
# ---------------------------------------------------------------------------


def _dense_apply(p: Mapping[str, jnp.ndarray], x: jnp.ndarray,
                 dt) -> jnp.ndarray:
    """``nn.Dense.__call__`` math, op for op (promote to ``dt``, dot,
    reshape-broadcast bias add)."""
    y = jax.lax.dot_general(
        x, p["kernel"].astype(dt), (((x.ndim - 1,), (0,)), ((), ())))
    if "bias" in p:
        y = y + jnp.reshape(p["bias"].astype(dt),
                            (1,) * (y.ndim - 1) + (-1,))
    return y


def fused_reference(params: Mapping, h: jnp.ndarray,
                    adj: BandAdjacency) -> jnp.ndarray:
    """The unfused composition with the fused op's signature: flax-Dense
    edge message → ``band_spmm`` aggregate → flax-GRUCell gate, in the
    model's compute dtype. This IS the ``impl="xla"`` path, and the
    program the interpret/pallas kernels are tested against."""
    dt = h.dtype
    msg = _dense_apply(params["edge_linear"], h.astype(dt), dt)
    agg = band_spmm(adj, msg)
    g = params["gru"]
    x, hc = agg.astype(dt), h.astype(dt)
    r = nn.sigmoid(_dense_apply(g["ir"], x, dt) + _dense_apply(g["hr"], hc, dt))
    z = nn.sigmoid(_dense_apply(g["iz"], x, dt) + _dense_apply(g["hz"], hc, dt))
    n = nn.tanh(_dense_apply(g["in"], x, dt)
                + r * _dense_apply(g["hn"], hc, dt))
    return (1.0 - z) * n + z * hc


# ---------------------------------------------------------------------------
# Packed weights (one [H, 3H] matmul per gate family inside the kernel)
# ---------------------------------------------------------------------------


def _precision(dt) -> jax.lax.Precision:
    # The band_spmm/tile_spmm rule: f32 keeps HIGHEST so the kernel stays
    # comparable with the unfused oracle; bf16 rides the native MXU path.
    return (jax.lax.Precision.HIGHEST if jnp.dtype(dt) == jnp.float32
            else jax.lax.Precision.DEFAULT)


def _packed_weights(params: Mapping, dt):
    """(ek, eb, wi, bi, wh, bh): edge weights plus the r|z|n gate kernels
    concatenated on the output axis — three dots become one MXU pass; the
    recurrent bias vector packs ``[0, 0, b_hn]`` so the hn-only bias rides
    the same add."""
    g = params["gru"]
    h = g["ir"]["kernel"].shape[0]
    ek = params["edge_linear"]["kernel"].astype(dt)
    eb = params["edge_linear"]["bias"].astype(dt).reshape(1, -1)
    wi = jnp.concatenate(
        [g["ir"]["kernel"], g["iz"]["kernel"], g["in"]["kernel"]],
        axis=1).astype(dt)
    bi = jnp.concatenate(
        [g["ir"]["bias"], g["iz"]["bias"], g["in"]["bias"]]
    ).astype(dt).reshape(1, -1)
    wh = jnp.concatenate(
        [g["hr"]["kernel"], g["hz"]["kernel"], g["hn"]["kernel"]],
        axis=1).astype(dt)
    bh = jnp.concatenate(
        [jnp.zeros((2 * h,), dt), g["hn"]["bias"].astype(dt)]
    ).reshape(1, -1)
    return ek, eb, wi, bi, wh, bh


def _vals_compute(adj: BandAdjacency, dt):
    """(vals, message dtype) under the upcast-only rule: f32 adjacency
    values (a multiplicity not bf16-exact) force f32 messages; otherwise
    the adjacency rides the model dtype."""
    vals = adj.vals
    if vals.dtype == jnp.float32 and jnp.dtype(dt) != jnp.float32:
        return vals, jnp.float32
    return vals.astype(dt), jnp.dtype(dt)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(vals_ref, hmsg_ref, hc_ref, ek_ref, eb_ref, wi_ref, bi_ref,
                wh_ref, bh_ref, out_ref, msg_win, *, n_tiles, bandwidth,
                hidden, dt, mdt):
    i = pl.program_id(0)
    b, w = bandwidth, 2 * bandwidth + 1
    prec = _precision(mdt)

    # Phase 1: edge-message tile i into the rolling window. The dense-slot
    # packed batch keeps b tiny, so the window (and its warm-up) is small.
    @pl.when(i < n_tiles)
    def _msg():
        m = jnp.dot(hmsg_ref[:].astype(mdt), ek_ref[:].astype(mdt),
                    preferred_element_type=jnp.float32, precision=prec)
        msg_win[i % w] = (m.astype(mdt)
                          + eb_ref[:].astype(mdt))

    # Phase 2: aggregate + GRU gate for row r = i - b, entirely in VMEM.
    @pl.when(i >= b)
    def _gate():
        r = i - b
        agg = jnp.zeros((hmsg_ref.shape[0], hidden), jnp.float32)
        for d in range(w):
            j = r + d - b
            contrib = jnp.dot(
                vals_ref[d, 0].astype(mdt), msg_win[j % w],
                preferred_element_type=jnp.float32, precision=prec)
            # Off-range sender tiles hold zero adjacency blocks, but the
            # window slot may hold uninitialized VMEM (NaN × 0 = NaN) —
            # the mask, not the zero blocks, is what makes padding inert.
            agg = agg + jnp.where((j >= 0) & (j < n_tiles), contrib, 0.0)
        x = agg.astype(dt)
        hc = hc_ref[:]
        gi = jnp.dot(x, wi_ref[:], preferred_element_type=jnp.float32,
                     precision=_precision(dt)).astype(dt) + bi_ref[:]
        gh = jnp.dot(hc, wh_ref[:], preferred_element_type=jnp.float32,
                     precision=_precision(dt)).astype(dt) + bh_ref[:]
        rg = jax.nn.sigmoid(gi[:, :hidden] + gh[:, :hidden])
        zg = jax.nn.sigmoid(gi[:, hidden:2 * hidden]
                            + gh[:, hidden:2 * hidden])
        ng = jnp.tanh(gi[:, 2 * hidden:] + rg * gh[:, 2 * hidden:])
        out_ref[:] = ((1.0 - zg) * ng + zg * hc).astype(out_ref.dtype)


def _run_fwd(params, h, adj: BandAdjacency, interpret: bool) -> jnp.ndarray:
    dt = h.dtype
    t, nt, b = adj.tile, adj.n_tiles, adj.bandwidth
    w = 2 * b + 1
    hidden = h.shape[1]
    vals, mdt = _vals_compute(adj, dt)
    ek, eb, wi, bi, wh, bh = _packed_weights(params, dt)

    kernel = functools.partial(
        _fwd_kernel, n_tiles=nt, bandwidth=b, hidden=hidden, dt=dt, mdt=mdt)
    const = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    out = pl.pallas_call(
        kernel,
        grid=(nt + b,),
        in_specs=[
            pl.BlockSpec((w, 1, t, t),
                         lambda i: (0, jnp.maximum(i - b, 0), 0, 0)),
            pl.BlockSpec((t, hidden), lambda i: (jnp.minimum(i, nt - 1), 0)),
            pl.BlockSpec((t, hidden), lambda i: (jnp.maximum(i - b, 0), 0)),
            const((hidden, hidden)), const((1, hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
        ],
        out_specs=pl.BlockSpec((t, hidden),
                               lambda i: (jnp.maximum(i - b, 0), 0)),
        out_shape=jax.ShapeDtypeStruct((nt * t, hidden), dt),
        scratch_shapes=[pltpu.VMEM((w, t, hidden), mdt)],
        interpret=interpret,
    )(vals, h, h, ek, eb, wi, bi, wh, bh)
    return out


# ---------------------------------------------------------------------------
# Backward kernel
# ---------------------------------------------------------------------------


def band_transpose_vals(vals: jnp.ndarray, bandwidth: int,
                        n_tiles: int) -> jnp.ndarray:
    """The block-band form of Aᵀ from the block-band form of A:
    ``t_vals[e, c] = vals[2B-e, c+e-B]ᵀ`` (zero where the source row tile
    falls outside the batch) — the same pad/slice idiom as band_spmm's
    shifted message tiles, plus a per-block transpose."""
    w = 2 * bandwidth + 1
    outs = []
    for e in range(w):
        src = vals[w - 1 - e]
        shift = e - bandwidth
        padded = jnp.pad(src, ((bandwidth, bandwidth), (0, 0), (0, 0)))
        sl = jax.lax.slice_in_dim(
            padded, shift + bandwidth, shift + bandwidth + n_tiles, axis=0)
        outs.append(jnp.swapaxes(sl, 1, 2))
    return jnp.stack(outs)


def _bwd_kernel(vals_ref, tvals_ref, hmsg_ref, hc_ref, hdwe_ref, g_ref,
                ek_ref, eb_ref, wi_ref, bi_ref, wh_ref, bh_ref,
                dh_ref, dek_ref, deb_ref, dwi_ref, dbi_ref, dwh_ref, dbh_ref,
                msg_win, dx_win, dhl_win, *, n_tiles, bandwidth, hidden,
                dt, mdt):
    i = pl.program_id(0)
    b, w = bandwidth, 2 * bandwidth + 1
    prec = _precision(mdt)
    pdt = _precision(dt)

    # Weight-grad accumulators live in the (VMEM-resident, constant-index)
    # output blocks; zero them exactly once, before any accumulation.
    @pl.when(i == 0)
    def _zero():
        for ref in (dek_ref, deb_ref, dwi_ref, dbi_ref, dwh_ref, dbh_ref):
            ref[:] = jnp.zeros_like(ref)

    # Phase 1: recompute edge-message tile i (the remat of the fused op —
    # nothing but h is saved as residual).
    @pl.when(i < n_tiles)
    def _msg():
        m = jnp.dot(hmsg_ref[:].astype(mdt), ek_ref[:].astype(mdt),
                    preferred_element_type=jnp.float32, precision=prec)
        msg_win[i % w] = m.astype(mdt) + eb_ref[:].astype(mdt)

    # Phase 2: gate backward at row r = i - b — recompute the forward
    # gates, then push the output cotangent through them. d agg and the
    # local carry cotangent land in rolling windows for phase 3.
    @pl.when((i >= b) & (i < n_tiles + b))
    def _gate_bwd():
        r = i - b
        agg = jnp.zeros((hmsg_ref.shape[0], hidden), jnp.float32)
        for d in range(w):
            j = r + d - b
            contrib = jnp.dot(
                vals_ref[d, 0].astype(mdt), msg_win[j % w],
                preferred_element_type=jnp.float32, precision=prec)
            agg = agg + jnp.where((j >= 0) & (j < n_tiles), contrib, 0.0)
        x = agg.astype(dt)
        hc = hc_ref[:]
        gi = jnp.dot(x, wi_ref[:], preferred_element_type=jnp.float32,
                     precision=pdt).astype(dt) + bi_ref[:]
        gh = jnp.dot(hc, wh_ref[:], preferred_element_type=jnp.float32,
                     precision=pdt).astype(dt) + bh_ref[:]
        rg = jax.nn.sigmoid(gi[:, :hidden] + gh[:, :hidden])
        zg = jax.nn.sigmoid(gi[:, hidden:2 * hidden]
                            + gh[:, hidden:2 * hidden])
        pre_hn = gh[:, 2 * hidden:]
        ng = jnp.tanh(gi[:, 2 * hidden:] + rg * pre_hn)

        g32 = g_ref[:].astype(jnp.float32)
        hc32 = hc.astype(jnp.float32)
        rg32, zg32, ng32 = (rg.astype(jnp.float32), zg.astype(jnp.float32),
                            ng.astype(jnp.float32))
        dz = g32 * (hc32 - ng32)
        dn = g32 * (1.0 - zg32)
        dhc = g32 * zg32
        dpre_n = dn * (1.0 - ng32 * ng32)
        drg = dpre_n * pre_hn.astype(jnp.float32)
        dpre_hn = dpre_n * rg32
        dpre_r = drg * rg32 * (1.0 - rg32)
        dpre_z = dz * zg32 * (1.0 - zg32)
        dpre_i = jnp.concatenate([dpre_r, dpre_z, dpre_n], axis=1)
        dpre_h = jnp.concatenate([dpre_r, dpre_z, dpre_hn], axis=1)

        dpre_i_c = dpre_i.astype(dt)
        dpre_h_c = dpre_h.astype(dt)
        # d agg = dpre_i @ Wiᵀ — contract the gate axis against Wi's.
        dx = jax.lax.dot_general(
            dpre_i_c, wi_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dx_win[r % w] = dx.astype(mdt)
        dhl = dhc + jax.lax.dot_general(
            dpre_h_c, wh_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dhl_win[r % w] = dhl

        # Gate weight grads: contract the node-tile axis, accumulate f32.
        dwi_ref[:] += jax.lax.dot_general(
            x, dpre_i_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dbi_ref[:] += jnp.sum(dpre_i, axis=0, keepdims=True)
        dwh_ref[:] += jax.lax.dot_general(
            hc, dpre_h_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dbh_ref[:] += jnp.sum(dpre_h, axis=0, keepdims=True)

    # Phase 3: d msg[c] = Σ Aᵀ[c] · d agg, then the edge weights' grads and
    # the total d h[c] — the dx window now covers c ± b.
    @pl.when(i >= 2 * b)
    def _dmsg():
        c = i - 2 * b
        dmsg = jnp.zeros((hmsg_ref.shape[0], hidden), jnp.float32)
        for e in range(w):
            j = c + e - b
            contrib = jnp.dot(
                tvals_ref[e, 0].astype(mdt), dx_win[j % w],
                preferred_element_type=jnp.float32, precision=prec)
            dmsg = dmsg + jnp.where((j >= 0) & (j < n_tiles), contrib, 0.0)
        dmsg_c = dmsg.astype(mdt)
        dek_ref[:] += jax.lax.dot_general(
            hdwe_ref[:].astype(mdt), dmsg_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        deb_ref[:] += jnp.sum(dmsg, axis=0, keepdims=True)
        dh_from_msg = jax.lax.dot_general(
            dmsg_c, ek_ref[:].astype(mdt), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dh_ref[:] = (dhl_win[c % w] + dh_from_msg).astype(dh_ref.dtype)


def _run_bwd(params, h, adj: BandAdjacency, g: jnp.ndarray,
             interpret: bool):
    dt = h.dtype
    t, nt, b = adj.tile, adj.n_tiles, adj.bandwidth
    w = 2 * b + 1
    hidden = h.shape[1]
    vals, mdt = _vals_compute(adj, dt)
    tvals = band_transpose_vals(vals, b, nt)
    ek, eb, wi, bi, wh, bh = _packed_weights(params, dt)

    kernel = functools.partial(
        _bwd_kernel, n_tiles=nt, bandwidth=b, hidden=hidden, dt=dt, mdt=mdt)
    const = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    f32 = jnp.float32
    band_blk = pl.BlockSpec((w, 1, t, t),
                            lambda i: (0, jnp.maximum(i - b, 0), 0, 0))
    tband_blk = pl.BlockSpec((w, 1, t, t),
                             lambda i: (0, jnp.maximum(i - 2 * b, 0), 0, 0))
    row = lambda off: pl.BlockSpec(
        (t, hidden),
        lambda i, off=off: (jnp.clip(i - off, 0, nt - 1), 0))
    dh, dek, deb, dwi, dbi, dwh, dbh = pl.pallas_call(
        kernel,
        grid=(nt + 2 * b,),
        in_specs=[
            band_blk, tband_blk,
            row(0),        # h for the message recompute
            row(b),        # h as the GRU carry
            row(2 * b),    # h against d msg for dW_e
            row(b),        # output cotangent at the gate row
            const((hidden, hidden)), const((1, hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
        ],
        out_specs=(
            pl.BlockSpec((t, hidden),
                         lambda i: (jnp.maximum(i - 2 * b, 0), 0)),
            const((hidden, hidden)), const((1, hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((nt * t, hidden), dt),
            jax.ShapeDtypeStruct((hidden, hidden), f32),
            jax.ShapeDtypeStruct((1, hidden), f32),
            jax.ShapeDtypeStruct((hidden, 3 * hidden), f32),
            jax.ShapeDtypeStruct((1, 3 * hidden), f32),
            jax.ShapeDtypeStruct((hidden, 3 * hidden), f32),
            jax.ShapeDtypeStruct((1, 3 * hidden), f32),
        ),
        scratch_shapes=[
            pltpu.VMEM((w, t, hidden), mdt),   # msg window
            pltpu.VMEM((w, t, hidden), mdt),   # d agg window
            pltpu.VMEM((w, t, hidden), f32),   # local d h window
        ],
        interpret=interpret,
    )(vals, tvals, h, h, h, g, ek, eb, wi, bi, wh, bh)
    return dh, dek, deb, dwi, dbi, dwh, dbh


def _unpack_grads(params, dek, deb, dwi, dbi, dwh, dbh):
    """Packed kernel-space gradients back to the flax param tree, in the
    params' own (f32 storage) dtypes."""
    h = params["gru"]["ir"]["kernel"].shape[0]

    def like(ref, val):
        return val.astype(ref.dtype)

    g = params["gru"]
    sl = lambda a, k: a[:, k * h:(k + 1) * h]
    out = {
        "edge_linear": {
            "kernel": like(params["edge_linear"]["kernel"], dek),
            "bias": like(params["edge_linear"]["bias"], deb[0]),
        },
        "gru": {
            "ir": {"kernel": like(g["ir"]["kernel"], sl(dwi, 0)),
                   "bias": like(g["ir"]["bias"], sl(dbi, 0)[0])},
            "iz": {"kernel": like(g["iz"]["kernel"], sl(dwi, 1)),
                   "bias": like(g["iz"]["bias"], sl(dbi, 1)[0])},
            "in": {"kernel": like(g["in"]["kernel"], sl(dwi, 2)),
                   "bias": like(g["in"]["bias"], sl(dbi, 2)[0])},
            "hr": {"kernel": like(g["hr"]["kernel"], sl(dwh, 0))},
            "hz": {"kernel": like(g["hz"]["kernel"], sl(dwh, 1))},
            "hn": {"kernel": like(g["hn"]["kernel"], sl(dwh, 2)),
                   "bias": like(g["hn"]["bias"], sl(dbh, 2)[0])},
        },
    }
    return out


# ---------------------------------------------------------------------------
# The differentiable fused op
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_pallas(params, h, adj: BandAdjacency,
                  interpret: bool) -> jnp.ndarray:
    return _run_fwd(params, h, adj, interpret)


def _fused_fwd(params, h, adj, interpret):
    # Residuals: params + h + the structural adjacency — no activations.
    # The backward kernel recomputes messages/gates tile by tile (the
    # in-kernel remat), so the fused step saves nothing [nodes, H]-sized.
    return _run_fwd(params, h, adj, interpret), (params, h, adj)


def _fused_bwd(interpret, res, g):
    params, h, adj = res
    dh, dek, deb, dwi, dbi, dwh, dbh = _run_bwd(params, h, adj, g, interpret)
    dparams = _unpack_grads(params, dek, deb, dwi, dbi, dwh, dbh)
    dadj = jax.tree_util.tree_map(jnp.zeros_like, adj)  # structural
    return dparams, dh, dadj


_fused_pallas.defvjp(_fused_fwd, _fused_bwd)


def fused_gate_step(params: Mapping, h: jnp.ndarray, adj: BandAdjacency,
                    impl: str = "auto") -> jnp.ndarray:
    """One fused gated graph step: ``h' = GRU(A @ (h W_e + b_e), h)``.

    ``params``: the flax GatedGraphStep subtree (``edge_linear`` +
    ``gru/{ir,iz,in,hr,hz,hn}``). ``impl``: "pallas" (the TPU megakernel)
    | "interpret" (same kernels on the Pallas interpreter — tests) |
    "xla" (the unfused reference composition — the CPU/tier-1 fallback)
    | "auto". Differentiable in ``params`` and ``h``; the adjacency is
    structural.
    """
    impl = resolve_impl(impl)
    if impl == "xla":
        return fused_reference(params, h, adj)
    if adj.vals.ndim != 4:
        raise ValueError(
            "fused kernel takes one shard's band adjacency (vals "
            f"[2B+1, T, t, t]); got ndim={adj.vals.ndim} — sharded batches "
            "dispatch through the band fallback (models/flowgnn.py)")
    return _fused_pallas(params, h, adj, impl == "interpret")


# ---------------------------------------------------------------------------
# Persistent K-step unroll: h VMEM-resident across the whole message pass
# ---------------------------------------------------------------------------


def _persist_fwd_kernel(vals_ref, h0_ref, ek_ref, eb_ref, wi_ref, bi_ref,
                        wh_ref, bh_ref, *refs, n_tiles, bandwidth, hidden,
                        dt, mdt, emit_hist):
    """K gated steps in one grid ``(K, T+B)``.

    ``hbuf`` — the resident ``h`` — is the constant-index output block in
    the plain forward (flushed once, as ``h_K``) and a VMEM scratch in the
    ``emit_hist`` variant (where the streamed ``hist`` output carries each
    step's ``h_{k+1}`` instead). The per-step math is copied from
    :func:`_fwd_kernel` op for op — the persistent program must stay
    bitwise equal to iterating the single-step kernel.
    """
    if emit_hist:
        hist_ref, hbuf, msg_win = refs
    else:
        hbuf, msg_win = refs
        hist_ref = None
    k = pl.program_id(0)
    i = pl.program_id(1)
    b, w = bandwidth, 2 * bandwidth + 1
    prec = _precision(mdt)

    # Phase 1: edge-message tile i of outer step k into the rolling
    # window. Step 0 reads the streamed h_0 block (and copies it through
    # into the resident buffer, so the carry read below never touches
    # HBM); later steps read the resident buffer in place.
    @pl.when(i < n_tiles)
    def _msg():
        it = jnp.minimum(i, n_tiles - 1)
        src = jnp.where(k == 0, h0_ref[:], hbuf[it])

        @pl.when(k == 0)
        def _seed():
            hbuf[it] = h0_ref[:]

        m = jnp.dot(src.astype(mdt), ek_ref[:].astype(mdt),
                    preferred_element_type=jnp.float32, precision=prec)
        msg_win[i % w] = m.astype(mdt) + eb_ref[:].astype(mdt)

    # Phase 2: aggregate + GRU gate for row r = i - b. The carry read and
    # the in-place overwrite of hbuf[r] happen in this same inner step —
    # no later phase of this or any following outer step reads h_k[r].
    @pl.when(i >= b)
    def _gate():
        r = i - b
        agg = jnp.zeros((h0_ref.shape[0], hidden), jnp.float32)
        for d in range(w):
            j = r + d - b
            contrib = jnp.dot(
                vals_ref[d, 0].astype(mdt), msg_win[j % w],
                preferred_element_type=jnp.float32, precision=prec)
            agg = agg + jnp.where((j >= 0) & (j < n_tiles), contrib, 0.0)
        x = agg.astype(dt)
        hc = hbuf[r]
        gi = jnp.dot(x, wi_ref[:], preferred_element_type=jnp.float32,
                     precision=_precision(dt)).astype(dt) + bi_ref[:]
        gh = jnp.dot(hc, wh_ref[:], preferred_element_type=jnp.float32,
                     precision=_precision(dt)).astype(dt) + bh_ref[:]
        rg = jax.nn.sigmoid(gi[:, :hidden] + gh[:, :hidden])
        zg = jax.nn.sigmoid(gi[:, hidden:2 * hidden]
                            + gh[:, hidden:2 * hidden])
        ng = jnp.tanh(gi[:, 2 * hidden:] + rg * gh[:, 2 * hidden:])
        new_h = ((1.0 - zg) * ng + zg * hc).astype(dt)
        hbuf[r] = new_h
        if emit_hist:
            hist_ref[0, 0] = new_h


def _run_persistent_fwd(params, h, adj: BandAdjacency, n_steps: int,
                        interpret: bool, emit_hist: bool = False):
    """The persistent forward. ``emit_hist=True`` is the backward's
    recompute sweep: runs ``n_steps - 1`` outer steps and streams each
    step's output ``h_1..h_{K-1}`` (the inputs of steps ``1..K-1``) to
    HBM instead of producing ``h_K``."""
    dt = h.dtype
    t, nt, b = adj.tile, adj.n_tiles, adj.bandwidth
    w = 2 * b + 1
    hidden = h.shape[1]
    vals, mdt = _vals_compute(adj, dt)
    ek, eb, wi, bi, wh, bh = _packed_weights(params, dt)
    rows = n_steps - 1 if emit_hist else n_steps

    kernel = functools.partial(
        _persist_fwd_kernel, n_tiles=nt, bandwidth=b, hidden=hidden,
        dt=dt, mdt=mdt, emit_hist=emit_hist)
    const = lambda shape: pl.BlockSpec(shape, lambda k, i: (0,) * len(shape))
    in_specs = [
        pl.BlockSpec((w, 1, t, t),
                     lambda k, i: (0, jnp.clip(i - b, 0, nt - 1), 0, 0)),
        # h_0 streams during outer step 0 only; afterwards the map parks
        # on its last block so the pipeline never re-fetches it — HBM
        # sees h exactly once on the way in.
        pl.BlockSpec((t, hidden),
                     lambda k, i: (jnp.where(k == 0,
                                             jnp.minimum(i, nt - 1),
                                             nt - 1), 0)),
        const((hidden, hidden)), const((1, hidden)),
        const((hidden, 3 * hidden)), const((1, 3 * hidden)),
        const((hidden, 3 * hidden)), const((1, 3 * hidden)),
    ]
    if emit_hist:
        out_specs = pl.BlockSpec(
            (1, 1, t, hidden),
            lambda k, i: (k, jnp.clip(i - b, 0, nt - 1), 0, 0))
        out_shape = jax.ShapeDtypeStruct((rows, nt, t, hidden), dt)
        scratch = [pltpu.VMEM((nt, t, hidden), dt),
                   pltpu.VMEM((w, t, hidden), mdt)]
    else:
        # The resident h IS the output: constant index map = one VMEM
        # block for the whole grid, one flush (h_K) at the end.
        out_specs = pl.BlockSpec((nt, t, hidden), lambda k, i: (0, 0, 0))
        out_shape = jax.ShapeDtypeStruct((nt, t, hidden), dt)
        scratch = [pltpu.VMEM((w, t, hidden), mdt)]
    out = pl.pallas_call(
        kernel,
        grid=(rows, nt + b),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(vals, h, ek, eb, wi, bi, wh, bh)
    return out if emit_hist else out.reshape(nt * t, hidden)


def _persist_bwd_kernel(vals_ref, tvals_ref, h0_ref, hist_ref, g_ref,
                        ek_ref, eb_ref, wi_ref, bi_ref, wh_ref, bh_ref,
                        dh_ref, dek_ref, deb_ref, dwi_ref, dbi_ref,
                        dwh_ref, dbh_ref,
                        hwin, msg_win, dx_win, dhl_win,
                        sek, seb, swi, sbi, swh, sbh, *,
                        n_steps, n_tiles, bandwidth, hidden, dt, mdt):
    """The reverse sweep: grid row ``j`` runs the backward of step
    ``s = K-1-j`` with the single-step kernel's three-phase machinery.

    The incoming cotangent for step ``s`` is the user cotangent on row 0
    and otherwise the VMEM-resident ``dh_ref`` block — written by the
    previous row's phase 3, read here at phase 2 (row ``r`` is read at
    inner step ``r+B`` and overwritten at ``r+2B``, so the in-place flow
    is ordered). ``h_s`` tiles stream once per row into a rolling window
    that serves all three phase offsets. Per-step weight-grad partial
    sums (``s*`` scratch) fold into the f32 totals at row end — the
    scan-VJP association, which is what keeps grads bitwise equal to the
    scan-of-fused-step oracle.
    """
    j = pl.program_id(0)
    i = pl.program_id(1)
    b, w = bandwidth, 2 * bandwidth + 1
    prec = _precision(mdt)
    pdt = _precision(dt)
    last_row = j == n_steps - 1  # s == 0: h_s comes from the residual h_0

    @pl.when((j == 0) & (i == 0))
    def _zero_totals():
        for ref in (dek_ref, deb_ref, dwi_ref, dbi_ref, dwh_ref, dbh_ref):
            ref[:] = jnp.zeros_like(ref)

    @pl.when(i == 0)
    def _zero_step():
        for ref in (sek, seb, swi, sbi, swh, sbh):
            ref[:] = jnp.zeros_like(ref)

    # Phase 1: stream h_s tile i into the h window and recompute the
    # edge-message tile (the in-kernel remat — residuals stay params,
    # h_0, adj; h_1..h_{K-1} come from the recompute sweep's hist).
    @pl.when(i < n_tiles)
    def _msg():
        src = jnp.where(last_row, h0_ref[:], hist_ref[0, 0])
        hwin[i % w] = src
        m = jnp.dot(src.astype(mdt), ek_ref[:].astype(mdt),
                    preferred_element_type=jnp.float32, precision=prec)
        msg_win[i % w] = m.astype(mdt) + eb_ref[:].astype(mdt)

    # Phase 2: gate backward at row r = i - b — recompute the forward
    # gates for step s, then push this step's cotangent through them.
    @pl.when((i >= b) & (i < n_tiles + b))
    def _gate_bwd():
        r = i - b
        agg = jnp.zeros((h0_ref.shape[0], hidden), jnp.float32)
        for d in range(w):
            jj = r + d - b
            contrib = jnp.dot(
                vals_ref[d, 0].astype(mdt), msg_win[jj % w],
                preferred_element_type=jnp.float32, precision=prec)
            agg = agg + jnp.where((jj >= 0) & (jj < n_tiles), contrib, 0.0)
        x = agg.astype(dt)
        hc = hwin[r % w]
        gi = jnp.dot(x, wi_ref[:], preferred_element_type=jnp.float32,
                     precision=pdt).astype(dt) + bi_ref[:]
        gh = jnp.dot(hc, wh_ref[:], preferred_element_type=jnp.float32,
                     precision=pdt).astype(dt) + bh_ref[:]
        rg = jax.nn.sigmoid(gi[:, :hidden] + gh[:, :hidden])
        zg = jax.nn.sigmoid(gi[:, hidden:2 * hidden]
                            + gh[:, hidden:2 * hidden])
        pre_hn = gh[:, 2 * hidden:]
        ng = jnp.tanh(gi[:, 2 * hidden:] + rg * pre_hn)

        # The cotangent entering step s: the user cotangent on the first
        # row (s = K-1), the resident dh block (step s+1's output
        # cotangent, already cast to the model dtype — the same cast the
        # scan path applies between steps) afterwards.
        gcur = jnp.where(j == 0, g_ref[:], dh_ref[r])
        g32 = gcur.astype(jnp.float32)
        hc32 = hc.astype(jnp.float32)
        rg32, zg32, ng32 = (rg.astype(jnp.float32), zg.astype(jnp.float32),
                            ng.astype(jnp.float32))
        dz = g32 * (hc32 - ng32)
        dn = g32 * (1.0 - zg32)
        dhc = g32 * zg32
        dpre_n = dn * (1.0 - ng32 * ng32)
        drg = dpre_n * pre_hn.astype(jnp.float32)
        dpre_hn = dpre_n * rg32
        dpre_r = drg * rg32 * (1.0 - rg32)
        dpre_z = dz * zg32 * (1.0 - zg32)
        dpre_i = jnp.concatenate([dpre_r, dpre_z, dpre_n], axis=1)
        dpre_h = jnp.concatenate([dpre_r, dpre_z, dpre_hn], axis=1)

        dpre_i_c = dpre_i.astype(dt)
        dpre_h_c = dpre_h.astype(dt)
        dx = jax.lax.dot_general(
            dpre_i_c, wi_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dx_win[r % w] = dx.astype(mdt)
        dhl = dhc + jax.lax.dot_general(
            dpre_h_c, wh_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        dhl_win[r % w] = dhl

        swi[:] += jax.lax.dot_general(
            x, dpre_i_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        sbi[:] += jnp.sum(dpre_i, axis=0, keepdims=True)
        swh[:] += jax.lax.dot_general(
            hc, dpre_h_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=pdt)
        sbh[:] += jnp.sum(dpre_h, axis=0, keepdims=True)

    # Phase 3: d msg[c] = Σ Aᵀ[c] · d agg, the edge-weight grads, and the
    # total d h_s[c] into the resident dh block (step s-1's cotangent).
    @pl.when(i >= 2 * b)
    def _dmsg():
        c = i - 2 * b
        dmsg = jnp.zeros((h0_ref.shape[0], hidden), jnp.float32)
        for e in range(w):
            jj = c + e - b
            contrib = jnp.dot(
                tvals_ref[e, 0].astype(mdt), dx_win[jj % w],
                preferred_element_type=jnp.float32, precision=prec)
            dmsg = dmsg + jnp.where((jj >= 0) & (jj < n_tiles),
                                    contrib, 0.0)
        dmsg_c = dmsg.astype(mdt)
        sek[:] += jax.lax.dot_general(
            hwin[c % w].astype(mdt), dmsg_c, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        seb[:] += jnp.sum(dmsg, axis=0, keepdims=True)
        dh_from_msg = jax.lax.dot_general(
            dmsg_c, ek_ref[:].astype(mdt), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dh_ref[c] = (dhl_win[c % w] + dh_from_msg).astype(dt)

    # Row end: fold this step's partial sums into the totals — the
    # left-fold-over-descending-steps association of the scan VJP.
    @pl.when(i == n_tiles + 2 * b - 1)
    def _fold():
        dek_ref[:] += sek[:]
        deb_ref[:] += seb[:]
        dwi_ref[:] += swi[:]
        dbi_ref[:] += sbi[:]
        dwh_ref[:] += swh[:]
        dbh_ref[:] += sbh[:]


def _run_persistent_bwd(params, h, adj: BandAdjacency, g: jnp.ndarray,
                        n_steps: int, interpret: bool):
    dt = h.dtype
    t, nt, b = adj.tile, adj.n_tiles, adj.bandwidth
    w = 2 * b + 1
    hidden = h.shape[1]
    vals, mdt = _vals_compute(adj, dt)
    tvals = band_transpose_vals(vals, b, nt)
    ek, eb, wi, bi, wh, bh = _packed_weights(params, dt)

    # The recompute sweep: h_1..h_{K-1} from the residual h_0, bitwise
    # the forward's values (same kernel program).
    hist = _run_persistent_fwd(params, h, adj, n_steps, interpret,
                               emit_hist=True)

    kernel = functools.partial(
        _persist_bwd_kernel, n_steps=n_steps, n_tiles=nt, bandwidth=b,
        hidden=hidden, dt=dt, mdt=mdt)
    const = lambda shape: pl.BlockSpec(shape, lambda j, i: (0,) * len(shape))
    f32 = jnp.float32
    dh, dek, deb, dwi, dbi, dwh, dbh = pl.pallas_call(
        kernel,
        grid=(n_steps, nt + 2 * b),
        in_specs=[
            pl.BlockSpec((w, 1, t, t),
                         lambda j, i: (0, jnp.clip(i - b, 0, nt - 1), 0, 0)),
            pl.BlockSpec(
                (w, 1, t, t),
                lambda j, i: (0, jnp.clip(i - 2 * b, 0, nt - 1), 0, 0)),
            # h_0: streamed on the last row (s = 0), parked otherwise.
            pl.BlockSpec(
                (t, hidden),
                lambda j, i: (jnp.where(j == n_steps - 1,
                                        jnp.minimum(i, nt - 1),
                                        nt - 1), 0)),
            # hist: h_s = hist[s-1] for s >= 1; parked on the last row.
            pl.BlockSpec(
                (1, 1, t, hidden),
                lambda j, i: (jnp.where(j < n_steps - 1,
                                        n_steps - 2 - j, 0),
                              jnp.minimum(i, nt - 1), 0, 0)),
            # The user cotangent: streamed on row 0 (s = K-1) only.
            pl.BlockSpec(
                (t, hidden),
                lambda j, i: (jnp.where(j == 0,
                                        jnp.clip(i - b, 0, nt - 1),
                                        nt - 1), 0)),
            const((hidden, hidden)), const((1, hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
        ],
        out_specs=(
            # The inter-step cotangent IS the dh output: VMEM-resident
            # (constant index map), flushed once as dh_0 at grid end.
            pl.BlockSpec((nt, t, hidden), lambda j, i: (0, 0, 0)),
            const((hidden, hidden)), const((1, hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
            const((hidden, 3 * hidden)), const((1, 3 * hidden)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((nt, t, hidden), dt),
            jax.ShapeDtypeStruct((hidden, hidden), f32),
            jax.ShapeDtypeStruct((1, hidden), f32),
            jax.ShapeDtypeStruct((hidden, 3 * hidden), f32),
            jax.ShapeDtypeStruct((1, 3 * hidden), f32),
            jax.ShapeDtypeStruct((hidden, 3 * hidden), f32),
            jax.ShapeDtypeStruct((1, 3 * hidden), f32),
        ),
        scratch_shapes=[
            pltpu.VMEM((w, t, hidden), dt),    # h_s window (3 offsets)
            pltpu.VMEM((w, t, hidden), mdt),   # msg window
            pltpu.VMEM((w, t, hidden), mdt),   # d agg window
            pltpu.VMEM((w, t, hidden), f32),   # local d h window
            pltpu.VMEM((hidden, hidden), f32),        # per-step dW_e
            pltpu.VMEM((1, hidden), f32),             # per-step db_e
            pltpu.VMEM((hidden, 3 * hidden), f32),    # per-step dW_i
            pltpu.VMEM((1, 3 * hidden), f32),         # per-step db_i
            pltpu.VMEM((hidden, 3 * hidden), f32),    # per-step dW_h
            pltpu.VMEM((1, 3 * hidden), f32),         # per-step db_h
        ],
        interpret=interpret,
    )(vals, tvals, h, hist, g, ek, eb, wi, bi, wh, bh)
    return dh.reshape(nt * t, hidden), dek, deb, dwi, dbi, dwh, dbh


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _persistent_pallas(params, h, adj: BandAdjacency, n_steps: int,
                       interpret: bool) -> jnp.ndarray:
    return _run_persistent_fwd(params, h, adj, n_steps, interpret)


def _persistent_vjp_fwd(params, h, adj, n_steps, interpret):
    # Residuals: params + h_0 + the structural adjacency — no per-step
    # activations. The backward re-runs the forward step chain (the
    # recompute sweep) and remats gates tile by tile inside the reverse
    # kernel, so the persistent unroll saves nothing [nodes, H]-sized.
    return _run_persistent_fwd(params, h, adj, n_steps, interpret), (
        params, h, adj)


def _persistent_vjp_bwd(n_steps, interpret, res, g):
    params, h, adj = res
    dh, dek, deb, dwi, dbi, dwh, dbh = _run_persistent_bwd(
        params, h, adj, g, n_steps, interpret)
    dparams = _unpack_grads(params, dek, deb, dwi, dbi, dwh, dbh)
    dadj = jax.tree_util.tree_map(jnp.zeros_like, adj)  # structural
    return dparams, dh, dadj


_persistent_pallas.defvjp(_persistent_vjp_fwd, _persistent_vjp_bwd)


#: Conservative VMEM budget for the persistent kernels' resident state
#: (v5e has ~16 MiB/core; leave headroom for the pipeline's double
#: buffers and compiler temporaries). The eligibility gate in
#: models/flowgnn.py degrades to the fused scan above this, instead of
#: letting Mosaic fail the allocation at compile time.
PERSISTENT_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def persistent_vmem_bytes(adj: BandAdjacency, hidden: int,
                          dtype) -> float:
    """Upper-bound VMEM residency of the persistent kernels (the
    backward is the larger program): the resident h/dh blocks, the four
    rolling windows, packed weights, and the per-step + total f32
    weight-grad blocks."""
    t, nt, b = adj.tile, adj.n_tiles, adj.bandwidth
    w = 2 * b + 1
    itemsize = jnp.dtype(dtype).itemsize
    mdt_itemsize = max(itemsize, adj.vals.dtype.itemsize)
    tile_h = float(t * hidden)
    resident = nt * tile_h * itemsize          # h (fwd) / dh (bwd) block
    windows = (w * tile_h * itemsize           # h_s window
               + 2 * w * tile_h * mdt_itemsize  # msg + d agg windows
               + w * tile_h * 4.0)             # local d h window (f32)
    weights = (8.0 * hidden * hidden + 7.0 * hidden) * itemsize
    grads = 2.0 * (8.0 * hidden * hidden + 7.0 * hidden) * 4.0
    band_blocks = 2.0 * w * t * t * adj.vals.dtype.itemsize  # A + Aᵀ
    return resident + windows + weights + grads + band_blocks


def persistent_vmem_ok(adj: BandAdjacency, hidden: int, dtype) -> bool:
    """Can the resident state fit the persistent kernels' VMEM budget?
    The dispatch gate: over budget the caller degrades to the per-step
    fused scan (2×K h HBM traffic back, but it runs) rather than dying
    in the Mosaic allocator."""
    return (persistent_vmem_bytes(adj, hidden, dtype)
            <= PERSISTENT_VMEM_BUDGET_BYTES)


def persistent_unroll(params: Mapping, h: jnp.ndarray, adj: BandAdjacency,
                      n_steps: int, impl: str = "auto") -> jnp.ndarray:
    """K shared-weight gated steps as ONE persistent kernel per direction.

    Semantics: ``h_K`` where ``h_{k+1} = GRU(A @ (h_k W_e + b_e), h_k)``
    — exactly ``n_steps`` applications of :func:`fused_gate_step` with
    the same params (the model's scan-with-broadcast-params), which is
    the parity oracle for forward AND gradients. ``impl`` as in
    :func:`fused_gate_step`; ``"xla"`` is the iterated reference
    composition (the CPU/tier-1 fallback). ``n_steps == 1`` degenerates
    to the single-step kernel. Differentiable in ``params`` and ``h``.
    """
    if n_steps < 1:
        raise ValueError(f"persistent_unroll needs n_steps >= 1, "
                         f"got {n_steps}")
    impl = resolve_impl(impl)
    if impl == "xla":
        for _ in range(n_steps):
            h = fused_reference(params, h, adj)
        return h
    if adj.vals.ndim != 4:
        raise ValueError(
            "persistent kernel takes one shard's band adjacency (vals "
            f"[2B+1, T, t, t]); got ndim={adj.vals.ndim} — sharded batches "
            "dispatch through the band fallback (models/flowgnn.py)")
    if n_steps == 1:
        return _fused_pallas(params, h, adj, impl == "interpret")
    return _persistent_pallas(params, h, adj, n_steps, impl == "interpret")


# ---------------------------------------------------------------------------
# Analytic cost accounting (Pallas is invisible to XLA's cost model)
# ---------------------------------------------------------------------------


def fused_step_cost(adj: BandAdjacency, hidden: int,
                    dtype="float32") -> Dict[str, float]:
    """FLOPs / HBM bytes of ONE fused forward step, counted the way the
    roofline counts the unfused ops: dense matmul FLOPs (2mnk) over the
    message dense, the 2B+1 band block-matmuls, and the packed gate
    matmuls; bytes = the HBM the kernel actually touches (h in twice +
    carry, h' out, adjacency once, weights once). The backward is ~2× the
    matmul work plus the Aᵀ pass — callers scale by steps as needed."""
    t, nt, b = adj.tile, adj.n_tiles, adj.bandwidth
    w = 2 * b + 1
    n = nt * t
    itemsize = jnp.dtype(dtype).itemsize
    flops = (
        2.0 * n * hidden * hidden            # msg = h @ We
        + 2.0 * w * nt * t * t * hidden      # agg = A @ msg (band bmms)
        + 2.0 * n * hidden * 3 * hidden      # x @ Wi
        + 2.0 * n * hidden * 3 * hidden      # h @ Wh
        + 10.0 * n * hidden                  # gate elementwise
    )
    bytes_accessed = (
        3.0 * n * hidden * itemsize          # h: msg read + carry read, h' out
        + float(adj.vals.size) * adj.vals.dtype.itemsize
        + (8.0 * hidden * hidden + 7.0 * hidden) * itemsize
    )
    # Backward: the in-kernel remat replays every forward matmul, then the
    # gate/edge cotangent matmuls (dx, dh_local, dWi, dWh each one packed
    # [n,3H] pass), the Aᵀ band pass, and dW_e / dh-from-msg.
    bwd_flops = (
        flops                                   # forward recompute
        + 4.0 * 2.0 * n * hidden * 3 * hidden   # dx, dh_local, dWi, dWh
        + 2.0 * w * nt * t * t * hidden         # d msg = Aᵀ @ d agg
        + 2.0 * 2.0 * n * hidden * hidden       # dW_e, dh from d msg
        + 30.0 * n * hidden                     # gate backward elementwise
    )
    # Backward HBM: h fetched through three row pipelines (message
    # recompute, carry, dW_e), the cotangent in, dh out, both band forms
    # (A and the host-built Aᵀ), weights in + packed f32 grads out.
    bwd_bytes_accessed = (
        5.0 * n * hidden * itemsize              # h ×3, g in, dh out
        + 2.0 * float(adj.vals.size) * adj.vals.dtype.itemsize
        + (8.0 * hidden * hidden + 7.0 * hidden) * itemsize
        + (8.0 * hidden * hidden + 7.0 * hidden) * 4.0   # f32 grads out
    )
    return {"flops": flops, "bwd_flops": bwd_flops,
            "bytes_accessed": bytes_accessed,
            "bwd_bytes_accessed": bwd_bytes_accessed,
            "flops_unfused_hbm_bytes": (
                # What the unfused chain moves: msg, agg and the six gate
                # pre-activations all round-trip [n, hidden] through HBM.
                bytes_accessed + 9.0 * n * hidden * itemsize)}


def analytic_extra_cost(message_impl: str, band_adj, hidden: int,
                        n_steps: int, dtype,
                        include_bwd: bool = True) -> Tuple[float, float]:
    """The ``(extra_flops, extra_bytes)`` a cost-model capture site
    should charge for Pallas kernel work XLA counts as zero — owning
    EVERY eligibility leg the model dispatch applies (band adjacency
    present and unsharded, a real kernel backend, and the persistent
    VMEM budget), so the accounting can never desynchronize from the
    program that actually runs. Returns (0, 0) whenever the executed
    program is the XLA composition (already in ``cost_analysis``).
    ``include_bwd=False`` is the forward-only serving case."""
    if message_impl not in ("fused", "persistent"):
        return 0.0, 0.0
    if band_adj is None or band_adj.vals.ndim != 4:
        return 0.0, 0.0
    if resolve_impl() == "xla":
        return 0.0, 0.0
    if message_impl == "persistent" and persistent_vmem_ok(
            band_adj, hidden, dtype):
        c = persistent_unroll_cost(band_adj, hidden, n_steps, dtype)
        return (
            c["flops"] + (c["bwd_flops"] if include_bwd else 0.0),
            c["bytes_accessed"] + (c["bwd_bytes_accessed"]
                                   if include_bwd else 0.0),
        )
    # "fused" — and the persistent flag's over-VMEM-budget degrade,
    # which runs the per-step fused scan.
    c = fused_step_cost(band_adj, hidden, dtype)
    return (
        n_steps * (c["flops"] + (c["bwd_flops"] if include_bwd else 0.0)),
        n_steps * (c["bytes_accessed"]
                   + (c["bwd_bytes_accessed"] if include_bwd else 0.0)),
    )


def persistent_unroll_cost(adj: BandAdjacency, hidden: int, n_steps: int,
                           dtype="float32") -> Dict[str, float]:
    """FLOPs / HBM bytes of the whole K-step persistent program, counted
    the same way :func:`fused_step_cost` counts one step.

    Totals are for ONE dispatch of the K-step program (what
    ``capture_compiled(extra_flops=…)`` wants); the ``*_per_step`` keys
    are the amortized per-step columns and the ``scan_*`` keys are what
    K dispatches of the single-step kernel move — the A/B the roofline
    quotes. The forward's h traffic is ``h_0`` in + ``h_K`` out, full
    stop: the 2×K per-step h-tile round-trips are gone (the adjacency
    still streams once per step — the rolling window restarts inside the
    grid). The backward charges the recompute sweep's hist write/read
    honestly: ``h_0`` in + (K-1) hist out, then (K-1) hist in + ``h_0``
    + g in + dh out, both band forms per step, weights once per call,
    packed f32 grads out once."""
    k = int(n_steps)
    if k < 1:
        raise ValueError(f"n_steps must be >= 1, got {n_steps}")
    base = fused_step_cost(adj, hidden, dtype)
    t, nt = adj.tile, adj.n_tiles
    n = nt * t
    itemsize = jnp.dtype(dtype).itemsize
    h_bytes = float(n * hidden * itemsize)
    adj_bytes = float(adj.vals.size) * adj.vals.dtype.itemsize
    w_bytes = (8.0 * hidden * hidden + 7.0 * hidden) * itemsize
    wgrad_bytes = (8.0 * hidden * hidden + 7.0 * hidden) * 4.0
    flops = k * base["flops"]
    if k == 1:
        # Degenerate: the single-step kernel IS the dispatched program.
        bytes_accessed = base["bytes_accessed"]
        bwd_flops = base["bwd_flops"]
        bwd_bytes = base["bwd_bytes_accessed"]
    else:
        bytes_accessed = 2.0 * h_bytes + k * adj_bytes + w_bytes
        # Reverse sweep replays every forward step in-kernel (the remat),
        # plus the recompute sweep's K-1 forward steps for the hist.
        bwd_flops = (k - 1) * base["flops"] + k * base["bwd_flops"]
        hist_sweep = (h_bytes + (k - 1) * h_bytes
                      + (k - 1) * adj_bytes + w_bytes)
        reverse_sweep = ((k - 1) * h_bytes   # hist in
                         + 3.0 * h_bytes     # h_0, g in; dh out
                         + 2.0 * k * adj_bytes  # A and Aᵀ, per step
                         + w_bytes + wgrad_bytes)
        bwd_bytes = hist_sweep + reverse_sweep
    return {
        "flops": flops,
        "bwd_flops": bwd_flops,
        "bytes_accessed": bytes_accessed,
        "bwd_bytes_accessed": bwd_bytes,
        "bytes_per_step": bytes_accessed / k,
        "bwd_bytes_per_step": bwd_bytes / k,
        "scan_bytes_accessed": k * base["bytes_accessed"],
        "scan_bwd_bytes_accessed": k * base["bwd_bytes_accessed"],
        # The headline term: per-step h bytes, persistent vs scanned —
        # 2/K tiles amortized against the scan's 3 (fwd; the README
        # table quotes both directions).
        "h_bytes_per_step": 2.0 * h_bytes / k,
        "scan_h_bytes_per_step": 3.0 * h_bytes,
    }

"""Content-hash result cache for the serving engine.

CI-scan traffic re-submits the same functions over and over (every push
rescans the whole changed file set); a content-addressed cache turns the
duplicate majority into queue-free sub-millisecond responses. Keys hash
the *model inputs* — graph structure + features (+ token source on the
combined lane) — never request ids or arrival metadata, so two scans of
the same function hit regardless of who sent them.
"""

from __future__ import annotations

import collections
import hashlib
import threading
from typing import Dict, Mapping, Optional

import numpy as np


def content_hash(graph: Mapping, code: Optional[str] = None) -> str:
    """Stable digest of a scoring request's model inputs.

    Canonicalizes arrays to int64 little-endian bytes so the digest is
    invariant to the caller's dtype choices (a JSON client sends lists,
    the offline scorer sends int32 arrays — same function, same key).
    ``code`` participates only when it will actually be scored (combined
    lane); a degraded/gnn-only request hashes the graph alone, so it
    shares its cache line with plain graph submissions.
    """
    h = hashlib.blake2b(digest_size=16)
    h.update(int(graph["num_nodes"]).to_bytes(8, "little"))
    for key in ("senders", "receivers"):
        arr = np.ascontiguousarray(np.asarray(graph[key], np.int64))
        h.update(arr.tobytes())
    for name in sorted(graph["feats"]):
        h.update(name.encode())
        arr = np.ascontiguousarray(np.asarray(graph["feats"][name], np.int64))
        h.update(arr.tobytes())
    if code is not None:
        h.update(b"\x00code\x00")
        h.update(str(code).encode("utf-8", "replace"))
    return h.hexdigest()


def text_hash(code: str) -> str:
    """Content key for the generation lane: the raw source text is the
    whole model input, namespaced apart from graph keys so a gen request
    and a scoring request can never collide on one cache line."""
    h = hashlib.blake2b(digest_size=16)
    h.update(b"gen\x00")
    h.update(str(code).encode("utf-8", "replace"))
    return h.hexdigest()


class ResultCache:
    """Thread-safe LRU of ``content_hash -> result dict``.

    ``capacity <= 0`` disables caching (get always misses, put drops).
    Stored values are treated as immutable — callers copy before mutating
    a returned dict.
    """

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self._entries: "collections.OrderedDict[str, Dict]" = (
            collections.OrderedDict()
        )
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key: str) -> Optional[Dict]:
        with self._lock:
            value = self._entries.get(key)
            if value is not None:
                self._entries.move_to_end(key)
            return value

    def put(self, key: str, value: Dict) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

"""Telemetry-driven adaptive flush policy.

Each replica's batcher has two flush levers (serve/batcher.py): the
deadline *fraction* (how much of a request's budget may be spent waiting
for companions) and the *fill* threshold (how full a bucket must be to
flush on occupancy alone). The static defaults are one operating point;
this policy moves them online from the replica's **own** telemetry —
the rolling latency window behind its ``serve_<rid>_latency_ms``
registry series (read at the ``ServingStats`` source: the process
registry's ring is never reset, and a fresh replica's controller must
not inherit a dead one's tail) and the engine's rolling occupancy — so
a replica drowning in tail latency flushes sooner and an idle one waits
longer for fuller buckets.

Guard rails, because a feedback loop on the serving path must be boring:

* **Clamped** — the batcher itself clamps the fraction to
  ``[flush_fraction_min, flush_fraction_max]`` and the fill threshold to
  ``[1, batch_slots]``; no policy state can escape the band.
* **Hysteresis** — a move needs ``adaptive_patience`` *consecutive*
  same-direction signals; one noisy window never swings the thresholds.
* **Audited** — every evaluation (move, hold, or clamp) is a
  ``serve.flush_policy`` trace event carrying the inputs (p99,
  occupancy, target) and outputs (fraction, fill), so ``cli trace
  report`` reconstructs the policy's whole decision history from the
  trace alone.

Time comes from the engine's clock (virtual in replay/bench, monotonic
live), so replayed policy behaviour is deterministic.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from deepdfa_tpu import telemetry
from deepdfa_tpu.serve.config import ServeConfig

__all__ = ["AdaptiveFlushPolicy"]


@dataclasses.dataclass
class _Decision:
    action: str               # "lower" | "raise" | "hold"
    fraction: float
    fill_slots: int
    p99_ms: float
    occupancy: float


class AdaptiveFlushPolicy:
    """One replica's flush-threshold controller.

    ``maybe_update(engine)`` is called from the pump path after flushes
    (engine.pump) at most once per ``adaptive_interval_s`` of engine
    clock. It reads the replica's p99 from the registry histogram when
    the engine is replica-tagged (falling back to the engine's own
    rolling window), compares against ``adaptive_target_p99_frac *
    deadline_ms``, and nudges the thresholds one ``adaptive_step`` at a
    time after ``adaptive_patience`` consecutive signals.
    """

    def __init__(self, config: ServeConfig, replica: Optional[str] = None):
        self.config = config
        self.replica = replica
        self.fraction = min(
            max(config.flush_fraction, config.flush_fraction_min),
            config.flush_fraction_max,
        )
        self.fill_slots = config.batch_slots
        self.target_p99_ms = (config.adaptive_target_p99_frac
                              * config.deadline_ms)
        self._pressure = 0      # consecutive over-target windows
        self._slack = 0         # consecutive well-under-target windows
        self._last_eval: Optional[float] = None
        self._last_traffic = -1  # completed+failures at the last decision
        # Occupancy baseline: stats.occupancy is a LIFETIME average, so
        # the controller differences it per decision window — a server
        # that spent an hour saturated must still see its buckets go
        # empty the minute traffic does.
        self._last_used = 0
        self._last_slots = 0
        self.decisions = 0

    # -- inputs ------------------------------------------------------------

    @staticmethod
    def _p99_ms(engine) -> float:
        """The replica's own p99: the rolling latency window behind its
        ``serve_<rid>_latency_ms`` registry series — read at the source
        (``ServingStats``, which mirrors every observation into that
        series) rather than from the process-global histogram, because
        the registry ring is never reset and outlives engine instances:
        a fresh fleet's controller must not be steered by a previous
        fleet's tail (the 1-vs-N bench runs back to back in one
        process)."""
        from deepdfa_tpu.core.metrics import latency_quantile

        return latency_quantile(engine.stats.latencies_ms, 0.99)

    def _window_occupancy(self, engine) -> float:
        """Occupancy over the batches flushed SINCE the last decision
        (``stats.occupancy`` is a lifetime average that an hour of
        saturation pins near 1.0 forever). No flushes since last time —
        cache-hit-only traffic — reads as 1.0: no evidence of empty
        buckets, so the raise branch stays conservative."""
        used = engine.stats.occupancy_used
        slots = engine.stats.occupancy_slots
        d_used, d_slots = used - self._last_used, slots - self._last_slots
        self._last_used, self._last_slots = used, slots
        return d_used / d_slots if d_slots > 0 else 1.0

    # -- the control step --------------------------------------------------

    def maybe_update(self, engine) -> Optional[Dict[str, Any]]:
        """Evaluate once per interval; returns the decision dict (also
        emitted as a ``serve.flush_policy`` event) or None when the
        interval has not elapsed."""
        now = engine.now()
        if (self._last_eval is not None
                and now - self._last_eval < self.config.adaptive_interval_s):
            return None
        # No decision without traffic: the pump loop spins every few ms
        # even on an idle server, and an idle replica has nothing to
        # decide — emitting interval-paced "hold" events forever would
        # bloat the trace with zero information. Every decision made IS
        # still emitted; idleness just isn't a decision.
        traffic = engine.stats.completed + engine.stats.failures
        if traffic == self._last_traffic:
            self._last_eval = now
            return None
        self._last_traffic = traffic
        self._last_eval = now
        decision = self._decide(self._p99_ms(engine),
                                self._window_occupancy(engine))
        engine.batcher.set_flush_policy(fraction=decision.fraction,
                                        fill_slots=decision.fill_slots)
        # The batcher clamped; read back so the audit records reality.
        self.fraction = engine.batcher.flush_fraction
        self.fill_slots = engine.batcher.fill_slots
        self.decisions += 1
        doc = {
            "replica": self.replica or "r0",
            "action": decision.action,
            "fraction": round(self.fraction, 4),
            "fill_slots": self.fill_slots,
            "p99_ms": round(decision.p99_ms, 3),
            "occupancy": round(decision.occupancy, 4),
            "target_p99_ms": round(self.target_p99_ms, 3),
            "pressure": self._pressure,
            "slack": self._slack,
        }
        # The audit: EVERY decision (hold included) is a trace event —
        # `cli trace report` replays the controller from events alone.
        telemetry.event("serve.flush_policy", **doc)
        return doc

    def _decide(self, p99_ms: float, occupancy: float) -> _Decision:
        cfg = self.config
        action = "hold"
        if p99_ms > self.target_p99_ms:
            # Tail latency over target: spend less of the budget waiting
            # and flush at smaller fills — latency buys occupancy back
            # once the queue drains.
            self._pressure += 1
            self._slack = 0
            if self._pressure >= cfg.adaptive_patience:
                action = "lower"
                self.fraction -= cfg.adaptive_step
                self.fill_slots = max(1, self.fill_slots // 2)
                self._pressure = 0
        elif p99_ms < 0.5 * self.target_p99_ms and occupancy < 0.5:
            # Comfortable tail + half-empty buckets: wait longer so
            # buckets fill (throughput), one step at a time.
            self._slack += 1
            self._pressure = 0
            if self._slack >= cfg.adaptive_patience:
                action = "raise"
                self.fraction += cfg.adaptive_step
                self.fill_slots = min(cfg.batch_slots, self.fill_slots * 2)
                self._slack = 0
        else:
            self._pressure = 0
            self._slack = 0
        self.fraction = min(max(self.fraction, cfg.flush_fraction_min),
                            cfg.flush_fraction_max)
        return _Decision(action, self.fraction, self.fill_slots,
                         p99_ms, occupancy)

"""TPU-native batched inference serving.

The path from a checkpoint to answering scoring requests: a bounded
request queue feeding a deadline-aware micro-batcher that packs incoming
functions into the same padded graph/token bucket shapes training uses
(graphs/batch.py's ladder via ``select_bucket``), an engine that
AOT-compiles every bucket shape at startup so steady-state traffic
triggers zero recompiles, a content-hash result cache (duplicate
submissions are the common case in CI-scan traffic), explicit
backpressure (429-style rejection with retry-after), and graceful
degradation (combined DDFA+LineVul falls back to GNN-only when the
tokenizer path errors).

Layout:
  config.py   ServeConfig: slots/budgets/deadlines/capacities + buckets,
              fleet size, adaptive-flush knobs, REPLICA_IDS
  cache.py    content_hash/text_hash + ResultCache (LRU)
  batcher.py  ServeRequest + MicroBatcher (admission, continuous-batching
              flush policy, live-tunable thresholds)
  policy.py   AdaptiveFlushPolicy (telemetry-driven threshold controller)
  engine.py   ServeEngine: warmup, submit, pump, drain, score_sync;
              lanes gnn/combined/gen (gen: batched-beam CodeT5 decode,
              warmed per (slot, src-length-bucket) — ISSUE 13)
  fleet.py    ServeFleet: N device-pinned replicas, routing, rolls
  procfleet.py ProcFleet: N engine OS processes (real ``cli serve``
              children), spawn/probe/roll/reap, PROCESS_IDS —
              shared-nothing crash domains (ISSUE 17)
  router.py   RouterHTTPServer: the accept/route tier in front of a
              ProcFleet — /score scatter by content key, /metrics
              aggregation, crash re-route to siblings
  http.py     stdlib http.server JSON endpoint (cli.py serve)
  replay.py   seeded bursty traces + virtual-clock replay + the
              open-loop fleet load harness + the calibrated
              process-timeline replay (bench, tests)

Design anchors: Just-in-Time Dynamic-Batching (arXiv:1904.07421) for the
deadline-aware flush policy; Fast Training of Sparse GNNs on Dense
Hardware (arXiv:1906.11786) for keeping padded static shapes end to end.
"""

from deepdfa_tpu.serve.batcher import (
    MicroBatcher,
    OversizedError,
    RejectedError,
    ServeRequest,
)
from deepdfa_tpu.serve.cache import ResultCache, content_hash, text_hash
from deepdfa_tpu.serve.config import (
    MAX_PROCESSES,
    MAX_REPLICAS,
    PROCESS_IDS,
    REPLICA_IDS,
    ServeConfig,
)
from deepdfa_tpu.serve.engine import ServeEngine
from deepdfa_tpu.serve.fleet import ServeFleet
from deepdfa_tpu.serve.policy import AdaptiveFlushPolicy
from deepdfa_tpu.serve.procfleet import NoLiveProcessError, ProcFleet

__all__ = [
    "AdaptiveFlushPolicy",
    "MAX_PROCESSES",
    "MAX_REPLICAS",
    "MicroBatcher",
    "NoLiveProcessError",
    "OversizedError",
    "PROCESS_IDS",
    "ProcFleet",
    "REPLICA_IDS",
    "RejectedError",
    "ResultCache",
    "ServeConfig",
    "ServeEngine",
    "ServeFleet",
    "ServeRequest",
    "content_hash",
    "text_hash",
]

"""Request queue + deadline-aware micro-batcher.

The serving half of the shape-bucketing problem ``graphs/batch.py``
solves for training: requests accumulate per lane (model path) and flush
as one padded micro-batch when either

  * the lane holds a full ``batch_slots`` bucket (fill-flush — maximum
    occupancy, no reason to wait), or
  * the oldest request has spent ``flush_fraction`` of its deadline
    budget waiting (deadline-flush — the other half of the budget is
    reserved for compute + response assembly).

This is the Just-in-Time Dynamic-Batching policy (arXiv:1904.07421)
specialized to a two-condition trigger. When several lanes are due at
once, the lane whose oldest request has the least remaining budget
flushes first — the SLA, not throughput, breaks ties.

**Continuous batching**: a bucket is sealed at *dispatch* (:meth:`take`),
not when its flush condition first held — admissions that land between a
lane becoming due and the pump taking it join the partially-filled
bucket instead of waiting out their own flush cycle (the
admit-into-in-flight-buckets half of JiT dynamic batching; the
route-around-a-busy-replica half lives in serve/fleet.py). Both flush
thresholds are live-tunable (:meth:`set_flush_policy`): the adaptive
policy (serve/policy.py) moves the deadline fraction and the fill
threshold online from the replica's own latency/occupancy telemetry.

Backpressure is explicit: admissions beyond ``queue_capacity`` raise
:class:`RejectedError` carrying a retry-after hint (the HTTP layer maps
it to 429 + Retry-After), and single graphs that could never fit a slot
raise :class:`OversizedError` (413) instead of poisoning a bucket.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Deque, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from deepdfa_tpu import telemetry
from deepdfa_tpu.serve.config import ServeConfig


class RejectedError(Exception):
    """Queue full — retry after ``retry_after_s``."""

    def __init__(self, retry_after_s: float):
        self.retry_after_s = retry_after_s
        super().__init__(
            f"serving queue full; retry after {retry_after_s:.3f}s"
        )


class OversizedError(Exception):
    """Request exceeds the per-slot graph budget (no bucket could hold it)."""


@dataclasses.dataclass
class ServeRequest:
    """One function to score, plus its result plumbing.

    ``event`` lets a transport thread block until the pump thread (or a
    cache hit) calls :meth:`finish`; single-threaded drivers (replay,
    offline scoring) just read ``result`` after draining.
    """

    rid: int
    key: str                      # content hash (cache line)
    graph: Optional[Mapping]      # None on the gen lane (text-only input)
    lane: str                     # "gnn" | "combined" | "gen"
    arrival: float                # engine-clock seconds
    deadline_s: float
    t_submit: float = 0.0         # telemetry clock (perf_counter seconds)
    input_ids: Optional[np.ndarray] = None   # combined + gen lanes
    src_bucket: Optional[int] = None         # gen lane: padded source len
    src_tokens: Optional[int] = None         # gen lane: RAW token count —
    # the pre-bucket size the traffic observatory charges in-slot pad
    # against (input_ids is already padded to src_bucket).
    # Distributed trace context (ISSUE 14): the trace id this request
    # rides (continued from a client's traceparent header, or minted
    # fresh at admission); the serve.request span carries both so the
    # offline report joins client-observed to server-observed latency.
    trace_id: Optional[str] = None
    trace_continued: bool = False
    degraded: bool = False        # tokenizer failed -> gnn fallback
    completed_at: Optional[float] = None     # engine-clock completion time
    result: Optional[Dict] = None
    event: threading.Event = dataclasses.field(
        default_factory=threading.Event, repr=False
    )

    def finish(self, result: Dict) -> None:
        self.result = result
        self.event.set()

    def flush_at(self, fraction: float) -> float:
        """Clock time at which this request forces a deadline-flush."""
        return self.arrival + fraction * self.deadline_s


class MicroBatcher:
    """Per-lane FIFO queues with the two-condition flush policy.

    Thread-safe: admission (transport threads) and due/take (the pump
    thread) serialize on one lock. Time never comes from the wall here —
    callers pass ``now`` from the engine's clock, which is virtual in
    replay/bench and monotonic in live serving.
    """

    def __init__(self, config: ServeConfig, lanes: Sequence[str] = ("gnn",),
                 replica: Optional[str] = None):
        self.config = config
        # Fleet identity: rids are per-ENGINE counters, so in a fleet the
        # enqueue events must carry the replica tag or two replicas' rid
        # 5 are indistinguishable in the trace (the fleet_roll audit
        # joins admissions to responses on (replica, rid)).
        self._replica = replica
        self._pending: Dict[str, Deque[ServeRequest]] = {
            lane: collections.deque() for lane in lanes
        }
        self._lock = threading.Lock()
        # Lame-duck drain (ISSUE 10): with drain mode on, any non-empty
        # lane is due IMMEDIATELY — partially-filled buckets flush now
        # instead of waiting for fill or the deadline fraction, so every
        # already-admitted request is answered inside the grace budget.
        self._drain_mode = False
        # Live flush thresholds (the adaptive policy's levers). Defaults
        # reproduce the static config exactly; set_flush_policy clamps.
        self._flush_fraction = config.flush_fraction
        self._fill_slots = config.batch_slots
        # Why each lane's LAST bucket sealed (fill / deadline / drain):
        # the engine stamps it onto the serve.flush span, so the trace
        # report's traffic section can attribute slot-underfill waste to
        # deadline pressure vs drain vs genuinely full buckets.
        self._last_cause: Dict[str, str] = {}

    def set_flush_policy(self, fraction: Optional[float] = None,
                         fill_slots: Optional[int] = None) -> None:
        """Retune the two flush thresholds online (serve/policy.py).

        ``fraction`` clamps to [flush_fraction_min, flush_fraction_max];
        ``fill_slots`` to [1, batch_slots]. The clamp lives HERE so no
        policy — adaptive, manual, or buggy — can push the batcher into a
        never-flushes or flush-every-request regime.
        """
        with self._lock:
            if fraction is not None:
                self._flush_fraction = min(
                    max(float(fraction), self.config.flush_fraction_min),
                    self.config.flush_fraction_max,
                )
            if fill_slots is not None:
                self._fill_slots = min(max(int(fill_slots), 1),
                                       self.config.batch_slots)

    @property
    def flush_fraction(self) -> float:
        return self._flush_fraction

    @property
    def fill_slots(self) -> int:
        return self._fill_slots

    def set_drain_mode(self, on: bool = True) -> None:
        with self._lock:
            self._drain_mode = bool(on)

    @property
    def drain_mode(self) -> bool:
        return self._drain_mode

    @property
    def lanes(self) -> Tuple[str, ...]:
        return tuple(self._pending)

    def depth(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._pending.values())

    def admit(self, req: ServeRequest) -> None:
        """Enqueue or raise (RejectedError / OversizedError).

        The per-request size caps make bucket budgets exact (any
        ``batch_slots`` admitted graphs fit the top bucket), so this is
        the only size check in the serving path.
        """
        if req.graph is not None:
            n = int(req.graph["num_nodes"])
            e = len(req.graph["senders"]) + n  # + self loops, as batching
            reason = self.config.admission_caps(n, e)
            if reason is not None:
                raise OversizedError(reason)
        # Gen-lane requests carry no graph; their size cap (token count
        # vs gen_src_len) is enforced at encode time in engine.submit.
        with self._lock:
            if req.lane not in self._pending:
                raise ValueError(f"unknown lane {req.lane!r}")
            if sum(len(q) for q in self._pending.values()) \
                    >= self.config.queue_capacity:
                # Retry once the current flush window has passed: by then
                # at least one bucket has drained.
                raise RejectedError(
                    self._flush_fraction * self.config.deadline_ms
                    / 1000.0
                )
            self._pending[req.lane].append(req)
            depth = sum(len(q) for q in self._pending.values())
        # Outside the lock: the enqueue step of the per-request trace
        # (admission -> enqueue -> flush -> respond), rid threaded through.
        attrs = dict(rid=req.rid, lane=req.lane, depth=depth)
        if self._replica is not None:
            attrs["replica"] = self._replica
        telemetry.event("serve.enqueue", **attrs)

    def due(self, now: float) -> Optional[str]:
        """The lane to flush at ``now``, or None.

        Fill-due and deadline-due lanes compete; the request with the
        least remaining deadline budget wins (deadline-flush vs
        fill-flush ordering is by urgency, not arrival of the condition).
        Deadline scans cover the WHOLE queue, not just the head:
        ``deadline_ms`` is per-request public API, so a short-deadline
        request behind a long-deadline head must still force its flush
        (flushes drain FIFO, so the head rides along).
        """
        with self._lock:
            best: Optional[Tuple[float, str]] = None
            for lane, q in self._pending.items():
                if not q:
                    continue
                filled = len(q) >= self._fill_slots
                deadline_due = now >= min(
                    r.flush_at(self._flush_fraction) for r in q
                )
                if not (filled or deadline_due or self._drain_mode):
                    continue
                remaining = min(r.arrival + r.deadline_s for r in q) - now
                if best is None or remaining < best[0]:
                    best = (remaining, lane)
            return best[1] if best else None

    def next_flush_time(self, now: float) -> Optional[float]:
        """Earliest clock time any lane becomes due (<= now when one
        already is) — the pump scheduler's sleep horizon."""
        with self._lock:
            t: Optional[float] = None
            for q in self._pending.values():
                if not q:
                    continue
                when = (now if (len(q) >= self._fill_slots
                                or self._drain_mode)
                        else min(r.flush_at(self._flush_fraction)
                                 for r in q))
                if t is None or when < t:
                    t = when
            return t

    def take(self, lane: str) -> List[ServeRequest]:
        """Pop the lane's next micro-batch (FIFO, up to ``batch_slots``).

        THE continuous-batching seal point: the bucket's membership is
        decided here, at dispatch — requests admitted after the lane
        became due (fill, deadline, or drain) but before the pump got to
        it ride this bucket instead of opening a fresh flush cycle.
        Always caps at the static ``batch_slots`` (the compiled-shape
        ladder top), not the live fill threshold: the fill knob decides
        *when* to flush, never a new shape.
        """
        with self._lock:
            q = self._pending[lane]
            # Classify the seal under the same lock that decides it: a
            # full bucket is a fill-flush even in drain mode; drain only
            # explains partially-filled seals.
            if len(q) >= self._fill_slots:
                self._last_cause[lane] = "fill"
            elif self._drain_mode:
                self._last_cause[lane] = "drain"
            else:
                self._last_cause[lane] = "deadline"
            out = [q.popleft() for _ in range(min(len(q),
                                                  self.config.batch_slots))]
            return out

    def last_flush_cause(self, lane: str) -> Optional[str]:
        """Why ``lane``'s most recent bucket sealed (None before any)."""
        with self._lock:
            return self._last_cause.get(lane)

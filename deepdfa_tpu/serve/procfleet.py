"""Shared-nothing multi-process serving: the engine-process fleet.

PR 11's :class:`~deepdfa_tpu.serve.fleet.ServeFleet` is N replicas
inside ONE Python process — one GIL, one crash domain. This module is
the same fleet idea promoted across the process boundary: each engine
is a real OS process (``python -m deepdfa_tpu.cli serve --port 0``)
owning its own AOT-warmed :class:`ServeEngine`, micro-batcher, pump
threads, and lifecycle coordinator, while THIS process runs only the
thin accept/route tier (serve/router.py).

Design points, in dependency order:

* **Spawn**: children are plain ``Popen`` (fork+exec — safe after
  threads exist) with env from :func:`telemetry.context.child_env`, so
  every child shards its telemetry into the parent's run and the merged
  trace shows the whole fleet with real pids. Readiness is the historic
  port-file handshake: ``cmd_serve`` writes the bound port only after
  warmup, so a port file IS the warm signal. The spawn then records the
  child's warmup compile count through ``/metrics`` — the
  zero-post-warmup-compiles assertion is checked against that baseline
  through the router, not inside the child.
* **Health**: a single probe thread polls every live child's
  ``/healthz``; ``probe_failures`` consecutive timeouts/refusals (or an
  observed child exit) mark the process dead, shed its traffic to
  siblings, and (by default) start a warmed replacement under the same
  statically-enumerated process id with a bumped generation.
* **Roll**: a rolling restart spawns the replacement FIRST, warms it to
  the same zero-compile bar, atomically swaps it into the routing
  table, then SIGTERMs the old process — its own PR-10 lifecycle
  coordinator runs the lame-duck drain (admitted requests answered,
  telemetry closed) before this process reaps it.
* **Routing state**: the fleet tracks a router-side ``outstanding``
  item count per process — the cross-process stand-in for the
  in-process fleet's ``engine.in_flight``/queue-depth override, so
  rendezvous content affinity still yields to load (the
  continuous-batching admission property survives the promotion).

Every wait on a child is deadline-bounded, and every kill precedes an
unbounded-looking reap (GL015/GL025); all mutable state is
instance-level behind one lock created in ``__init__`` (GL018/GL022),
and no child forward happens while the lock is held (GL023).
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from deepdfa_tpu import telemetry
from deepdfa_tpu.serve.config import MAX_PROCESSES, PROCESS_IDS
from deepdfa_tpu.serve.fleet import _stable_hash
from deepdfa_tpu.telemetry import context as trace_context

logger = logging.getLogger("deepdfa.serve.procfleet")


class NoLiveProcessError(Exception):
    """Every engine process is dead or draining — the router answers
    503 and keeps probing; admitted work already forwarded is still
    being answered behind this."""


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"{name} must be a number, got {raw!r}")


class EngineProc:
    """One engine OS process plus its router-side routing state."""

    def __init__(self, rid: str, generation: int):
        self.rid = rid
        self.generation = generation
        self.popen: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.state = "starting"  # starting | live | draining | dead
        self.outstanding = 0     # router-tracked in-flight items
        self.probe_failures = 0
        self.compiles_at_live: Optional[float] = None
        self.spawned_at = time.monotonic()

    @property
    def pid(self) -> Optional[int]:
        return self.popen.pid if self.popen is not None else None

    def describe(self) -> Dict[str, object]:
        return {"pid": self.pid, "port": self.port, "state": self.state,
                "generation": self.generation,
                "outstanding": self.outstanding}


class ProcFleet:
    """N engine processes behind one router process.

    ``child_args`` are appended to every child's
    ``deepdfa_tpu.cli serve`` argv (model config, batch knobs,
    ``--run-dir`` — everything except the port plumbing this class
    owns). Tests may override ``argv_for(rid, port_file)`` to front a
    stub child; the default argv names ``deepdfa_tpu.cli``, so its env
    always comes from the trace-context ``child_env`` helper (GL020).
    """

    def __init__(self, n: int, child_args: Sequence[str] = (), *,
                 host: str = "127.0.0.1",
                 probe_interval_s: Optional[float] = None,
                 probe_timeout_s: Optional[float] = None,
                 probe_failures: Optional[int] = None,
                 spawn_deadline_s: Optional[float] = None,
                 drain_grace_s: Optional[float] = None,
                 auto_respawn: bool = True,
                 argv_for: Optional[Callable[[str, str], List[str]]] = None,
                 child_env: Optional[Callable[[str], Dict[str, str]]] = None,
                 state_dir: Optional[str] = None):
        if not 1 <= n <= MAX_PROCESSES:
            raise ValueError(
                f"processes must be in [1, {MAX_PROCESSES}] (the statically-"
                "enumerated PROCESS_IDS set bounds per-process metric and "
                "trace cardinality; grow it in serve/config.py to go wider)")
        self.n = n
        self.host = host
        self.child_args = list(child_args)
        self.probe_interval_s = (
            probe_interval_s if probe_interval_s is not None
            else _env_float("DEEPDFA_SERVE_PROBE_INTERVAL_S", 1.0))
        self.probe_timeout_s = (
            probe_timeout_s if probe_timeout_s is not None
            else _env_float("DEEPDFA_SERVE_PROBE_TIMEOUT_S", 2.0))
        self.probe_failures = (
            probe_failures if probe_failures is not None
            else int(_env_float("DEEPDFA_SERVE_PROBE_FAILURES", 2)))
        self.spawn_deadline_s = (
            spawn_deadline_s if spawn_deadline_s is not None
            else _env_float("DEEPDFA_SERVE_SPAWN_DEADLINE_S", 300.0))
        self.drain_grace_s = (
            drain_grace_s if drain_grace_s is not None
            else _env_float("DEEPDFA_DRAIN_GRACE_S", 10.0))
        self.auto_respawn = auto_respawn
        self._argv_for = argv_for or self._default_argv
        self._proc_child_env = child_env or self._default_child_env
        self._dir = state_dir or tempfile.mkdtemp(prefix="deepdfa-procfleet-")
        self._lock = threading.Lock()
        self._procs: Dict[str, EngineProc] = {}
        self._spawn_errors: Dict[str, str] = {}
        self._rr = 0
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._workers: List[threading.Thread] = []

    # -- spawn / readiness -------------------------------------------------

    def _default_argv(self, rid: str, port_file: str) -> List[str]:
        return [sys.executable, "-m", "deepdfa_tpu.cli", "serve",
                "--host", self.host, "--port", "0",
                "--port-file", port_file, *self.child_args]

    def _default_child_env(self, rid: str) -> Dict[str, str]:
        # The child joins the parent's telemetry run: one merged trace
        # shows the router and every engine process with real pids.
        return trace_context.child_env(f"engine-{rid}")

    def start(self) -> None:
        """Spawn every engine process and block until all are live
        (port bound, warm, zero-compile baseline recorded) or raise
        after a deadline-bounded wait, reaping any stragglers."""
        rids = PROCESS_IDS[: self.n]
        threads = [threading.Thread(target=self._spawn, args=(rid, 0),
                                    name=f"spawn-{rid}", daemon=True)
                   for rid in rids]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.spawn_deadline_s + 30.0)
        failed = [rid for rid in rids
                  if (p := self._procs.get(rid)) is None or p.state != "live"]
        if failed:
            errors = {rid: self._spawn_errors.get(rid, "spawn timed out")
                      for rid in failed}
            self.shutdown()
            raise RuntimeError(f"engine processes failed to start: {errors}")
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="procfleet-probe", daemon=True)
        self._probe_thread.start()

    def _spawn(self, rid: str, generation: int) -> bool:
        """Spawn one engine process, wait for warm-readiness, then
        atomically install it in the routing table. Returns True when
        the process reached live."""
        proc = EngineProc(rid, generation)
        port_file = os.path.join(self._dir, f"{rid}.g{generation}.port")
        stderr_path = os.path.join(self._dir, f"{rid}.g{generation}.stderr")
        argv = self._argv_for(rid, port_file)
        env = self._proc_child_env(rid)
        with open(stderr_path, "wb") as errf:
            proc.popen = subprocess.Popen(argv, env=env,
                                          stdout=subprocess.DEVNULL,
                                          stderr=errf)
        telemetry.event("proc.spawn", proc=rid, pid=proc.pid,
                        generation=generation)
        deadline = time.monotonic() + self.spawn_deadline_s
        port: Optional[int] = None
        while time.monotonic() < deadline and not self._stop.is_set():
            if os.path.exists(port_file):
                with open(port_file, encoding="utf-8") as f:
                    text = f.read().strip()
                if text:
                    port = int(text)
                    break
            if proc.popen.poll() is not None:
                break
            time.sleep(0.05)
        if port is None:
            self._fail_spawn(proc, stderr_path,
                             "never bound its port (warmup wedged or "
                             "startup crashed)")
            return False
        proc.port = port
        # The port file is written after warmup, so the child is already
        # serving. Record the warmup-compile baseline through its own
        # /metrics: every later compile is a post-warmup compile.
        snap = self._fetch_json(proc, "/metrics", deadline - time.monotonic())
        if snap is None:
            self._fail_spawn(proc, stderr_path,
                             "bound its port but never answered /metrics")
            return False
        proc.compiles_at_live = float(snap.get("compiles", 0))
        with self._lock:
            old = self._procs.get(rid)
            proc.state = "live"
            self._procs[rid] = proc  # atomic routing swap
            self._spawn_errors.pop(rid, None)
        telemetry.event("proc.live", proc=rid, pid=proc.pid, port=port,
                        generation=generation,
                        spawn_s=round(time.monotonic() - proc.spawned_at, 3),
                        warmup_compiles=proc.compiles_at_live)
        if old is not None and old is not proc and old.state != "dead":
            # Rolling replacement: the predecessor is out of rotation the
            # moment the swap above lands; drain and reap it.
            self._retire(old)
        return True

    def _fail_spawn(self, proc: EngineProc, stderr_path: str,
                    why: str) -> None:
        tail = ""
        try:
            with open(stderr_path, "rb") as f:
                tail = f.read()[-2000:].decode("utf-8", "replace")
        except OSError:
            pass
        self._reap(proc, grace_s=0.0)
        proc.state = "dead"
        msg = f"{why}; stderr tail: {tail!r}" if tail else why
        with self._lock:
            self._spawn_errors[proc.rid] = msg
        telemetry.event("proc.dead", proc=proc.rid, pid=proc.pid,
                        generation=proc.generation, reason="spawn")
        logger.error("engine %s g%d failed to start: %s", proc.rid,
                     proc.generation, msg)

    def _reap(self, proc: EngineProc, grace_s: float) -> Optional[int]:
        """SIGTERM (when grace allows) then kill-then-wait: the wait is
        always bounded because a kill precedes it (GL015)."""
        popen = proc.popen
        if popen is None:
            return None
        if grace_s > 0 and popen.poll() is None:
            try:
                popen.send_signal(signal.SIGTERM)
            except OSError:
                pass
            try:
                popen.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                pass
        if popen.poll() is None:
            try:
                popen.kill()
            except OSError:
                pass
        try:
            popen.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            logger.error("engine %s pid %s did not exit after SIGKILL",
                         proc.rid, popen.pid)
        telemetry.event("proc.reap", proc=proc.rid, pid=popen.pid,
                        generation=proc.generation,
                        exit=popen.returncode)
        return popen.returncode

    def _retire(self, proc: EngineProc) -> None:
        """Lame-duck an out-of-rotation predecessor: SIGTERM lets its
        own lifecycle coordinator answer admitted requests and close
        telemetry; the bounded reap backstops a wedged drain."""
        proc.state = "draining"
        self._reap(proc, grace_s=self.drain_grace_s + 15.0)
        proc.state = "dead"

    # -- health / crash isolation ------------------------------------------

    def _fetch_json(self, proc: EngineProc, path: str,
                    timeout_s: float) -> Optional[dict]:
        if proc.port is None:
            return None
        conn = http.client.HTTPConnection(self.host, proc.port,
                                          timeout=max(timeout_s, 0.1))
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            return json.loads(body.decode("utf-8"))
        except (OSError, ValueError):
            return None
        finally:
            conn.close()

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            for proc in self.live():
                if self._stop.is_set():
                    return
                if proc.popen is not None and proc.popen.poll() is not None:
                    self.mark_dead(proc.rid, "exited",
                                   generation=proc.generation)
                    continue
                doc = self._fetch_json(proc, "/healthz",
                                       self.probe_timeout_s)
                if doc is None:
                    proc.probe_failures += 1
                    if proc.probe_failures >= self.probe_failures:
                        self.mark_dead(proc.rid, "probe",
                                       generation=proc.generation)
                else:
                    proc.probe_failures = 0

    def mark_dead(self, rid: str, reason: str, *,
                  generation: Optional[int] = None) -> bool:
        """Take a process out of rotation (crash isolation): its traffic
        sheds to siblings immediately; a warmed replacement is started
        under the same id unless respawn is off or shutdown began.
        Returns False when the process was already dead or replaced."""
        with self._lock:
            proc = self._procs.get(rid)
            if proc is None or proc.state != "live":
                return False
            if generation is not None and proc.generation != generation:
                return False  # a replacement already took the slot
            proc.state = "dead"
        telemetry.event("proc.dead", proc=rid, pid=proc.pid,
                        generation=proc.generation, reason=reason)
        telemetry.REGISTRY.counter("router_proc_deaths_total").inc()
        logger.warning("engine %s g%d pid %s marked dead (%s)", rid,
                       proc.generation, proc.pid, reason)
        self._reap(proc, grace_s=0.0)
        if self.auto_respawn and not self._stop.is_set():
            t = threading.Thread(target=self._spawn,
                                 args=(rid, proc.generation + 1),
                                 name=f"respawn-{rid}", daemon=True)
            t.start()
            with self._lock:
                self._workers.append(t)
        return True

    def roll(self, rid: str) -> None:
        """Rolling restart of one engine process: replacement first
        (spawned, warmed, zero-compile baseline through the router),
        atomic routing swap, then lame-duck-drain and reap the old
        process. Raises when the replacement never reaches live — the
        incumbent keeps serving in that case."""
        with self._lock:
            old = self._procs.get(rid)
            generation = old.generation + 1 if old is not None else 0
        telemetry.event("proc.roll", proc=rid, generation=generation)
        if not self._spawn(rid, generation):
            raise RuntimeError(
                f"rolling restart of {rid} failed: "
                f"{self._spawn_errors.get(rid, 'replacement never warmed')}")

    # -- routing state (used by serve/router.py) ---------------------------

    def live(self) -> List[EngineProc]:
        with self._lock:
            return [p for p in self._procs.values() if p.state == "live"]

    def route(self, key: Optional[str]) -> EngineProc:
        """The in-process fleet's rendezvous routing, across the process
        boundary: same graph-only content key, same stable hash, and the
        same yield-to-load override with router-tracked outstanding
        items standing in for ``engine.in_flight``."""
        live = self.live()
        if not live:
            raise NoLiveProcessError("no live engine process")
        if len(live) == 1:
            return live[0]
        with self._lock:
            if key is not None:
                pref = max(live,
                           key=lambda p: _stable_hash(f"{key}|{p.rid}"))
                if pref.outstanding == 0:
                    return pref
            order = live[self._rr % len(live):] + live[:self._rr % len(live)]
            self._rr += 1
            return min(order, key=lambda p: p.outstanding)

    def begin_forward(self, proc: EngineProc, n_items: int) -> None:
        with self._lock:
            proc.outstanding += n_items

    def end_forward(self, proc: EngineProc, n_items: int) -> None:
        with self._lock:
            proc.outstanding = max(0, proc.outstanding - n_items)

    # -- aggregation -------------------------------------------------------

    def processes(self) -> Dict[str, Dict[str, object]]:
        """Per-process metadata for /metrics and /healthz — keys are the
        statically-enumerated process ids."""
        with self._lock:
            return {rid: p.describe() for rid, p in self._procs.items()}

    def fetch_snapshots(self, timeout_s: float = 2.0,
                        ) -> Dict[str, Optional[dict]]:
        """Every live child's /metrics JSON body (None where the fetch
        failed — the child is counted, not silently dropped)."""
        return {p.rid: self._fetch_json(p, "/metrics", timeout_s)
                for p in self.live()}

    def compiles_after_warmup(self, timeout_s: float = 5.0) -> float:
        """Total compiles across live children since each went live —
        the zero-post-warmup-compiles assertion, checked through the
        router (the bench and chaos gates)."""
        total = 0.0
        for proc in self.live():
            snap = self._fetch_json(proc, "/metrics", timeout_s)
            if snap is not None and proc.compiles_at_live is not None:
                total += float(snap.get("compiles", 0)) - proc.compiles_at_live
        return total

    # -- shutdown ----------------------------------------------------------

    def shutdown(self) -> None:
        """Stop probing, lame-duck every child (SIGTERM → bounded wait →
        kill), reap all. Idempotent; every join is bounded."""
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(
                timeout=self.probe_interval_s + self.probe_timeout_s + 10.0)
        with self._lock:
            workers = list(self._workers)
            procs = list(self._procs.values())
        for t in workers:
            t.join(timeout=self.spawn_deadline_s + 10.0)
        for proc in procs:
            if proc.popen is not None and proc.popen.poll() is None:
                try:
                    proc.popen.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.drain_grace_s + 15.0
        for proc in procs:
            if proc.popen is None:
                continue
            try:
                proc.popen.wait(
                    timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                pass
            if proc.popen.poll() is None:
                try:
                    proc.popen.kill()
                except OSError:
                    pass
                try:
                    proc.popen.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    logger.error("engine %s pid %s survived SIGKILL",
                                 proc.rid, proc.popen.pid)
            proc.state = "dead"

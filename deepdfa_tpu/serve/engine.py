"""The serving engine: bucket-warmed AOT inference over micro-batches.

Owns the three serving invariants:

  * **Zero steady-state recompiles.** Every shape the jitted inference
    programs can see is ``(lane, slot-bucket)`` — derivable from
    ServeConfig alone — and :meth:`ServeEngine.warmup` AOT-compiles all
    of them at startup. ``ServingStats.compiles`` counts every compile;
    after warmup it must not move (the acceptance gate in
    tests/test_serve.py and bench.py).
  * **Content-addressed caching.** Duplicate submissions (the CI-scan
    common case) are answered from the LRU without touching the queue.
  * **Graceful degradation.** A combined-lane request whose tokenizer
    path errors falls back to the GNN-only lane, flagged ``degraded`` in
    its response, instead of failing the request.

Time comes from an injected ``clock`` callable (monotonic seconds): live
serving passes ``time.monotonic``, replay/bench/tests pass a virtual
clock — nothing in the engine reads the wall directly, which is what
makes the bench trace deterministic.

Host-sync discipline (graftlint GL004): each micro-batch's probabilities
cross to the host once, via one ``np.asarray`` at response assembly;
per-request ``float()`` reads index that numpy array, never a device
buffer.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deepdfa_tpu import contracts, telemetry
from deepdfa_tpu.core.config import subkeys_for
from deepdfa_tpu.core.metrics import ServingStats
from deepdfa_tpu.resilience import inject
from deepdfa_tpu.graphs.batch import batch_graphs
from deepdfa_tpu.models.infer import make_combined_infer, make_gnn_infer
from deepdfa_tpu.serve.batcher import (
    MicroBatcher,
    OversizedError,
    RejectedError,
    ServeRequest,
)
from deepdfa_tpu.serve.cache import ResultCache, content_hash, text_hash
from deepdfa_tpu.serve.config import ServeConfig

logger = logging.getLogger(__name__)


class BadRequestError(Exception):
    """Malformed scoring payload (missing subkeys, out-of-range edges)."""


@dataclasses.dataclass
class _Lane:
    name: str
    infer: Callable
    params: Any
    subkeys: Sequence[str]
    band: bool  # uses_band_adj: banded adjacency, tile-aligned budgets
    graph_cfg: Any = None  # the lane's FlowGNNConfig (fused cost capture)


@dataclasses.dataclass
class _GenLane:
    """The generation lane (ISSUE 13): a CodeT5-shaped encoder-decoder
    served through the batched-beam decode of models/t5_generate.py.
    ``infer(params, ids) -> (sequences, scores)`` is the AOT-compiled
    unit, one executable per (slot-bucket, src-bucket) shape."""

    model: Any
    params: Any
    tokenizer: Any
    infer: Callable


def _make_gen_infer(model, config: ServeConfig) -> Callable:
    """(params, ids [slots, src_bucket]) -> (seqs [slots, gen_max_len],
    scores [slots]). Beam > 1 rides the batched ancestry cache with
    length-bucketed early exit (an all-decided micro-batch stops paying
    the remaining max_len steps); beam 1 is the greedy scan."""
    from deepdfa_tpu.models.t5_generate import beam_search, greedy_decode

    if config.gen_beam_size > 1:
        def infer(params, ids):
            return beam_search(model, params, ids, config.gen_max_len,
                               beam_size=config.gen_beam_size)
    else:
        def infer(params, ids):
            seq = greedy_decode(model, params, ids, config.gen_max_len)
            return seq, jnp.zeros((ids.shape[0],), jnp.float32)
    return infer


def bucket_batch(config: ServeConfig, graphs: Sequence[Mapping], slots: int,
                 subkeys: Sequence[str], band: bool = False):
    """Pack ``graphs`` into the ``slots``-slot serving bucket shape.

    THE bucket-shape constructor: warmup examples, live micro-batches,
    and smoke-mode init batches all come through here, so a shape
    mismatch between warmup and steady state cannot exist by
    construction.
    """
    from deepdfa_tpu.ops.tile_spmm import DEFAULT_TILE

    budget = config.budget_for(slots, tile=DEFAULT_TILE if band else None)
    return batch_graphs(
        graphs, slots, budget["max_nodes"], budget["max_edges"], subkeys,
        build_band_adj=band,
        band_bandwidth=config.band_bandwidth if band else None,
        # Serve lanes capture their shapes at the admission edge
        # (engine.submit) — counting them again here would double-book
        # serve traffic into the train series.
        shape_series=None,
    )


def random_gnn_params(model, config: ServeConfig, seed: int = 0):
    """Random-init FlowGNN params shaped for this serving config — smoke
    and bench mode (the serving stack is real, the scores are not)."""
    empty = bucket_batch(
        config, [], 1, subkeys_for(model.config.feature),
        band=model.config.uses_band_adj,
    )
    return model.init(jax.random.PRNGKey(seed), empty)


class ServeEngine:
    """Checkpoint-to-responses inference engine.

    ``gnn_model``/``gnn_params``: a standalone FlowGNN classifier
    (label_style "graph") — always present; it is both the graph-only
    scoring path and the degradation target. ``combined_model``/
    ``combined_params`` (+ ``tokenizer``): the DeepDFA+LineVul lane for
    requests that carry source code. ``gen_model``/``gen_params`` (+
    ``gen_tokenizer``): the CodeT5 generation lane (ISSUE 13) — source
    text in, batched-beam decoded tokens out, warmed per (slot-bucket,
    src-length-bucket) shape under the same zero-recompile discipline.

    Threading: ``submit`` may run on many transport threads;
    ``pump``/``drain`` must run on exactly one (the pump thread or the
    driving loop). The batcher and cache carry the locks.
    """

    def __init__(
        self,
        gnn_model,
        gnn_params,
        config: Optional[ServeConfig] = None,
        combined_model=None,
        combined_params=None,
        tokenizer=None,
        clock: Callable[[], float] = time.monotonic,
        replica: Optional[str] = None,
        device=None,
        policy=None,
        gen_model=None,
        gen_params=None,
        gen_tokenizer=None,
    ):
        self.config = config or ServeConfig()
        # Fleet identity (serve/fleet.py): `replica` must come from the
        # statically-enumerated REPLICA_IDS set — it names this engine's
        # metric series and trace spans. `device` pins params AND every
        # micro-batch to one device, so N replicas dispatch to N devices
        # instead of all landing on jax's default. `policy` is the
        # adaptive flush controller, driven from pump().
        self.replica = replica
        self._device = device
        self.policy = policy
        self.stats = ServingStats(self.config.latency_window,
                                  replica=replica)
        self.cache = ResultCache(self.config.cache_capacity)
        self._clock = clock
        self._rid = itertools.count()
        # Requests currently inside _run_batch (the in-flight bucket):
        # the fleet router reads this to route arrivals toward replicas
        # with bucket capacity while this one executes.
        self.in_flight = 0
        # Monotonic flush ordinal for the fault hook: counts every
        # _run_batch invocation, failed or not (stats.batches counts only
        # successes, which would pin a fault plan's index on failure).
        self._flush_ordinal = itertools.count()
        self._compiled: Dict[Tuple[str, int], Any] = {}
        # Compile count recorded at the end of warmup(): the live SLO
        # monitor's compiles_after_warmup baseline (None until warmed).
        self.warmup_compiles: Optional[int] = None
        # Lame-duck drain flag (enter_lame_duck): the batcher flushes
        # immediately and the transport sheds NEW admissions.
        self.lame_duck = False

        if device is not None:
            # Replica pinning: committed params make the AOT executables
            # compile for (and run on) this device; batches follow in
            # _graph_batch. On a one-device host this is a no-op copy.
            gnn_params = jax.device_put(gnn_params, device)
            if combined_params is not None:
                combined_params = jax.device_put(combined_params, device)
            if gen_params is not None:
                gen_params = jax.device_put(gen_params, device)

        self._lanes: Dict[str, _Lane] = {
            "gnn": self._make_lane("gnn", make_gnn_infer(gnn_model),
                                   gnn_params, gnn_model.config),
        }
        self.tokenizer = tokenizer
        if combined_model is not None:
            if tokenizer is None:
                raise ValueError("combined serving needs a tokenizer")
            self._lanes["combined"] = self._make_lane(
                "combined", make_combined_infer(combined_model),
                combined_params, combined_model.graph_config,
            )
        self._gen: Optional[_GenLane] = None
        if gen_model is not None:
            if gen_tokenizer is None:
                raise ValueError("the gen lane needs a gen_tokenizer")
            self._gen = _GenLane(
                model=gen_model, params=gen_params, tokenizer=gen_tokenizer,
                infer=_make_gen_infer(gen_model, self.config),
            )
        lanes = tuple(self._lanes) + (("gen",) if self._gen else ())
        self.batcher = MicroBatcher(self.config, lanes=lanes,
                                    replica=replica)

    @staticmethod
    def _make_lane(name, infer, params, graph_cfg) -> _Lane:
        if graph_cfg.message_impl not in ("segment", "band", "fused",
                                          "persistent"):
            raise ValueError(
                f"serving supports message_impl 'segment', 'band', 'fused' "
                f"or 'persistent' (pinned bandwidth), got "
                f"{graph_cfg.message_impl!r} — per-batch adjacency budgets "
                "would mint new compiled shapes at runtime"
            )
        # uses_band_adj, not a literal impl compare: the fused lane rides
        # the same pinned-bandwidth band adjacency, and an impl-string
        # test here silently dropped new band-family lanes back onto
        # segment-shaped batches (the flag-audit fix, ISSUE 9).
        return _Lane(name, infer, params, subkeys_for(graph_cfg.feature),
                     band=graph_cfg.uses_band_adj, graph_cfg=graph_cfg)

    def now(self) -> float:
        return self._clock()

    @property
    def clock(self) -> Callable[[], float]:
        """The injected clock (replay drivers introspect timelines)."""
        return self._clock

    @property
    def required_subkeys(self) -> List[str]:
        """Union of every lane's feature subkeys — the feats a request
        graph must carry (shared by serve admission and the scan
        featurizer, so the two surfaces cannot drift)."""
        return sorted({k for lane in self._lanes.values()
                       for k in lane.subkeys})

    # -- bucket shapes -----------------------------------------------------

    @property
    def n_warm(self) -> int:
        """Compiled (lane, slot-bucket) executables currently held."""
        return len(self._compiled)

    def warm_buckets(self) -> List[Tuple[str, int]]:
        return [(lane, slots) for lane in self._lanes
                for slots in self.config.slot_buckets]

    def gen_warm_buckets(self) -> List[Tuple[str, int, int]]:
        """Every (lane, slot-bucket, src-bucket) decode-program shape the
        gen lane may dispatch — the length-bucket ladder crossed with the
        slot ladder; empty without a gen lane."""
        if self._gen is None:
            return []
        return [("gen", slots, src_b)
                for slots in self.config.slot_buckets
                for src_b in self.config.gen_src_buckets]

    @property
    def has_gen_lane(self) -> bool:
        return self._gen is not None

    def warmup(self) -> int:
        """AOT-compile every (lane, slot-bucket) shape — including the
        gen lane's (slot, src-length) decode ladder; returns the count.

        After this returns, a trace whose every micro-batch fits
        ``batch_slots`` (and whose gen sources fit ``gen_src_len``) runs
        with zero new compiles.
        """
        before = self.stats.compiles
        for lane, slots in self.warm_buckets():
            self._executable(lane, slots)
        for _, slots, src_b in self.gen_warm_buckets():
            self._executable("gen", slots, src_b)
        # The trace's warmup marker: any jax.compile event after this is
        # a silent recompile, and `cli trace report` must say so (the
        # compiles-after-warmup-must-be-0 gate for serve traces).
        telemetry.event("serve.warmup_done",
                        warmed=self.stats.compiles - before,
                        buckets=self.n_warm)
        self.warmup_compiles = self.stats.compiles
        return self.stats.compiles - before

    @property
    def compiles_after_warmup(self) -> Optional[int]:
        """Silent recompiles since warmup() finished (the must-stay-0
        serving invariant, live); None before warmup."""
        if self.warmup_compiles is None:
            return None
        return self.stats.compiles - self.warmup_compiles

    def _executable(self, lane: str, slots: int,
                    src_bucket: Optional[int] = None):
        key: Tuple = ((lane, slots) if src_bucket is None
                      else (lane, slots, src_bucket))
        exe = self._compiled.get(key)
        if exe is None:
            exe = (self._compile_gen(slots, src_bucket)
                   if src_bucket is not None
                   else self._compile(lane, slots))
            self._compiled[key] = exe
        return exe

    def _compile_gen(self, slots: int, src_bucket: int):
        """AOT-compile one gen decode program: batched beam (or greedy)
        over a [slots, src_bucket] source block, static gen_max_len/
        gen_beam_size — the zero-steady-state-recompile discipline
        applied to generation."""
        gen = self._gen
        assert gen is not None
        t0 = time.perf_counter()
        with telemetry.span("serve.compile", lane="gen", slots=slots,
                            src_bucket=src_bucket):
            ids = jnp.zeros((slots, src_bucket), jnp.int32)
            if self._device is not None:
                ids = jax.device_put(ids, self._device)
            exe = jax.jit(gen.infer).lower(gen.params, ids).compile()
        from deepdfa_tpu.telemetry import costmodel

        costmodel.capture_compiled(
            f"serve.gen.s{slots}.t{src_bucket}", exe, span="serve.flush",
            lane="gen", slots=slots,
            steps_per_call=self.config.gen_max_len,
        )
        self.stats.bump("compiles")
        logger.info("compiled gen bucket slots=%d src=%d in %.2fs", slots,
                    src_bucket, time.perf_counter() - t0)
        return exe

    def _compile(self, lane_name: str, slots: int):
        lane = self._lanes[lane_name]
        t0 = time.perf_counter()
        with telemetry.span("serve.compile", lane=lane_name, slots=slots):
            empty = self._graph_batch(lane, [], slots)
            if lane_name == "combined":
                ids = jnp.zeros((slots, self.config.block_size), jnp.int32)
                if self._device is not None:
                    ids = jax.device_put(ids, self._device)
                lowered = jax.jit(lane.infer).lower(lane.params, ids, empty)
            else:
                lowered = jax.jit(lane.infer).lower(lane.params, empty)
            exe = lowered.compile()
        # Cost-model capture for the roofline report: this executable IS
        # the AOT artifact, so the capture costs one cost_analysis read,
        # no extra compile. Joined to serve.flush spans by (lane, slots).
        # Fused lanes add the Pallas kernel's analytic forward FLOPs —
        # XLA's cost model counts the custom call as zero.
        from deepdfa_tpu.telemetry import costmodel

        extra_flops = extra_bytes = 0.0
        cfg = lane.graph_cfg
        if cfg is not None:
            # ONE helper owns every eligibility leg (band adjacency,
            # backend, the persistent VMEM budget), so the serving
            # roofline charges the program each lane actually compiles.
            # Forward-only: serving never runs the backward.
            from deepdfa_tpu.ops.fused_gnn import analytic_extra_cost

            extra_flops, extra_bytes = analytic_extra_cost(
                cfg.message_impl, empty.band_adj, cfg.ggnn_hidden,
                cfg.n_steps, cfg.dtype, include_bwd=False)
        costmodel.capture_compiled(
            f"serve.{lane_name}.s{slots}", exe, span="serve.flush",
            lane=lane_name, slots=slots, extra_flops=extra_flops,
            extra_bytes=extra_bytes,
        )
        self.stats.bump("compiles")
        logger.info("compiled %s bucket slots=%d in %.2fs", lane_name, slots,
                    time.perf_counter() - t0)
        return exe

    def _graph_batch(self, lane: _Lane, graphs: Sequence[Mapping],
                     slots: int):
        gb = bucket_batch(self.config, graphs, slots, lane.subkeys,
                          band=lane.band)
        if self._device is not None:
            # The replica's executables are compiled for its pinned
            # device; batches must land there too or dispatch pays a
            # cross-device transfer (or an AOT placement error).
            gb = jax.device_put(gb, self._device)
        return gb

    # -- admission ---------------------------------------------------------

    def _normalize_graph(self, graph: Mapping) -> Dict:
        """Validate + canonicalize one request graph — the SAME contract
        the offline loaders enforce (``contracts.validate_example``), so
        online and offline ingestion cannot drift. ContractError maps to
        BadRequestError (the HTTP 400 class, kept distinct from capacity
        rejections); the validator reproduces the historic 400
        message classes byte-for-byte, asserted by the regression test in
        tests/test_serve.py."""
        union = self.required_subkeys
        try:
            return contracts.validate_example(graph, union,
                                              with_label=False,
                                              boundary="serve",
                                              stats=contracts.STATS)
        except contracts.ContractError as e:
            raise BadRequestError(str(e))

    def _encode_gen(self, code: str):
        """(padded ids, src bucket, raw token count) for one gen request
        — the gen lane's only size check (the token-count analog of
        admission_caps)."""
        from deepdfa_tpu.data.text import encode_function_t5

        tok = self._gen.tokenizer
        n = len(tok.tokenize(str(code))) + 2  # + bos/eos
        # Raw pre-bucket demand, observed BEFORE the cap check: the
        # ladder recommender needs to see oversize arrivals too.
        telemetry.observe_shape("traffic_shape_serve_gen_src_tokens", n)
        if n > self.config.gen_src_len:
            raise OversizedError(
                f"source has {n} tokens > gen-lane cap "
                f"{self.config.gen_src_len}")
        src_b = self.config.gen_src_bucket_for(n)
        return encode_function_t5(code, tok, block_size=src_b), src_b, n

    def submit(self, graph: Optional[Mapping], code: Optional[str] = None,
               deadline_ms: Optional[float] = None,
               lane: Optional[str] = None,
               trace_id: Optional[str] = None,
               trace_continued: bool = False) -> ServeRequest:
        """Admit one scoring or generation request; returns its
        ServeRequest handle.

        ``lane``: None routes by content as before (code + combined lane
        -> "combined", else "gnn"); ``lane="gen"`` submits ``code`` to
        the generation lane (no graph needed). Cache hits complete
        immediately (result set, event signalled); misses enqueue for
        the next micro-batch. Raises BadRequestError / OversizedError /
        RejectedError — the transport maps them to 400 / 413 / 429.

        ``trace_id``/``trace_continued`` (ISSUE 14): the distributed
        trace this request belongs to — continued from a client's
        traceparent header by the HTTP layer, threaded onto the
        ``serve.request`` span for offline client↔server joins.
        """
        now = self._clock()
        self.stats.bump("submitted")
        deadline_s = (deadline_ms if deadline_ms is not None
                      else self.config.deadline_ms) / 1000.0

        if lane == "gen":
            if self._gen is None:
                raise BadRequestError(
                    "lane 'gen': no generation lane attached (start serve "
                    "with a gen model)")
            if code is None:
                raise BadRequestError("lane 'gen' requires 'code'")
            input_ids, src_b, src_tokens = self._encode_gen(code)
            req = ServeRequest(
                rid=next(self._rid), key=text_hash(code), graph=None,
                lane="gen", arrival=now, deadline_s=deadline_s,
                input_ids=input_ids, src_bucket=src_b,
                src_tokens=src_tokens,
                t_submit=telemetry.now(),
                trace_id=trace_id, trace_continued=trace_continued,
            )
            return self._finish_submit(req, now)
        if lane is not None:
            raise BadRequestError(
                f"unknown lane {lane!r} (expected 'gen' or omitted)")

        norm = self._normalize_graph(graph)
        lane, input_ids, degraded = "gnn", None, False
        if code is not None and "combined" in self._lanes:
            try:
                from deepdfa_tpu.data.text import encode_function

                input_ids = encode_function(code, self.tokenizer,
                                            self.config.block_size)
                lane = "combined"
            except Exception:
                # Tokenizer path down for this payload: degrade to the
                # graph-only lane rather than failing the request.
                logger.warning("tokenizer failed; degrading to gnn lane",
                               exc_info=True)
                degraded = True
                self.stats.bump("degraded")
        # Raw pre-bucket shape at the admission edge (ISSUE 20): the
        # series name is formatted from the resolved lane, a member of
        # the code-enumerated lane set (GL014 holds — observe_shape
        # rejects names outside telemetry.sketch.SHAPE_SERIES).
        telemetry.observe_shape(f"traffic_shape_serve_{lane}_nodes",
                                int(norm["num_nodes"]))
        telemetry.observe_shape(f"traffic_shape_serve_{lane}_edges",
                                len(norm["senders"]))

        key = content_hash(norm, code if lane == "combined" else None)
        req = ServeRequest(
            rid=next(self._rid), key=key, graph=norm, lane=lane,
            arrival=now, deadline_s=deadline_s,
            input_ids=input_ids, degraded=degraded,
            t_submit=telemetry.now(),
            trace_id=trace_id, trace_continued=trace_continued,
        )
        return self._finish_submit(req, now)

    @staticmethod
    def _trace_attrs(req: ServeRequest) -> Dict[str, Any]:
        """Span attrs for the request's distributed-trace identity —
        empty for untraced submissions (replay/bench keep zero extra
        keys on the hot path)."""
        if req.trace_id is None:
            return {}
        return {"trace_id": req.trace_id,
                "trace_continued": req.trace_continued}

    def _finish_submit(self, req: ServeRequest, now: float) -> ServeRequest:
        """The shared admission tail: cache lookup, enqueue, accounting."""
        cached = self.cache.get(req.key)
        if cached is not None:
            self.stats.bump("cache_hits")
            self.stats.bump("completed")
            self.stats.observe_latency(0.0)
            req.completed_at = now
            req.finish(dict(cached, rid=req.rid, cached=True,
                            degraded=req.degraded))
            hit_attrs: Dict[str, Any] = dict(rid=req.rid, lane=req.lane,
                                             cached=True,
                                             **self._trace_attrs(req))
            if self.replica is not None:
                hit_attrs["replica"] = self.replica
            telemetry.record_span("serve.request", req.t_submit,
                                  **hit_attrs)
            return req
        try:
            self.batcher.admit(req)
        except OversizedError:
            self.stats.bump("oversized")
            raise
        except RejectedError:
            self.stats.bump("rejected")
            raise
        # Counted only for ADMITTED requests: a rejected submission that
        # gets retried must not inflate the miss count (cache_hit_rate
        # feeds the bench report).
        self.stats.bump("cache_misses")
        return req

    # -- execution ---------------------------------------------------------

    def pump(self, max_batches: Optional[int] = None) -> int:
        """Flush every lane currently due; returns micro-batches run.

        ``max_batches`` bounds the flushes per call — the fleet replay's
        discrete-event driver pumps one bucket at a time so arrivals
        interleave with this replica's flushes exactly as they would
        against a busy device.
        """
        n = 0
        while max_batches is None or n < max_batches:
            lane = self.batcher.due(self._clock())
            if lane is None:
                break
            reqs = self.batcher.take(lane)
            if reqs:
                self._run_batch(lane, reqs)
                n += 1
        if self.policy is not None:
            # Adaptive flush (serve/policy.py): rate-limited inside, on
            # the engine clock, so replayed policy runs are deterministic.
            self.policy.maybe_update(self)
        return n

    def drain(self) -> int:
        """Flush everything pending regardless of deadlines (offline
        scoring, shutdown)."""
        n = 0
        while self.batcher.depth():
            for lane in self.batcher.lanes:
                reqs = self.batcher.take(lane)
                if reqs:
                    self._run_batch(lane, reqs)
                    n += 1
        return n

    def pending(self) -> int:
        return self.batcher.depth()

    def load(self) -> int:
        """Queued + in-flight requests — the fleet router's load signal."""
        return self.batcher.depth() + self.in_flight

    def enter_lame_duck(self) -> None:
        """Lame-duck mode (ISSUE 10): the batcher flushes partially-filled
        buckets immediately (no fill/deadline wait), so the pump answers
        every already-admitted request as fast as the device allows.
        Admission control (503 + Retry-After for NEW requests) lives at
        the transport — in-flight producers like the scan service must
        still be able to score what they already accepted. Idempotent."""
        if not self.lame_duck:
            self.lame_duck = True
            self.batcher.set_drain_mode(True)
            telemetry.event("lifecycle.lame_duck", pending=self.pending())

    def next_flush_time(self) -> Optional[float]:
        return self.batcher.next_flush_time(self._clock())

    def _gen_values(self, reqs: List[ServeRequest], slots: int) -> List[Dict]:
        """Execute one gen micro-batch; per-request result values.

        Sources pad to the batch's largest length bucket (every request
        bucket is on the warmed ladder, so the max is too); empty slots
        stay all-pad rows whose decode output is discarded."""
        gen = self._gen
        src_b = max(r.src_bucket for r in reqs)
        exe = self._executable("gen", slots, src_b)
        pad_id = int(gen.model.cfg.pad_token_id)
        eos_id = int(gen.model.cfg.eos_token_id)
        ids = np.full((slots, src_b), pad_id, np.int32)
        for i, r in enumerate(reqs):
            ids[i, : len(r.input_ids)] = r.input_ids
        ids_dev = (jnp.asarray(ids) if self._device is None
                   else jax.device_put(ids, self._device))
        seqs, scores = exe(gen.params, ids_dev)
        # One host transfer per micro-batch (GL004 discipline below).
        s, sc = np.asarray(seqs), np.asarray(scores)
        from deepdfa_tpu.train.gen_loop import strip_ids

        return [{"tokens": strip_ids(s[i], pad_id, eos_id),
                 "score": float(sc[i]), "model": "gen"}
                for i in range(len(reqs))]

    def _flush_elems(self, lane_name: str, reqs: List[ServeRequest],
                     slots: int) -> "tuple[int, int, int]":
        """(elems_used, elems_per_slot, elems_budget) of one flush — the
        element axis of the padding decomposition. Graph lanes count
        nodes against the per-slot admission cap and the bucket's pow2/
        tile-rounded node budget; the gen lane counts raw source tokens
        against the batch's padded src bucket."""
        if lane_name == "gen":
            per_slot = max(r.src_bucket for r in reqs)
            used = sum(int(r.src_tokens) if r.src_tokens is not None
                       else len(r.input_ids) for r in reqs)
            return used, per_slot, slots * per_slot
        from deepdfa_tpu.ops.tile_spmm import DEFAULT_TILE

        lane = self._lanes[lane_name]
        budget = self.config.budget_for(
            slots, tile=DEFAULT_TILE if lane.band else None)
        used = sum(int(r.graph["num_nodes"]) for r in reqs)
        return used, self.config.max_nodes_per_graph, budget["max_nodes"]

    def _run_batch(self, lane_name: str, reqs: List[ServeRequest]) -> None:
        slots = self.config.bucket_for(len(reqs))
        ordinal = next(self._flush_ordinal)
        w0 = time.perf_counter()
        e_used, e_slot, e_budget = self._flush_elems(lane_name, reqs, slots)
        span_attrs: Dict[str, Any] = dict(lane=lane_name, n=len(reqs),
                                          slots=slots, ordinal=ordinal,
                                          elems=e_used, elems_slot=e_slot,
                                          elems_budget=e_budget)
        cause = self.batcher.last_flush_cause(lane_name)
        if cause is not None:
            span_attrs["cause"] = cause
        if self.replica is not None:
            span_attrs["replica"] = self.replica
        flush_span = telemetry.span("serve.flush", **span_attrs)
        self.in_flight = len(reqs)
        try:
            with flush_span:
                # Fault hook (index = flush ordinal): a `raise` here
                # simulates an executable/device failure mid-flush.
                inject.fire("serve.batch", index=ordinal)
                if lane_name == "gen":
                    values = self._gen_values(reqs, slots)
                else:
                    lane = self._lanes[lane_name]
                    exe = self._executable(lane_name, slots)
                    gb = self._graph_batch(lane, [r.graph for r in reqs],
                                           slots)
                    if lane_name == "combined":
                        pad_id = int(self.tokenizer.pad_token_id)
                        ids = np.full((slots, self.config.block_size),
                                      pad_id, np.int32)
                        for i, r in enumerate(reqs):
                            ids[i] = r.input_ids
                        ids_dev = (jnp.asarray(ids) if self._device is None
                                   else jax.device_put(ids, self._device))
                        probs = exe(lane.params, ids_dev, gb)
                    else:
                        probs = exe(lane.params, gb)
                    # One host transfer per micro-batch; everything after
                    # this indexes numpy (GL004: per-request reads must
                    # not ride on device buffers). It is also the span's
                    # honest device barrier — the flush duration includes
                    # execution.
                    p = np.asarray(probs)
                    values = [{"prob": float(p[i]), "model": lane_name}
                              for i in range(len(reqs))]
        except Exception as e:
            # Flush isolation: THIS micro-batch's requests fail (HTTP 500
            # class), the queue keeps draining, and later flushes run on
            # the already-compiled executables — one bad batch must not
            # wedge the pump thread or leak hung requests.
            self.in_flight = 0
            logger.exception("micro-batch failed (%s lane, %d requests)",
                             lane_name, len(reqs))
            self.stats.bump("failures", by=len(reqs))
            detail = f"{type(e).__name__}: {e}"
            for r in reqs:
                r.finish({"rid": r.rid, "error": "internal",
                          "detail": detail, "cached": False,
                          "degraded": r.degraded})
                telemetry.record_span("serve.request", r.t_submit,
                                      rid=r.rid, lane=lane_name,
                                      cached=False, error=type(e).__name__,
                                      **self._trace_attrs(r))
            return
        # Completion-time accounting, clock-shape aware: fleet replay
        # timelines expose flush_done(dt) (per-replica busy horizons, so
        # N replicas' measured compute overlaps on the virtual clock);
        # plain virtual clocks expose advance() (single serial timeline);
        # live monotonic clocks tick on their own.
        elapsed = time.perf_counter() - w0
        flush_done = getattr(self._clock, "flush_done", None)
        if flush_done is not None:
            done = flush_done(elapsed)
        else:
            advance = getattr(self._clock, "advance", None)
            if advance is not None:
                advance(elapsed)
            done = self._clock()
        t_done = telemetry.now()
        self.in_flight = 0
        self.stats.record_batch(len(reqs), slots, lane=lane_name,
                                elems_used=e_used, elems_per_slot=e_slot,
                                elems_budget=e_budget)
        for i, r in enumerate(reqs):
            # The cache line holds only content-derived values; "degraded"
            # describes THIS request's handling (its tokenizer failure),
            # not the content, so it must never ride a shared cache entry.
            value = values[i]
            self.cache.put(r.key, value)
            r.completed_at = done
            r.finish(dict(value, rid=r.rid, cached=False,
                          degraded=r.degraded))
            self.stats.bump("completed")
            self.stats.observe_latency(done - r.arrival)
            # The admission->respond span, rid threaded through; queue_ms
            # is the pre-flush share of it (both ends on the telemetry
            # clock — never the engine's virtual clock).
            req_attrs: Dict[str, Any] = dict(
                rid=r.rid, lane=lane_name, cached=False,
                degraded=r.degraded,
                queue_ms=max(w0 - r.t_submit, 0.0) * 1e3,
                flush_ordinal=ordinal,
                **self._trace_attrs(r),
            )
            if self.replica is not None:
                req_attrs["replica"] = self.replica
            telemetry.record_span("serve.request", r.t_submit, t_done,
                                  **req_attrs)

    # -- offline client ----------------------------------------------------

    def score_sync(self, graphs: Sequence[Mapping],
                   codes: Optional[Sequence[Optional[str]]] = None,
                   ) -> List[Dict]:
        """Score a list of functions through the full serving path
        (cache + batcher + bucketed execution), returning results in
        submission order — the ``cli.py score`` engine.

        Backpressure is absorbed, not surfaced: a rejected submit drains
        the queue and retries (an offline client has nowhere to shed load
        to). Per-function admission failures (oversize graph, malformed
        payload) come back as inline ``{"error", "detail"}`` entries — one
        bad dataset row must not abort the other N thousand.
        """
        out: List[Optional[ServeRequest]] = []
        errors: Dict[int, Dict] = {}
        for i, graph in enumerate(graphs):
            code = codes[i] if codes is not None else None
            try:
                out.append(self.submit(graph, code=code))
            except RejectedError:
                self.drain()
                out.append(self.submit(graph, code=code))
            except OversizedError as e:
                errors[i] = {"error": "oversized", "detail": str(e)}
                out.append(None)
            except BadRequestError as e:
                errors[i] = {"error": "bad_request", "detail": str(e)}
                out.append(None)
        self.drain()
        return [errors[i] if r is None else r.result
                for i, r in enumerate(out)]

    def snapshot(self) -> Dict[str, float]:
        return self.stats.snapshot(queue_depth=self.batcher.depth())

"""Serving configuration: bucket shapes, flush policy, capacities.

The bucket contract is the whole design: every shape the jitted inference
program can see is derivable from this config alone, so the engine can
AOT-compile all of them at startup and steady-state traffic never
recompiles. Slot counts round up the power-of-two ladder
(``graphs.batch.select_bucket`` — the same rounding rule training
batching uses), node/edge budgets scale per slot exactly like
``DataConfig.max_nodes``/``max_edges``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from deepdfa_tpu.graphs.batch import select_bucket

# The statically-enumerated replica-id set (the PR-7 predeclare
# discipline): every per-replica metric name in the process is formatted
# from a member of THIS tuple, never from runtime fleet state, so the
# Prometheus exposition's cardinality is bounded by code (GL014) and a
# fleet's counters can all be predeclared at server init. Growing the
# fleet beyond this set is a code change, not a config change — that is
# the point.
REPLICA_IDS = ("r0", "r1", "r2", "r3", "r4", "r5", "r6", "r7")
MAX_REPLICAS = len(REPLICA_IDS)

# The statically-enumerated engine-PROCESS id set (ISSUE 17), the same
# discipline one level up: a shared-nothing fleet of OS processes behind
# the router tier (serve/procfleet.py). Every per-process metric or
# trace-process name is formatted from a member of THIS tuple, so
# cardinality stays code-bounded across restarts — a replacement process
# reuses its predecessor's id with a bumped generation.
PROCESS_IDS = ("p0", "p1", "p2", "p3", "p4", "p5", "p6", "p7")
MAX_PROCESSES = len(PROCESS_IDS)


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    # Micro-batch geometry. Per-request caps (one graph per request) make
    # the bucket budgets exact: any `s` admitted requests fit the s-slot
    # bucket by construction, so admission is the only size check.
    batch_slots: int = 16            # largest micro-batch (slot-ladder top)
    max_nodes_per_graph: int = 64    # admission cap, as DataConfig
    max_edges_per_node: int = 4      # admission cap (incl. self loops)

    # Flush policy: a lane flushes when it holds ``batch_slots`` requests
    # (fill-flush) OR when the oldest request has spent ``flush_fraction``
    # of its deadline budget waiting (deadline-flush) — half-spent by
    # default, leaving the other half for compute + response assembly.
    deadline_ms: float = 100.0
    flush_fraction: float = 0.5

    # Backpressure: pending requests beyond ``queue_capacity`` are
    # rejected with a retry-after hint instead of growing latency
    # unboundedly.
    queue_capacity: int = 256

    # Content-hash result cache entries (0 disables caching).
    cache_capacity: int = 4096

    # Combined-lane text geometry (must match the checkpoint's block_size).
    block_size: int = 512

    # Rolling latency-quantile window (core.metrics.ServingStats).
    latency_window: int = 8192

    # Pinned block-band width for message_impl="band" models: serving must
    # fix it up front (a per-batch bucketed width would mint new compiled
    # shapes at runtime). 1 covers any packing of <=128-node graphs
    # (every edge stays within one 128-tile of the diagonal).
    band_bandwidth: int = 1

    # Fleet geometry: engine replicas behind the front-end, each pinned
    # to its own shard of the device mesh and AOT-warmed independently
    # (serve/fleet.py). Bounded by the statically-enumerated REPLICA_IDS
    # set so per-replica metric names stay code-enumerable.
    replicas: int = 1

    # Generation lane (ISSUE 13): CodeT5 batched-beam decode as a served
    # lane. Source token counts round up the ``gen_src_buckets`` pow2
    # ladder (select_bucket from ``gen_src_min_bucket`` to
    # ``gen_src_len``); every (slot-bucket, src-bucket) decode program is
    # AOT-warmed at startup like the scoring lanes, so steady-state gen
    # traffic never compiles. ``gen_max_len`` / ``gen_beam_size`` are
    # static decode-program shape — a per-request max_len would mint new
    # executables at runtime.
    gen_src_len: int = 64            # oversize cap AND ladder top
    gen_src_min_bucket: int = 64     # ladder base (== top: one bucket)
    gen_max_len: int = 32            # generated tokens per request
    gen_beam_size: int = 4           # 1 = greedy decode

    # Telemetry-driven adaptive flush (serve/policy.py): each replica's
    # batcher tunes its deadline-fraction and fill thresholds online from
    # its own p99/occupancy, clamped to [flush_fraction_min,
    # flush_fraction_max] with `adaptive_patience` consecutive signals of
    # hysteresis; every decision is a `serve.flush_policy` trace event.
    adaptive_flush: bool = False
    flush_fraction_min: float = 0.1
    flush_fraction_max: float = 0.9
    adaptive_interval_s: float = 0.25   # evaluation cadence (engine clock)
    adaptive_step: float = 0.1          # deadline-fraction step per move
    adaptive_patience: int = 2          # consecutive signals before a move
    adaptive_target_p99_frac: float = 0.8  # p99 target, share of deadline

    def __post_init__(self):
        if self.batch_slots < 1:
            raise ValueError("batch_slots must be >= 1")
        if not 0.0 < self.flush_fraction <= 1.0:
            raise ValueError("flush_fraction must be in (0, 1]")
        if self.queue_capacity < self.batch_slots:
            raise ValueError(
                "queue_capacity below batch_slots could never fill a bucket"
            )
        if not 1 <= self.replicas <= MAX_REPLICAS:
            raise ValueError(
                f"replicas must be in [1, {MAX_REPLICAS}] (the statically-"
                "enumerated REPLICA_IDS set bounds per-replica metric "
                "cardinality; grow it in serve/config.py to go wider)"
            )
        if not (0.0 < self.flush_fraction_min
                <= self.flush_fraction_max <= 1.0):
            raise ValueError(
                "need 0 < flush_fraction_min <= flush_fraction_max <= 1"
            )
        if self.adaptive_patience < 1:
            raise ValueError("adaptive_patience must be >= 1")
        if not 1 <= self.gen_src_min_bucket <= self.gen_src_len:
            raise ValueError(
                "need 1 <= gen_src_min_bucket <= gen_src_len")
        if self.gen_max_len < 1 or self.gen_beam_size < 1:
            raise ValueError("gen_max_len and gen_beam_size must be >= 1")

    @property
    def slot_buckets(self) -> List[int]:
        """Every micro-batch slot count the engine may emit (ascending)."""
        out: List[int] = []
        s = 1
        while s < self.batch_slots:
            out.append(s)
            s *= 2
        out.append(self.batch_slots)
        return out

    def bucket_for(self, n_requests: int) -> int:
        return select_bucket(n_requests, maximum=self.batch_slots, minimum=1)

    @property
    def gen_src_buckets(self) -> List[int]:
        """Every source-length bucket the gen lane may pad to (ascending
        pow2 ladder from ``gen_src_min_bucket`` to ``gen_src_len`` — the
        select_bucket rounding rule applied to token counts)."""
        out: List[int] = []
        s = self.gen_src_min_bucket
        while s < self.gen_src_len:
            out.append(s)
            s *= 2
        out.append(self.gen_src_len)
        return out

    def gen_src_bucket_for(self, n_tokens: int) -> int:
        """The padded source length for an ``n_tokens``-token request
        (callers reject > gen_src_len before asking)."""
        return select_bucket(n_tokens, maximum=self.gen_src_len,
                             minimum=self.gen_src_min_bucket)

    def budget_for(self, slots: int,
                   tile: Optional[int] = None) -> Dict[str, int]:
        """Padded node/edge budgets of the ``slots``-slot bucket.

        ``tile``: align the node budget up to a tile multiple (the
        band/tile adjacency layouts require it).
        """
        max_nodes = select_bucket(slots * self.max_nodes_per_graph)
        if tile:
            max_nodes = -(-max_nodes // tile) * tile
        return {
            "n_graphs": slots,
            "max_nodes": max_nodes,
            "max_edges": select_bucket(max_nodes * self.max_edges_per_node),
        }

    def admission_caps(self, num_nodes: int, num_edges: int) -> Optional[str]:
        """None when a graph fits a single slot; else the rejection reason.

        ``num_edges`` counts self loops (batching adds one per node).
        """
        if num_nodes > self.max_nodes_per_graph:
            return (f"graph has {num_nodes} nodes > per-request cap "
                    f"{self.max_nodes_per_graph}")
        if num_edges > num_nodes * self.max_edges_per_node:
            return (f"graph has {num_edges} edges (incl. self loops) > "
                    f"per-request cap {num_nodes * self.max_edges_per_node}")
        return None

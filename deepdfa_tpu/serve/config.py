"""Serving configuration: bucket shapes, flush policy, capacities.

The bucket contract is the whole design: every shape the jitted inference
program can see is derivable from this config alone, so the engine can
AOT-compile all of them at startup and steady-state traffic never
recompiles. Slot counts round up the power-of-two ladder
(``graphs.batch.select_bucket`` — the same rounding rule training
batching uses), node/edge budgets scale per slot exactly like
``DataConfig.max_nodes``/``max_edges``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from deepdfa_tpu.graphs.batch import select_bucket


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    # Micro-batch geometry. Per-request caps (one graph per request) make
    # the bucket budgets exact: any `s` admitted requests fit the s-slot
    # bucket by construction, so admission is the only size check.
    batch_slots: int = 16            # largest micro-batch (slot-ladder top)
    max_nodes_per_graph: int = 64    # admission cap, as DataConfig
    max_edges_per_node: int = 4      # admission cap (incl. self loops)

    # Flush policy: a lane flushes when it holds ``batch_slots`` requests
    # (fill-flush) OR when the oldest request has spent ``flush_fraction``
    # of its deadline budget waiting (deadline-flush) — half-spent by
    # default, leaving the other half for compute + response assembly.
    deadline_ms: float = 100.0
    flush_fraction: float = 0.5

    # Backpressure: pending requests beyond ``queue_capacity`` are
    # rejected with a retry-after hint instead of growing latency
    # unboundedly.
    queue_capacity: int = 256

    # Content-hash result cache entries (0 disables caching).
    cache_capacity: int = 4096

    # Combined-lane text geometry (must match the checkpoint's block_size).
    block_size: int = 512

    # Rolling latency-quantile window (core.metrics.ServingStats).
    latency_window: int = 8192

    # Pinned block-band width for message_impl="band" models: serving must
    # fix it up front (a per-batch bucketed width would mint new compiled
    # shapes at runtime). 1 covers any packing of <=128-node graphs
    # (every edge stays within one 128-tile of the diagonal).
    band_bandwidth: int = 1

    def __post_init__(self):
        if self.batch_slots < 1:
            raise ValueError("batch_slots must be >= 1")
        if not 0.0 < self.flush_fraction <= 1.0:
            raise ValueError("flush_fraction must be in (0, 1]")
        if self.queue_capacity < self.batch_slots:
            raise ValueError(
                "queue_capacity below batch_slots could never fill a bucket"
            )

    @property
    def slot_buckets(self) -> List[int]:
        """Every micro-batch slot count the engine may emit (ascending)."""
        out: List[int] = []
        s = 1
        while s < self.batch_slots:
            out.append(s)
            s *= 2
        out.append(self.batch_slots)
        return out

    def bucket_for(self, n_requests: int) -> int:
        return select_bucket(n_requests, maximum=self.batch_slots, minimum=1)

    def budget_for(self, slots: int,
                   tile: Optional[int] = None) -> Dict[str, int]:
        """Padded node/edge budgets of the ``slots``-slot bucket.

        ``tile``: align the node budget up to a tile multiple (the
        band/tile adjacency layouts require it).
        """
        max_nodes = select_bucket(slots * self.max_nodes_per_graph)
        if tile:
            max_nodes = -(-max_nodes // tile) * tile
        return {
            "n_graphs": slots,
            "max_nodes": max_nodes,
            "max_edges": select_bucket(max_nodes * self.max_edges_per_node),
        }

    def admission_caps(self, num_nodes: int, num_edges: int) -> Optional[str]:
        """None when a graph fits a single slot; else the rejection reason.

        ``num_edges`` counts self loops (batching adds one per node).
        """
        if num_nodes > self.max_nodes_per_graph:
            return (f"graph has {num_nodes} nodes > per-request cap "
                    f"{self.max_nodes_per_graph}")
        if num_edges > num_nodes * self.max_edges_per_node:
            return (f"graph has {num_edges} edges (incl. self loops) > "
                    f"per-request cap {num_nodes * self.max_edges_per_node}")
        return None

"""The accept/route tier of the multi-process serving fleet (ISSUE 17).

One thin HTTP process fronting the :class:`~deepdfa_tpu.serve.procfleet.
ProcFleet` of engine OS processes. It speaks the historic serving
surface — ``POST /score``, ``POST /scan``, ``GET /metrics``,
``GET /healthz`` — and owns three responsibilities only:

* **Routing**: each function in a POST is routed by the same graph-only
  content key (code excluded) and rendezvous hash the in-process fleet
  uses, with the router-side outstanding-items count standing in for
  the mid-flush/queue-depth override. Items sharing a target coalesce
  into ONE forwarded sub-batch per (client POST, engine process), so
  the child's micro-batcher still sees batches, not single items.
* **Crash isolation**: a connection failure on a forward marks the
  child dead (the probe thread backs this up for silent hangs) and
  re-routes that sub-batch to a live sibling — an admitted request is
  answered or explicitly rejected, never dropped. Scoring is pure, so
  a request re-executed after a mid-flush crash is safe.
* **Aggregation**: ``/metrics`` sums the children's ServingStats
  snapshots (counters summed, occupancy and hit-rate sample-weighted,
  latency quantiles reported as the worst process's — honest across
  shards), merges per-(lane, bucket) padding-waste exactly, and adds a
  ``processes`` section with real pids — the chaos scenario reads its
  SIGKILL victims from here. ``/healthz`` degrades when some-but-not-
  all processes are live, mirroring the in-process fleet's contract.

Every forward carries a ``traceparent`` continuing the client's trace
(or a fresh one), so the merged trace joins client → router.request →
router.forward → the child's serve.request across real pids.
"""

from __future__ import annotations

import contextlib
import http.client
import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from deepdfa_tpu import telemetry
from deepdfa_tpu.core.metrics import merge_padding_cells
from deepdfa_tpu.serve.config import ServeConfig
from deepdfa_tpu.serve.procfleet import (EngineProc, NoLiveProcessError,
                                         ProcFleet)
from deepdfa_tpu.telemetry import context as trace_context

logger = logging.getLogger("deepdfa.serve.router")


def predeclare_router_metrics() -> None:
    """PR-7 predeclare discipline: every router series exists from
    startup.

    The per-process loop iterates a *literal* constant tuple — the
    GL014-documented bounded shape; drift between it and
    ``PROCESS_IDS`` is pinned by a test in tests/test_procfleet.py.
    """
    for name in ("router_requests_total", "router_rerouted_total",
                 "router_shed_total", "router_proc_deaths_total"):
        telemetry.REGISTRY.counter(name)
    for rid in ("p0", "p1", "p2", "p3", "p4", "p5", "p6", "p7"):
        telemetry.REGISTRY.counter(f"router_forwards_{rid}_total")


def routing_key(fn: Dict) -> Optional[str]:
    """The fleet's routing key, computed from the raw wire payload:
    gen lane routes on the source text, everything else on the
    graph-only content hash (code excluded) — same affinity the
    in-process fleet gives each function. Malformed payloads route on
    load alone; the child's admission validator owns the 400 shape."""
    from deepdfa_tpu.serve.cache import content_hash, text_hash

    try:
        if fn.get("lane") == "gen":
            code = fn.get("code")
            return text_hash(code) if code is not None else None
        return content_hash(fn["graph"])
    except Exception:
        return None


def aggregate_snapshots(snaps: Dict[str, Optional[dict]]) -> Dict:
    """Fleet-wide ServingStats body from per-process snapshots.

    Counters and sample counts sum; ``batch_occupancy`` weights by
    batches and ``cache_hit_rate`` by lookups; latency quantiles take
    the worst process (a cross-process pool of the underlying windows
    does not exist here, and the max is the honest conservative bound);
    per-(lane, bucket) padding merges exactly on used/slot counts."""
    present = [s for s in snaps.values() if s]
    out: Dict[str, object] = {}
    keys = sorted({k for s in present for k, v in s.items()
                   if isinstance(v, (int, float))
                   and not isinstance(v, bool)})
    for k in keys:
        vals = [s.get(k, 0) or 0 for s in present]
        if k in ("latency_p50_ms", "latency_p99_ms"):
            out[k] = max(vals) if vals else 0.0
        elif k == "batch_occupancy":
            w = [s.get("batches", 0) or 0 for s in present]
            out[k] = (sum(v * x for v, x in zip(vals, w)) / sum(w)
                      if sum(w) else 0.0)
        elif k == "cache_hit_rate":
            w = [(s.get("cache_hits", 0) or 0)
                 + (s.get("cache_misses", 0) or 0) for s in present]
            out[k] = (sum(v * x for v, x in zip(vals, w)) / sum(w)
                      if sum(w) else 0.0)
        elif k in ("padding_waste_pct", "elem_waste_pct"):
            w = [_occ_slots(s) for s in present]
            out[k] = (sum(v * x for v, x in zip(vals, w)) / sum(w)
                      if sum(w) else 0.0)
        else:
            out[k] = sum(vals)
    padding = merge_padding_cells(
        s.get("padding_waste") for s in present)
    if padding:
        out["padding_waste"] = padding
        e_used = sum(c.get("elems_used", 0) for c in padding.values())
        e_budget = sum(c.get("elems_budget", 0) for c in padding.values())
        if e_budget:
            # Exact (not batch-weighted): the merged element counts ARE
            # the fleet-wide ledger, so recompute rather than average.
            out["elem_waste_pct"] = round(
                100.0 * (1.0 - e_used / e_budget), 4)
    return out


def _occ_slots(snap: dict) -> float:
    # occupancy_slots is not in the snapshot body; weight the overall
    # waste by batches — proportional enough for a fleet-level number.
    return snap.get("batches", 0) or 0


class RouterHandler(BaseHTTPRequestHandler):
    server: "RouterHTTPServer"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route access logs to logging
        logger.debug("%s %s", self.address_string(), fmt % args)

    def _send_json(self, code: int, payload: Dict,
                   headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, body: str, content_type: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # -- GET ---------------------------------------------------------------

    def do_GET(self) -> None:
        fleet = self.server.fleet
        if self.path == "/healthz":
            procs = fleet.processes()
            live = sum(1 for p in procs.values() if p["state"] == "live")
            doc: Dict[str, object] = {
                "status": "ok", "size": fleet.n, "live": live,
                "processes": procs, "inflight": self.server.inflight,
                "telemetry_drops": telemetry.drop_count(),
            }
            if self.server.draining:
                doc["status"] = "draining"
            elif live == 0:
                doc["status"] = "unavailable"
            elif live < fleet.n:
                doc["status"] = "degraded"
            self._send_json(200 if doc["status"] == "ok" else 503, doc)
        elif self.path == "/metrics":
            snaps = fleet.fetch_snapshots(
                timeout_s=max(fleet.probe_timeout_s, 1.0))
            doc = aggregate_snapshots(snaps)
            procs = fleet.processes()
            for rid, snap in snaps.items():
                if rid in procs and snap is not None:
                    procs[rid]["snapshot"] = snap
            doc["n_processes"] = fleet.n
            doc["processes"] = procs
            accept = self.headers.get("Accept", "") or ""
            if "text/plain" in accept or "openmetrics" in accept:
                body = telemetry.REGISTRY.prometheus_text(
                    extra={f"serve_{k}": v for k, v in doc.items()})
                self._send_text(200, body, "text/plain; version=0.0.4")
            else:
                self._send_json(200, doc)
        else:
            self._send_json(404, {"error": "not_found"})

    # -- POST --------------------------------------------------------------

    def _reject_draining(self) -> bool:
        if not self.server.draining:
            return False
        retry_s = self.server.drain_retry_after_s()
        self._send_json(503, {"error": "draining",
                              "retry_after_s": retry_s},
                        headers={"Retry-After":
                                 str(max(int(-(-retry_s // 1)), 1))})
        return True

    def _request_trace(self) -> Tuple[str, bool]:
        raw = self.headers.get(trace_context.TRACEPARENT_HEADER)
        if raw is not None:
            parsed = trace_context.parse_traceparent(raw)
            if parsed is not None:
                return parsed[0], True
            telemetry.REGISTRY.counter("trace_ctx_malformed_total").inc()
        return trace_context.new_trace_id(), False

    def do_POST(self) -> None:
        with self.server.track_inflight():
            if self._reject_draining():
                return
            if self.path == "/score":
                self._do_score()
            elif self.path == "/scan":
                self._do_scan()
            else:
                self._send_json(404, {"error": "not_found"})

    def _read_doc(self) -> Optional[dict]:
        try:
            length = int(self.headers.get("Content-Length", "0"))
            doc = json.loads(self.rfile.read(length).decode("utf-8"))
            if not isinstance(doc, dict):
                raise ValueError("body must be a JSON object")
            return doc
        except Exception as e:
            self._send_json(400, {"error": "bad_request", "detail": str(e)})
            return None

    def _do_score(self) -> None:
        doc = self._read_doc()
        if doc is None:
            return
        try:
            functions = doc["functions"]
            if not isinstance(functions, list) or not functions:
                raise ValueError("'functions' must be a non-empty list")
            deadline_ms = doc.get("deadline_ms")
            if deadline_ms is not None:
                deadline_ms = float(deadline_ms)
                if not deadline_ms > 0:
                    raise ValueError("deadline_ms must be > 0")
        except Exception as e:
            self._send_json(400, {"error": "bad_request", "detail": str(e)})
            return
        fleet = self.server.fleet
        telemetry.REGISTRY.counter("router_requests_total").inc()
        trace_id, trace_continued = self._request_trace()
        results: List[Dict] = [{} for _ in functions]
        with telemetry.span("router.request", n_functions=len(functions),
                            trace_id=trace_id,
                            trace_continued=trace_continued) as hs:
            groups: Dict[str, Tuple[EngineProc, List[int]]] = {}
            for i, fn in enumerate(functions):
                if not isinstance(fn, dict):
                    results[i] = {"error": "bad_request",
                                  "detail": "function entries must be "
                                            "objects"}
                    continue
                try:
                    proc = fleet.route(routing_key(fn))
                except NoLiveProcessError:
                    telemetry.REGISTRY.counter("router_shed_total").inc()
                    results[i] = {
                        "error": "rejected",
                        "retry_after_s": fleet.spawn_deadline_s
                        if fleet.auto_respawn
                        else self.server.serve_config.deadline_ms / 1000.0}
                    continue
                groups.setdefault(proc.rid, (proc, []))[1].append(i)
            rerouted = 0
            for proc, idxs in groups.values():
                rerouted += self._dispatch(proc, functions, idxs,
                                           deadline_ms, trace_id, results)
            if rerouted:
                telemetry.REGISTRY.counter(
                    "router_rerouted_total").inc(rerouted)
            if results and all(r.get("error") == "rejected"
                               for r in results):
                retry = max(float(r.get("retry_after_s", 1.0))
                            for r in results)
                hs.set(status=429, rerouted=rerouted)
                self._send_json(429, {"error": "rejected",
                                      "retry_after_s": retry},
                                headers={"Retry-After":
                                         str(max(int(-(-retry // 1)), 1))})
                return
            status = 500 if (results
                             and all(r.get("error") == "internal"
                                     for r in results)) else 200
            hs.set(status=status, rerouted=rerouted,
                   procs=sorted(groups))
            self._send_json(status, {"results": results})

    def _dispatch(self, proc: EngineProc, functions: List[Dict],
                  idxs: List[int], deadline_ms: Optional[float],
                  trace_id: str, results: List[Dict]) -> int:
        """Forward one sub-batch, re-routing to live siblings when the
        target dies under us (crash isolation) or rejects the whole
        group (the fleet's retry-once-on-a-sibling contract). Returns
        the number of items that had to be re-routed."""
        fleet = self.server.fleet
        config = self.server.serve_config
        payload: Dict[str, object] = {
            "functions": [functions[i] for i in idxs]}
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        timeout_s = ((deadline_ms or config.deadline_ms) / 1000.0) \
            * 10 + 30.0
        header_tp = trace_context.make_traceparent(trace_id)
        rerouted = 0
        tried: List[EngineProc] = []
        target: Optional[EngineProc] = proc
        allow_reject_retry = True
        while target is not None:
            tried.append(target)
            fleet.begin_forward(target, len(idxs))
            try:
                with telemetry.span("router.forward", proc=target.rid,
                                    generation=target.generation,
                                    pid=target.pid, n=len(idxs),
                                    trace_id=trace_id) as fs:
                    status, body = self._post_child(
                        target, "/score", payload, header_tp, timeout_s)
                    fs.set(status=status if status is not None else 0)
            finally:
                fleet.end_forward(target, len(idxs))
            telemetry.REGISTRY.counter(
                f"router_forwards_{target.rid}_total").inc()
            if status is None:
                # Died between accept and dispatch (or mid-flush):
                # mark dead, shed this sub-batch to a live sibling.
                fleet.mark_dead(target.rid, "connection",
                                generation=target.generation)
                rerouted += len(idxs)
                target = self._next_target(tried)
                continue
            if status == 429:
                if allow_reject_retry:
                    allow_reject_retry = False
                    nxt = self._next_target(tried)
                    if nxt is not None:
                        rerouted += len(idxs)
                        target = nxt
                        continue
                retry = (body or {}).get("retry_after_s",
                                         config.deadline_ms / 1000.0)
                for i in idxs:
                    results[i] = {"error": "rejected",
                                  "retry_after_s": retry}
                return rerouted
            child_results = (body or {}).get("results")
            if not isinstance(child_results, list) \
                    or len(child_results) != len(idxs):
                for i in idxs:
                    results[i] = {"error": "internal",
                                  "detail": "malformed engine response"}
                return rerouted
            for i, entry in zip(idxs, child_results):
                results[i] = entry
            return rerouted
        # Every live process was tried and lost: the inline-error shape
        # survives (500 overall when every item in the POST died).
        telemetry.REGISTRY.counter("router_shed_total").inc(len(idxs))
        for i in idxs:
            results[i] = {"error": "internal",
                          "detail": "no live engine process"}
        return rerouted

    def _next_target(self, tried: List[EngineProc]) -> Optional[EngineProc]:
        live = [p for p in self.server.fleet.live() if p not in tried]
        if not live:
            return None
        return min(live, key=lambda p: p.outstanding)

    def _post_child(self, proc: EngineProc, path: str, payload: Dict,
                    traceparent: str, timeout_s: float,
                    ) -> Tuple[Optional[int], Optional[dict]]:
        if proc.port is None:
            return None, None
        body = json.dumps(payload).encode()
        conn = http.client.HTTPConnection(self.server.fleet.host,
                                          proc.port, timeout=timeout_s)
        try:
            conn.request("POST", path, body=body, headers={
                "Content-Type": "application/json",
                trace_context.TRACEPARENT_HEADER: traceparent})
            resp = conn.getresponse()
            raw = resp.read()
            try:
                return resp.status, json.loads(raw.decode("utf-8"))
            except ValueError:
                return resp.status, None
        except OSError:
            return None, None
        finally:
            conn.close()

    def _do_scan(self) -> None:
        """POST /scan rides the same tier: the whole envelope forwards
        to one live process routed on the first source's text hash (scan
        results are per-POST artifacts, not per-function cache lines),
        with the same dead-child re-route. Children without a scan
        service answer 501 and the router relays it."""
        from deepdfa_tpu.serve.cache import text_hash

        doc = self._read_doc()
        if doc is None:
            return
        functions = doc.get("functions")
        if not isinstance(functions, list) or not functions:
            self._send_json(400, {"error": "bad_request",
                                  "detail": "'functions' must be a "
                                            "non-empty list"})
            return
        key = None
        first = functions[0]
        if isinstance(first, dict) and isinstance(first.get("source"), str):
            key = text_hash(first["source"])
        fleet = self.server.fleet
        trace_id, trace_continued = self._request_trace()
        header_tp = trace_context.make_traceparent(trace_id)
        timeout_s = (self.server.serve_config.deadline_ms / 1000.0) \
            * 10 + 120.0
        with telemetry.span("router.scan", n_functions=len(functions),
                            trace_id=trace_id,
                            trace_continued=trace_continued) as hs:
            tried: List[EngineProc] = []
            while True:
                try:
                    target = fleet.route(key)
                except NoLiveProcessError:
                    target = None
                if target is None or target in tried:
                    target = self._next_target(tried)
                if target is None:
                    hs.set(status=503)
                    self._send_json(503, {"error": "draining",
                                          "retry_after_s":
                                          fleet.spawn_deadline_s})
                    return
                tried.append(target)
                fleet.begin_forward(target, len(functions))
                try:
                    status, body = self._post_child(
                        target, "/scan", doc, header_tp, timeout_s)
                finally:
                    fleet.end_forward(target, len(functions))
                if status is None:
                    fleet.mark_dead(target.rid, "connection",
                                    generation=target.generation)
                    continue
                hs.set(status=status, proc=target.rid)
                self._send_json(status, body if body is not None
                                else {"error": "internal"})
                return


class RouterHTTPServer(ThreadingHTTPServer):
    """The router's transport: one handler thread per connection, all
    blocking on child HTTP round-trips, drain machinery mirroring
    :class:`ServeHTTPServer` so the PR-10 lifecycle drives the same
    lame-duck dance one level up."""

    daemon_threads = True

    def __init__(self, addr, fleet: ProcFleet, config: ServeConfig):
        predeclare_router_metrics()
        super().__init__(addr, RouterHandler)
        self.fleet = fleet
        self.serve_config = config
        self.draining = False
        self.drain_notice = None
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    @contextlib.contextmanager
    def track_inflight(self):
        with self._inflight_lock:
            self._inflight += 1
        try:
            yield
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def drain_retry_after_s(self) -> float:
        notice = self.drain_notice
        floor = (self.serve_config.flush_fraction
                 * self.serve_config.deadline_ms / 1000.0)
        if notice is None:
            return max(floor, 1.0)
        return max(notice.remaining(), floor, 1.0)

    def begin_drain(self, notice=None) -> None:
        self.drain_notice = notice
        self.draining = True

    def await_drained(self, deadline_s: float, beat=None,
                      poll_s: float = 0.01) -> bool:
        import time

        deadline = time.monotonic() + max(deadline_s, 0.0)
        last = -1
        while time.monotonic() < deadline:
            n = self.inflight
            if n == 0:
                return True
            if beat is not None and n != last:
                beat()
                last = n
            time.sleep(poll_s)
        return self.inflight == 0


def serve_forever_router(fleet: ProcFleet, config: ServeConfig,
                         host: str = "127.0.0.1", port: int = 8080,
                         port_file: Optional[str] = None):
    """Blocking router entry, the multi-process analogue of
    :func:`serve.http.serve_forever`: bind (after the fleet is live, so
    the port file IS the whole-fleet warm signal), serve, and register
    with the lifecycle coordinator — a preemption notice drains the
    router (admissions 503, in-flight forwards answered), then shuts
    the fleet down child by child (each child runs its own lame-duck).
    Returns the notice (None on a plain shutdown)."""
    from deepdfa_tpu.resilience import lifecycle

    server = RouterHTTPServer((host, port), fleet, config)
    if port_file:
        tmp = f"{port_file}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(str(server.server_address[1]))
        os.replace(tmp, port_file)
    logger.info("routing on %s:%d (%d engine process(es))", host,
                server.server_address[1], fleet.n)

    coordinator = lifecycle.coordinator()
    participant_box: Dict[str, object] = {}

    def on_notice(notice) -> None:
        participant = participant_box.get("p")
        beat = participant.beat if participant else (lambda: None)
        with telemetry.span("lifecycle.drain_router"):
            server.begin_drain(notice)
            beat()
            budget = participant.deadline_s if participant \
                else notice.grace_s
            drained = server.await_drained(
                min(budget, notice.remaining()), beat=beat)
            if not drained:
                logger.error("router drain overran its budget: "
                             "inflight=%d", server.inflight)
            beat()
            fleet.shutdown()
        if participant:
            participant.drained(ok=drained)
        telemetry.flush()
        server.shutdown()

    participant_box["p"] = coordinator.register("serve",
                                                on_notice=on_notice)
    try:
        server.serve_forever()
    finally:
        try:
            server.shutdown()
        finally:
            coordinator.unregister(participant_box["p"])
    return coordinator.notice

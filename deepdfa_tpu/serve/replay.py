"""Seeded traffic traces + virtual-clock replay.

The serving bench's measurement harness: arrivals come from a *seeded*
bursty generator (no wall-clock randomness — the trace is identical
every run), the clock is virtual, and only measured compute advances it.
Each replay step either (a) advances the clock to the next arrival and
submits, or (b) advances it to the next flush time and pumps, adding the
pump's measured wall duration to the virtual clock so queueing delay
downstream of slow compute is accounted exactly. Per-request latency =
completion clock − arrival clock, combining queue wait and compute like
a real deployment.

Used by bench.py (serve_p99_ms / serve_graphs_per_sec) and by the
tests/test_serve.py acceptance check (zero post-warmup compiles, ≥50%
occupancy, responses match the offline eval path).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from deepdfa_tpu.core.config import FeatureSpec
from deepdfa_tpu.serve.engine import ServeEngine


class VirtualClock:
    """Injectable monotonic clock: ``clock()`` reads, the driver advances."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt

    def advance_to(self, t: float) -> None:
        self.t = max(self.t, t)


@dataclasses.dataclass
class TraceEvent:
    at: float                 # virtual arrival time (seconds)
    graph: Mapping
    code: Optional[str] = None


def bursty_trace(
    n_requests: int,
    feature: FeatureSpec = FeatureSpec(),
    seed: int = 0,
    burst_mean: float = 12.0,
    gap_ms_range: "tuple[float, float]" = (5.0, 60.0),
    intra_ms: float = 0.3,
    duplicate_fraction: float = 0.25,
    with_code: bool = False,
) -> List[TraceEvent]:
    """CI-scan-shaped traffic: bursts of near-simultaneous requests
    separated by idle gaps, with a duplicate fraction (re-scans of
    unchanged functions) to exercise the content cache.

    Fully determined by ``seed`` — timestamps are generated numbers, not
    wall readings.
    """
    from deepdfa_tpu.data.synthetic import synthetic_bigvul

    rng = np.random.default_rng(seed)
    uniques = synthetic_bigvul(n_requests, feature, positive_fraction=0.5,
                               seed=seed)
    events: List[TraceEvent] = []
    t = 0.0
    next_unique = 0
    while len(events) < n_requests:
        burst = max(1, int(rng.poisson(burst_mean)))
        for _ in range(min(burst, n_requests - len(events))):
            if next_unique and rng.random() < duplicate_fraction:
                g = uniques[int(rng.integers(next_unique))]
            else:
                g = uniques[next_unique]
                next_unique = min(next_unique + 1, len(uniques) - 1)
            code = None
            if with_code:
                code = f"int f_{int(g['id'])}(char *p) {{ return p[0]; }}"
            events.append(TraceEvent(at=t, graph=g, code=code))
            t += intra_ms / 1000.0
        t += float(rng.uniform(*gap_ms_range)) / 1000.0
    return events


def replay(
    engine: ServeEngine,
    trace: Sequence[TraceEvent],
    clock: VirtualClock,
) -> Dict:
    """Drive ``engine`` (whose clock must be ``clock``) through ``trace``.

    The engine itself credits the virtual clock with each micro-batch's
    measured compute time (the ``advance()`` contract in
    engine._run_batch), so recorded latencies cover queue wait AND
    compute. Returns the engine's metrics snapshot plus the replayed
    requests (submission order) for correctness checks. Rejected
    submissions are pumped-and-retried once (an offline driver has no
    caller to shed to); a second rejection is recorded and the event
    dropped.
    """
    from deepdfa_tpu.serve.batcher import RejectedError

    requests = []
    dropped = 0
    i = 0
    while i < len(trace) or engine.pending():
        t_arrival = trace[i].at if i < len(trace) else float("inf")
        t_flush = engine.next_flush_time()
        if t_flush is None:
            t_flush = float("inf")
        if t_flush <= t_arrival:
            clock.advance_to(t_flush)
            ran = engine.pump()
            if not ran and not engine.pending():
                break
            continue
        clock.advance_to(t_arrival)
        ev = trace[i]
        i += 1
        try:
            requests.append(engine.submit(ev.graph, code=ev.code))
        except RejectedError:
            engine.pump()
            try:
                requests.append(engine.submit(ev.graph, code=ev.code))
            except RejectedError:
                dropped += 1
    report = engine.snapshot()
    report["dropped"] = dropped
    span = clock() - (trace[0].at if trace else 0.0)
    report["span_s"] = span
    report["graphs_per_sec"] = (len(requests) / span) if span > 0 else 0.0
    return {"metrics": report, "requests": requests}
